# Top-level developer workflow. `make check` is the full correctness
# gate (docs/OPERATIONS.md §7): static conformance + lint first — so a
# stale binary or a protocol drift fails BEFORE ten minutes of tests run
# against it — then the tier-1 suite, then the sanitizer legs. The
# sanitizer legs self-skip when the toolchain lacks the runtime library,
# so `make check` stays runnable everywhere tier-1 is.
PY ?= python
CXX ?= g++

.PHONY: check lint verify-model xla-budget xla-budget-restamp test \
        native asan-test tsan-test chaos-test reshard-soak \
        upgrade-soak parity-fuzz llm-soak controller-soak \
        reserve-soak federation-soak uring-test audit-soak storm-soak

check: lint verify-model xla-budget test chaos-test upgrade-soak \
       parity-fuzz uring-test llm-soak controller-soak reserve-soak \
       federation-soak audit-soak storm-soak asan-test tsan-test

# Static gate: ruff (style/pyflakes/asyncio, config in pyproject.toml;
# optional — the container may not ship it) + drl-check (wire/ABI
# conformance, concurrency + JAX hot-path lints, build freshness —
# always on; it has no dependencies beyond the stdlib and numpy).
lint:
	@if command -v ruff >/dev/null 2>&1; then \
	  ruff check .; \
	else \
	  echo "lint: ruff not installed — skipping style pass" \
	       "(pip install ruff to enable)"; \
	fi
	$(PY) -m tools.drl_check

# Protocol model checking + lock-order analysis (docs/OPERATIONS.md
# §15): extracts the epoch/config/reservation/breaker state machines
# from the live code and explores their product exhaustively under an
# adversarial scheduler (>= 10^5 states in ~10 s; state/depth caps are
# printed whenever they truncate — never silently). Exit 1 prints the
# minimized counterexample traces; regenerate their replay pytests
# with `python -m tools.drl_verify --emit-replays <dir>`.
verify-model:
	$(PY) -m tools.drl_verify

# Compiled-artifact conformance (docs/OPERATIONS.md §19): traces every
# jitted admission kernel to jaxpr/StableHLO and checks hot-path
# purity, donation conformance, retrace stability, and the op-count
# budget ledger (tools/drl_xla/budgets.json). Frozen here (--no-restamp)
# so a drifted ledger FAILS the gate instead of silently rewriting
# itself mid-check; run `make xla-budget-restamp` after a deliberate
# kernel change to re-stamp, then commit the budgets.json diff.
xla-budget:
	JAX_PLATFORMS=cpu $(PY) -m tools.drl_xla --no-restamp

xla-budget-restamp:
	JAX_PLATFORMS=cpu $(PY) -m tools.drl_xla

# Tier-1: the suite every PR must keep green (ROADMAP.md).
test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
	  --continue-on-collection-errors -p no:cacheprovider

# Chaos harness: the seeded fault-injection soak (docs/OPERATIONS.md §8)
# — a live topology driven through a deterministic fault schedule, plus
# the at-most-once retry differential. Also part of tier-1; this target
# runs it alone, verbosely, for failure-mode work.
chaos-test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_chaos.py -v \
	  -p no:cacheprovider

# Membership soak: join/leave/hot-split under seeded chaos load
# (docs/OPERATIONS.md §9). `make reshard-soak SEED=...` replays any
# schedule bit-for-bit — the same determinism contract as chaos-test.
SEED ?= 20260803
reshard-soak:
	JAX_PLATFORMS=cpu DRL_RESHARD_SEED=$(SEED) $(PY) -m pytest \
	  tests/test_reshard.py -v -p no:cacheprovider

# Rolling-restart soak: restart every node of a 3-node cluster under
# seeded wire chaos + live traffic with a mid-roll live limit mutation
# (docs/OPERATIONS.md §10). `make upgrade-soak SEED=...` replays any
# schedule bit-for-bit, the chaos-test determinism contract.
upgrade-soak:
	JAX_PLATFORMS=cpu DRL_UPGRADE_SEED=$(SEED) $(PY) -m pytest \
	  tests/test_upgrade.py -v -p no:cacheprovider

# LLM multi-tenant admission soak: seeded Zipf-tenant × log-normal-cost
# schedule with a noisy-neighbor scavenger flood through the
# hierarchical wire lanes, plus the admission-subsystem unit surface
# (docs/OPERATIONS.md §11). `make llm-soak SEED=...` replays any
# schedule bit-for-bit — the chaos-test determinism contract.
llm-soak:
	JAX_PLATFORMS=cpu DRL_LLM_SEED=$(SEED) $(PY) -m pytest \
	  tests/test_llm_admission.py -v -p no:cacheprovider

# Estimate-reserve-settle soak: the seeded streaming schedule
# (estimate = actual × log-normal error) under wire chaos with a
# mid-soak drain-and-handoff and a live OP_CONFIG budget mutation,
# plus the reservation ledger's unit surface (docs/OPERATIONS.md §14).
# `make reserve-soak SEED=...` replays any schedule bit-for-bit — the
# chaos-test determinism contract.
reserve-soak:
	JAX_PLATFORMS=cpu DRL_RESERVE_SEED=$(SEED) $(PY) -m pytest \
	  tests/test_reservations.py -v -p no:cacheprovider

# Global quota federation soak: the seeded 3-region WAN-lease schedule
# under chaos on the federation seams, with a full partition of one
# region spanning > 2 lease periods (slice → monotonic expiry →
# fair-share envelope), a home crash/restart off the v4 checkpoint
# chain, and the Σ-regional-admits ≤ global cap + ε(RTT, lease_len)
# differential audit (docs/OPERATIONS.md §16).
# `make federation-soak SEED=...` replays any schedule bit-for-bit —
# the chaos-test determinism contract.
federation-soak:
	JAX_PLATFORMS=cpu DRL_FEDERATION_SEED=$(SEED) $(PY) -m pytest \
	  tests/test_federation.py -v -p no:cacheprovider

# Autonomous control plane soak: the seeded diurnal + flash-crowd swing
# driven against a live 3-node fleet under wire + controller.tick chaos
# with zero operator calls (docs/OPERATIONS.md §13) — plus the
# controller's policy unit surface (hysteresis, cooldown, budget,
# dry-run parity). `make controller-soak SEED=...` replays any action
# schedule bit-for-bit, the chaos-test determinism contract.
controller-soak:
	JAX_PLATFORMS=cpu DRL_CONTROLLER_SEED=$(SEED) $(PY) -m pytest \
	  tests/test_controller.py -v -p no:cacheprovider

# Retry-storm goodput soak: the seeded overload schedule (client
# timeout < loaded server latency, multiplicative retries) through the
# baseline/naive/defended arms over the real wire — defended holds ≥
# 80% of no-storm first-attempt goodput while naive collapses < 50%,
# retries/scavenger/doomed work shed before any viable interactive
# first attempt, the over-budget tail routes to the overflow pool, and
# the stores' own records audit to zero over-admission
# (docs/OPERATIONS.md §20). `make storm-soak SEED=...` replays any
# grant/shed/route schedule bit-for-bit — the chaos-test determinism
# contract.
storm-soak:
	JAX_PLATFORMS=cpu DRL_STORM_SEED=$(SEED) $(PY) -m pytest \
	  tests/test_storm.py -v -p no:cacheprovider

# Conservation audit soak: the seeded audit.leak injection (a deny
# flipped into a granted reply with NO store debit) must breach the
# reply/witness identity within three watchdog ticks and yield exactly
# one black-box incident bundle, with zero false alarms on the clean
# arms (docs/OPERATIONS.md §18). `make audit-soak SEED=...` replays
# any alert schedule bit-for-bit — the chaos-test determinism contract.
audit-soak:
	JAX_PLATFORMS=cpu DRL_AUDIT_SEED=$(SEED) $(PY) -m pytest \
	  tests/test_audit.py -v -p no:cacheprovider

# Native-vs-asyncio differential fuzz, verbosely (also part of tier-1):
# reply-for-reply byte identity over randomized scalar AND bulk
# (ACQUIRE_MANY) traffic, including traced/MOVED/retired-config frames,
# plus the multi-shard arms (round 11: 4-shard server, same replies)
# and the shard-ABI/envelope/retire-fan-out suite.
parity-fuzz:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_native_parity_fuzz.py \
	  tests/test_native_bulk.py tests/test_native_shards.py \
	  -v -p no:cacheprovider

# io_uring transport suite (round 16, docs/OPERATIONS.md §17): the
# feature-detection matrix always runs (kill switch, simulated seccomp
# denial, stale-binary fallback — those ARE the epoll-fallback paths),
# and the live-ring arms self-skip inside pytest when the kernel lacks
# io_uring. The banner below makes that skip loud at the make level so
# "uring-test passed" on a ringless host is never read as ring
# coverage. Parity arms for the uring transport ride parity-fuzz.
uring-test:
	@JAX_PLATFORMS=cpu $(PY) -c "import sys; from distributedratelimiting.redis_tpu.runtime.native_frontend import uring_probe; ok, why = uring_probe(); sys.stdout.write('' if ok else 'uring-test: NO RING on this host (%s) -- live-ring arms SELF-SKIP; running the fallback/feature-detection matrix only\n' % why)" || true
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_native_uring.py \
	  -v -p no:cacheprovider

# Explicit native builds (the loader also builds on first import).
native:
	$(MAKE) -C native all

# Sanitizer legs (native/Makefile): skip, loudly, when the compiler has
# no runtime for them — tier-1 and the static gate still ran.
ASAN_RT = $(shell $(CXX) -print-file-name=libasan.so)
TSAN_RT = $(shell $(CXX) -print-file-name=libtsan.so)

asan-test:
	@if [ -e "$(ASAN_RT)" ]; then \
	  $(MAKE) -C native asan-test; \
	else \
	  echo "asan-test: $(CXX) has no libasan — skipping sanitizer leg"; \
	fi

tsan-test:
	@if [ -e "$(TSAN_RT)" ]; then \
	  $(MAKE) -C native tsan-test; \
	else \
	  echo "tsan-test: $(CXX) has no libtsan — skipping sanitizer leg"; \
	fi
