"""Benchmark: permit decisions/sec/chip at 10M active keys.

North star (BASELINE.json): >= 50M permit decisions/sec aggregate on a
v5e-8 with p99 acquire < 2ms, i.e. >= 6.25M decisions/sec/chip.
``vs_baseline`` is measured throughput / 6.25M (the per-chip north-star
share — the reference itself publishes no numbers, BASELINE.md).

Prints ONE JSON line. Extra keys carry secondary measurements (single-batch
dispatch rate, end-to-end asyncio path, p99) without changing the schema.

Method (headline): steady-state device throughput of the batched
refill-and-decrement kernel over a 10M-slot HBM table — batches of 8K
random keys, SCAN_K batches pipelined per dispatch via lax.scan (each batch
keeps its own ``now`` operand), donated state buffers, host->device
transfer of fresh request arrays included in the timed loop, in-batch
duplicate serialization ON (exact invariant-3 semantics).

The pipeline is transfer-bound, not compute-bound (the kernel runs at
~3.3B decisions/s on resident operands; transfers overlap across queued
dispatches, with a sharp sustained-rate cliff above ~1MB per dispatch),
so the headline path uses the 3-bytes-per-decision operand layout
(``acquire_scan_packed24``: 24-bit packed slot ids, unit permits). The
5-bytes-per-decision mixed-count path (``acquire_scan_compact``) is
reported as a secondary metric.
"""

from __future__ import annotations

import asyncio
import json
import sys
import time

import numpy as np


N_SLOTS = 10_000_000
BATCH = 8192
SCAN_K = 32           # 768KB/dispatch packed24 — under the ~1MB sustained
                      # transfer cliff while amortizing dispatch overhead
                      # (measured sweep in benchmarks/RESULTS.md)
ITERS = 100           # timed dispatches of SCAN_K batches each
COMPACT_SCAN_K = 20   # 5B/decision path's sweet spot under the same cliff
CAPACITY = 100.0
RATE_PER_SEC = 50.0
NORTH_STAR_PER_CHIP = 50e6 / 8


def bench_kernel_throughput(jnp, K, clock):
    """Headline: 24-bit-packed scanned kernel path at 10M keys."""
    import jax

    rate_per_tick = jnp.float32(RATE_PER_SEC / 1024.0)
    cap = jnp.float32(CAPACITY)
    state = K.init_bucket_state(N_SLOTS)
    rng = np.random.default_rng(0)

    staged = [
        K.pack_slots24(rng.integers(0, N_SLOTS, (SCAN_K, BATCH)))
        for _ in range(4)
    ]

    def dispatch(state, packed):
        base = clock.now_ticks()
        nows = np.arange(SCAN_K, dtype=np.int32) + base
        return K.acquire_scan_packed24(
            state, jnp.asarray(packed), jnp.asarray(nows), cap,
            rate_per_tick,
        )

    # Warmup: compile + touch every page of the donated buffers.
    state, granted, _ = dispatch(state, staged[0])
    jax.block_until_ready(granted)

    # Best-of-3 timed windows: the tunneled link's sustained bandwidth
    # fluctuates run to run; the max window is the pipeline's real rate.
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        for i in range(ITERS):
            state, granted, _ = dispatch(state, staged[i % len(staged)])
        jax.block_until_ready(granted)
        dt = time.perf_counter() - t0
        best = max(best, ITERS * SCAN_K * BATCH / dt)
    return best, state


def bench_compact_throughput(jnp, K, clock, state):
    """Secondary: mixed-count 5-bytes/decision path (i32 slot + u8 count)."""
    import jax

    rate_per_tick = jnp.float32(RATE_PER_SEC / 1024.0)
    cap = jnp.float32(CAPACITY)
    rng = np.random.default_rng(1)
    sk = COMPACT_SCAN_K
    staged = [
        (rng.integers(0, N_SLOTS, (sk, BATCH)).astype(np.int32),
         np.ones((sk, BATCH), np.uint8))
        for _ in range(4)
    ]

    def dispatch(state, arrays):
        slots, counts = arrays
        nows = np.arange(sk, dtype=np.int32) + clock.now_ticks()
        return K.acquire_scan_compact(
            state, jnp.asarray(slots), jnp.asarray(counts),
            jnp.asarray(nows), cap, rate_per_tick,
        )

    state, granted, _ = dispatch(state, staged[0])
    jax.block_until_ready(granted)
    iters = 60
    t0 = time.perf_counter()
    for i in range(iters):
        state, granted, _ = dispatch(state, staged[i % 4])
    jax.block_until_ready(granted)
    dt = time.perf_counter() - t0
    return iters * sk * BATCH / dt, state


def bench_single_batch(jnp, K, clock, state):
    """Secondary: one-batch-per-dispatch rate (latency-oriented path)."""
    import jax

    rate_per_tick = jnp.float32(RATE_PER_SEC / 1024.0)
    cap = jnp.float32(CAPACITY)
    rng = np.random.default_rng(1)
    slots = [jnp.asarray(rng.integers(0, N_SLOTS, BATCH), np.int32)
             for _ in range(4)]
    counts = jnp.ones((BATCH,), jnp.int32)
    valid = jnp.ones((BATCH,), bool)

    state, granted, _ = K.acquire_batch(
        state, slots[0], counts, valid, jnp.int32(clock.now_ticks()),
        cap, rate_per_tick, handle_duplicates=False)
    jax.block_until_ready(granted)
    iters = 100
    t0 = time.perf_counter()
    for i in range(iters):
        state, granted, _ = K.acquire_batch(
            state, slots[i % 4], counts, valid,
            jnp.int32(clock.now_ticks()), cap, rate_per_tick,
            handle_duplicates=False)
    jax.block_until_ready(granted)
    dt = time.perf_counter() - t0
    return iters * BATCH / dt


async def bench_e2e_bulk(store_mod, limiter_mod, options_mod):
    """End-to-end BULK serving path: ``acquire_many`` arrays through the
    partitioned limiter — key→slot resolve + packing + scanned dispatch +
    single-fetch readback all included; several calls overlap in flight.
    Returns (verdict-only decisions/s, with-remaining decisions/s)."""
    store = store_mod.DeviceBucketStore(n_slots=1 << 21, max_batch=8192)
    lim = limiter_mod.PartitionedRateLimiter(
        options_mod.TokenBucketOptions(
            token_limit=10_000_000, tokens_per_period=10_000_000,
            instance_name="bulk"), store)
    n = 1 << 17
    rng = np.random.default_rng(2)
    pool = [f"user{i}" for i in range(1_000_000)]
    calls = [[pool[j] for j in rng.integers(0, len(pool), n)]
             for _ in range(8)]

    async def run_round(with_remaining):
        await lim.acquire_many(calls[0], with_remaining=with_remaining)  # warm
        t0 = time.perf_counter()
        results = await asyncio.gather(
            *(lim.acquire_many(c, with_remaining=with_remaining)
              for c in calls))
        dt = time.perf_counter() - t0
        return sum(len(r) for r in results) / dt

    verdict_only = max([await run_round(False) for _ in range(2)])
    with_remaining = await run_round(True)
    await store.aclose()
    return verdict_only, with_remaining


def bench_pallas_sweep(store_mod):
    """Assert the COMPILED (non-interpret) Pallas streaming sweep works on
    this platform: force it on, trigger a sweep over reclaimable slots, and
    report whether the Mosaic path ran without falling back."""
    from distributedratelimiting.redis_tpu.runtime.clock import ManualClock

    clock = ManualClock()
    store = store_mod.DeviceBucketStore(n_slots=1024, clock=clock,
                                        use_pallas_sweep=True)
    for i in range(64):
        store.acquire_blocking(f"sweep{i}", 1, 10.0, 10.0)
    clock.advance_seconds(5.0)  # everything refills → TTL-expired
    table = next(iter(store._tables.values()))
    table._sweep(None)
    ok = (store.use_pallas_sweep
          and store.metrics.pallas_sweep_failures == 0
          and store.metrics.slots_evicted >= 64)
    return bool(ok)


async def bench_e2e_async(store_mod, limiter_mod, options_mod):
    """End-to-end asyncio path: micro-batched partitioned limiter driven by
    a closed-loop worker pool deep enough to keep several flush readbacks in
    flight (readback RTT dominates on tunneled links and overlaps across
    flushes). Returns (decisions/s, p99 seconds)."""
    store = store_mod.DeviceBucketStore(
        n_slots=1 << 17, max_batch=4096, max_delay_s=300e-6, max_inflight=16)
    lim = limiter_mod.PartitionedRateLimiter(
        options_mod.TokenBucketOptions(
            token_limit=10_000_000, tokens_per_period=10_000_000,
            instance_name="bench"), store)
    lat: list[float] = []
    workers = 16384
    reqs_per_worker = 3

    async def worker(w):
        for j in range(reqs_per_worker):
            t0 = time.perf_counter()
            await lim.acquire_async(f"user{(w * 7 + j) % 10000}", 1)
            lat.append(time.perf_counter() - t0)

    # Warm the kernel (one compile per table) at full depth.
    await asyncio.gather(*(worker(w) for w in range(2048)))
    lat.clear()

    t0 = time.perf_counter()
    await asyncio.gather(*(worker(w) for w in range(workers)))
    dt = time.perf_counter() - t0
    throughput = len(lat) / dt

    # Low-load latency probe: p99 without saturation queueing — at this
    # depth each request's latency ≈ flush deadline + one device round
    # trip (RTT-bound on tunneled links; ~sub-ms on co-located hosts).
    lat.clear()
    await asyncio.gather(*(worker(w) for w in range(64)))
    lat.sort()
    p99_low = lat[int(len(lat) * 0.99)]
    await store.aclose()
    return throughput, p99_low


def main():
    import jax
    import jax.numpy as jnp

    from distributedratelimiting.redis_tpu.models import partitioned
    from distributedratelimiting.redis_tpu.models import options as options_mod
    from distributedratelimiting.redis_tpu.ops import kernels as K
    from distributedratelimiting.redis_tpu.runtime import store as store_mod
    from distributedratelimiting.redis_tpu.runtime.clock import MonotonicClock

    platform = jax.devices()[0].platform
    clock = MonotonicClock()

    throughput, state = bench_kernel_throughput(jnp, K, clock)
    compact, state = bench_compact_throughput(jnp, K, clock, state)
    single = bench_single_batch(jnp, K, clock, state)
    del state  # free the 10M-slot table before the serving-path stores
    bulk_rate, bulk_with_rem = asyncio.run(
        bench_e2e_bulk(store_mod, partitioned, options_mod))
    e2e_rate, p99 = asyncio.run(
        bench_e2e_async(store_mod, partitioned, options_mod))
    pallas_ok = bench_pallas_sweep(store_mod) if platform == "tpu" else None

    print(json.dumps({
        "metric": "permit_decisions_per_sec_per_chip",
        "value": round(throughput),
        "unit": "decisions/s",
        "vs_baseline": round(throughput / NORTH_STAR_PER_CHIP, 3),
        "platform": platform,
        "n_keys": N_SLOTS,
        "batch": BATCH,
        "scan_depth": SCAN_K,
        "compact_path_decisions_per_sec": round(compact),
        "single_batch_decisions_per_sec": round(single),
        "e2e_bulk_decisions_per_sec": round(bulk_rate),
        "e2e_bulk_with_remaining_decisions_per_sec": round(bulk_with_rem),
        "e2e_async_decisions_per_sec": round(e2e_rate),
        "e2e_p99_low_load_ms": round(p99 * 1e3, 3),
        "pallas_sweep_ok": pallas_ok,
    }))


if __name__ == "__main__":
    sys.exit(main())
