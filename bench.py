"""Benchmark: permit decisions/sec/chip at 10M active keys.

North star (BASELINE.json): >= 50M permit decisions/sec aggregate on a
v5e-8 with p99 acquire < 2ms, i.e. >= 6.25M decisions/sec/chip.
``vs_baseline`` is measured throughput / 6.25M (the per-chip north-star
share — the reference itself publishes no numbers, BASELINE.md).

Emission contract (the r04 lesson, VERDICT.md round 4 #1): the bench
prints the FULL result JSON after *every* completed section with
``"partial": true`` — the driver's tail capture parses the LAST JSON
line, so a timeout/wedge mid-run still leaves every finished metric on
record. The final line has ``"partial": false``. A global wall-clock
budget (``BENCH_BUDGET_S``, default 1200s) bounds the whole run: when it
runs out, remaining sections are marked ``skipped_budget`` and the bench
exits 0 with what it has. The device is NEVER initialised in this
process until a disposable-child probe has seen a healthy init window
(``BENCH_PROBE_S``); if no window appears, device sections are marked
``skipped_unhealthy_device`` and the CPU stand-in sections still run.
Each device section runs on a timeout-guarded daemon thread so a tunnel
wedge mid-run costs one section, not the whole evidence pipeline.
Kill-test hooks: ``BENCH_SIM_WEDGE=1`` makes the probe child hang;
``BENCH_SIM_HANG_SECTION=<name>`` wedges one named section.

Method (headline): steady-state device throughput of the batched
refill-and-decrement kernel over a 10M-slot HBM table — batches of 8K
random keys, SCAN_K batches pipelined per dispatch via lax.scan (each batch
keeps its own ``now`` operand), donated state buffers, host->device
transfer of fresh request arrays included in the timed loop, in-batch
duplicate serialization ON (exact invariant-3 semantics).

The pipeline is transfer-bound, not compute-bound (the kernel runs at
~3.3B decisions/s on resident operands; transfers overlap across queued
dispatches, with a sharp sustained-rate cliff above ~1MB per dispatch),
so the headline path uses the 3-bytes-per-decision operand layout
(``acquire_scan_packed24``: 24-bit packed slot ids, unit permits). The
5-bytes-per-decision mixed-count path (``acquire_scan_compact``) is
reported as a secondary metric.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import threading
import time

import numpy as np


N_SLOTS = 10_000_000
BATCH = 8192
SCAN_K = 32           # 768KB/dispatch packed24 — under the ~1MB sustained
                      # transfer cliff while amortizing dispatch overhead
                      # (measured sweep in benchmarks/RESULTS.md)
ITERS = 100           # timed dispatches of SCAN_K batches each
COMPACT_SCAN_K = 16   # 5B/decision fused path: 640KB/dispatch — pinned
                      # UNDER the cliff with margin (the old K=20 sat at
                      # 800KB, on the cliff's edge; see RESULTS.md r04)
CAPACITY = 100.0
RATE_PER_SEC = 50.0
NORTH_STAR_PER_CHIP = 50e6 / 8
TIMED_WINDOWS = 3     # best-of-N for every throughput metric: the tunneled
                      # link's sustained bandwidth swings 2-4x minute to
                      # minute (RESULTS.md r04 root-cause); the max window
                      # is the pipeline's real rate, the link probe below
                      # records the environment it ran in


def bench_kernel_throughput(jnp, K, clock):
    """Headline: 24-bit-packed scanned kernel path at 10M keys."""
    import jax

    rate_per_tick = jnp.float32(RATE_PER_SEC / 1024.0)
    cap = jnp.float32(CAPACITY)
    state = K.init_bucket_state(N_SLOTS)
    rng = np.random.default_rng(0)

    staged = [
        K.pack_slots24(rng.integers(0, N_SLOTS, (SCAN_K, BATCH)))
        for _ in range(4)
    ]

    def dispatch(state, packed):
        base = clock.now_ticks()
        nows = np.arange(SCAN_K, dtype=np.int32) + base
        return K.acquire_scan_packed24(
            state, jnp.asarray(packed), jnp.asarray(nows), cap,
            rate_per_tick,
        )

    # Warmup: compile + touch every page of the donated buffers.
    state, granted, _ = dispatch(state, staged[0])
    jax.block_until_ready(granted)

    # Best-of-N timed windows: the tunneled link's sustained bandwidth
    # fluctuates run to run; the max window is the pipeline's real rate.
    best = 0.0
    for _ in range(TIMED_WINDOWS):
        t0 = time.perf_counter()
        for i in range(ITERS):
            state, granted, _ = dispatch(state, staged[i % len(staged)])
        jax.block_until_ready(granted)
        dt = time.perf_counter() - t0
        best = max(best, ITERS * SCAN_K * BATCH / dt)
    return best, state


def bench_link_probe(jnp):
    """Raw host→device upload rate of one under-cliff buffer — records the
    tunnel's state next to the throughput numbers so a slow round is
    distinguishable from a code regression (the r03 lesson, RESULTS.md)."""
    import jax

    x = np.ones((768 * 1024,), np.uint8)
    jax.block_until_ready(jax.device_put(x))
    best = 0.0
    for _ in range(TIMED_WINDOWS):
        t0 = time.perf_counter()
        for _ in range(10):
            jax.block_until_ready(jax.device_put(x))
        best = max(best, 10 * x.nbytes / (time.perf_counter() - t0))
    return best / 1e6


def bench_rtt_probe(jnp):
    """RTT control for the latency-bound metrics (VERDICT r5 weak #2 /
    next #5): (a) ``link_rtt_ms`` — median round trip of a tiny
    up+down transfer, the per-flush floor every e2e latency number
    rides on; (b) ``link_pipeline_overlap_x`` — wall time of 8 serial
    result fetches over one overlapped ``device_get`` of 8 (the r05
    "degraded window" discovery: bandwidth, RTT, and pipelining swing
    INDEPENDENTLY on the tunneled link — a healthy-RTT window can still
    refuse to overlap fetches). Recorded beside every round's numbers so
    a latency slide is attributable at a glance, the way
    ``link_upload_mb_per_s`` already de-noised throughput."""
    import jax

    x = np.ones((8,), np.uint8)
    jax.block_until_ready(jax.device_put(x))  # warm the path
    rtts = []
    for _ in range(15):
        t0 = time.perf_counter()
        np.asarray(jax.device_put(x))  # one up + one down = one RTT
        rtts.append(time.perf_counter() - t0)
    rtts.sort()
    rtt_ms = rtts[len(rtts) // 2] * 1e3

    def fresh():
        # jax arrays cache their host copy after the first fetch — every
        # timed fetch needs arrays that have never come back.
        arrs = [jax.device_put(np.full((4096,), i, np.uint8))
                for i in range(8)]
        jax.block_until_ready(arrs)
        return arrs

    best_serial = float("inf")
    best_overlap = float("inf")
    for _ in range(3):
        arrs = fresh()
        t0 = time.perf_counter()
        for a in arrs:
            np.asarray(a)
        best_serial = min(best_serial, time.perf_counter() - t0)
        arrs = fresh()
        t0 = time.perf_counter()
        jax.device_get(arrs)  # one call: fetches overlap
        best_overlap = min(best_overlap, time.perf_counter() - t0)
    overlap_x = best_serial / best_overlap if best_overlap > 0 else 1.0
    return rtt_ms, overlap_x


def bench_compact_throughput(jnp, K, clock, state):
    """Secondary: mixed-count 5-bytes/decision path, fused into ONE
    operand per dispatch (``pack_compact5`` + ``acquire_scan_compact_fused``
    — per-transfer floors on the tunneled link penalize the old two-array
    layout on slow-link days)."""
    import jax

    rate_per_tick = jnp.float32(RATE_PER_SEC / 1024.0)
    cap = jnp.float32(CAPACITY)
    rng = np.random.default_rng(1)
    sk = COMPACT_SCAN_K
    staged = [
        K.pack_compact5(rng.integers(0, N_SLOTS, (sk, BATCH)).astype(np.int32),
                        np.ones((sk, BATCH), np.uint8))
        for _ in range(4)
    ]

    def dispatch(state, fused):
        nows = np.arange(sk, dtype=np.int32) + clock.now_ticks()
        return K.acquire_scan_compact_fused(
            state, jnp.asarray(fused), jnp.asarray(nows), cap, rate_per_tick,
        )

    state, granted, _ = dispatch(state, staged[0])
    jax.block_until_ready(granted)
    iters = 60
    best = 0.0
    for _ in range(TIMED_WINDOWS):
        t0 = time.perf_counter()
        for i in range(iters):
            state, granted, _ = dispatch(state, staged[i % 4])
        jax.block_until_ready(granted)
        best = max(best, iters * sk * BATCH / (time.perf_counter() - t0))
    return best, state


def bench_single_batch(jnp, K, clock, state):
    """Secondary: one-batch-per-dispatch rate (latency-oriented path)."""
    import jax

    rate_per_tick = jnp.float32(RATE_PER_SEC / 1024.0)
    cap = jnp.float32(CAPACITY)
    rng = np.random.default_rng(1)
    slots = [jnp.asarray(rng.integers(0, N_SLOTS, BATCH), np.int32)
             for _ in range(4)]
    counts = jnp.ones((BATCH,), jnp.int32)
    valid = jnp.ones((BATCH,), bool)

    state, granted, _ = K.acquire_batch(
        state, slots[0], counts, valid, jnp.int32(clock.now_ticks()),
        cap, rate_per_tick, handle_duplicates=False)
    jax.block_until_ready(granted)
    iters = 100
    best = 0.0
    for _ in range(TIMED_WINDOWS):
        t0 = time.perf_counter()
        for i in range(iters):
            state, granted, _ = K.acquire_batch(
                state, slots[i % 4], counts, valid,
                jnp.int32(clock.now_ticks()), cap, rate_per_tick,
                handle_duplicates=False)
        jax.block_until_ready(granted)
        best = max(best, iters * BATCH / (time.perf_counter() - t0))
    return best


async def bench_e2e_bulk(store_mod, limiter_mod, options_mod):
    """End-to-end BULK serving path: ``acquire_many`` arrays through the
    partitioned limiter — key→slot resolve + packing + scanned dispatch +
    single-fetch readback all included; several calls overlap in flight.
    Returns (verdict-only decisions/s, with-remaining decisions/s)."""
    store = store_mod.DeviceBucketStore(n_slots=1 << 21, max_batch=8192)
    lim = limiter_mod.PartitionedRateLimiter(
        options_mod.TokenBucketOptions(
            token_limit=10_000_000, tokens_per_period=10_000_000,
            instance_name="bulk"), store)
    n = 1 << 17
    rng = np.random.default_rng(2)
    pool = [f"user{i}" for i in range(1_000_000)]
    calls = [[pool[j] for j in rng.integers(0, len(pool), n)]
             for _ in range(8)]

    async def run_round(with_remaining):
        await lim.acquire_many(calls[0], with_remaining=with_remaining)  # warm
        t0 = time.perf_counter()
        results = await asyncio.gather(
            *(lim.acquire_many(c, with_remaining=with_remaining)
              for c in calls))
        dt = time.perf_counter() - t0
        return sum(len(r) for r in results) / dt

    verdict_only = max([await run_round(False) for _ in range(2)])
    with_remaining = await run_round(True)
    await store.aclose()
    return verdict_only, with_remaining


async def bench_fp_bulk():
    """Device-resident-directory bulk path: the same whole-array workload
    through `FingerprintBucketStore` — key→slot probe/insert happens
    IN-KERNEL on 64-bit fingerprints; the host's per-call duty is one
    native hashing pass (no host directory). Reported beside the
    host-directory bulk number so the operand-bytes vs host-work trade
    (docs/DESIGN.md §5b) is tracked per round on the real chip."""
    from distributedratelimiting.redis_tpu.runtime.fp_store import (
        FingerprintBucketStore,
    )

    store = FingerprintBucketStore(n_slots=1 << 21, max_batch=8192)
    n = 1 << 17
    rng = np.random.default_rng(3)
    pool = [f"user{i}" for i in range(1_000_000)]
    calls = [[pool[j] for j in rng.integers(0, len(pool), n)]
             for _ in range(4)]
    counts = [1] * n

    async def run_round():
        t0 = time.perf_counter()
        results = await asyncio.gather(
            *(store.acquire_many(c, counts, 10_000_000.0, 10_000_000.0,
                                 with_remaining=False) for c in calls))
        dt = time.perf_counter() - t0
        return sum(len(r) for r in results) / dt

    await run_round()  # warm: insert pass + compile at the exact shapes
    rate = max([await run_round() for _ in range(2)])
    await store.aclose()
    return rate


async def bench_e2e_remote_bulk(store_mod):
    """End-to-end REMOTE bulk path: acquire_many through a real localhost
    socket — wire encode + chunking + server decode + scanned device
    dispatch + bulk reply — the reference's actual topology (every decision
    crosses a wire there, one RTT each; here one RTT carries ~80K)."""
    from distributedratelimiting.redis_tpu.runtime.remote import (
        RemoteBucketStore,
    )
    from distributedratelimiting.redis_tpu.runtime.server import (
        BucketStoreServer,
    )

    backing = store_mod.DeviceBucketStore(n_slots=1 << 21, max_batch=8192)
    async with BucketStoreServer(backing) as srv:
        store = RemoteBucketStore(address=(srv.host, srv.port))
        try:
            n = 1 << 17
            rng = np.random.default_rng(3)
            pool = [f"user{i}" for i in range(1_000_000)]
            calls = [[pool[j] for j in rng.integers(0, len(pool), n)]
                     for _ in range(4)]
            counts = [1] * n

            async def run_round():
                t0 = time.perf_counter()
                results = await asyncio.gather(
                    *(store.acquire_many(c, counts, 10_000_000.0,
                                         10_000_000.0,
                                         with_remaining=False)
                      for c in calls))
                dt = time.perf_counter() - t0
                return sum(len(r) for r in results) / dt

            await run_round()  # warm: connect + compile + first chunks
            rate = max([await run_round() for _ in range(2)])
        finally:
            await store.aclose()
    await backing.aclose()
    return rate


async def bench_e2e_async_nproc(store_mod, n_clients: int = 4):
    """N-process per-request scaling: one server process owns the device;
    ``n_clients`` separate client processes drive the per-request
    ``acquire`` contract over TCP concurrently. The per-PROCESS async
    ceiling is Python task scheduling (~14µs/request measured — see
    RESULTS.md r04); this shows how the serving story scales past it:
    client-side Python multiplies out across processes, all coalescing
    into the one store's micro-batches."""
    import os
    import subprocess
    import sys

    backing = store_mod.DeviceBucketStore(
        n_slots=1 << 17, max_batch=4096, max_delay_s=300e-6, max_inflight=16)
    from distributedratelimiting.redis_tpu.runtime.server import (
        BucketStoreServer,
    )

    from distributedratelimiting.redis_tpu.utils.cpu_bootstrap import (
        FORCE_CPU_ENV,
    )

    env = os.environ.copy()
    env[FORCE_CPU_ENV] = "1"  # clients never touch the device
    async with BucketStoreServer(backing, host="127.0.0.1") as srv:
        procs = [
            subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--nproc-client", srv.host, str(srv.port), str(i)],
                stdout=subprocess.PIPE, text=True, env=env)
            for i in range(n_clients)
        ]

        def harvest(p):
            try:
                out, _ = p.communicate(timeout=300)
                return json.loads(out.strip().splitlines()[-1])["rate"]
            except Exception:  # a dead/hung client degrades the aggregate,
                p.kill()      # never the whole bench run
                return 0.0

        rates = await asyncio.gather(
            *(asyncio.to_thread(harvest, p) for p in procs))
    await backing.aclose()
    return sum(rates), [r for r in rates if r]


def _nproc_client(host: str, port: str, wid: str) -> None:
    """One client process of the N-process scaling bench: closed-loop
    per-request acquires over a RemoteBucketStore."""
    import faulthandler

    # A stalled client gets killed by the parent's harvest timeout and
    # silently reads as rate 0 — dump where it actually was first.
    faulthandler.dump_traceback_later(240, exit=True)
    from distributedratelimiting.redis_tpu.utils.cpu_bootstrap import (
        maybe_force_cpu_from_env,
    )

    # The parent sets FORCE_CPU_ENV: acting on it is what keeps the
    # client off the device — on the tunneled-TPU rig a second process
    # touching the axon plugin while the parent holds the chip hangs at
    # backend init (observed as all clients timing out → nproc rate 0).
    maybe_force_cpu_from_env()
    from distributedratelimiting.redis_tpu.runtime.remote import (
        RemoteBucketStore,
    )

    async def run() -> None:
        # Per-request framing: closed-loop workers' requests then merge
        # across ALL clients in the SERVER's micro-batcher (one device
        # dispatch per round). Client-side coalescing would make each
        # client's flush its own bulk dispatch — N clients ⇒ N sequential
        # device round-trips per closed-loop round, which on a
        # tunneled-device rig (~65ms RTT) collapses throughput ~50×
        # (measured; co-located devices don't care).
        store = RemoteBucketStore(address=(host, int(port)),
                                  coalesce_requests=False)

        async def worker(w: int, reqs: int) -> None:
            for j in range(reqs):
                await store.acquire(f"u{wid}-{w}-{j % 1000}", 1,
                                    10_000_000.0, 10_000_000.0)

        await asyncio.gather(*(worker(w, 30) for w in range(32)))  # warm
        t0 = time.perf_counter()
        await asyncio.gather(*(worker(w, 150) for w in range(64)))
        rate = 64 * 150 / (time.perf_counter() - t0)
        await store.aclose()
        print(json.dumps({"rate": rate}))

    asyncio.run(run())


def bench_pallas_sweep(store_mod):
    """Assert the COMPILED (non-interpret) Pallas streaming sweep works on
    this platform: force it on, trigger a sweep over reclaimable slots, and
    report whether the Mosaic path ran without falling back."""
    from distributedratelimiting.redis_tpu.runtime.clock import ManualClock

    clock = ManualClock()
    store = store_mod.DeviceBucketStore(n_slots=1024, clock=clock,
                                        use_pallas_sweep=True)
    for i in range(64):
        store.acquire_blocking(f"sweep{i}", 1, 10.0, 10.0)
    clock.advance_seconds(5.0)  # everything refills → TTL-expired
    table = next(iter(store._tables.values()))
    table._sweep(None)
    ok = (store.use_pallas_sweep
          and store.metrics.pallas_sweep_failures == 0
          and store.metrics.slots_evicted >= 64)
    return bool(ok)


async def bench_e2e_async(store_mod, limiter_mod, options_mod):
    """End-to-end asyncio path: micro-batched partitioned limiter driven by
    a closed-loop worker pool deep enough to keep several flush readbacks in
    flight (readback RTT dominates on tunneled links and overlaps across
    flushes). Returns (decisions/s, p99 seconds)."""
    store = store_mod.DeviceBucketStore(
        n_slots=1 << 17, max_batch=4096, max_delay_s=300e-6, max_inflight=16)
    lim = limiter_mod.PartitionedRateLimiter(
        options_mod.TokenBucketOptions(
            token_limit=10_000_000, tokens_per_period=10_000_000,
            instance_name="bench"), store)
    lat: list[float] = []
    workers = 16384
    reqs_per_worker = 3

    async def worker(w):
        for j in range(reqs_per_worker):
            t0 = time.perf_counter()
            await lim.acquire_async(f"user{(w * 7 + j) % 10000}", 1)
            lat.append(time.perf_counter() - t0)

    # Warm the kernel (one compile per table) at full depth.
    await asyncio.gather(*(worker(w) for w in range(2048)))
    lat.clear()

    t0 = time.perf_counter()
    await asyncio.gather(*(worker(w) for w in range(workers)))
    dt = time.perf_counter() - t0
    throughput = len(lat) / dt

    # Low-load latency probe: p99 without saturation queueing — at this
    # depth each request's latency ≈ flush deadline + one device round
    # trip (RTT-bound on tunneled links; ~sub-ms on co-located hosts).
    # ≥10K samples so the p99 rests on ~100 observations, not 2.
    lat.clear()

    async def low_load_worker(w):
        for j in range(160):
            t0 = time.perf_counter()
            await lim.acquire_async(f"user{(w * 7 + j) % 10000}", 1)
            lat.append(time.perf_counter() - t0)

    await asyncio.gather(*(low_load_worker(w) for w in range(64)))
    lat.sort()
    p99_low = lat[int(len(lat) * 0.99)]
    await store.aclose()
    return throughput, p99_low


async def bench_serving_p99(store_mod, on_d64=None):
    """SERVER-side p99: request-arrival → result-ready on a
    BucketStoreServer fronting the device store — ≥10K samples from the
    server's own histogram (utils/metrics.LatencyHistogram) at a bounded
    closed-loop depth (64 in flight) so the number is steady-state serving
    latency, not open-loop queueing blowup; then a short depth-4 window
    (640 samples — low-confidence by design, the sample count is emitted
    with it) to separate link RTT from queueing. ``on_d64`` fires with
    the depth-64 numbers as soon as they exist, so a tunnel wedge during
    the extra window cannot discard the headline measurement.

    On THIS environment the device itself sits behind a network tunnel, so
    every micro-batch flush carries that tunnel's RTT and the TPU number
    reports it; the co-located-device number the <2ms north star targets
    is measured by the CPU-platform child (`_serving_p99_child`), where
    the device round trip is µs-class and what remains is the framework's
    own overhead (batcher deadline + dispatch + readback + fan-out)."""
    from distributedratelimiting.redis_tpu.runtime.remote import (
        RemoteBucketStore,
    )
    from distributedratelimiting.redis_tpu.runtime.server import (
        BucketStoreServer,
    )

    backing = store_mod.DeviceBucketStore(
        n_slots=1 << 17, max_batch=4096, max_delay_s=300e-6, max_inflight=16)
    async with BucketStoreServer(backing) as srv:
        # Per-request framing so every request is its own latency sample
        # (client coalescing would make samples = flushes).
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
        try:
            async def worker(w, reqs):
                for j in range(reqs):
                    await store.acquire(f"user{(w * 11 + j) % 10000}", 1,
                                        10_000_000.0, 10_000_000.0)

            # Warm (compile + connect), then reset the histogram so the
            # p99 reflects steady state, not the first compile.
            await asyncio.gather(*(worker(w, 10) for w in range(64)))
            srv.serving_latency.reset()
            await asyncio.gather(*(worker(w, 160) for w in range(64)))
            stats = await store.stats()
            if on_d64 is not None:
                on_d64(stats["serving_p99_ms"], stats["serving_p50_ms"],
                       stats["serving_samples"])
            # Low-depth window too: over a high-RTT tunnel the depth-64
            # number is queueing on the link RTT; depth 4 reads as
            # ~one flush RTT and separates link latency from queueing
            # in the recorded evidence.
            srv.serving_latency.reset()
            await asyncio.gather(*(worker(w, 160) for w in range(4)))
            stats4 = await store.stats()
        finally:
            await store.aclose()
    await backing.aclose()
    return (stats["serving_p99_ms"], stats["serving_p50_ms"],
            stats["serving_samples"], stats4["serving_p99_ms"],
            stats4["serving_p50_ms"], stats4["serving_samples"])


def bench_serving_p99_cpu(timeout_s: float = 600.0,
                          backing: str = "device",
                          native: bool = False,
                          tier0: bool = False) -> dict | None:
    """Co-located-device stand-in for the <2ms serving north star, now a
    TWO-process rig (VERDICT r4 #3b): the server child owns the store +
    kernel on its own core; a separate load child drives closed-loop
    per-request traffic at depths 4/16/64. The p99 is the SERVER's own
    arrival→ready histogram over a post-warmup window (stats reset flag),
    so client-side Python scheduling no longer pollutes the number the
    way the old single-process probe did. Returns the per-depth dict, or
    None if either child failed.

    ``backing="instant"`` swaps the XLA-CPU device store for
    ``InProcessBucketStore`` — a pure-Python kernel that answers in
    microseconds. The serving p99 against it is the FRAMEWORK's own
    overhead (wire + asyncio + per-request handling) with the kernel
    removed; (device-backed p99 − instant p99) isolates what the
    stand-in's XLA-CPU flush contributes, which is the part a real
    co-located TPU replaces with its ~0.04 ms kernel + PCIe-class RTT
    (VERDICT r5 #3's decomposition)."""
    import concurrent.futures
    import subprocess

    from distributedratelimiting.redis_tpu.utils.cpu_bootstrap import (
        FORCE_CPU_ENV,
    )

    env = os.environ.copy()
    env[FORCE_CPU_ENV] = "1"
    deadline = time.monotonic() + timeout_s
    server_argv = [sys.executable, os.path.abspath(__file__),
                   "--serving-server-child", backing]
    if native:
        server_argv.append("native")
    if tier0:
        server_argv.append("tier0")
    server = subprocess.Popen(
        server_argv,
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True, env=env)
    # No `with` around the executor: its shutdown joins the reader thread,
    # which only returns at EOF — a child that never prints would turn the
    # timeout below into a circular wait. The finally's kill/close EOFs
    # the pipe, so the parked thread always unblocks before process exit.
    pool = concurrent.futures.ThreadPoolExecutor(1)
    try:
        line = pool.submit(server.stdout.readline).result(
            timeout=min(120.0, timeout_s))
        addr = json.loads(line)
        load_flag = ("--native-load-child" if native
                     else "--serving-load-child")
        load_argv = [sys.executable, os.path.abspath(__file__),
                     load_flag, addr["host"], str(addr["port"])]
        if tier0:
            load_argv.append("hot")  # hot-key workload: tier-0's case
        load = subprocess.run(
            load_argv,
            env=env, capture_output=True, text=True,
            timeout=max(deadline - time.monotonic(), 30.0))
        if load.returncode != 0:
            return None
        return json.loads(load.stdout.strip().splitlines()[-1])
    except Exception:  # child hung/died: skip the co-located stand-in
        return None
    finally:
        try:
            server.stdin.close()  # the server child parks on stdin EOF
            server.wait(timeout=10)
        except Exception:
            server.kill()
        pool.shutdown(wait=False)


def _serving_server_child(backing_kind: str = "device",
                          native: bool = False,
                          tier0: bool = False,
                          shards: int = 1,
                          pin: bool = False,
                          uring: str | None = None) -> None:
    """Server half of the co-located stand-in: owns the (CPU-platform)
    device store and its kernel — or, for ``backing_kind="instant"``, the
    pure-Python ``InProcessBucketStore`` whose microsecond kernel makes
    the serving histogram a pure framework-overhead measurement. With
    ``native=True`` the sockets are served by the C++ front-end
    (native/frontend.cc) — epoll by default, or the io_uring data plane
    when ``uring`` is ``"on"``/``"sqpoll"`` (round 16). Parks until the
    parent closes stdin, then prints ONE more JSON line — the transport
    counters (fe_uring_counts: data-plane syscalls, ring enters, SQEs,
    fallbacks) and this process's rusage CPU-seconds — so the rig can
    charge syscalls/frame and cycles/row to the server, not the client."""
    if pin:
        # CPU discipline for the pinned multi-shard rig: the C shard
        # threads get CPUs 0..N-1 EXCLUSIVELY (fe_start_sharded pins
        # them there); every Python thread of this process — the
        # asyncio loop that serves residue frames and runs the tier-0
        # sync pump, and the per-shard pump threads — is herded onto
        # the next few CPUs so neither the shards nor the load child
        # can starve the reconciliation loop (a sync pump starved past
        # max_stale_s fails SAFE — stale replicas stop deciding — but
        # the resulting all-residue storm is exactly the regime the
        # sweep must not measure by accident).
        nproc = os.cpu_count() or 1
        herd = set(range(shards, min(shards + 4, nproc))) or {0}
        try:
            os.sched_setaffinity(0, herd)
        except OSError:
            pass
    from distributedratelimiting.redis_tpu.utils.cpu_bootstrap import (
        maybe_force_cpu_from_env,
    )

    maybe_force_cpu_from_env()
    from distributedratelimiting.redis_tpu.runtime import store as store_mod
    from distributedratelimiting.redis_tpu.runtime.server import (
        BucketStoreServer,
    )

    async def run() -> None:
        if backing_kind == "instant":
            backing = store_mod.InProcessBucketStore()
        else:
            backing = store_mod.DeviceBucketStore(
                n_slots=1 << 17, max_batch=4096, max_delay_s=300e-6,
                max_inflight=16)
        native_tier0 = False
        if tier0:
            from distributedratelimiting.redis_tpu.runtime.native_frontend import (
                Tier0Config,
            )

            # Tight sync cadence: the bench window is seconds long and the
            # hit-rate/overadmit gauges should reflect settled envelopes.
            # The pinned multi-shard rig also raises max_budget: at
            # node-level demand (~2M permits/s/key) the 1M default gives
            # each key <1s of envelope headroom, so any sync-round
            # hiccup longer than that tips the whole keyspace into the
            # all-residue regime (fail-safe but Python-speed — see
            # docs/OPERATIONS.md §12). 16M ≈ 10s of headroom.
            native_tier0 = (Tier0Config(sync_interval_s=0.01,
                                        max_budget=float(1 << 24))
                            if pin else
                            Tier0Config(sync_interval_s=0.01))
        async with BucketStoreServer(backing,
                                     native_frontend=native,
                                     native_tier0=native_tier0,
                                     native_shards=shards,
                                     native_pin_shards=pin,
                                     native_uring=uring) as srv:
            print(json.dumps({"host": srv.host, "port": srv.port}),
                  flush=True)
            await asyncio.get_running_loop().run_in_executor(
                None, sys.stdin.read)
            # Shutdown report, read by _shard_rig AFTER it closes our
            # stdin: transport counters must be sampled while the
            # front-end is still up (the handle dies with the context
            # manager), and rusage here charges the server process only.
            import resource

            ru = resource.getrusage(resource.RUSAGE_SELF)
            tail: dict = {"server_cpu_s": round(ru.ru_utime
                                                + ru.ru_stime, 4)}
            if native and srv._native is not None:
                ts = srv._native.transport_stats()
                if ts is not None:
                    tail["transport"] = ts
                # ε-consumption counters (round 18): cumulative tier-0
                # grant tokens + the per-slice split (fe_t0_eps) so the
                # recapture lanes can price local-admission drift per
                # shard slice beside the transport economics.
                t0 = srv._native.tier0_stats()
                if t0:
                    tail["t0_grant_tokens"] = t0.get("grant_tokens",
                                                     0.0)
                    tail["t0_overadmit_total"] = t0.get(
                        "overadmit_total", 0.0)
                eps = srv._native.t0_eps_tokens()
                if eps:
                    tail["t0_eps_tokens"] = eps
            print(json.dumps(tail), flush=True)
        await backing.aclose()

    asyncio.run(run())


def _native_load_child(host: str, port: str,
                       workload: str = "uniform") -> None:
    """Load half of the native-front-end rig: the C closed-loop load
    generator (native_frontend.native_loadgen) at a depth sweep, with the
    server's own C-side histogram sampled per window — both directions of
    the ceiling (req/s and p99) come from native measurement, so Python
    client scheduling bounds neither. ``workload="hot"`` collapses the
    keyspace to one key per connection — the tier-0 admission cache's
    target shape — and reports the server's tier-0 gauges beside the
    rates."""
    from distributedratelimiting.redis_tpu.utils.cpu_bootstrap import (
        maybe_force_cpu_from_env,
    )

    maybe_force_cpu_from_env()
    from distributedratelimiting.redis_tpu.runtime.native_frontend import (
        native_loadgen,
    )
    from distributedratelimiting.redis_tpu.runtime.remote import (
        RemoteBucketStore,
    )

    keyspace = 1 if workload == "hot" else 1000

    async def run() -> None:
        store = RemoteBucketStore(address=(host, int(port)),
                                  coalesce_requests=False)
        out: dict = {}
        # Warm: connects, compiles nothing (instant backing), seeds keys
        # (and, for the hot workload, installs the tier-0 replicas).
        await asyncio.to_thread(native_loadgen, host, int(port),
                                conns=4, depth=16, reqs_per_conn=2000,
                                keyspace=keyspace)
        for depth in (4, 16, 64, 256):
            await store.stats(reset=True)
            replies, _, elapsed = await asyncio.to_thread(
                native_loadgen, host, int(port), conns=4, depth=depth,
                reqs_per_conn=50000, keyspace=keyspace)
            stats = await store.stats()
            out[f"d{depth}"] = {
                "rate": replies / elapsed,
                "p50_ms": stats["serving_p50_ms"],
                "p99_ms": stats["serving_p99_ms"],
                "samples": stats["serving_samples"],
            }
        stats = await store.stats()
        if "tier0" in stats:
            out["tier0"] = stats["tier0"]
        await store.aclose()
        print(json.dumps(out), flush=True)

    asyncio.run(run())


def _bulk_load_child(host: str, port: str, workload: str = "hot") -> None:
    """Load half of the native-BULK rig: closed-loop ACQUIRE_MANY frames
    (4096 rows each) from a few concurrent submitters. The Python client
    cost is per-frame, amortized over 4096 rows, so it bounds nothing —
    the server's bulk lane is the measured ceiling. ``workload="hot"``
    draws from 64 keys at high capacity (every row tier-0-hostable: the
    native lane's target shape); ``"cold"`` draws from 100K keys (all
    residue — the zero-copy handoff itself)."""
    from distributedratelimiting.redis_tpu.utils.cpu_bootstrap import (
        maybe_force_cpu_from_env,
    )

    maybe_force_cpu_from_env()
    from distributedratelimiting.redis_tpu.runtime.remote import (
        RemoteBucketStore,
    )

    keyspace = 64 if workload == "hot" else 100_000
    n = 4096
    capacity, fill = 1e8, 1e8

    async def run() -> None:
        store = RemoteBucketStore(address=(host, int(port)))
        keys = [f"b{i % keyspace}" for i in range(n)]
        counts = [1] * n
        rows = 0

        async def worker(reps: int) -> None:
            nonlocal rows
            for _ in range(reps):
                res = await store.acquire_many(keys, counts, capacity,
                                               fill)
                rows += len(res.granted)

        # Warm: connects, seeds keys, installs tier-0 replicas (the
        # first frames are all-residue by construction).
        await asyncio.gather(*(worker(4) for _ in range(4)))
        pre = await store.stats(reset=True)
        rows = 0
        t0 = time.perf_counter()
        await asyncio.gather(*(worker(25) for _ in range(4)))
        dt = time.perf_counter() - t0
        stats = await store.stats()
        out = {
            "rows_per_s": rows / dt,
            "rows": rows,
            "elapsed_s": dt,
            "p50_ms": stats["serving_p50_ms"],
            "p99_ms": stats["serving_p99_ms"],
            "samples": stats["serving_samples"],
        }
        if "tier0" in stats:
            out["tier0_hit_rate"] = stats["tier0"]["hit_rate"]
            if "tier0" in pre:
                # Measured-window hit rate: the warm frames' deliberate
                # all-residue installs must not dilute the steady-state
                # figure the acceptance bound names.
                d = {k: stats["tier0"][k] - pre["tier0"][k]
                     for k in ("hits", "local_denies", "misses")}
                eligible = sum(d.values())
                if eligible:
                    out["window_tier0_hit_rate"] = (
                        (d["hits"] + d["local_denies"]) / eligible)
        if "native_bulk" in stats:
            out["native_bulk"] = stats["native_bulk"]
        await store.aclose()
        print(json.dumps(out), flush=True)

    asyncio.run(run())


def _bulk_rig(server_args: "list[str]", load_args: "list[str]",
              timeout_s: float) -> dict | None:
    """One two-process bulk measurement: a --serving-server-child with
    ``server_args`` and a --bulk-load-child with ``load_args`` (the
    bench_serving_p99_cpu child discipline — a wedged store op costs the
    section, not the runner). Returns the load child's JSON, or None."""
    import concurrent.futures
    import subprocess

    from distributedratelimiting.redis_tpu.utils.cpu_bootstrap import (
        FORCE_CPU_ENV,
    )

    env = os.environ.copy()
    env[FORCE_CPU_ENV] = "1"
    deadline = time.monotonic() + timeout_s
    server = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__),
         "--serving-server-child", *server_args],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True, env=env)
    pool = concurrent.futures.ThreadPoolExecutor(1)
    try:
        line = pool.submit(server.stdout.readline).result(
            timeout=min(120.0, timeout_s))
        addr = json.loads(line)
        load = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--bulk-load-child", addr["host"], str(addr["port"]),
             *load_args],
            env=env, capture_output=True, text=True,
            timeout=max(deadline - time.monotonic(), 30.0))
        if load.returncode != 0:
            return None
        return json.loads(load.stdout.strip().splitlines()[-1])
    except Exception:
        return None
    finally:
        try:
            server.stdin.close()
            server.wait(timeout=10)
        except Exception:
            server.kill()
        pool.shutdown(wait=False)


def bench_native_bulk(timeout_s: float = 420.0) -> dict | None:
    """``serving_native_bulk`` section: the native bulk lane measured
    against the asyncio bulk path (instant backing, hot keyspace — the
    ≥2×-per-core acceptance arm at high tier-0 hit rate) AND against a
    device-class backing (XLA-CPU stand-in: multi-ms flush — the regime
    the 2 ms p99 north star fears; the real-device number stays owed in
    benchmarks/recapture.py's ledger until a healthy TPU window)."""
    budget = max(timeout_s / 4.0, 60.0)
    native = _bulk_rig(["instant", "native", "tier0"], ["hot"], budget)
    asy = _bulk_rig(["instant"], ["hot"], budget)
    device = _bulk_rig(["device", "native", "tier0"], ["hot"], budget)
    # Cold arm: 100K-key uniform draws, tier-0 off — every row is
    # residue, so this is the zero-copy handoff itself against the
    # multi-ms flush (the no-shield worst case of the regime).
    device_cold = _bulk_rig(["device", "native"], ["cold"], budget)
    if native is None or asy is None:
        return None
    out = {"native": native, "asyncio": asy}
    if device is not None:
        out["device"] = device
    if device_cold is not None:
        out["device_cold"] = device_cold
    return out


def _serving_load_child(host: str, port: str) -> None:
    """Load half: closed-loop per-request acquires at a depth sweep; each
    depth's window is warm → stats(reset) → ≥10K measured samples →
    stats. Reports the server-side serving histogram AND the store's
    flush histogram (dispatch+kernel+readback) so serving p99 decomposes
    into device-side floor vs framework queueing."""
    from distributedratelimiting.redis_tpu.utils.cpu_bootstrap import (
        maybe_force_cpu_from_env,
    )

    maybe_force_cpu_from_env()
    from distributedratelimiting.redis_tpu.runtime.remote import (
        RemoteBucketStore,
    )

    async def run() -> None:
        store = RemoteBucketStore(address=(host, int(port)),
                                  coalesce_requests=False)
        out: dict = {}

        async def worker(w: int, reqs: int) -> None:
            for j in range(reqs):
                await store.acquire(f"user{(w * 11 + j) % 10000}", 1,
                                    10_000_000.0, 10_000_000.0)

        for depth in (4, 16, 64):
            await asyncio.gather(*(worker(w, 40) for w in range(depth)))
            await store.stats(reset=True)
            reqs = max(10240 // depth, 160)
            await asyncio.gather(*(worker(w, reqs) for w in range(depth)))
            stats = await store.stats()
            flush = stats.get("store", {})
            out[f"d{depth}"] = {
                "p99_ms": stats["serving_p99_ms"],
                "p50_ms": stats["serving_p50_ms"],
                "samples": stats["serving_samples"],
                "flush_p99_ms": flush.get("flush_p99_ms"),
                "flush_p50_ms": flush.get("flush_p50_ms"),
            }
        await store.aclose()
        print(json.dumps(out), flush=True)

    asyncio.run(run())


def _shard_load_child(host: str, port: str, shards: str) -> None:
    """Load half of the multi-shard rig: 3 loadgen threads per shard,
    each a C closed-loop bulk client (fe_lg_bulk — frames built and
    replies counted in C, so the client bounds nothing) pinned AWAY
    from the shard CPUs (the server child pins shard i to CPU i; an
    unpinned client thread scheduled onto a shard CPU steals exactly
    the core the measurement is charging). The kernel's SO_REUSEPORT
    hash spreads each thread's 4 connections across shards. Reports
    the aggregate rows/s over the threads' own windows plus the
    server's merged and per-shard gauges, and the total frames/rows
    this child pushed (warm included) so the rig can divide the
    server's lifetime syscall counter by a lifetime denominator.
    ``DRL_BENCH_SHARD_FRAMES`` / ``DRL_BENCH_SHARD_ROWS`` shrink the
    per-thread workload for small hosts (defaults 400 / 4096)."""
    from distributedratelimiting.redis_tpu.utils.cpu_bootstrap import (
        maybe_force_cpu_from_env,
    )

    maybe_force_cpu_from_env()
    import threading

    from distributedratelimiting.redis_tpu.runtime.native_frontend import (
        native_bulk_loadgen,
    )
    from distributedratelimiting.redis_tpu.runtime.remote import (
        RemoteBucketStore,
    )

    n_shards = int(shards)
    nproc = os.cpu_count() or 1
    # Mirror of the server child's CPU discipline: shards own CPUs
    # 0..N-1, the server's Python threads own the next 4 — clients take
    # what's left (everything, when the box is too small to carve).
    first = min(n_shards + 4, max(nproc - 2, 0))
    client_cpus = (set(range(first, nproc))
                   if first < nproc else set(range(nproc)))
    n_threads = max(6, 4 * n_shards)
    frames_hot = int(os.environ.get("DRL_BENCH_SHARD_FRAMES", "400"))
    rows_hot = int(os.environ.get("DRL_BENCH_SHARD_ROWS", "4096"))

    def one(out: list, warm: bool) -> None:
        try:
            os.sched_setaffinity(0, client_cpus)
        except OSError:
            pass  # restricted cpuset: measure unpinned
        frames, rows, granted, el = native_bulk_loadgen(
            host, int(port), conns=4, depth=2 if warm else 8,
            frames_per_conn=10 if warm else frames_hot,
            rows_per_frame=1024 if warm else rows_hot, keyspace=64)
        out.append((frames, rows, granted, el))

    async def run() -> None:
        store = RemoteBucketStore(address=(host, int(port)))
        # Warm: connects + installs the 64 hot keys' tier-0 replicas.
        rows_out: list = []
        th = [threading.Thread(target=one, args=(rows_out, True))
              for _ in range(4)]
        for t in th:
            t.start()
        for t in th:
            t.join()
        frames_sent = sum(f for f, _r, _g, _el in rows_out)
        rows_sent = sum(r for _f, r, _g, _el in rows_out)
        await store.stats(reset=True)
        best = 0.0
        for _ in range(3):
            rows_out = []
            th = [threading.Thread(target=one, args=(rows_out, False))
                  for _ in range(n_threads)]
            for t in th:
                t.start()
            for t in th:
                t.join()
            best = max(best, sum(r / el for _f, r, _g, el in rows_out))
            frames_sent += sum(f for f, _r, _g, _el in rows_out)
            rows_sent += sum(r for _f, r, _g, _el in rows_out)
        stats = await store.stats()
        out = {
            "rows_per_s": best,
            "shards": n_shards,
            "load_threads": n_threads,
            "frames_sent": frames_sent,
            "rows_sent": rows_sent,
            "p50_ms": stats["serving_p50_ms"],
            "p99_ms": stats["serving_p99_ms"],
        }
        nb = stats.get("native_bulk")
        if nb:
            out["rows_local_frac"] = (nb["rows_local"]
                                      / max(nb["rows"], 1))
        if "tier0" in stats:
            out["tier0_hit_rate"] = stats["tier0"]["hit_rate"]
        per = stats.get("shards")
        if per:
            out["per_shard_rows"] = [r["native_bulk"]["rows"]
                                     for r in per]
        await store.aclose()
        print(json.dumps(out), flush=True)

    asyncio.run(run())


def _shard_rig(shards: int, timeout_s: float,
               uring: str | None = None) -> dict | None:
    """One multi-shard measurement: an instant-backed native server
    child with ``shards`` pinned shards (tier-0 armed), driven by a
    --shard-load-child (the bench_serving_p99_cpu child discipline).
    ``uring`` picks the transport arm ("on"/"sqpoll"; None = epoll).
    After the load finishes the server's stdin is closed and its
    shutdown line (transport counters + rusage) is folded into the
    load child's result."""
    import concurrent.futures
    import subprocess

    from distributedratelimiting.redis_tpu.utils.cpu_bootstrap import (
        FORCE_CPU_ENV,
    )

    env = os.environ.copy()
    env[FORCE_CPU_ENV] = "1"
    deadline = time.monotonic() + timeout_s
    argv = [sys.executable, os.path.abspath(__file__),
            "--serving-server-child", "instant", "native", "tier0",
            f"shards={shards}", "pin"]
    if uring is not None:
        argv.append(f"uring={uring}")
    server = subprocess.Popen(
        argv,
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True, env=env)
    pool = concurrent.futures.ThreadPoolExecutor(1)
    try:
        line = pool.submit(server.stdout.readline).result(
            timeout=min(120.0, timeout_s))
        addr = json.loads(line)
        load = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--shard-load-child", addr["host"], str(addr["port"]),
             str(shards)],
            env=env, capture_output=True, text=True,
            timeout=max(deadline - time.monotonic(), 30.0))
        if load.returncode != 0:
            return None
        res = json.loads(load.stdout.strip().splitlines()[-1])
        server.stdin.close()
        tail = pool.submit(server.stdout.readline).result(timeout=30.0)
        if tail.strip():
            res.update(json.loads(tail))
        return res
    except Exception:
        return None
    finally:
        try:
            if not server.stdin.closed:
                server.stdin.close()
            server.wait(timeout=10)
        except Exception:
            server.kill()
        pool.shutdown(wait=False)


def bench_native_shards(timeout_s: float = 600.0) -> dict | None:
    """``serving_native_shards`` section: the multi-shard front-end's
    node-level scaling curve (round 11). One native server per point,
    shards ∈ {1, 2, 4, 8} pinned to CPUs 0..N-1, instant backing,
    tier-0 armed, hot 64-key ACQUIRE_MANY workload from the C bulk
    loadgen — rows/s through ONE port as a function of shard count.
    The acceptance bound is s4 ≥ 3.5× s1 on the same machine; the
    device-backed arm stays owed in benchmarks/recapture.py
    (native_fe_shard_sweep) until a TPU window."""
    out: dict = {}
    budget = max(timeout_s / 4.5, 60.0)
    for s in (1, 2, 4, 8):
        res = _shard_rig(s, budget)
        if res is None:
            if s == 1:
                return None  # nothing to normalize against
            continue
        out[f"s{s}"] = res
    if "s1" in out and "s4" in out:
        out["speedup_4v1"] = (out["s4"]["rows_per_s"]
                              / out["s1"]["rows_per_s"])
    if "s1" in out and "s8" in out:
        out["speedup_8v1"] = (out["s8"]["rows_per_s"]
                              / out["s1"]["rows_per_s"])
    return out


def _nominal_mhz() -> float:
    """Nominal clock for the cycles/row stand-in: first ``cpu MHz``
    row of /proc/cpuinfo, 2 GHz when the field is absent (ARM,
    containers that mask cpuinfo). A stand-in, not a cycle counter —
    the column is only compared across arms on the SAME host."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("cpu mhz"):
                    return float(line.split(":", 1)[1])
    except (OSError, ValueError, IndexError):
        pass
    return 2000.0


def bench_native_uring(timeout_s: float = 540.0) -> dict | None:
    """``serving_native_uring`` section: transport economics of the
    multi-shard front-end (round 16). The serving_native_shards rig,
    run once per transport arm — epoll, io_uring, io_uring+SQPOLL —
    at 1/4/8 shards. Two headline columns per arm:

    - syscalls/frame — the server's own data-plane syscall counter
      (every accept/recv/send/epoll_wait/io_uring_enter is counted in
      C at the call site, both transports) divided by the frames the
      load child pushed over the server's lifetime. This is the number
      the io_uring rebuild exists to shrink: one ring enter drains and
      submits for every ready connection, where epoll pays a recv and
      a send per connection per burst, and SQPOLL retires the submit
      enter too.
    - cycles/row — server-process rusage CPU-seconds x nominal MHz
      divided by rows pushed: an honest CPU stand-in (documented as
      such in RESULTS.md), not a hardware cycle counter.

    A uring arm whose shards fell back to epoll (old kernel, seccomp)
    is reported with ``fell_back: true`` instead of being passed off
    as ring numbers; kernels with no io_uring at all run the epoll arm
    only and say so in ``probe``."""
    from distributedratelimiting.redis_tpu.runtime.native_frontend import (
        uring_probe,
    )

    ok, reason = uring_probe()
    mhz = _nominal_mhz()
    out: dict = {"uring_available": ok, "probe": reason,
                 "nominal_mhz": round(mhz, 1)}
    arms = [("epoll", None)]
    if ok:
        arms += [("uring", "on"), ("sqpoll", "sqpoll")]
    points = [(name, uring, s) for name, uring in arms for s in (1, 4, 8)]
    budget = max(timeout_s / (len(points) + 1.0), 40.0)
    got_any = False
    for name, uring, s in points:
        res = _shard_rig(s, budget, uring=uring)
        if res is None:
            continue
        row: dict = {"rows_per_s": res["rows_per_s"],
                     "p50_ms": res["p50_ms"], "p99_ms": res["p99_ms"]}
        tr = res.get("transport")
        if tr is not None:
            row["uring_shards"] = tr["uring_shards"]
            row["fallbacks"] = tr["fallbacks"]
            if uring is not None and tr["uring_shards"] < s:
                row["fell_back"] = True  # loud: NOT ring numbers
            frames = res.get("frames_sent")
            if frames:
                row["syscalls_per_frame"] = round(
                    tr["io_syscalls"] / frames, 3)
        cpu_s = res.get("server_cpu_s")
        rows_sent = res.get("rows_sent")
        if cpu_s and rows_sent:
            row["cycles_per_row"] = round(
                cpu_s * mhz * 1e6 / rows_sent, 1)
        out[f"{name}_s{s}"] = row
        got_any = True
    return out if got_any else None


def bench_metrics_overhead() -> tuple[float, float, float, int,
                                      float, float]:
    """``serving_metrics_overhead`` section: the observability plane's
    whole-cost audit. Same closed-loop per-request rig (asyncio server,
    instant in-process backing so the kernel contributes nothing) run
    twice — plane ENABLED (heavy-hitter sketch fed per request, stage
    stamps, flight recorder armed, /metrics listener up and scraped
    mid-run) vs ``observability=False``. The documented contract is
    <3% throughput cost with the plane on; exposition itself is
    pull-only, so the scrape rides the measured window to keep the
    audit honest.

    A third arm audits DISTRIBUTED TRACING at the production default
    (head sampling 1%): tracing toggles on the process-global tracer
    around ABBA window blocks on the plane-enabled rig, so the delta
    isolates the tracing hooks (coin flips, context captures, span
    machinery on the sampled 1%) under the same <3% contract.

    Returns (on_rate, off_rate, overhead_pct — the median of paired
    per-window deltas, scrape_bytes, tracing_on_rate,
    tracing_overhead_pct)."""
    from distributedratelimiting.redis_tpu.runtime.remote import (
        RemoteBucketStore,
    )
    from distributedratelimiting.redis_tpu.runtime.server import (
        BucketStoreServer,
    )
    from distributedratelimiting.redis_tpu.runtime.store import (
        InProcessBucketStore,
    )
    from distributedratelimiting.redis_tpu.utils import tracing

    async def main() -> tuple[float, float, float, int, float, float]:
        async def make(observability: bool):
            srv = BucketStoreServer(
                InProcessBucketStore(), observability=observability,
                metrics_port=0 if observability else None)
            await srv.start()
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            return srv, store

        async def window(store, depth: int = 32, reqs: int = 150) -> float:
            async def worker(w: int) -> None:
                for j in range(reqs):
                    await store.acquire(f"user{(w * 13 + j) % 512}", 1,
                                        1e7, 1e7)

            t0 = time.perf_counter()
            await asyncio.gather(*(worker(w) for w in range(depth)))
            return depth * reqs / (time.perf_counter() - t0)

        srv_on, store_on = await make(True)
        srv_off, store_off = await make(False)
        try:
            # Warm both rigs, then measure ABBA-ordered window blocks
            # (on,off,off,on) and take the median per-block delta. The
            # shared-core scheduler drifts on multi-second scales, which
            # defeated every simpler estimator tried here (sequential
            # single-shot: -3%..+49% "overhead"; interleaved best-of-3:
            # ±5% A/A floor; strict on-first pairs: alternation bias —
            # a same-period slow phase lands on one side every time).
            # ABBA cancels linear drift inside each block by symmetry.
            await window(store_on, depth=16, reqs=40)
            await window(store_off, depth=16, reqs=40)
            blocks = []
            for _ in range(4):
                a1 = await window(store_on)
                b1 = await window(store_off)
                b2 = await window(store_off)
                a2 = await window(store_on)
                blocks.append(((a1 + a2) / 2, (b1 + b2) / 2))
            on_rate = max(a for a, _ in blocks)
            off_rate = max(b for _, b in blocks)
            deltas = sorted((b - a) / b for a, b in blocks)
            median_delta = deltas[len(deltas) // 2]
            # One mid-run scrape proves the plane was live and bills the
            # exposition to the enabled side.
            reader, writer = await asyncio.open_connection(
                srv_on.host, srv_on.metrics_port)
            writer.write(b"GET /metrics HTTP/1.1\r\nHost: bench\r\n\r\n")
            await writer.drain()
            data = await reader.read()
            writer.close()
            # Tracing arm: same ABBA discipline, toggling the global
            # tracer around window blocks on the SAME enabled rig (both
            # rigs share the process-global tracer, so a two-rig pairing
            # would contaminate the control side).
            tblocks = []
            try:
                for _ in range(4):
                    tracing.configure(enabled=True, sample_rate=0.01,
                                      keep_rate=0.1)
                    a1 = await window(store_on)
                    tracing.configure(enabled=False)
                    b1 = await window(store_on)
                    b2 = await window(store_on)
                    tracing.configure(enabled=True, sample_rate=0.01,
                                      keep_rate=0.1)
                    a2 = await window(store_on)
                    tracing.configure(enabled=False)
                    tblocks.append(((a1 + a2) / 2, (b1 + b2) / 2))
            finally:
                tracing.configure(enabled=False)
                tracing.get_tracer().reset()
            trace_rate = max(a for a, _ in tblocks)
            tdeltas = sorted((b - a) / b for a, b in tblocks)
            trace_pct = tdeltas[len(tdeltas) // 2] * 100.0
            return (on_rate, off_rate, median_delta * 100.0, len(data),
                    trace_rate, trace_pct)
        finally:
            await store_on.aclose()
            await store_off.aclose()
            await srv_on.aclose()
            await srv_off.aclose()

    return asyncio.run(main())


def bench_audit_overhead() -> tuple[float, float, float, int]:
    """``audit_overhead`` section: the conservation audit plane's
    steady-state serving cost (runtime/audit.py). Two otherwise
    identical closed-loop rigs — the ε-ledger + burn-rate watchdog
    ticking at 10x the production cadence (tick_s=0.05 vs the 0.5
    default, so the measured number upper-bounds the deployed cost) vs
    the ``audit=False`` ablation — under the same ABBA window-block
    discipline as ``serving_metrics_overhead``. The hot-path cost is
    two float adds per scalar grant; everything else rides the
    background tick. Contract: <3%.

    Returns (on_rate, off_rate, overhead_pct, audit_ticks — the
    enabled rig's tick count, proving the plane was live inside the
    measured windows)."""
    from distributedratelimiting.redis_tpu.runtime.audit import (
        AuditConfig,
    )
    from distributedratelimiting.redis_tpu.runtime.remote import (
        RemoteBucketStore,
    )
    from distributedratelimiting.redis_tpu.runtime.server import (
        BucketStoreServer,
    )
    from distributedratelimiting.redis_tpu.runtime.store import (
        InProcessBucketStore,
    )

    async def main() -> tuple[float, float, float, int]:
        async def make(audit):
            srv = BucketStoreServer(InProcessBucketStore(), audit=audit)
            await srv.start()
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            return srv, store

        async def window(store, depth: int = 32, reqs: int = 150) -> float:
            async def worker(w: int) -> None:
                for j in range(reqs):
                    await store.acquire(f"user{(w * 13 + j) % 512}", 1,
                                        1e7, 1e7)

            t0 = time.perf_counter()
            await asyncio.gather(*(worker(w) for w in range(depth)))
            return depth * reqs / (time.perf_counter() - t0)

        srv_on, store_on = await make(AuditConfig(tick_s=0.05))
        srv_off, store_off = await make(False)
        try:
            await window(store_on, depth=16, reqs=40)
            await window(store_off, depth=16, reqs=40)
            blocks = []
            for _ in range(4):
                a1 = await window(store_on)
                b1 = await window(store_off)
                b2 = await window(store_off)
                a2 = await window(store_on)
                blocks.append(((a1 + a2) / 2, (b1 + b2) / 2))
            on_rate = max(a for a, _ in blocks)
            off_rate = max(b for _, b in blocks)
            deltas = sorted((b - a) / b for a, b in blocks)
            ticks = srv_on.auditor.ticks
            return (on_rate, off_rate,
                    deltas[len(deltas) // 2] * 100.0, ticks)
        finally:
            await store_on.aclose()
            await store_off.aclose()
            await srv_on.aclose()
            await srv_off.aclose()

    return asyncio.run(main())


def bench_e2e_async_nproc_cpu(timeout_s: float = 600.0) -> tuple[float, int]:
    """Run the N-process scaling bench with a CPU-platform server child.

    The metric is per-request PYTHON/SOCKET scaling across processes —
    the device is explicitly not the bound (per-process rates measure
    alike on TPU and CPU). Running the server on the tunneled TPU is
    additionally not robust: concurrent client-process startups can wedge
    an in-flight device fetch indefinitely (observed repeatedly; parent
    stack parked in ``jax...Array._value`` while every client waits on a
    reply — a tunnel-environment artifact, not framework code), which
    read as rate 0. The CPU child measures the same contract
    deterministically, exactly like the serving-p99 co-located stand-in.
    """
    import os
    import subprocess
    import sys

    from distributedratelimiting.redis_tpu.utils.cpu_bootstrap import (
        FORCE_CPU_ENV,
    )

    env = os.environ.copy()
    env[FORCE_CPU_ENV] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--nproc-child"],
            env=env, capture_output=True, timeout=timeout_s, text=True)
        if proc.returncode != 0:
            return 0.0, 0
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        return out["rate"], out["clients"]
    except Exception:
        return 0.0, 0


def _nproc_child() -> None:
    from distributedratelimiting.redis_tpu.utils.cpu_bootstrap import (
        maybe_force_cpu_from_env,
    )

    maybe_force_cpu_from_env()
    from distributedratelimiting.redis_tpu.runtime import store as store_mod

    rate, rates = asyncio.run(bench_e2e_async_nproc(store_mod))
    print(json.dumps({"rate": rate, "clients": len(rates)}))


# --------------------------------------------------------------------------
# Orchestration: incremental, budget-bounded, hang-tolerant (r04 post-mortem:
# one JSON at the end of main() + a 10-min probe + an unguarded
# jax.devices() produced ZERO bytes of evidence when the tunnel flapped).
# --------------------------------------------------------------------------

_T0 = time.monotonic()
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "1200"))
PROBE_S = float(os.environ.get("BENCH_PROBE_S", "240"))
SECTION_TIMEOUT_S = float(os.environ.get("BENCH_SECTION_TIMEOUT_S", "420"))
SIM_WEDGE = os.environ.get("BENCH_SIM_WEDGE") == "1"
SIM_HANG_SECTION = os.environ.get("BENCH_SIM_HANG_SECTION", "")

RESULT: dict = {
    "metric": "permit_decisions_per_sec_per_chip",
    "value": None,
    "unit": "decisions/s",
    "vs_baseline": None,
    "platform": None,
    "n_keys": N_SLOTS,
    "batch": BATCH,
    "scan_depth": SCAN_K,
    "link_upload_mb_per_s": None,
    # RTT control beside the latency-bound metrics (VERDICT r5 next #5):
    # the tunnel's round-trip floor and fetch-pipelining factor recorded
    # with every run — e2e_async/low-load p99 slides are attributable to
    # the link state at a glance (e2e_async_link_rtt_ms is the copy taken
    # when that section ran, since the link swings minute to minute).
    "link_rtt_ms": None,
    "link_pipeline_overlap_x": None,
    "e2e_async_link_rtt_ms": None,
    "compact_path_decisions_per_sec": None,
    "single_batch_decisions_per_sec": None,
    "e2e_bulk_decisions_per_sec": None,
    "e2e_bulk_with_remaining_decisions_per_sec": None,
    "e2e_fp_bulk_decisions_per_sec": None,
    "e2e_remote_bulk_decisions_per_sec": None,
    "e2e_async_decisions_per_sec": None,
    "e2e_async_nproc_decisions_per_sec": None,
    "e2e_async_nproc_clients": None,
    "e2e_p99_low_load_ms": None,
    "serving_p99_ms": None,
    "serving_p50_ms": None,
    "serving_p99_samples": None,
    "serving_p99_d4_ms": None,
    "serving_p50_d4_ms": None,
    "serving_p99_d4_samples": None,
    # Co-located-device stand-in (two CPU-platform children, server and
    # load on separate cores): the framework's own serving overhead, the
    # number the <2ms north star bounds. Headline keys are the depth-64
    # window; d4/d16 plus the flush histogram (device dispatch + kernel +
    # readback) give the queueing-vs-kernel decomposition.
    "serving_p99_colocated_ms": None,
    "serving_p50_colocated_ms": None,
    "serving_p99_colocated_d4_ms": None,
    "serving_p99_colocated_d16_ms": None,
    "flush_p99_colocated_ms": None,
    "flush_p50_colocated_ms": None,
    # Same rig with InProcessBucketStore (pure-Python microsecond
    # kernel): serving latency with the kernel term removed — the
    # framework-overhead floor of the decomposition; see
    # bench_serving_p99_cpu(backing="instant").
    "serving_p99_instant_ms": None,
    "serving_p50_instant_ms": None,
    "serving_p99_instant_d4_ms": None,
    "serving_p99_instant_d16_ms": None,
    # Native C++ front-end (native/frontend.cc) over the instant backing,
    # driven by the C load generator: the per-request serving ceiling
    # with per-request Python removed from BOTH ends — the number that
    # supersedes the ~13K req/s/core asyncio wire ceiling.
    "serving_native_req_per_s_d64": None,
    "serving_native_req_per_s_d256": None,
    "serving_native_p50_d16_ms": None,
    "serving_native_p99_d16_ms": None,
    "serving_native_p99_d64_ms": None,
    # Tier-0 admission cache over the same rig, hot-key workload (one key
    # per loadgen connection): decisions answered inside the C epoll loop
    # from the per-key replica table, reconciled by the async debit pump.
    # The ratio vs serving_native_req_per_s_d256 is the tentpole's win;
    # hit_rate and the overadmit gauges audit the epsilon contract.
    "serving_native_tier0_req_per_s_d64": None,
    "serving_native_tier0_req_per_s_d256": None,
    "serving_native_tier0_p99_d64_ms": None,
    "serving_native_tier0_hit_rate": None,
    "serving_native_tier0_overadmit_total": None,
    "serving_native_tier0_overadmit_max": None,
    "serving_native_tier0_speedup_vs_off": None,
    # Native bulk lane (round 8): ACQUIRE_MANY rows/s through the C
    # lane (hot keyspace, tier-0 per-row decisions) vs the asyncio bulk
    # path on the same instant backing — the ≥2×-per-core acceptance
    # ratio — plus the same rig against a device-class (multi-ms flush)
    # backing, the regime the 2 ms p99 north star fears. The real-device
    # number stays owed in benchmarks/recapture.py until a TPU window.
    "serving_native_bulk_rows_per_s": None,
    "serving_native_bulk_p99_ms": None,
    "serving_native_bulk_tier0_hit_rate": None,
    "serving_native_bulk_asyncio_rows_per_s": None,
    "serving_native_bulk_speedup_vs_asyncio": None,
    "serving_native_bulk_device_rows_per_s": None,
    "serving_native_bulk_device_p99_ms": None,
    "serving_native_bulk_device_cold_rows_per_s": None,
    "serving_native_bulk_device_cold_p99_ms": None,
    # Multi-shard native front-end (round 11): bulk rows/s through ONE
    # port as a function of SO_REUSEPORT epoll shard count (pinned,
    # instant backing, hot keyspace, tier-0 armed — the node-level
    # scaling curve the 50M/s aggregate model multiplies). Acceptance:
    # s4 >= 3.5x s1 on the same machine.
    "serving_native_shards_rows_per_s_s1": None,
    "serving_native_shards_rows_per_s_s2": None,
    "serving_native_shards_rows_per_s_s4": None,
    "serving_native_shards_rows_per_s_s8": None,
    "serving_native_shards_speedup_4v1": None,
    "serving_native_shards_speedup_8v1": None,
    "serving_native_shards_p99_s4_ms": None,
    "serving_native_shards_local_frac_s4": None,
    # io_uring data plane (round 16): the same pinned shard rig per
    # transport arm — epoll vs uring vs uring+SQPOLL — with the
    # server's C-side data-plane syscall counter divided by frames
    # pushed, and rusage-derived cycles/row. Acceptance: syscalls/frame
    # on the ring ≤ 1/10 of epoll's at the multi-connection point.
    "serving_native_uring_available": None,
    "serving_native_uring_syscalls_per_frame_epoll_s4": None,
    "serving_native_uring_syscalls_per_frame_uring_s4": None,
    "serving_native_uring_syscalls_per_frame_sqpoll_s4": None,
    "serving_native_uring_syscall_reduction_s4": None,
    "serving_native_uring_rows_per_s_uring_s4": None,
    "serving_native_uring_p99_uring_s4_ms": None,
    # Observability-plane cost audit: closed-loop per-request rate with
    # the plane (heavy hitters + flight recorder + /metrics listener +
    # stage stamps) enabled vs observability=False. Contract: <3%.
    "serving_metrics_on_req_per_s": None,
    "serving_metrics_off_req_per_s": None,
    "serving_metrics_overhead_pct": None,
    "serving_metrics_scrape_bytes": None,
    # Distributed-tracing arm of the same audit: head-sampled (1%)
    # tracing toggled on the plane-enabled rig; same <3% contract.
    "serving_tracing_on_req_per_s": None,
    "serving_tracing_overhead_pct": None,
    # Conservation audit plane arm (runtime/audit.py): ε-ledger +
    # watchdog ticking at 10x production cadence vs audit=False; same
    # ABBA estimator, same <3% contract. audit_ticks proves liveness.
    "serving_audit_on_req_per_s": None,
    "serving_audit_off_req_per_s": None,
    "serving_audit_overhead_pct": None,
    "serving_audit_ticks": None,
    "pallas_sweep_ok": None,
    "device_probe": None,
    "budget_s": BUDGET_S,
    "elapsed_s": 0.0,
    "section_status": {},
    "partial": True,
}


def _remaining() -> float:
    return BUDGET_S - (time.monotonic() - _T0)


def _emit() -> None:
    """Print the full result JSON as one line; the driver's tail capture
    parses the LAST line, so every call supersedes the previous one."""
    RESULT["elapsed_s"] = round(time.monotonic() - _T0, 1)
    print(json.dumps(RESULT), flush=True)


def _section(name: str, fn, timeout_s: float | None = None):
    """Run one bench section on a timeout-guarded daemon thread.

    Returns (status, value): status is "ok" | "hung" | "skipped_budget" |
    "error". A hung section leaves its thread parked (it cannot be
    cancelled mid-device-op) but the orchestrator moves on and the final
    exit path uses os._exit so a parked thread cannot block process exit.
    Always emits the partial JSON before returning.
    """
    if _remaining() < 20.0:
        RESULT["section_status"][name] = "skipped_budget"
        _emit()
        return "skipped_budget", None
    if SIM_HANG_SECTION == name:
        fn = lambda: time.sleep(1e6)  # noqa: E731 — kill-test hook
    timeout = min(timeout_s or SECTION_TIMEOUT_S, max(_remaining(), 20.0))
    box: dict = {}

    def target():
        try:
            box["v"] = fn()
        except BaseException as e:  # noqa: BLE001 — a section must never
            box["e"] = f"{type(e).__name__}: {e}"  # take down the bench
    th = threading.Thread(target=target, daemon=True, name=f"bench-{name}")
    th.start()
    th.join(timeout)
    if th.is_alive():
        print(f"bench: section {name} hung (> {timeout:.0f}s)",
              file=sys.stderr, flush=True)
        RESULT["section_status"][name] = "hung"
        _emit()
        return "hung", None
    if "e" in box:
        print(f"bench: section {name} failed: {box['e']}",
              file=sys.stderr, flush=True)
        RESULT["section_status"][name] = f"error: {box['e'][:200]}"
        _emit()
        return "error", None
    RESULT["section_status"][name] = "ok"
    _emit()
    return "ok", box.get("v")


def _probe_device(max_wait_s: float) -> str | None:
    """Look for a healthy device-init window WITHOUT initialising the
    backend in this process: each probe is a disposable child with a
    60s timeout (a hung init in the committed process is unrecoverable —
    the exact r04 wedge). Returns the device platform string, or None if
    no healthy window appeared (deterministic init errors also return
    None: retrying cannot fix a bad install, and proceeding to init
    in-process is exactly what r04 proved fatal)."""
    import subprocess

    code = ("import time; time.sleep(1e6)" if SIM_WEDGE
            else "import jax; print(jax.devices()[0].platform)")
    deadline = time.monotonic() + max_wait_s
    attempt = 0
    while True:
        attempt += 1
        child_timeout = min(60.0, max(deadline - time.monotonic(), 5.0))
        try:
            r = subprocess.run(
                [sys.executable, "-c", code], timeout=child_timeout,
                capture_output=True, text=True, env=os.environ.copy())
            if r.returncode == 0:
                return r.stdout.strip().splitlines()[-1]
            err = (r.stderr or "").strip()[-400:]
            print("bench: device init fails deterministically; device "
                  f"sections will be skipped. Child stderr tail: {err}",
                  file=sys.stderr, flush=True)
            RESULT["device_probe_error"] = err[-200:]
            return None
        except subprocess.TimeoutExpired:
            print(f"bench: device init window unhealthy "
                  f"(probe {attempt} timed out)", file=sys.stderr, flush=True)
        if time.monotonic() >= deadline:
            return None
        time.sleep(5)


def _run_device_sections() -> bool:
    """Run every device-touching section in order, sharing kernel state.
    Returns True if any section hung (tunnel wedged — caller must use
    os._exit so the parked thread can't block exit). After a hang, the
    remaining device sections are skipped: the tunnel serialises device
    work, so a wedged fetch poisons every later dispatch."""
    import jax.numpy as jnp

    from distributedratelimiting.redis_tpu.models import partitioned
    from distributedratelimiting.redis_tpu.models import options as options_mod
    from distributedratelimiting.redis_tpu.ops import kernels as K
    from distributedratelimiting.redis_tpu.runtime import store as store_mod
    from distributedratelimiting.redis_tpu.runtime.clock import MonotonicClock

    clock = MonotonicClock()
    ctx: dict = {}
    wedged = False

    def run(name, fn, keys, timeout_s=None):
        nonlocal wedged
        if wedged:
            RESULT["section_status"][name] = "skipped_after_hang"
            _emit()
            return
        status, value = _section(name, fn, timeout_s)
        if status == "hung":
            wedged = True
        elif status == "ok" and keys:
            vals = value if isinstance(value, tuple) else (value,)
            for k, v in zip(keys, vals):
                RESULT[k] = v
            _emit()  # _section's emit predates the stores: re-emit so the
            # tail never shows this section "ok" with its metrics null

    def sec_link():
        return round(bench_link_probe(jnp), 1)

    def sec_rtt():
        rtt_ms, overlap_x = bench_rtt_probe(jnp)
        return round(rtt_ms, 2), round(overlap_x, 2)

    def sec_headline():
        rate, state = bench_kernel_throughput(jnp, K, clock)
        ctx["state"] = state
        RESULT["vs_baseline"] = round(rate / NORTH_STAR_PER_CHIP, 3)
        return round(rate)

    def sec_compact():
        rate, state = bench_compact_throughput(jnp, K, clock, ctx["state"])
        ctx["state"] = state
        return round(rate)

    def sec_single():
        rate = bench_single_batch(jnp, K, clock, ctx["state"])
        del ctx["state"]  # free the 10M-slot table before serving stores
        return round(rate)

    def sec_bulk():
        a, b = asyncio.run(bench_e2e_bulk(store_mod, partitioned,
                                          options_mod))
        return round(a), round(b)

    def sec_fp_bulk():
        return round(asyncio.run(bench_fp_bulk()))

    def sec_remote_bulk():
        return round(asyncio.run(bench_e2e_remote_bulk(store_mod)))

    def sec_e2e_async():
        rate, p99 = asyncio.run(
            bench_e2e_async(store_mod, partitioned, options_mod))
        # Stamp the RTT the link showed THIS run next to the numbers it
        # bounds (the link swings between sections, but the same-run
        # probe is the control the round-over-round comparison needs).
        RESULT["e2e_async_link_rtt_ms"] = RESULT["link_rtt_ms"]
        return round(rate), round(p99 * 1e3, 3)

    def sec_serving_p99():
        def on_d64(p99, p50, n):
            # Land the headline numbers the moment they exist: a wedge
            # during the extra depth-4 window must not discard them.
            RESULT["serving_p99_ms"] = round(p99, 3)
            RESULT["serving_p50_ms"] = round(p50, 3)
            RESULT["serving_p99_samples"] = n
            _emit()

        p99, p50, n, p99_d4, p50_d4, n4 = asyncio.run(
            bench_serving_p99(store_mod, on_d64=on_d64))
        return (round(p99, 3), round(p50, 3), n,
                round(p99_d4, 3), round(p50_d4, 3), n4)

    def sec_pallas():
        return bench_pallas_sweep(store_mod)

    run("link_probe", sec_link, ["link_upload_mb_per_s"], timeout_s=120)
    run("link_rtt_probe", sec_rtt,
        ["link_rtt_ms", "link_pipeline_overlap_x"], timeout_s=120)
    run("headline", sec_headline, ["value"])
    run("compact", sec_compact, ["compact_path_decisions_per_sec"])
    run("single_batch", sec_single, ["single_batch_decisions_per_sec"])
    run("e2e_bulk", sec_bulk, ["e2e_bulk_decisions_per_sec",
                               "e2e_bulk_with_remaining_decisions_per_sec"])
    run("fp_bulk", sec_fp_bulk, ["e2e_fp_bulk_decisions_per_sec"])
    run("remote_bulk", sec_remote_bulk,
        ["e2e_remote_bulk_decisions_per_sec"])
    run("e2e_async", sec_e2e_async,
        ["e2e_async_decisions_per_sec", "e2e_p99_low_load_ms"])
    run("serving_p99", sec_serving_p99,
        ["serving_p99_ms", "serving_p50_ms", "serving_p99_samples",
         "serving_p99_d4_ms", "serving_p50_d4_ms",
         "serving_p99_d4_samples"])
    if RESULT["platform"] == "tpu":
        run("pallas_sweep", sec_pallas, ["pallas_sweep_ok"])
    return wedged


def main() -> int:
    _emit()  # first line lands before any device or child work
    platform = _probe_device(min(PROBE_S, max(_remaining() - 60.0, 5.0)))
    RESULT["device_probe"] = "ok" if platform else "unhealthy"
    RESULT["platform"] = platform or "unavailable"
    _emit()

    wedged = False
    if platform:
        wedged = _run_device_sections()
    else:
        for name in ("link_probe", "link_rtt_probe", "headline", "compact",
                     "single_batch", "e2e_bulk", "fp_bulk", "remote_bulk",
                     "e2e_async", "serving_p99"):
            RESULT["section_status"][name] = "skipped_unhealthy_device"
        _emit()

    def sec_nproc():
        rate, clients = bench_e2e_async_nproc_cpu(
            timeout_s=min(600.0, max(_remaining(), 30.0)))
        if clients == 0:  # child died/timed out: a failed section must
            # never read as a measured rate of 0 (evidence fidelity)
            raise RuntimeError("nproc CPU child failed or timed out")
        return rate, clients

    status, value = _section("nproc", sec_nproc, timeout_s=620)
    if status == "ok":
        RESULT["e2e_async_nproc_decisions_per_sec"] = round(value[0])
        RESULT["e2e_async_nproc_clients"] = value[1]
        _emit()

    def sec_serving_cpu():
        out = bench_serving_p99_cpu(
            timeout_s=min(600.0, max(_remaining(), 30.0)))
        if out is None:
            raise RuntimeError("serving-p99 CPU children failed or timed out")
        return out

    status, value = _section("serving_p99_colocated", sec_serving_cpu,
                             timeout_s=620)
    if status == "ok" and value is not None:
        d64, d16, d4 = value["d64"], value["d16"], value["d4"]
        RESULT["serving_p99_colocated_ms"] = round(d64["p99_ms"], 3)
        RESULT["serving_p50_colocated_ms"] = round(d64["p50_ms"], 3)
        RESULT["serving_p99_colocated_d4_ms"] = round(d4["p99_ms"], 3)
        RESULT["serving_p99_colocated_d16_ms"] = round(d16["p99_ms"], 3)
        if d64.get("flush_p99_ms") is not None:
            RESULT["flush_p99_colocated_ms"] = round(d64["flush_p99_ms"], 3)
            RESULT["flush_p50_colocated_ms"] = round(d64["flush_p50_ms"], 3)
        _emit()

    def sec_serving_instant():
        out = bench_serving_p99_cpu(
            timeout_s=min(300.0, max(_remaining(), 30.0)),
            backing="instant")
        if out is None:
            raise RuntimeError("instant-serving children failed/timed out")
        return out

    status, value = _section("serving_p99_instant", sec_serving_instant,
                             timeout_s=320)
    if status == "ok" and value is not None:
        d64, d16, d4 = value["d64"], value["d16"], value["d4"]
        RESULT["serving_p99_instant_ms"] = round(d64["p99_ms"], 3)
        RESULT["serving_p50_instant_ms"] = round(d64["p50_ms"], 3)
        RESULT["serving_p99_instant_d4_ms"] = round(d4["p99_ms"], 3)
        RESULT["serving_p99_instant_d16_ms"] = round(d16["p99_ms"], 3)
        _emit()

    def sec_serving_native():
        out = bench_serving_p99_cpu(
            timeout_s=min(300.0, max(_remaining(), 30.0)),
            backing="instant", native=True)
        if out is None:
            raise RuntimeError("native-frontend children failed/timed out")
        return out

    status, value = _section("serving_native", sec_serving_native,
                             timeout_s=320)
    if status == "ok" and value is not None:
        RESULT["serving_native_req_per_s_d64"] = round(value["d64"]["rate"])
        RESULT["serving_native_req_per_s_d256"] = round(
            value["d256"]["rate"])
        RESULT["serving_native_p50_d16_ms"] = round(
            value["d16"]["p50_ms"], 3)
        RESULT["serving_native_p99_d16_ms"] = round(
            value["d16"]["p99_ms"], 3)
        RESULT["serving_native_p99_d64_ms"] = round(
            value["d64"]["p99_ms"], 3)
        _emit()

    def sec_serving_native_tier0():
        out = bench_serving_p99_cpu(
            timeout_s=min(300.0, max(_remaining(), 30.0)),
            backing="instant", native=True, tier0=True)
        if out is None:
            raise RuntimeError("tier0-frontend children failed/timed out")
        return out

    status, value = _section("serving_native_tier0",
                             sec_serving_native_tier0, timeout_s=320)
    if status == "ok" and value is not None:
        RESULT["serving_native_tier0_req_per_s_d64"] = round(
            value["d64"]["rate"])
        RESULT["serving_native_tier0_req_per_s_d256"] = round(
            value["d256"]["rate"])
        RESULT["serving_native_tier0_p99_d64_ms"] = round(
            value["d64"]["p99_ms"], 3)
        t0 = value.get("tier0") or {}
        if t0:
            RESULT["serving_native_tier0_hit_rate"] = round(
                t0.get("hit_rate", 0.0), 4)
            RESULT["serving_native_tier0_overadmit_total"] = t0.get(
                "overadmit_total")
            RESULT["serving_native_tier0_overadmit_max"] = t0.get(
                "overadmit_max")
        off = RESULT["serving_native_req_per_s_d256"]
        if off:
            RESULT["serving_native_tier0_speedup_vs_off"] = round(
                value["d256"]["rate"] / off, 2)
        _emit()

    def sec_serving_native_bulk():
        out = bench_native_bulk(timeout_s=min(420.0,
                                              max(_remaining(), 30.0)))
        if out is None:
            raise RuntimeError("native-bulk children failed or timed out")
        return out

    status, value = _section("serving_native_bulk",
                             sec_serving_native_bulk, timeout_s=440)
    if status == "ok" and value is not None:
        nat, asy = value["native"], value["asyncio"]
        RESULT["serving_native_bulk_rows_per_s"] = round(nat["rows_per_s"])
        RESULT["serving_native_bulk_p99_ms"] = round(nat["p99_ms"], 3)
        hit = nat.get("window_tier0_hit_rate", nat.get("tier0_hit_rate"))
        if hit is not None:
            RESULT["serving_native_bulk_tier0_hit_rate"] = round(hit, 4)
        RESULT["serving_native_bulk_asyncio_rows_per_s"] = round(
            asy["rows_per_s"])
        if asy["rows_per_s"]:
            RESULT["serving_native_bulk_speedup_vs_asyncio"] = round(
                nat["rows_per_s"] / asy["rows_per_s"], 2)
        for arm, key in (("device", "serving_native_bulk_device"),
                         ("device_cold",
                          "serving_native_bulk_device_cold")):
            dev = value.get(arm)
            if dev is not None:
                RESULT[f"{key}_rows_per_s"] = round(dev["rows_per_s"])
                RESULT[f"{key}_p99_ms"] = round(dev["p99_ms"], 3)
        _emit()

    def sec_native_shards():
        out = bench_native_shards(timeout_s=min(600.0,
                                                max(_remaining(), 60.0)))
        if out is None:
            raise RuntimeError("shard-sweep children failed or timed out")
        return out

    status, value = _section("serving_native_shards", sec_native_shards,
                             timeout_s=620)
    if status == "ok" and value is not None:
        for s_n in (1, 2, 4, 8):
            arm = value.get(f"s{s_n}")
            if arm is not None:
                RESULT[f"serving_native_shards_rows_per_s_s{s_n}"] = \
                    round(arm["rows_per_s"])
        if value.get("speedup_4v1") is not None:
            RESULT["serving_native_shards_speedup_4v1"] = round(
                value["speedup_4v1"], 2)
        if value.get("speedup_8v1") is not None:
            RESULT["serving_native_shards_speedup_8v1"] = round(
                value["speedup_8v1"], 2)
        s4 = value.get("s4")
        if s4 is not None:
            RESULT["serving_native_shards_p99_s4_ms"] = round(
                s4["p99_ms"], 3)
            if "rows_local_frac" in s4:
                RESULT["serving_native_shards_local_frac_s4"] = round(
                    s4["rows_local_frac"], 4)
        _emit()

    def sec_native_uring():
        out = bench_native_uring(timeout_s=min(540.0,
                                               max(_remaining(), 60.0)))
        if out is None:
            raise RuntimeError("uring-sweep children failed or timed out")
        return out

    status, value = _section("serving_native_uring", sec_native_uring,
                             timeout_s=560)
    if status == "ok" and value is not None:
        RESULT["serving_native_uring_available"] = value.get(
            "uring_available")
        spf = {}
        for arm in ("epoll", "uring", "sqpoll"):
            row = value.get(f"{arm}_s4")
            if row is None or row.get("fell_back"):
                continue
            if "syscalls_per_frame" in row:
                spf[arm] = row["syscalls_per_frame"]
                RESULT[f"serving_native_uring_syscalls_per_frame"
                       f"_{arm}_s4"] = row["syscalls_per_frame"]
        if "epoll" in spf and ("sqpoll" in spf or "uring" in spf):
            ring = spf.get("sqpoll", spf.get("uring"))
            if ring:
                RESULT["serving_native_uring_syscall_reduction_s4"] = \
                    round(spf["epoll"] / ring, 2)
        u4 = value.get("uring_s4")
        if u4 is not None and not u4.get("fell_back"):
            RESULT["serving_native_uring_rows_per_s_uring_s4"] = round(
                u4["rows_per_s"])
            RESULT["serving_native_uring_p99_uring_s4_ms"] = round(
                u4["p99_ms"], 3)
        _emit()

    def sec_metrics_overhead():
        (on_rate, off_rate, pct, scraped,
         trace_rate, trace_pct) = bench_metrics_overhead()
        return (round(on_rate), round(off_rate), round(pct, 2), scraped,
                round(trace_rate), round(trace_pct, 2))

    status, value = _section("serving_metrics_overhead",
                             sec_metrics_overhead, timeout_s=240)
    if status == "ok" and value is not None:
        (RESULT["serving_metrics_on_req_per_s"],
         RESULT["serving_metrics_off_req_per_s"],
         RESULT["serving_metrics_overhead_pct"],
         RESULT["serving_metrics_scrape_bytes"],
         RESULT["serving_tracing_on_req_per_s"],
         RESULT["serving_tracing_overhead_pct"]) = value
        _emit()

    def sec_audit_overhead():
        on_rate, off_rate, pct, ticks = bench_audit_overhead()
        return round(on_rate), round(off_rate), round(pct, 2), ticks

    status, value = _section("audit_overhead", sec_audit_overhead,
                             timeout_s=240)
    if status == "ok" and value is not None:
        (RESULT["serving_audit_on_req_per_s"],
         RESULT["serving_audit_off_req_per_s"],
         RESULT["serving_audit_overhead_pct"],
         RESULT["serving_audit_ticks"]) = value
        _emit()

    # Second chance for the chip: if the first probe found no window but
    # budget remains, re-probe and run the device sections late — a
    # flapping tunnel (r04: healthy/wedged minute to minute) often opens
    # a window while the CPU sections run.
    if not platform and not wedged and _remaining() > 360.0:
        platform = _probe_device(min(120.0, _remaining() - 300.0))
        if platform:
            RESULT["device_probe"] = "ok_late"
            RESULT["platform"] = platform
            _emit()
            wedged = _run_device_sections()

    RESULT["partial"] = False
    _emit()
    if wedged:
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)  # a parked daemon thread mid-device-op can hang exit
    return 0


if __name__ == "__main__":
    if "--serving-server-child" in sys.argv:
        i = sys.argv.index("--serving-server-child")
        kind = sys.argv[i + 1] if len(sys.argv) > i + 1 else "device"
        rest = sys.argv[i + 2:]
        shards = 1
        uring = None
        for arg in rest:
            if arg.startswith("shards="):
                shards = int(arg.split("=", 1)[1])
            elif arg.startswith("uring="):
                uring = arg.split("=", 1)[1]
        _serving_server_child(kind, native="native" in rest,
                              tier0="tier0" in rest, shards=shards,
                              pin="pin" in rest, uring=uring)
        sys.exit(0)
    if "--shard-load-child" in sys.argv:
        i = sys.argv.index("--shard-load-child")
        _shard_load_child(sys.argv[i + 1], sys.argv[i + 2],
                          sys.argv[i + 3])
        sys.exit(0)
    if "--native-load-child" in sys.argv:
        i = sys.argv.index("--native-load-child")
        workload = (sys.argv[i + 3]
                    if len(sys.argv) > i + 3 else "uniform")
        _native_load_child(sys.argv[i + 1], sys.argv[i + 2], workload)
        sys.exit(0)
    if "--serving-load-child" in sys.argv:
        i = sys.argv.index("--serving-load-child")
        _serving_load_child(sys.argv[i + 1], sys.argv[i + 2])
        sys.exit(0)
    if "--bulk-load-child" in sys.argv:
        i = sys.argv.index("--bulk-load-child")
        workload = sys.argv[i + 3] if len(sys.argv) > i + 3 else "hot"
        _bulk_load_child(sys.argv[i + 1], sys.argv[i + 2], workload)
        sys.exit(0)
    if "--nproc-child" in sys.argv:
        _nproc_child()
        sys.exit(0)
    if "--nproc-client" in sys.argv:
        i = sys.argv.index("--nproc-client")
        _nproc_client(sys.argv[i + 1], sys.argv[i + 2], sys.argv[i + 3])
        sys.exit(0)
    sys.exit(main())
