"""Extraction: find every jitted admission kernel, rebuild its operand
shapes, and trace it to a compiled artifact.

Discovery is AST-level (``@jax.jit`` / ``@partial(jax.jit, ...)``
decorators in the four ``ops/`` modules, launch sites in the two
``runtime/`` stores), so a kernel the analyzers never saw is a
*structural* failure — exit 2, never a fake clean. Shapes are derived
from each kernel's signature plus the packed-operand layouts the bodies
themselves encode (``packed[3]`` ⇒ a 4-row flush operand,
``_unpack_compact5`` ⇒ the 5-byte fused layout, ``[..., 2]`` off an
``astype`` alias ⇒ the packed24 3-byte rows): an operand the deriver
cannot place is an :class:`ExtractionError`, not a skip.

Tracing happens under ``JAX_PLATFORMS=cpu``. The properties the
analyzers read — jaxpr primitive counts, input→output aliasing
attributes in the lowered StableHLO, jit cache entries — are decided at
trace/lowering time and are platform-portable; only wall-clock is not,
and the ledger makes no wall-clock claims (docs/DESIGN.md §23).
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import importlib
import importlib.util
import pathlib
import re
import sys
import warnings

__all__ = [
    "DIMS", "KERNEL_FLOOR", "LAUNCH_SITE_FLOOR", "OPS_FILES",
    "RUNTIME_FILES", "ExtractionError", "KernelDecl", "Leaf",
    "KernelArtifact", "discover", "launch_sites", "trace_kernels",
    "source_hashes",
]

#: Representative trace dims: B requests/flush, K scan steps, N table
#: slots. Small on purpose — op COUNTS, aliasing, and cache entries are
#: shape-independent for these kernels (everything is vectorized over
#: B/N; nothing unrolls per element), and small shapes keep the full
#: 46-kernel trace in seconds. N != B so an aval match between a table
#: leaf and an output is never a batch-array coincidence.
DIMS = {"B": 8, "K": 2, "N": 64}

#: ops/ holds 46 jitted kernels today (33 kernels.py + 12
#: fp_directory.py + 1 pallas). The floor is the drl-verify
#: extractor-richness posture: fewer extracted kernels means the
#: extractor went blind (decorator refactor, file move), and a blind
#: extractor must fail loudly (exit 2), not report a clean ledger.
KERNEL_FLOOR = 40
#: runtime/store.py + runtime/fp_store.py dispatch those kernels from
#: ~45 call sites today; same posture.
LAUNCH_SITE_FLOOR = 25

OPS_FILES = ("ops/kernels.py", "ops/fp_directory.py",
             "ops/bucket_math.py", "ops/pallas_kernels.py")
RUNTIME_FILES = ("runtime/store.py", "runtime/fp_store.py")
_PKG_DIR = ("distributedratelimiting", "redis_tpu")


class ExtractionError(RuntimeError):
    """The extractor cannot see (missing file, un-derivable operand,
    un-jitted symbol). Always exit 2 — never degrade to a clean run."""


@dataclasses.dataclass(frozen=True)
class KernelDecl:
    """One ``@jax.jit``-decorated function, as the AST sees it."""

    name: str
    file: str                 # repo-relative
    line: int
    path: pathlib.Path        # absolute source path
    donate_argnums: tuple[int, ...]
    static_argnames: tuple[str, ...]
    params: tuple[tuple[str, str | None], ...]   # (name, annotation)

    @property
    def key(self) -> str:
        return f"{self.file}::{self.name}"


@dataclasses.dataclass(frozen=True)
class Leaf:
    """One flattened array argument of a traced kernel."""

    name: str                 # e.g. "state.tokens"
    index: int                # position in the flattened operand list
    shape: tuple[int, ...]
    dtype: str
    table: bool               # N-sized resident state (HBM in prod)
    donated: bool             # per the jit wrapper (lowered.args_info)


@dataclasses.dataclass
class KernelArtifact:
    """A kernel traced to its compiled artifact."""

    decl: KernelDecl
    fn: object                # the live jitted callable
    args1: tuple
    args2: tuple              # same shapes/dtypes, different values
    statics: dict             # statics for args1 (trace/lowering call)
    statics2: dict            # variant statics — the retrace probe's
                              # second call (differs iff a data value
                              # is routed through static_argnames)
    leaves: tuple[Leaf, ...]
    jaxpr: object             # ClosedJaxpr
    lowered_text: str
    kept: tuple[int, ...]     # flat arg indices surviving DCE
    aliased: frozenset[int]   # flat arg indices with tf.aliasing_output
    out_avals: tuple[tuple[tuple[int, ...], str], ...]


# -- AST discovery ----------------------------------------------------------

def _literal(node: ast.AST):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


def _jit_call_info(dec: ast.expr) -> "dict | None":
    """Recognize ``@jax.jit``, ``@jit``, ``@jax.jit(...)`` and
    ``@(functools.)partial(jax.jit, ...)``; return the decorator's
    keyword map (donate_argnums / static_argnames live there)."""
    def is_jit_ref(node: ast.expr) -> bool:
        if isinstance(node, ast.Name):
            return node.id == "jit"
        if isinstance(node, ast.Attribute):
            return node.attr == "jit"
        return False

    if is_jit_ref(dec):
        return {}
    if not isinstance(dec, ast.Call):
        return None
    if is_jit_ref(dec.func):
        return {kw.arg: kw.value for kw in dec.keywords if kw.arg}
    fname = dec.func.attr if isinstance(dec.func, ast.Attribute) else (
        dec.func.id if isinstance(dec.func, ast.Name) else "")
    if fname == "partial" and dec.args and is_jit_ref(dec.args[0]):
        return {kw.arg: kw.value for kw in dec.keywords if kw.arg}
    return None


def _decl_from_def(node: ast.FunctionDef, file: str,
                   path: pathlib.Path) -> "KernelDecl | None":
    for dec in node.decorator_list:
        kws = _jit_call_info(dec)
        if kws is None:
            continue
        donate = _literal(kws["donate_argnums"]) \
            if "donate_argnums" in kws else ()
        if isinstance(donate, int):
            donate = (donate,)
        statics = _literal(kws["static_argnames"]) \
            if "static_argnames" in kws else ()
        if isinstance(statics, str):
            statics = (statics,)
        statics = list(statics or ())
        params = tuple(
            (a.arg, ast.unparse(a.annotation) if a.annotation else None)
            for a in node.args.args)
        # static_argnums names the same contract by position — fold it
        # into the name set so the operand model skips those too.
        nums = _literal(kws["static_argnums"]) \
            if "static_argnums" in kws else ()
        if isinstance(nums, int):
            nums = (nums,)
        for i in nums or ():
            if 0 <= i < len(params):
                statics.append(params[i][0])
        return KernelDecl(
            name=node.name, file=file, line=node.lineno, path=path,
            donate_argnums=tuple(donate or ()),
            static_argnames=tuple(statics or ()), params=params)
    return None


def discover(root: pathlib.Path, *, kernel_floor: int = KERNEL_FLOOR
             ) -> "list[KernelDecl]":
    """Every jitted kernel in the ops/ modules, floor-checked."""
    base = root.joinpath(*_PKG_DIR)
    decls: list[KernelDecl] = []
    seen_any_file = False
    for relf in OPS_FILES:
        path = base / relf
        if not path.exists():
            continue
        seen_any_file = True
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError as exc:
            raise ExtractionError(f"cannot parse {relf}: {exc}") from exc
        file = str(pathlib.PurePosixPath(*_PKG_DIR) / relf)
        for node in tree.body:
            if isinstance(node, ast.FunctionDef):
                d = _decl_from_def(node, file, path)
                if d is not None:
                    decls.append(d)
    if not seen_any_file:
        raise ExtractionError(
            f"no ops/ modules found under {base} — the extractor is "
            "pointed at the wrong tree")
    if len(decls) < kernel_floor:
        raise ExtractionError(
            f"extracted only {len(decls)} jitted kernels from ops/ "
            f"(floor {kernel_floor}) — the decorator extractor has gone "
            "blind; a clean report from a blind extractor is worthless")
    return decls


def launch_sites(root: pathlib.Path, decls: "list[KernelDecl]", *,
                 site_floor: int = LAUNCH_SITE_FLOOR
                 ) -> "dict[str, list[tuple[str, int]]]":
    """Kernel name → [(file, line)] dispatch sites in the runtime
    stores (``K.acquire_batch_packed(...)`` or a direct import)."""
    names = {d.name for d in decls}
    sites: dict[str, list[tuple[str, int]]] = {}
    base = root.joinpath(*_PKG_DIR)
    total = 0
    for relf in RUNTIME_FILES:
        path = base / relf
        if not path.exists():
            raise ExtractionError(f"launch-site file missing: {relf}")
        file = str(pathlib.PurePosixPath(*_PKG_DIR) / relf)
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            called = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if called in names:
                sites.setdefault(called, []).append((file, node.lineno))
                total += 1
    if total < site_floor:
        raise ExtractionError(
            f"found only {total} kernel launch sites in runtime/ "
            f"(floor {site_floor}) — the launch-site extractor has gone "
            "blind")
    return sites


# -- operand-layout derivation ----------------------------------------------

#: Fused-operand unpack helpers whose layout is part of the wire
#: contract (bytes-per-decision): helper name → (trailing dim, dtype).
_HELPER_LAYOUTS = {
    "_unpack_compact5": (5, "uint8"),    # pack_compact5: u8[..., 5]
    "_unpack_fp12": (3, "uint32"),       # pack_fp12:    u32[..., 3]
}


def _operand_layout(tree: ast.Module, func: ast.FunctionDef,
                    pname: str, _depth: int = 0):
    """How does this kernel index its packed operand? Returns
    ``("rows", R)`` for the i32[R, B] flush layouts or
    ``("trailing", T, dtype)`` for byte-packed trailing-dim layouts —
    derived from the subscripts the body (or the unpack helper it
    calls) actually performs."""
    if _depth > 3:
        return None
    aliases = {pname}
    max_row = None
    trailing = None
    for node in ast.walk(func):
        # track `p = packed.astype(...)` style aliases
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and isinstance(node.value.func.value, ast.Name)
                and node.value.func.value.id in aliases):
            aliases.add(node.targets[0].id)
    for node in ast.walk(func):
        if isinstance(node, ast.Subscript) and \
                isinstance(node.value, ast.Name) and \
                node.value.id in aliases:
            sl = node.slice
            idxs = sl.elts if isinstance(sl, ast.Tuple) else [sl]
            first = idxs[0]
            if isinstance(first, ast.Constant) and \
                    isinstance(first.value, int):
                max_row = max(max_row or 0, first.value)
            elif isinstance(first, ast.Constant) and \
                    first.value is Ellipsis and len(idxs) > 1 and \
                    isinstance(idxs[-1], ast.Constant) and \
                    isinstance(idxs[-1].value, int):
                trailing = max(trailing or 0, idxs[-1].value)
        if isinstance(node, ast.Call):
            callee = node.func.id if isinstance(node.func, ast.Name) \
                else None
            if callee is None:
                continue
            arg_pos = [i for i, a in enumerate(node.args)
                       if isinstance(a, ast.Name) and a.id in aliases]
            if not arg_pos:
                continue
            if callee in _HELPER_LAYOUTS:
                t, dt = _HELPER_LAYOUTS[callee]
                return ("trailing", t, dt)
            helper = next((n for n in tree.body
                           if isinstance(n, ast.FunctionDef)
                           and n.name == callee), None)
            if helper is not None and arg_pos[0] < len(helper.args.args):
                inner = _operand_layout(
                    tree, helper, helper.args.args[arg_pos[0]].arg,
                    _depth + 1)
                if inner is not None:
                    return inner
    if trailing is not None:
        return ("trailing", trailing + 1, "uint8")
    if max_row is not None:
        return ("rows", max_row + 1)
    # scan kernels destructure the stacked operand inside a nested scan
    # body under a local name (`(fused, now) = xs`); the unpack-helper
    # call is still the layout authority, whatever the local is called.
    if pname.startswith("fused"):
        for node in ast.walk(func):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in _HELPER_LAYOUTS:
                t, dt = _HELPER_LAYOUTS[node.func.id]
                return ("trailing", t, dt)
    return None


# -- representative operand construction ------------------------------------

_STATE_FIELD_DTYPES = {
    "tokens": "float32", "last_ts": "int32", "exists": "bool",
    "value": "float32", "period": "float32",
    "prev_count": "float32", "curr_count": "float32",
    "window_idx": "int32", "active": "int32",
}


def _resolve_annotation(module, annotation: str):
    obj = module
    for part in annotation.split("."):
        obj = getattr(obj, part, None)
        if obj is None:
            return None
    return obj


def _build_args(decl: KernelDecl, module, tree: ast.Module,
                func: ast.FunctionDef, dims: dict, variant: int):
    """Concrete operands for one trace. ``variant`` perturbs every
    numeric value (same shapes/dtypes) — the retrace probe's second
    call. Returns (args, leaves, statics) where leaves mirrors jax's
    flattening order (argument order, NamedTuple field order).

    Statics get variant-dependent values too (except ``interpret``,
    which is a genuine mode flag): a data operand routed through
    static_argnames/static_argnums keys the jit cache per value, and
    the retrace probe can only see that if the probe actually varies
    the static — that IS the leak xla-retrace exists to catch."""
    import numpy as np

    B, K, N = dims["B"], dims["K"], dims["N"]
    v = variant
    pnames = [p for p, _ in decl.params]
    scanned = "nows_k" in pnames
    args: list = []
    leaves: list[tuple[str, bool]] = []   # (leaf name, table?)
    statics: dict = {}
    for pname in decl.static_argnames:
        if pname == "interpret":
            statics[pname] = True
        elif pname in pnames:
            statics[pname] = 64 + v

    def arr(value, dtype, shape=None):
        a = np.asarray(value, dtype=dtype)
        return a if shape is None else np.broadcast_to(a, shape).copy()

    def slots(shape):
        flat = (np.arange(int(np.prod(shape))) + v) % N
        return flat.reshape(shape).astype(np.int32)

    for pname, annotation in decl.params:
        if pname in decl.static_argnames:
            continue   # statics are not operands; defaults apply
        state_cls = _resolve_annotation(module, annotation) \
            if annotation else None
        if state_cls is not None and hasattr(state_cls, "_fields"):
            fields = []
            for f in state_cls._fields:
                dt = _STATE_FIELD_DTYPES.get(f)
                if dt is None:
                    raise ExtractionError(
                        f"{decl.key}: state field {annotation}.{f} has "
                        "no dtype rule — teach extract.py its layout")
                if dt == "bool":
                    fields.append(arr(True, np.bool_, (N,)))
                elif dt == "float32":
                    fields.append(arr(1.0 + v, np.float32, (N,)))
                else:
                    fields.append(arr(v, np.int32, (N,)))
                leaves.append((f"{pname}.{f}", True))
            args.append(state_cls(*fields))
            continue
        if pname == "fp":
            args.append(arr(v, np.uint32, (N, 2)))
            leaves.append((pname, True))
        elif pname == "kpair":
            base = (np.arange(B * 2).reshape(B, 2) + 1 + v)
            args.append(base.astype(np.uint32))
            leaves.append((pname, False))
        elif pname == "exists_i8":
            args.append(arr(1, np.int8, (N,)))
            leaves.append((pname, True))
        elif pname in ("tokens", "last_ts", "exists"):
            table = "exists_i8" in pnames   # pallas sweep: N-sized plane
            n = N if table else B
            if pname == "tokens":
                args.append(arr(1.0 + v, np.float32, (n,)))
            elif pname == "last_ts":
                args.append(arr(v, np.int32, (n,)))
            else:
                args.append(arr(True, np.bool_, (n,)))
            leaves.append((pname, table))
        elif pname in ("packed", "fused", "fused_k"):
            layout = _operand_layout(tree, func, pname)
            if layout is None:
                raise ExtractionError(
                    f"{decl.key}: cannot derive the {pname!r} operand "
                    "layout from the body — teach extract.py (or the "
                    "kernel) its packing")
            if layout[0] == "rows":
                rows = np.full((layout[1], B), 1 + v, np.int32)
                rows[0] = slots((B,))
                args.append(rows)
            else:
                _, t, dt = layout
                shape = (K, B, t) if scanned else (B, t)
                fill = (1 + v) & 0x3
                args.append(arr(fill, np.dtype(dt), shape))
            leaves.append((pname, False))
        elif pname.endswith("_k"):
            if pname == "nows_k":
                args.append((100 + v + np.arange(K) * 10
                             ).astype(np.int32))
            elif pname.startswith("valid"):
                args.append(arr(True, np.bool_, (K, B)))
            elif pname.startswith("slots"):
                args.append(slots((K, B)))
            else:
                args.append(arr(1 + v, np.int32, (K, B)))
            leaves.append((pname, False))
        elif pname == "slots":
            args.append(slots((B,)))
            leaves.append((pname, False))
        elif pname == "valid":
            args.append(arr(True, np.bool_, (B,)))
            leaves.append((pname, False))
        elif pname in ("counts", "deltas", "limits"):
            args.append(arr(1 + v, np.int32, (B,)))
            leaves.append((pname, False))
        elif pname in ("amounts", "local_counts", "prefix",
                       "prev_count", "curr_count"):
            args.append(arr(1.0 + v, np.float32, (B,)))
            leaves.append((pname, False))
        elif pname == "window_idx":
            args.append(arr(v, np.int32, (B,)))
            leaves.append((pname, False))
        elif pname == "now":
            args.append(np.int32(100 + v))
            leaves.append((pname, False))
        elif "capacity" in pname or "limit" in pname:
            args.append(np.float32(8.0 + v))
            leaves.append((pname, False))
        elif "rate" in pname or "decay" in pname:
            args.append(np.float32(0.5 + 0.25 * v))
            leaves.append((pname, False))
        elif "ticks" in pname or "windows" in pname:
            args.append(np.int32(64 + v))
            leaves.append((pname, False))
        else:
            raise ExtractionError(
                f"{decl.key}: no shape rule for parameter {pname!r} — "
                "a kernel the extractor cannot operand-model is a "
                "kernel the analyzers cannot see; add the rule")
    return tuple(args), leaves, statics


# -- tracing ----------------------------------------------------------------

_ARG_ATTR_RE = re.compile(
    r"%arg(\d+): tensor<[^>]*>\s*(\{[^}]*\})?")


def _parse_aliased(text: str) -> "frozenset[int]":
    """MLIR positions (0-based, post-DCE) whose parameter carries a
    ``tf.aliasing_output`` attribute in the lowered module. Typed
    ``%argN: tensor<...>`` bindings only occur in function signatures;
    the public @main comes first, so first occurrence per index wins
    over any private helper func reusing the numbering."""
    seen: dict[int, bool] = {}
    for m in _ARG_ATTR_RE.finditer(text):
        idx = int(m.group(1))
        if idx not in seen:
            seen[idx] = bool(m.group(2) and
                             "tf.aliasing_output" in m.group(2))
    return frozenset(i for i, ok in seen.items() if ok)


def _load_module(decl_path: pathlib.Path, root: pathlib.Path):
    """Import the kernel module. The real tree imports by package name
    (so the analyzers and the serving path share the SAME jit objects
    and caches); any other root gets an isolated file-load."""
    base = root.joinpath(*_PKG_DIR)
    try:
        relative = decl_path.resolve().relative_to(base.resolve())
        dotted = ".".join(_PKG_DIR + tuple(relative.with_suffix("").parts))
        mod = importlib.import_module(dotted)
        if pathlib.Path(mod.__file__).resolve() == decl_path.resolve():
            return mod
    except (ValueError, ImportError):
        pass
    tag = hashlib.sha1(str(decl_path).encode()).hexdigest()[:10]
    name = f"_drl_xla_target_{decl_path.stem}_{tag}"
    spec = importlib.util.spec_from_file_location(name, decl_path)
    if spec is None or spec.loader is None:
        raise ExtractionError(f"cannot load module {decl_path}")
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def trace_kernels(decls: "list[KernelDecl]", root: pathlib.Path,
                  dims: "dict | None" = None) -> "list[KernelArtifact]":
    """Trace every discovered kernel to jaxpr + lowered StableHLO.
    Any failure is an ExtractionError: a kernel that cannot be traced
    is a kernel whose artifact nobody is checking."""
    import jax

    dims = dims or DIMS
    artifacts: list[KernelArtifact] = []
    by_path: dict[pathlib.Path, list[KernelDecl]] = {}
    for d in decls:
        by_path.setdefault(d.path, []).append(d)
    for path, group in sorted(by_path.items()):
        module = _load_module(path, root)
        tree = ast.parse(path.read_text())
        funcs = {n.name: n for n in tree.body
                 if isinstance(n, ast.FunctionDef)}
        for decl in group:
            fn = getattr(module, decl.name, None)
            if fn is None or not hasattr(fn, "lower"):
                raise ExtractionError(
                    f"{decl.key}: decorated with jax.jit in the AST but "
                    "not a jit wrapper at runtime — the artifact the "
                    "tree ships is not the one the source claims")
            try:
                args1, leaf_meta, statics = _build_args(
                    decl, module, tree, funcs[decl.name], dims, 0)
                args2, _, statics2 = _build_args(
                    decl, module, tree, funcs[decl.name], dims, 1)
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    lowered = fn.lower(*args1, **statics)
                    text = lowered.as_text()
                    closed = fn.trace(*args1, **statics).jaxpr
            except ExtractionError:
                raise
            except Exception as exc:
                raise ExtractionError(
                    f"{decl.key}: trace failed ({type(exc).__name__}: "
                    f"{exc}) — the operand model no longer matches the "
                    "kernel; fix extract.py's shape rules") from exc
            info_leaves = jax.tree_util.tree_leaves(
                lowered.args_info, is_leaf=lambda x: hasattr(x, "donated"))
            flat1 = jax.tree_util.tree_leaves(args1)
            if not (len(info_leaves) == len(flat1) == len(leaf_meta)):
                raise ExtractionError(
                    f"{decl.key}: operand flattening mismatch "
                    f"({len(info_leaves)} vs {len(flat1)} vs "
                    f"{len(leaf_meta)} leaves)")
            try:
                kept = sorted(
                    lowered._lowering.compile_args["kept_var_idx"])
            except Exception:
                kept = list(range(len(flat1)))
            leaves = tuple(
                Leaf(name=nm, index=i, shape=tuple(a.shape),
                     dtype=str(a.dtype), table=tbl,
                     donated=bool(getattr(info, "donated", False)))
                for i, ((nm, tbl), a, info)
                in enumerate(zip(leaf_meta, flat1, info_leaves)))
            out_avals = tuple(
                (tuple(av.shape), str(av.dtype))
                for av in closed.out_avals)
            artifacts.append(KernelArtifact(
                decl=decl, fn=fn, args1=args1, args2=args2,
                statics=statics, statics2=statics2,
                leaves=leaves, jaxpr=closed,
                lowered_text=text, kept=tuple(kept),
                aliased=_parse_aliased(text), out_avals=out_avals))
    return artifacts


def source_hashes(root: pathlib.Path) -> "dict[str, str]":
    """sha256 of every ops/ module the ledger describes — the stamp
    that makes a stale budgets.json a freshness finding (the .so.hash
    sidecar idiom from tools/drl_check/build_freshness.py)."""
    base = root.joinpath(*_PKG_DIR)
    out = {}
    for relf in OPS_FILES:
        path = base / relf
        if path.exists():
            file = str(pathlib.PurePosixPath(*_PKG_DIR) / relf)
            out[file] = hashlib.sha256(path.read_bytes()).hexdigest()
    return out
