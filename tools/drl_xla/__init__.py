"""drl-xla — compiled-artifact conformance for the admission kernels.

drl-check (PR 4) lints the AST and drl-verify (PR 14) model-checks the
protocol state machines; this tool closes the remaining gap — what the
~46 ``@jax.jit`` kernels in ``ops/`` actually **compile to**. It
discovers every jitted kernel and its runtime launch sites via ast,
rebuilds representative operands from the signatures and the packed
layouts, traces each kernel to jaxpr + lowered StableHLO under
``JAX_PLATFORMS=cpu``, and runs four analyzers over the artifacts:

- **hot-path purity** (``xla-purity``): no Python callbacks, host
  transfers, or 64-bit promotion reachable in an admission jaxpr;
- **donation conformance** (``xla-donation``): every state-table
  argument both declared donated AND actually aliased in the lowered
  module — an XLA-declined donation is a silent HBM doubling;
- **retrace stability** (``xla-retrace``): two calls, same
  shapes/dtypes, different values ⇒ exactly one jit cache entry;
- **op-count budget ledger** (``xla-budget`` / ``xla-stale-ledger``):
  checked-in per-kernel {launches, gather, scatter, while, sort,
  operands, results} in ``budgets.json`` — tightening auto-restamps,
  loosening fails with the diff.

Posture (drl-check's): exit 0 clean, 1 with file:line findings on both
sides of a diff, 2 when the extractor itself is blind — never a fake
clean. Runbook: docs/OPERATIONS.md §19; contract: docs/DESIGN.md §23.
"""

from __future__ import annotations

import pathlib

from tools.drl_xla import analyzers, budgets, extract

__all__ = ["run_all", "analyzers", "budgets", "extract"]


def run_all(root: "pathlib.Path | None" = None, *, restamp: bool = False,
            ledger: "pathlib.Path | None" = None,
            dims: "dict | None" = None):
    """Full pipeline. Returns ``(findings, report)`` where report maps
    stage names to their artifacts (for the non-vacuity pins in
    tests/test_drl_xla.py). ExtractionError propagates — the CLI turns
    it into exit 2."""
    root = root or pathlib.Path(__file__).resolve().parents[2]
    decls = extract.discover(root)
    sites = extract.launch_sites(root, decls)
    artifacts = extract.trace_kernels(decls, root, dims)
    findings = []
    findings += analyzers.check_purity(artifacts, sites)
    findings += analyzers.check_donation(artifacts, sites)
    findings += analyzers.check_retrace(artifacts, sites)
    budget_findings, status = budgets.compare(
        root, artifacts, sites=sites, path=ledger, restamp=restamp)
    findings += budget_findings
    findings = analyzers.apply_suppressions(findings, root, decls)
    report = {
        "decls": decls, "sites": sites, "artifacts": artifacts,
        "budget_status": status,
        "measured": budgets.measure_all(artifacts),
    }
    return findings, report
