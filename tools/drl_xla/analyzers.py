"""The compiled-artifact analyzers: purity, donation conformance, and
retrace stability.

Each analyzer reads what the kernel *compiled to* (jaxpr, lowered
StableHLO, jit cache), not what the decorator claims — the decorator is
a request; the artifact is the fact. Findings anchor at the kernel's
``def`` line and carry the runtime launch sites as related locations,
so a violation names both the kernel and the serving path that pays
for it.

Suppression: ``# drl-check: ok(xla-...)`` on (or directly above) the
kernel's ``def`` line, via the shared registry in
tools/drl_check/common.py. drl-xla audits its own suppressions — an
``ok(xla-*)`` comment whose rule no longer fires here is reported as
``stale-suppression`` by THIS tool (drl-check's stale-suppression pass
skips xla-* rules; it cannot re-run a compile-level analyzer).
"""

from __future__ import annotations

import pathlib
import warnings

from tools.drl_check.common import _SUPPRESS_RE, Finding

from tools.drl_xla import budgets, extract

__all__ = [
    "check_purity", "check_donation", "check_retrace",
    "apply_suppressions", "XLA_RULES",
]

XLA_RULES = frozenset({
    "xla-purity", "xla-donation", "xla-retrace", "xla-budget",
    "xla-stale-ledger",
})

#: Callback primitives that re-enter Python from a compiled admission
#: kernel — a host round-trip per launch on the serving path.
_CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "debug_print",
})
#: Primitives that move data across the host/device boundary mid-kernel.
_TRANSFER_PRIMS = frozenset({"device_put", "copy_to_host"})

_WIDE_DTYPES = ("float64", "int64", "uint64", "complex128")


def _related(decl, sites):
    rel = [(decl.file, decl.line, "kernel definition")]
    for sf, sl in (sites or {}).get(decl.name, [])[:3]:
        rel.append((sf, sl, "launch site"))
    return tuple(rel)


# -- hot-path purity --------------------------------------------------------

def check_purity(artifacts, sites=None) -> "list[Finding]":
    findings: list[Finding] = []
    for art in artifacts:
        decl = art.decl
        callbacks: list[str] = []
        transfers: list[str] = []
        wide: set[str] = set()
        for eqn in budgets._iter_eqns(art.jaxpr.jaxpr):
            name = eqn.primitive.name
            if name in _CALLBACK_PRIMS:
                callbacks.append(name)
            elif name in _TRANSFER_PRIMS:
                transfers.append(name)
            for var in tuple(eqn.outvars) + tuple(eqn.invars):
                dt = str(getattr(getattr(var, "aval", None), "dtype", ""))
                if dt in _WIDE_DTYPES:
                    wide.add(dt)
            new_dtype = eqn.params.get("new_dtype")
            if new_dtype is not None and str(new_dtype) in _WIDE_DTYPES:
                wide.add(str(new_dtype))
        if callbacks:
            findings.append(Finding(
                "xla-purity",
                f"{decl.name}: compiled artifact re-enters Python via "
                f"{', '.join(sorted(set(callbacks)))} "
                f"(x{len(callbacks)}) — a host round-trip inside an "
                "admission kernel serializes every launch on the "
                "serving path",
                decl.file, decl.line, _related(decl, sites)))
        if transfers:
            findings.append(Finding(
                "xla-purity",
                f"{decl.name}: compiled artifact contains a mid-kernel "
                f"host/device transfer ({', '.join(sorted(set(transfers)))})"
                " — operands must arrive packed, once, per launch",
                decl.file, decl.line, _related(decl, sites)))
        if wide:
            findings.append(Finding(
                "xla-purity",
                f"{decl.name}: 64-bit values reach the compiled "
                f"artifact ({', '.join(sorted(wide))}) — the state "
                "plane is 32-bit by contract; a silent f64 promotion "
                "doubles HBM traffic and diverges from the wire "
                "encoding (AST twin: drl-check rule jit-f64)",
                decl.file, decl.line, _related(decl, sites)))
    return findings


# -- donation conformance ---------------------------------------------------

def check_donation(artifacts, sites=None) -> "list[Finding]":
    """Every state-table argument must be BOTH declared donated and
    actually aliased in the lowered artifact. Half one: a donated leaf
    with no ``tf.aliasing_output`` attribute is an XLA-declined
    donation — the table is silently double-buffered. Half two: an
    un-donated table leaf whose exact aval appears among the outputs
    is a donation the kernel forgot to declare — same doubling, by
    omission."""
    findings: list[Finding] = []
    for art in artifacts:
        decl = art.decl
        rank = {flat: pos for pos, flat in enumerate(art.kept)}
        for leaf in art.leaves:
            if leaf.donated:
                pos = rank.get(leaf.index)
                if pos is None or pos not in art.aliased:
                    why = ("was dead-code-eliminated from the module"
                           if pos is None else
                           "carries no tf.aliasing_output attribute "
                           "in the lowered StableHLO")
                    findings.append(Finding(
                        "xla-donation",
                        f"{decl.name}: argument {leaf.name!r} is "
                        f"declared donated but {why} — XLA declined "
                        "the alias, so the buffer is double-buffered "
                        "at runtime (a silent HBM capacity bug at "
                        "table scale); don't trust the decorator",
                        decl.file, decl.line, _related(decl, sites)))
            elif leaf.table:
                aval = (leaf.shape, leaf.dtype)
                if aval in art.out_avals:
                    findings.append(Finding(
                        "xla-donation",
                        f"{decl.name}: table-sized argument "
                        f"{leaf.name!r} "
                        f"({leaf.dtype}[{','.join(map(str, leaf.shape))}]) "
                        "is not donated although the kernel returns an "
                        "output of identical shape/dtype — the update "
                        "allocates a second copy of a resident plane "
                        "every launch; declare it in donate_argnums",
                        decl.file, decl.line, _related(decl, sites)))
    return findings


# -- retrace stability ------------------------------------------------------

def check_retrace(artifacts, sites=None) -> "list[Finding]":
    """Call each kernel twice with different concrete values at
    identical shapes/dtypes; exactly one cache entry may exist. A
    second entry means some value is keying the trace (a Python scalar
    routed through static_argnames / closed over at trace time) — the
    kernel recompiles per distinct cost/config value in production."""
    findings: list[Finding] = []
    for art in artifacts:
        decl = art.decl
        fn = art.fn
        if not hasattr(fn, "_cache_size"):
            raise extract.ExtractionError(
                f"{decl.key}: jit wrapper exposes no _cache_size — the "
                "retrace probe cannot see; update tools/drl_xla for "
                "this jax version")
        if hasattr(fn, "clear_cache"):
            fn.clear_cache()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")   # declined-donation noise
            fn(*art.args1, **art.statics)     # — check_donation owns it
            fn(*art.args2, **art.statics2)
        entries = fn._cache_size()
        if entries != 1:
            findings.append(Finding(
                "xla-retrace",
                f"{decl.name}: two calls at identical shapes/dtypes "
                f"but different values produced {entries} jit cache "
                "entries — a concrete value is keying the trace "
                "(static_argnames on a data operand, or a Python "
                "scalar closed over at trace time); the kernel "
                "recompiles per distinct value in production "
                "(AST twin: drl-check rule jit-closed-scalar)",
                decl.file, decl.line, _related(decl, sites)))
    return findings


# -- suppression plumbing ---------------------------------------------------

def _comments(path: pathlib.Path) -> "list[tuple[int, list[str]]]":
    out = []
    try:
        text = path.read_text()
    except OSError:
        return out
    for i, line in enumerate(text.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out.append((i, [r.strip() for r in m.group(1).split(",")]))
    return out


def apply_suppressions(findings: "list[Finding]", root: pathlib.Path,
                       decls) -> "list[Finding]":
    """Honor ``# drl-check: ok(xla-...)`` at the kernel's def line and
    audit the comments themselves: an xla-* suppression that ate
    nothing this run is stale — delete it so the next real finding
    there is loud, not pre-excused (``ok(stale-suppression)`` opts a
    comment out, same escape hatch as drl-check)."""
    by_file: dict[str, list[tuple[int, list[str]]]] = {}
    for path in sorted({d.path for d in decls}):
        relf = str(path.resolve().relative_to(root.resolve())) \
            if path.resolve().is_relative_to(root.resolve()) \
            else str(path)
        by_file[relf] = _comments(path)

    used: set[tuple[str, int, str]] = set()
    kept: list[Finding] = []
    for f in findings:
        hit = None
        for line, rules in by_file.get(f.file, ()):
            if f.rule in rules and line in (f.line, f.line - 1):
                hit = (f.file, line, f.rule)
                break
        if hit is None:
            kept.append(f)
        else:
            used.add(hit)
    for relf, comments in sorted(by_file.items()):
        for line, rules in comments:
            if "stale-suppression" in rules:
                continue
            for rule in rules:
                if rule in XLA_RULES and (relf, line, rule) not in used:
                    kept.append(Finding(
                        "stale-suppression",
                        f"suppressed rule {rule!r} no longer fires at "
                        "this site under drl-xla — the artifact it "
                        "excused is gone; delete the comment",
                        relf, line))
    return sorted(kept, key=lambda f: (f.file, f.line, f.rule))
