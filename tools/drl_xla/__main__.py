"""CLI: ``python -m tools.drl_xla [--json] [--only STAGE] [--root DIR]
[--no-restamp] [--ledger PATH]``.

Exit codes (the drl-check contract):

- ``0`` — every kernel extracted, every analyzer clean, ledger exact
  (or freshly restamped after a tightening).
- ``1`` — findings, printed with file:line on both sides.
- ``2`` — the extractor or an analyzer itself failed (blind extractor,
  un-derivable operand, missing floor). A tool that cannot see must
  say so — never report clean.

``--no-restamp`` freezes the ledger: any drift (even a tightening)
becomes an ``xla-stale-ledger`` finding instead of a write. The
``make check`` gate and the tier-1 pins use it (``make xla-budget``);
``make xla-budget-restamp`` runs without it so an improvement lands in
the diff you are about to commit.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import pathlib
import sys

# Must precede any jax import: the artifacts are traced on the CPU
# lowering path by contract (platform-portable for these properties).
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from tools.drl_xla import analyzers, budgets, extract  # noqa: E402

_STAGES = ("purity", "donation", "retrace", "budget")


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.drl_xla",
        description="compiled-artifact conformance for the admission "
                    "kernels (jaxpr/HLO budget ledger)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings + measurements")
    parser.add_argument("--only", choices=_STAGES, default=None,
                        help="run a single analyzer (extraction always "
                        "runs; the floors still apply)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: this checkout)")
    parser.add_argument("--no-restamp", action="store_true",
                        help="treat ANY ledger drift as a finding "
                        "instead of rewriting budgets.json")
    parser.add_argument("--ledger", default=None,
                        help="alternate budgets.json path")
    args = parser.parse_args(argv)
    root = pathlib.Path(args.root).resolve() if args.root \
        else pathlib.Path(__file__).resolve().parents[2]
    ledger = pathlib.Path(args.ledger) if args.ledger else None

    try:
        decls = extract.discover(root)
        sites = extract.launch_sites(root, decls)
        artifacts = extract.trace_kernels(decls, root)
    except extract.ExtractionError as exc:
        print(f"drl-xla: extraction failed: {exc}", file=sys.stderr)
        return 2

    findings = []
    status = "skipped"
    try:
        if args.only in (None, "purity"):
            findings += analyzers.check_purity(artifacts, sites)
        if args.only in (None, "donation"):
            findings += analyzers.check_donation(artifacts, sites)
        if args.only in (None, "retrace"):
            findings += analyzers.check_retrace(artifacts, sites)
        if args.only in (None, "budget"):
            budget_findings, status = budgets.compare(
                root, artifacts, sites=sites, path=ledger,
                restamp=not args.no_restamp)
            findings += budget_findings
        findings = analyzers.apply_suppressions(findings, root, decls)
    except extract.ExtractionError as exc:
        print(f"drl-xla: analyzer blinded: {exc}", file=sys.stderr)
        return 2
    except Exception as exc:  # a checker bug must be loud, rc 2
        print(f"drl-xla: checker bug: {type(exc).__name__}: {exc}",
              file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps({
            "kernels": len(artifacts),
            "launch_sites": sum(len(v) for v in sites.values()),
            "budget_status": status,
            "measured": budgets.measure_all(artifacts),
            "findings": [
                {"rule": f.rule, "message": f.message, "file": f.file,
                 "line": f.line,
                 "related": [list(r) for r in f.related]}
                for f in findings],
        }, indent=2, sort_keys=True))
    else:
        for f in findings:
            print(f.format())
        by_rule = collections.Counter(f.rule for f in findings)
        summary = ", ".join(f"{r}={n}" for r, n in sorted(by_rule.items()))
        print(f"drl-xla: {len(artifacts)} kernels, "
              f"{sum(len(v) for v in sites.values())} launch sites, "
              f"ledger {status}; "
              + (f"{len(findings)} finding(s): {summary}"
                 if findings else "clean"))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
