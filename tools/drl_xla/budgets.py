"""The op-count budget ledger: per-kernel compiled-artifact costs as a
checked-in, ratcheted fact.

``budgets.json`` records, for every jitted admission kernel, the
primitive counts that price the serving path — kernel launches,
gathers, scatters, device-side loops (while + scan), sorts, and the
operand/result counts (host↔device transfers per launch, the r04
lesson). The ratchet: **tightening is auto-accepted** (the ledger is
restamped in place and the improvement becomes the new floor);
**loosening fails loudly** with the per-key diff. That is what turns
"``acquire_hierarchical_packed`` pays two table gathers" from prose
into a recorded fact the ROADMAP-item-1 fused kernel must visibly
beat.

Freshness rides the ``.so.hash`` sidecar idiom
(tools/drl_check/build_freshness.py): the ledger carries the sha256 of
every ops/ source it describes plus the jax version and trace dims.
A ledger whose stamp disagrees with the tree is a finding
(``xla-stale-ledger``) in ``--no-restamp`` mode — never a silent pass.

Counts are *static program size* (each primitive occurrence counted
once, loop bodies not multiplied by trip count) measured on the jaxpr,
recursively through scan/while/cond/pjit sub-jaxprs. No wall-clock
claims — docs/DESIGN.md §23 spells out what the ledger does and does
not prove.
"""

from __future__ import annotations

import hashlib
import json
import pathlib

from tools.drl_check.common import Finding

from tools.drl_xla import extract

__all__ = [
    "BUDGET_KEYS", "ledger_path", "measure", "measure_all", "load",
    "make_ledger", "compare", "ledger_hash", "key_line",
]

#: The budgeted keys. launches/gather/scatter/while/sort are the
#: artifact-shape ratchet; operands/results price host↔device transfer
#: count per launch (operand COUNT, not bytes, dominates on tunneled
#: links — ops/kernels.py's own contract).
BUDGET_KEYS = ("launches", "gather", "scatter", "while", "sort",
               "operands", "results")


def ledger_path(root: pathlib.Path) -> pathlib.Path:
    return root / "tools" / "drl_xla" / "budgets.json"


def _subjaxprs(eqn):
    from jax import core
    for v in eqn.params.values():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for x in vals:
            if isinstance(x, core.ClosedJaxpr):
                yield x.jaxpr
            elif isinstance(x, core.Jaxpr):
                yield x


def _iter_eqns(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _subjaxprs(eqn):
            yield from _iter_eqns(sub)


def measure(artifact: "extract.KernelArtifact") -> "dict[str, int]":
    counts = {k: 0 for k in BUDGET_KEYS}
    counts["launches"] = 1   # one fused dispatch per jitted kernel
    for eqn in _iter_eqns(artifact.jaxpr.jaxpr):
        name = eqn.primitive.name
        if name == "gather":
            counts["gather"] += 1
        elif name.startswith("scatter"):
            counts["scatter"] += 1
        elif name in ("while", "scan"):
            counts["while"] += 1
        elif name == "sort":
            counts["sort"] += 1
        elif name == "pallas_call":
            counts["launches"] += 1   # a nested device launch
    counts["operands"] = len(artifact.kept)
    counts["results"] = len(artifact.out_avals)
    return counts


def measure_all(artifacts) -> "dict[str, dict[str, int]]":
    return {a.decl.key: measure(a) for a in artifacts}


def load(path: pathlib.Path) -> "dict | None":
    if not path.exists():
        return None
    try:
        return json.loads(path.read_text())
    except ValueError:
        return {}   # a torn ledger is drift, not a crash


def make_ledger(root: pathlib.Path, measured: "dict[str, dict[str, int]]"
                ) -> dict:
    import jax
    return {
        "stamp": {
            "sources": extract.source_hashes(root),
            "jax": jax.__version__,
            "dims": dict(extract.DIMS),
        },
        "kernels": {k: dict(sorted(v.items()))
                    for k, v in sorted(measured.items())},
    }


def dumps(ledger: dict) -> str:
    return json.dumps(ledger, indent=2, sort_keys=True) + "\n"


def ledger_hash(path: pathlib.Path) -> "str | None":
    """Short content hash of the checked-in ledger — the annotation
    benchmarks/recapture.py stamps on every device-debt row so a
    settled debt names the exact compiled artifacts it measured."""
    if not path.exists():
        return None
    return hashlib.sha256(path.read_bytes()).hexdigest()[:12]


def key_line(path: pathlib.Path, key: str) -> int:
    """Line of a kernel's entry inside budgets.json (findings point at
    the ledger side too — file:line on BOTH sides of the diff)."""
    if not path.exists():
        return 1
    needle = f'"{key}":'
    for i, line in enumerate(path.read_text().splitlines(), start=1):
        if needle in line:
            return i
    return 1


def compare(root: pathlib.Path, artifacts, *,
            sites: "dict[str, list[tuple[str, int]]] | None" = None,
            path: "pathlib.Path | None" = None, restamp: bool = True):
    """Measured artifacts vs the checked-in ledger.

    Returns ``(findings, status)``; status is one of ``"clean"``
    (exact match), ``"restamped"`` (drift auto-accepted and written),
    ``"loosened"`` (budget findings emitted, ledger untouched) or
    ``"stale"`` (--no-restamp and the ledger needs a restamp).
    """
    path = path or ledger_path(root)
    measured = measure_all(artifacts)
    fresh = make_ledger(root, measured)
    old = load(path)
    findings: list[Finding] = []
    by_key = {a.decl.key: a for a in artifacts}

    old_kernels = (old or {}).get("kernels", {})
    loosened = False
    for key, counts in sorted(measured.items()):
        recorded = old_kernels.get(key)
        if recorded is None:
            continue   # new kernel: drift, restampable
        worse = {k: (recorded.get(k, 0), counts[k]) for k in BUDGET_KEYS
                 if counts[k] > recorded.get(k, counts[k])}
        if worse:
            loosened = True
            decl = by_key[key].decl
            diff = ", ".join(f"{k} {a}→{b}" for k, (a, b)
                             in sorted(worse.items()))
            related = [(decl.file, decl.line, "kernel definition")]
            for sf, sl in (sites or {}).get(decl.name, [])[:3]:
                related.append((sf, sl, "launch site"))
            findings.append(Finding(
                "xla-budget",
                f"{decl.name}: compiled artifact loosened its op "
                f"budget ({diff}) — the ledger ratchet only moves "
                "down; make the kernel meet its recorded cost, or "
                "restamp deliberately (make xla-budget-restamp) and "
                "say why in the commit",
                str(path.relative_to(root)) if path.is_relative_to(root)
                else str(path),
                key_line(path, key), tuple(related)))
    if loosened:
        return findings, "loosened"

    drift = old is None or old != fresh
    if not drift:
        return [], "clean"
    if restamp:
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(dumps(fresh))
        return [], "restamped"
    why = ("no ledger exists" if old is None else
           "stamp or counts no longer match the tree")
    changed = [f for f, h in fresh["stamp"]["sources"].items()
               if (old or {}).get("stamp", {}).get("sources", {})
               .get(f) != h]
    related = tuple((f, 1, "source hash differs from the stamp")
                    for f in changed[:4])
    findings.append(Finding(
        "xla-stale-ledger",
        f"budgets.json is stale ({why}) — the ledger does not "
        "describe the artifacts this tree compiles to; run "
        "`python -m tools.drl_xla` (or `make xla-budget`) to restamp, "
        "then commit the ledger",
        str(path.relative_to(root)) if path.is_relative_to(root)
        else str(path), 1, related))
    return findings, "stale"
