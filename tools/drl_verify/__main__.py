"""CLI: ``python -m tools.drl_verify [--json] [--emit-replays DIR]``.

Exit status: 0 = every invariant holds over the explored product and
the lock graph is cycle-free; 1 = violations (counterexample traces on
stdout, replay pytests written with ``--emit-replays``); 2 = checker /
extraction crash — a blinded checker is loud, never a fake 'clean'.

State/depth caps are explicit flags and every truncation is printed:
a bounded run can never read as an exhaustive one."""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from tools.drl_verify import (
    DEFAULT_MAX_DEPTH,
    DEFAULT_MAX_STATES,
    DEFAULT_PRODUCT_STATES,
    run_verify,
)


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        prog="drl-verify",
        description="exhaustive protocol model checker (placement / "
                    "config / reservation / federation / breaker "
                    "machines) + cross-language lock-order analyzer "
                    "(see tools/drl_verify)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable results on stdout")
    parser.add_argument("--root", default=None,
                        help="repo root (default: inferred)")
    parser.add_argument("--max-states", type=int,
                        default=DEFAULT_MAX_STATES,
                        help="per-world state cap (truncation is "
                             "always reported)")
    parser.add_argument("--product-states", type=int,
                        default=DEFAULT_PRODUCT_STATES,
                        help="state cap for the migration x config "
                             "product world")
    parser.add_argument("--max-depth", type=int,
                        default=DEFAULT_MAX_DEPTH)
    parser.add_argument("--no-product", action="store_true",
                        help="skip the (large) product world")
    parser.add_argument("--no-lockorder", action="store_true",
                        help="skip the lock-order analyzer")
    parser.add_argument("--emit-replays", metavar="DIR", default=None,
                        help="write one generated replay pytest per "
                             "violation into DIR")
    args = parser.parse_args(argv)

    try:
        res = run_verify(
            pathlib.Path(args.root) if args.root else None,
            max_states=args.max_states,
            product_states=args.product_states,
            max_depth=args.max_depth,
            include_product=not args.no_product,
            include_lockorder=not args.no_lockorder,
            log=lambda m: print(f"drl-verify: {m}", file=sys.stderr))
    except Exception as exc:  # noqa: BLE001 — checker bug: loud, rc 2
        print(f"drl-verify: checker crashed: {exc!r}", file=sys.stderr)
        return 2

    emitted = []
    if args.emit_replays and res.violations:
        from tools.drl_verify.replay import (
            generate_pytest,
            replay_filename,
        )

        out = pathlib.Path(args.emit_replays)
        out.mkdir(parents=True, exist_ok=True)
        for v in res.violations:
            path = out / replay_filename(v)
            path.write_text(generate_pytest(v))
            emitted.append(str(path))

    if args.json:
        print(json.dumps({
            "states": res.total_states,
            "invariants": sorted(res.invariants_checked),
            "worlds": [{
                "name": r.world, "states": r.states,
                "transitions": r.transitions, "depth": r.depth,
                "truncated": r.truncated,
                "violations": [{
                    "invariant": v.invariant, "detail": v.detail,
                    "trace": list(v.trace),
                } for v in r.violations],
            } for r in res.results],
            "lock_findings": [{
                "rule": f.rule, "file": f.file, "line": f.line,
                "message": f.message,
                "related": [list(r) for r in f.related],
            } for f in res.lock_findings],
            "unmodeled_idempotent_ops": res.unmodeled,
            "replays_written": emitted,
        }, indent=2))
    else:
        for v in res.violations:
            print(v.format())
        for f in res.lock_findings:
            print(f.format())
        for op in res.unmodeled:
            print(f"error[idempotent-unmodeled]: {op} is in "
                  "_IDEMPOTENT_OPS but has no replay model — extend "
                  "tools/drl_verify/machines.py (MODELED_OPS / "
                  "READ_OPS) or reclassify the op")
        n = (len(res.violations) + len(res.lock_findings)
             + len(res.unmodeled))
        verdict = "clean" if n == 0 else f"{n} violation(s)"
        print(f"drl-verify: {verdict} — {res.total_states} product "
              f"states explored, {len(res.invariants_checked)} "
              "invariants checked"
              + (f", {len(emitted)} replay test(s) written"
                 if emitted else ""))
    return 0 if res.clean else 1


if __name__ == "__main__":
    sys.exit(main())
