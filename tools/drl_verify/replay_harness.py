"""Replay a drl-verify counterexample trace against the REAL code.

This is the model-to-code bridge in the code direction: every action
label a world can emit maps here to calls on the live implementation —
:class:`NodePlacementState` pairs over :class:`InProcessBucketStore`
(with real :class:`ReservationLedger` attachments), a real
:class:`ConfigState`, a real :class:`CircuitBreaker` under a manual
clock. A violation trace the model produced is replayed step for step
and the harness asserts the same invariants on the real objects:

- If the model's violation came from a *seeded divergence* (a mutated
  source copy), the replay PASSES on the live tree — proving the live
  code still carries the guard the mutant lost.
- If the replay FAILS on the live tree, the model found a real defect
  and the failing generated test is the regression test to promote
  (the ISSUE-14 settle-dedup fix shipped exactly this way).

The harness is intentionally wire-free: it drives the same objects the
server dispatch drives, one async step at a time, with the placement
gate applied the way ``server.py`` applies it. Unknown labels raise —
a world/harness drift is a loud error, not a silently skipped step."""

from __future__ import annotations

import asyncio
import dataclasses

__all__ = ["ReplayReport", "replay", "HARNESSES"]

CAP = 2.0
TCAP = 4.0   # tenant config must differ from the key config
KEY = "drlv:key"
TENANT = "drlv:tenant"
RID = "drlv:rid"
WINDOW_S = 5.0


@dataclasses.dataclass
class ReplayReport:
    ok: bool
    detail: str
    granted: int = 0
    refunds: int = 0
    steps: int = 0


class _ManualClock:
    """time.monotonic stand-in AND a store Clock (now_ticks)."""

    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def now_ticks(self) -> int:
        from distributedratelimiting.redis_tpu.ops import bucket_math

        return int(self.t * bucket_math.TICKS_PER_SECOND)

    def advance(self, dt: float) -> None:
        self.t += dt


class MigrationHarness:
    """src/dst NodePlacementState over InProcessBucketStores, one key
    migrating from node 0 to node 1 at epoch 1, with a reservation row
    riding the handoff and settles gated exactly like _serve_settle."""

    def __init__(self) -> None:
        from distributedratelimiting.redis_tpu.runtime.placement import (
            NodePlacementState,
            PlacementMap,
            PlacementError,
            StalePlacementError,
        )
        from distributedratelimiting.redis_tpu.runtime.store import (
            InProcessBucketStore,
        )

        self.PlacementError = PlacementError
        self.StaleError = StalePlacementError
        self.clock = _ManualClock()
        self.src_store = InProcessBucketStore(clock=self.clock)
        self.dst_store = InProcessBucketStore(clock=self.clock)
        self.src_led = self.src_store.reservation_ledger(
            clock=self.clock)
        self.dst_led = self.dst_store.reservation_ledger(
            clock=self.clock)
        self.map0 = PlacementMap.initial(2)
        # Pin the key to node 0 at epoch 0, node 1 at epoch 1 — the
        # override route is exact regardless of the key's slot hash.
        self.map0 = PlacementMap(0, self.map0.slot_owner,
                                 {KEY: 0, TENANT: 0})
        self.map1 = self.map0.with_assignments(
            set_overrides={KEY: 1, TENANT: 1})
        self.twin1 = self.map0.with_assignments(
            set_overrides={KEY: 0, TENANT: 0})
        self.src = NodePlacementState(clock=self.clock)
        self.dst = NodePlacementState(clock=self.clock)
        self.src.announce({"map": self.map0.to_dict(), "node_id": 0})
        self.dst.announce({"map": self.map0.to_dict(), "node_id": 1})
        self.client_epoch = 0
        self.granted = 0
        self.refunds = 0
        self.envelope_minted = 0.0
        self.pulled: "dict | None" = None   # coordinator's export copy
        self.res_live = False

    # -- setup driven by the trace's root ----------------------------------
    async def prepare_root(self, root) -> None:
        # root: MigState namedtuple — honor sb (pre-spent) and res0.
        sb = getattr(root, "sb", CAP)
        if sb >= 0:
            spend = int(CAP - sb)
            for _ in range(spend):
                res = await self.src_store.acquire(KEY, 1, CAP, 0.0)
                assert res.granted
                self.granted += 1
            if spend == 0:
                # Touch the table so the entry exists at full balance.
                await self.src_store.acquire(KEY, 0, CAP, 0.0)
        if getattr(root, "res0", False):
            res = await self.src_led.reserve(
                RID, TENANT, KEY, 1.0, TCAP, 0.0, CAP, 0.0)
            assert res.granted
            self.res_live = True

    # -- one action ---------------------------------------------------------
    async def step(self, label: str) -> None:
        if label in ("crash", "retry"):
            return
        if label in ("pull", "dup_pull"):
            try:
                reply = await self.src.pull(
                    {"target_epoch": 1,
                     "keys": [KEY, TENANT],
                     "window_s": WINDOW_S}, self.src_store)
            except self.PlacementError:
                return  # tombstoned / stale: the routable error reply
            if not reply.get("cached"):
                # Each export episode mints one fair-share envelope for
                # the key: headroom_budget(CAP, fraction) — the
                # documented budget×episodes epsilon term, independent
                # of the exported balance (placement.envelope_step).
                self.envelope_minted += (
                    CAP * self.src._fraction)
            entries = dict(reply["entries"])
            for page in range(1, reply["pages"]):
                more = await self.src.pull(
                    {"target_epoch": 1, "page": page}, self.src_store)
                for k, v in more["entries"].items():
                    entries.setdefault(k, [])
                    entries[k] = list(entries[k]) + list(v)
            if label == "pull" or self.pulled is None:
                self.pulled = entries
            return
        if label.startswith("push_") or label.startswith("dup_push_"):
            b = int(label[-1])
            chunk = self._batch(b)
            await self.dst.push({"target_epoch": 1, "batch": b,
                                 "entries": chunk}, self.dst_store)
            return
        if label in ("commit_dst", "dup_commit_dst"):
            self._announce(self.dst, self.map1, node_id=1)
            return
        if label in ("commit_src", "dup_commit_src"):
            self._announce(self.src, self.map1, node_id=0)
            return
        if label == "coord_abort":
            self.src.announce({"abort_epoch": 1})
            self.dst.announce({"abort_epoch": 1})
            return
        if label == "expire":
            self.clock.advance(WINDOW_S + 1.0)
            self.src.gate(KEY)       # expiry fires on the next touch
            self.src.gate(TENANT)
            return
        if label.startswith("stale_announce"):
            node = self.src if label.endswith("src") else self.dst
            self._announce(node, self.map0,
                           node_id=0 if node is self.src else 1)
            return
        if label == "twin_announce_dst":
            self._announce(self.dst, self.twin1, node_id=1)
            return
        if label == "acquire":
            await self._acquire()
            return
        if label == "refresh":
            if self.dst.epoch > self.client_epoch:
                self.client_epoch = self.dst.epoch
            return
        if label.endswith("settle_src") or label.endswith("settle_dst"):
            at_src = label.endswith("src")
            await self._settle(self.src if at_src else self.dst,
                               self.src_led if at_src else self.dst_led)
            return
        raise AssertionError(f"harness does not map label {label!r}")

    def _announce(self, node, pmap, node_id: int) -> None:
        try:
            node.announce({"map": pmap.to_dict(), "node_id": node_id})
        except self.StaleError:
            pass  # the routable stale/conflict error reply

    def _batch(self, b: int) -> dict:
        entries = self.pulled or {}
        if b == 0:
            return {k: v for k, v in entries.items()
                    if k not in ("reservations", "debts")}
        return {k: v for k, v in entries.items()
                if k in ("reservations", "debts")}

    async def _acquire(self) -> None:
        node = self.src if self.client_epoch == 0 else self.dst
        store = (self.src_store if self.client_epoch == 0
                 else self.dst_store)
        verdict = node.gate(KEY)
        if verdict is None:
            res = await store.acquire(KEY, 1, CAP, 0.0)
            if res.granted:
                self.granted += 1
            return
        what, info = verdict
        if what == "envelope":
            granted, _rem = node.envelope_acquire(
                info, KEY, 1, CAP, 0.0, "bucket")
            if granted:
                self.granted += 1
            return
        # Moved: chase to the OWNER the verdict names (node id == the
        # epoch that owns in this two-node topology) — a pre-commit
        # probe at dst answers moved-back-to-src, not moved-forward.
        self.client_epoch = int(info)

    async def _settle(self, node, led) -> None:
        # Mirrors server._serve_settle: placement gate on the TENANT,
        # parked -> deferral, moved -> reroute, else ledger settle.
        verdict = node.gate(TENANT)
        if verdict is not None:
            what, info = verdict
            if what == "moved":
                self.client_epoch = int(info)   # follow the owner
            return
        res = await led.settle(RID, TENANT, 0.0)
        if res.refunded > 0:
            self.refunds += 1

    # -- final assertions ----------------------------------------------------
    def check(self) -> "list[str]":
        problems = []
        bound = CAP + self.envelope_minted
        if self.granted > bound:
            problems.append(
                f"no-double-admit: granted {self.granted} > CAP + "
                f"minted envelopes = {bound}")
        if self.refunds > 1:
            problems.append(
                f"settle-dedup: {self.refunds} refunds issued for "
                f"one rid across the src/dst ledgers")
        for led in (self.src_led, self.dst_led):
            live = led.outstanding_count()
            gauge = sum(1 for v in led.outstanding_by_tenant().values()
                        if v > 0)
            if gauge > live:
                problems.append(
                    f"outstanding-conserved: gauge {gauge} > rows "
                    f"{live}")
        if self.src.epoch > 1 or self.dst.epoch > 1 \
                or self.src.epoch < 0:
            problems.append("epoch-monotonic: epoch out of range")
        return problems


class ReservationHarness:
    """One real ReservationLedger over an InProcessBucketStore."""

    def __init__(self) -> None:
        from distributedratelimiting.redis_tpu.runtime.store import (
            InProcessBucketStore,
        )

        self.clock = _ManualClock()
        self.store = InProcessBucketStore(clock=self.clock)
        self.led = self.store.reservation_ledger(clock=self.clock)
        self.refunds = 0
        self.stash: "tuple | None" = None

    async def prepare_root(self, root) -> None:
        tb = getattr(root, "tb", CAP)
        spend = int(CAP - tb)
        if spend:
            await self.store.acquire(TENANT, spend, CAP, 0.0)

    async def step(self, label: str) -> None:
        led = self.led
        if label in ("reserve", "dup_reserve"):
            await led.reserve(RID, TENANT, KEY, 1.0, TCAP, 0.0,
                              CAP, 0.0)
            return
        if label in ("settle_refund", "dup_settle"):
            res = await led.settle(RID, TENANT, 0.0)
            if res.refunded > 0:
                self.refunds += 1
            return
        if label == "settle_debt":
            await led.settle(RID, TENANT, 2.0)
            return
        if label == "expire":
            self.clock.advance(led.default_ttl_s + 1.0)
            led.expire()
            return
        if label == "export":
            self.stash = led.export_rows(lambda t: True, tag="epoch:1")
            return
        if label in ("restore", "dup_restore"):
            if self.stash is not None:
                led.restore_rows(*self.stash)
            return
        raise AssertionError(f"harness does not map label {label!r}")

    def check(self) -> "list[str]":
        problems = []
        if self.refunds > 1:
            problems.append(
                f"settle-dedup: {self.refunds} refunds for one rid")
        led = self.led
        if led.outstanding_count() != len(led._entries):
            problems.append("outstanding-conserved: count drift")
        gauge = led.outstanding_tokens()
        true_rows = sum(e.reserved for e in led._entries.values())
        if abs(gauge - true_rows) > 1e-9:
            problems.append(
                f"outstanding-conserved: gauge {gauge} != rows "
                f"{true_rows}")
        debt = sum(led.debts().values())
        if debt - (led.debt_tokens_created
                   - led.debt_tokens_collected) > 1e-9:
            problems.append(
                f"debt-conserved: debt {debt} > created "
                f"{led.debt_tokens_created} - collected "
                f"{led.debt_tokens_collected}")
        return problems


class ConfigHarness:
    """One real ConfigState over an InProcessBucketStore; the model's
    commit micro-steps (commit1_a/commit1_b) collapse into the single
    real commit on the first of the pair."""

    A = (2.0, 0.0)
    B = (2.0, 1.0)
    C = (2.0, 3.0)

    def __init__(self) -> None:
        from distributedratelimiting.redis_tpu.runtime.liveconfig import (
            ConfigState,
            ConfigRule,
            StaleConfigError,
            ConfigError,
        )
        from distributedratelimiting.redis_tpu.runtime.store import (
            InProcessBucketStore,
        )

        self.clock = _ManualClock()
        self.store = InProcessBucketStore(clock=self.clock)
        self.cs = ConfigState()
        self.Rule = ConfigRule
        self.errors = (StaleConfigError, ConfigError)
        self.client_cfg = self.A
        self.granted = 0
        self.versions_seen = [0]
        self._committed1 = False

    async def prepare_root(self, root) -> None:
        spend = int(CAP - getattr(root, "balA", CAP))
        for _ in range(spend):
            res = await self.store.acquire(KEY, 1, *self.A)
            assert res.granted
            self.granted += 1

    async def _announce(self, payload) -> None:
        try:
            await self.cs.announce(payload, self.store)
        except self.errors:
            pass  # the routable error reply
        self.versions_seen.append(self.cs.version)

    async def step(self, label: str) -> None:
        rule1 = {"kind": "bucket", "old": list(self.A),
                 "new": list(self.B)}
        twin = {"kind": "bucket", "old": list(self.A),
                "new": list(self.C)}
        if label in ("prepare1", "dup_prepare1"):
            await self._announce({"prepare": rule1, "version": 1})
        elif label == "stale_prepare1":
            await self._announce({"prepare": rule1, "version": 1})
        elif label == "prepare_twin":
            await self._announce({"prepare": twin, "version": 1})
        elif label == "abort1":
            await self._announce({"abort": 1})
        elif label == "commit1_a":
            if not self._committed1:
                self._committed1 = True
                await self._announce({"commit": 1})
        elif label == "commit1_b":
            pass  # folded into commit1_a — the real commit is atomic
        elif label == "dup_commit1":
            await self._announce({"commit": 1})
        elif label in ("adopt2", "dup_adopt2"):
            await self._announce({"adopt": {
                "version": 2,
                "rules": [{"kind": "bucket", "old": list(self.A),
                           "new": list(self.C), "version": 2}]}})
        elif label == "stale_adopt0":
            await self._announce({"adopt": {"version": 0, "rules": []}})
        elif label == "acquire":
            fwd = self.cs.forward("bucket", *self.client_cfg)
            if fwd is not None:
                self.client_cfg = (fwd[0], fwd[1])
                return
            res = await self.store.acquire(KEY, 1, *self.client_cfg)
            if res.granted:
                self.granted += 1
        else:
            raise AssertionError(
                f"harness does not map label {label!r}")

    def check(self) -> "list[str]":
        problems = []
        if any(b < a for a, b in zip(self.versions_seen,
                                     self.versions_seen[1:])):
            problems.append(
                "config-version-monotonic: committed version went "
                f"backwards along {self.versions_seen}")
        if self.granted > CAP:
            problems.append(
                f"config-rebase-order: granted {self.granted} > "
                f"CAP {CAP} across the rewrite chain")
        return problems


class BreakerHarness:
    """One real CircuitBreaker under a manual clock. A model tick is
    0.6 s against a 1.0 s recovery timeout (2 ticks elapse it, like
    the model's TO = 2)."""

    TICK = 0.6

    def __init__(self) -> None:
        from distributedratelimiting.redis_tpu.utils.resilience import (
            BreakerConfig,
            CircuitBreaker,
        )

        self.clock = _ManualClock()
        self.br = CircuitBreaker(
            BreakerConfig(failure_threshold=2, recovery_timeout_s=1.0,
                          half_open_successes=1),
            clock=self.clock)
        self.outstanding = 0
        self.problems: "list[str]" = []

    async def prepare_root(self, root) -> None:
        return None

    async def step(self, label: str) -> None:
        br = self.br
        if label == "tick":
            self.clock.advance(self.TICK)
        elif label == "fail":
            br.record_failure()
            if br.state == "closed" and br._failures >= 2:
                self.problems.append(
                    "breaker-opens-at-threshold: threshold reached "
                    "but state is closed")
        elif label == "success":
            br.record_success()
        elif label == "allow":
            verdict = br.allow()
            if verdict == "probe":
                self.outstanding += 1
                if self.outstanding > 1:
                    # The reclaim path writes the stale holder off.
                    self.outstanding = 1
        elif label == "probe_success":
            if self.outstanding:
                self.outstanding -= 1
            was = self.br.state
            br.record_success()
            if was == "half_open" and br.state != "closed":
                self.problems.append(
                    "breaker-recloses: successful probe left state "
                    f"{br.state}")
        elif label == "probe_failure":
            if self.outstanding:
                self.outstanding -= 1
            was = br.state
            br.record_failure()
            if was == "half_open" and br.state == "closed":
                self.problems.append(
                    "breaker-failure-never-closes: failed probe "
                    "closed the breaker")
        elif label == "probe_abandon":
            if self.outstanding:
                self.outstanding -= 1
        else:
            raise AssertionError(
                f"harness does not map label {label!r}")

    def check(self) -> "list[str]":
        br = self.br
        problems = list(self.problems)
        # No-wedge: after a full recovery window, allow() must answer
        # something other than reject.
        self.clock.advance(2.0)
        if br.allow() == "reject":
            problems.append(
                "breaker-no-wedge: allow() rejects after a full "
                "recovery window")
        return problems


class FederationHarness:
    """One real FederationLedger (home, over an InProcessBucketStore)
    plus a real RegionFederation lease record under SEPARATE manual
    monotonic clocks for the two ends — a model tick is 0.6 s against
    a 1.0 s lease TTL (two ticks elapse it, the model's FED_TTL = 2).
    The wall clocks are independently skewable and must never move a
    lease lifetime."""

    TICK = 0.6
    TTL_S = 1.0
    REGION = "drlv:region"

    def __init__(self) -> None:
        from distributedratelimiting.redis_tpu.runtime.federation import (
            FederationLedger,
            RegionFederation,
        )
        from distributedratelimiting.redis_tpu.runtime.store import (
            InProcessBucketStore,
        )

        self.home_mono = _ManualClock()
        self.region_mono = _ManualClock()
        self.wall_skew = [0.0]
        self.store = InProcessBucketStore(clock=self.home_mono)
        self.led: FederationLedger = self.store.federation_ledger(
            clock=self.home_mono,
            wall=lambda: 1e9 + self.wall_skew[0],
            default_ttl_s=self.TTL_S)
        self.agent = RegionFederation(
            self.REGION, self.led,
            tenants={TENANT: (CAP, 0.0)},
            ttl_s=self.TTL_S, clock=self.region_mono,
            wall=lambda: 1e9 + self.wall_skew[0])
        self.lo = self.agent._leases[TENANT]
        self.lease_seq = 0
        self.last_lease_payload: "dict | None" = None
        self.last_renew_payload: "dict | None" = None
        self.last_reclaim_payload: "dict | None" = None
        self.admitted = 0.0
        self.slice_budget = 0.0
        self.env_budget = 0.0
        self.epochs_seen = [0]
        self.refunds_by_lease: "dict[str, int]" = {}
        self.problems: "list[str]" = []

    async def prepare_root(self, root) -> None:
        if getattr(root, "skew", False):
            self.wall_skew[0] = 3600.0

    def _note_refund(self, lease_id: str, reply: dict) -> None:
        if float(reply.get("refunded", 0.0)) > 0:
            self.refunds_by_lease[lease_id] = \
                self.refunds_by_lease.get(lease_id, 0) + 1

    async def step(self, label: str) -> None:
        led, lo = self.led, self.lo
        if label == "lease":
            self.lease_seq += 1
            payload = {"region": self.REGION,
                       "lease_id": f"L{self.lease_seq}",
                       "tenant": TENANT, "demand": 1.0,
                       "global_cap": CAP, "global_rate": 0.0,
                       "ttl_s": self.TTL_S}
            self.last_lease_payload = payload
            reply = await led.lease(payload)
            if reply.get("granted"):
                if lo.lease_id is None and self.slice_budget == 0 \
                        and not lo.applied:
                    # First grant mints the slice budget; re-leases
                    # under the same config re-mint nothing (the
                    # regional bucket's state persists).
                    self.slice_budget = float(reply["slice"][0])
                lo.lease_id = payload["lease_id"]
                lo.degraded = False
                self.agent._arm(lo, self.region_mono())
                await self.agent._adopt(TENANT, lo,
                                        int(reply["epoch"]),
                                        reply["slice"])
            return
        if label == "dup_lease":
            if self.last_lease_payload is None:
                return
            before = (led.outstanding_leases(), self.lo.epoch)
            reply = await led.lease(dict(self.last_lease_payload))
            if not reply.get("duplicate"):
                self.problems.append(
                    "idempotent-replay: a replayed OP_FED_LEASE was "
                    "not answered from the recorded grant")
            after = (led.outstanding_leases(), self.lo.epoch)
            if before != after:
                self.problems.append(
                    "idempotent-replay: a replayed OP_FED_LEASE "
                    f"changed state {before} -> {after}")
            return
        if label == "stale_reply":
            await self.agent._adopt(TENANT, lo, lo.epoch - 1,
                                    [999.0, 999.0])
            return
        if label == "home_tick":
            self.home_mono.advance(self.TICK)
            self.led.expire()
            return
        if label == "region_tick":
            self.region_mono.advance(self.TICK)
            if (lo.lease_id is not None and not lo.degraded
                    and self.region_mono() >= lo.expires_mono):
                await self.agent._degrade(TENANT, lo)
                self.env_budget = float(
                    (lo.applied or (1.0, 0.0))[0])
            return
        if label in ("renew", "dup_renew"):
            if label == "renew" or self.last_renew_payload is None:
                if lo.lease_id is None:
                    return
                payload = {"region": self.REGION,
                           "lease_id": lo.lease_id, "tenant": TENANT,
                           "total": self.admitted, "demand": 1.0}
                self.last_renew_payload = payload
            else:
                payload = dict(self.last_renew_payload)
            reply = await led.renew(payload)
            self._note_refund(payload["lease_id"], reply)
            if reply.get("outcome") == "ok" and label == "renew":
                self.agent._arm(lo, self.region_mono())
                lo.degraded = False
                await self.agent._adopt(TENANT, lo,
                                        int(reply.get("epoch", 0)),
                                        reply.get("slice")
                                        or [lo.slice_cap,
                                            lo.slice_rate])
            elif reply.get("outcome") in ("expired", "unknown") \
                    and label == "renew":
                lo.lease_id = None
            return
        if label in ("reclaim", "dup_reclaim"):
            if label == "reclaim":
                if lo.lease_id is None:
                    return
                payload = {"region": self.REGION,
                           "lease_id": lo.lease_id, "tenant": TENANT,
                           "total": self.admitted}
                self.last_reclaim_payload = payload
            else:
                if self.last_reclaim_payload is None:
                    return
                payload = dict(self.last_reclaim_payload)
            reply = await led.reclaim(payload)
            self._note_refund(payload["lease_id"], reply)
            if label == "dup_reclaim" \
                    and reply.get("outcome") not in ("duplicate",
                                                     "unknown"):
                self.problems.append(
                    "fed-reclaim-idempotent: a replayed "
                    "OP_FED_RECLAIM re-executed "
                    f"({reply.get('outcome')})")
            if label == "reclaim" \
                    and reply.get("outcome") in ("reclaimed",
                                                 "duplicate"):
                lo.lease_id = None
            return
        if label == "admit":
            if lo.degraded:
                if self.env_budget >= 1:
                    self.env_budget -= 1
                    self.admitted += 1
            elif lo.lease_id is not None and self.slice_budget >= 1:
                self.slice_budget -= 1
                self.admitted += 1
            return
        if label == "skew":
            self.wall_skew[0] = 3600.0
            # Skew must not move lease lifetimes: with NO monotonic
            # advance, nothing new may expire.
            before = self.led.leases_expired
            self.led.expire()
            if self.led.leases_expired != before:
                self.problems.append(
                    "fed-no-skew-extension: a wall-clock skew alone "
                    "expired a lease")
            return
        raise AssertionError(f"harness does not map label {label!r}")

    def check(self) -> "list[str]":
        problems = list(self.problems)
        for lease_id, n in self.refunds_by_lease.items():
            if n > 1:
                problems.append(
                    f"fed-reclaim-idempotent: {n} heal refunds "
                    f"issued for lease {lease_id}")
        # Home accounting: every charge landed in the bucket or in
        # debt (clamped refunds can only UNDER-credit — conservative).
        bal = self.store.peek_blocking(TENANT, CAP, 0.0)
        spent = CAP - bal
        debt = sum(self.led.debts().values())
        if self.led.charged_tokens - self.led.refunded_tokens \
                > spent + debt + 1e-9:
            problems.append(
                "fed-global-bound: home charged "
                f"{self.led.charged_tokens} - refunded "
                f"{self.led.refunded_tokens} but only {spent} spent "
                f"+ {debt} debt are accounted")
        return problems


HARNESSES = {
    "migration": MigrationHarness,
    "reservation": ReservationHarness,
    "config": ConfigHarness,
    "breaker": BreakerHarness,
    "federation": FederationHarness,
}


def replay(world: str, trace, root=None) -> ReplayReport:
    """Replay ``trace`` (a list of action labels) for ``world`` against
    the real implementation and evaluate the invariants. For product
    worlds, ``left:``/``right:`` labels route to the two harnesses."""
    if "x" in world and world not in HARNESSES:
        lname, _, rname = world.partition("x")
        left = HARNESSES[lname]()
        right = HARNESSES[rname]()

        async def run_product():
            await left.prepare_root(root[0] if root else None)
            await right.prepare_root(root[1] if root else None)
            for label in trace:
                side, _, inner = label.partition(":")
                await (left if side == "left" else right).step(inner)
            return left.check() + right.check()

        problems = asyncio.run(run_product())
        return ReplayReport(ok=not problems, detail="; ".join(problems),
                            steps=len(trace))

    h = HARNESSES[world]()

    async def run():
        await h.prepare_root(root)
        for label in trace:
            await h.step(label)
        return h.check()

    problems = asyncio.run(run())
    return ReplayReport(
        ok=not problems, detail="; ".join(problems) or "clean",
        granted=getattr(h, "granted", 0),
        refunds=getattr(h, "refunds", 0), steps=len(trace))
