"""drl-verify — exhaustive protocol model checking + lock-order
analysis for the repo's five distributed state machines.

PRs 6–15 stacked five interacting protocols — placement epochs
(``runtime/placement.py``), config versions (``runtime/liveconfig.py``),
reservation rid-idempotency (``runtime/reservations.py``), the WAN
federation lease machine (``runtime/federation.py``), and the
breaker lifecycle (``utils/resilience.py``) — whose safety arguments
lived in prose (docs/DESIGN.md §12–§20) and in seeded soaks that
sample a vanishing fraction of interleavings. This package checks the
*protocols* themselves:

1. **Extract** (:mod:`.extract`) small formal models from the live
   code via ``ast`` — guard comparisons, dedup probes, the breaker's
   transition table, the client's ``_IDEMPOTENT_OPS`` classification —
   so the models can never silently drift from the implementation.
2. **Explore** (:mod:`.machines` + :mod:`.explorer`) their product
   exhaustively under an adversarial scheduler (message loss and
   duplication, idempotent retry, coordinator crash, window expiry,
   concurrent reshape × live-limit mutation) checking machine-readable
   invariants; every violation carries a minimized counterexample
   trace AND a generated pytest (:mod:`.replay`) that replays it
   against the real in-process implementation
   (:mod:`.replay_harness`) — the model-to-code gap closes in both
   directions.
3. **Lock order** (:mod:`.lockorder`): one static lock-acquisition
   graph across Python (``with``/``async with`` scopes) and
   ``native/frontend.cc`` (``lock_guard`` sites by mutex type, the
   ``fe_t0_retire`` all-slices combined section), failing on cycles
   and on non-canonical slice sweeps.

CLI: ``python -m tools.drl_verify`` — exit 0 on the live tree, 1 with
traces on violation, 2 on a checker/extraction crash (never a fake
'clean'). ``make verify-model`` wires it into ``make check`` with
bounded, LOUDLY-logged state/depth caps. Runbook:
docs/OPERATIONS.md §15; modeling contract: docs/DESIGN.md §19."""

from __future__ import annotations

import dataclasses
import pathlib

__all__ = ["run_verify", "VerifyResult"]

#: Exploration bounds for `make check` (CLI flags override): the five
#: base worlds complete EXHAUSTIVELY far below these; the migration ×
#: config product is cut off at the cap — reported, never silent.
DEFAULT_MAX_STATES = 400_000
DEFAULT_PRODUCT_STATES = 150_000
DEFAULT_MAX_DEPTH = 64


@dataclasses.dataclass
class VerifyResult:
    results: list          # per-world ExploreResult
    violations: list       # flattened Violation list
    lock_findings: list    # lockorder Finding list
    unmodeled: "list[str]"
    facts: object

    @property
    def total_states(self) -> int:
        return sum(r.states for r in self.results)

    @property
    def invariants_checked(self) -> "set[str]":
        out: set = set()
        for r in self.results:
            out |= set(r.invariants)
        return out

    @property
    def clean(self) -> bool:
        return (not self.violations and not self.lock_findings
                and not self.unmodeled)


def run_verify(root: "pathlib.Path | None" = None, *,
               max_states: int = DEFAULT_MAX_STATES,
               product_states: int = DEFAULT_PRODUCT_STATES,
               max_depth: int = DEFAULT_MAX_DEPTH,
               include_product: bool = True,
               include_lockorder: bool = True,
               log=lambda msg: None) -> VerifyResult:
    """Run the whole suite against ``root`` (default: this repo)."""
    from tools.drl_verify import lockorder
    from tools.drl_verify.explorer import explore
    from tools.drl_verify.extract import extract_facts
    from tools.drl_verify.machines import (
        all_worlds,
        unmodeled_idempotent_ops,
    )

    root = pathlib.Path(root) if root else \
        pathlib.Path(__file__).resolve().parents[2]
    facts = extract_facts(root)
    unmodeled = unmodeled_idempotent_ops(facts)

    results = []
    violations = []
    for world in all_worlds(facts, include_product=include_product):
        cap = (product_states if "x" in world.name else max_states)
        r = explore(world, max_states=cap, max_depth=max_depth)
        results.append(r)
        violations.extend(r.violations)
        note = ""
        if r.truncated_states:
            note = f" [CAPPED at max_states={cap}]"
        elif r.truncated_depth:
            note = f" [CAPPED at max_depth={max_depth}]"
        else:
            note = " [exhaustive]"
        log(f"world {world.name}: {r.states} states, "
            f"{r.transitions} transitions, depth {r.depth}, "
            f"{len(r.violations)} violation(s){note}")

    lock_findings = lockorder.check(root) if include_lockorder else []
    if include_lockorder:
        log(f"lock-order: {len(lock_findings)} finding(s)")
    return VerifyResult(results, violations, lock_findings,
                        unmodeled, facts)
