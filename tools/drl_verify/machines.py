"""The formal models — five interacting worlds built from extracted
facts (:mod:`tools.drl_verify.extract`), explored exhaustively by
:mod:`tools.drl_verify.explorer`.

Each world is a small deterministic labeled transition system whose
*behavior* is parameterized by the facts extracted from the live code:
a guard the implementation dropped is a guard the model drops, and the
exploration then produces the counterexample that guard existed to
prevent. The adversarial scheduler is the action alphabet itself —
message duplication (every ``dup_*`` label), loss (the scheduler simply
never delivering), coordinator crash, handoff-window expiry,
stale/conflicting control frames, and client traffic interleaved
anywhere.

Worlds and their invariants (names are the machine-readable contract —
docs/DESIGN.md §19 maps each back to the prose it formalizes):

- **migration** — the product of src/dst :class:`NodePlacementState`
  machines, the exactly-once import ledger, a crash-able coordinator,
  a reservation row riding the handoff, and a stale-mapped client:
  ``no-double-admit``, ``epoch-monotonic``, ``idempotent-replay``,
  ``abort-restores-old-epoch``, ``settle-dedup``,
  ``res-survives-migration``, ``outstanding-conserved``,
  ``same-epoch-map-immutable``.
- **config** — one node's :class:`ConfigState` with a stale-cached
  client; the commit's gate-flip and rebase are separate micro-steps
  so traffic interleaves exactly where DESIGN.md §13's epsilon lives:
  ``config-version-monotonic``, ``config-rebase-order``,
  ``same-version-rule-immutable``, ``idempotent-replay``.
- **reservation** — one :class:`ReservationLedger` with debt pay-down,
  TTL expiry, and the migration export/restore lane with tagged debt
  rows: ``settle-dedup``, ``debt-conserved``,
  ``outstanding-conserved``, ``idempotent-replay``.
- **federation** — the WAN lease machine (one home
  :class:`FederationLedger`, one region agent, independent monotonic
  clocks, wall-clock skew, partition/heal):
  ``fed-lease-monotonic``, ``fed-no-skew-extension``,
  ``fed-global-bound``, ``fed-reclaim-idempotent``,
  ``idempotent-replay``.
- **breaker** — the :class:`CircuitBreaker` rebuilt from its extracted
  transition table: ``breaker-single-probe``,
  ``breaker-failure-never-closes``, ``breaker-opens-at-threshold``,
  ``breaker-recloses``, ``breaker-no-wedge``.

``idempotent-replay`` is the op-classification bridge: every op in the
extracted ``_IDEMPOTENT_OPS`` must either be a pure read
(:data:`READ_OPS`) or be covered by a ``dup_*`` action in some world
(:data:`MODELED_OPS`); an op added to the set with no replay model is
itself a violation (``idempotent-unmodeled``) — the set cannot grow
past what has been verified.

Token arithmetic is exact and tiny (CAP = 2, one envelope unit, no
refill), which makes the over-admission bounds *equalities at the
boundary*: the clean tree explores tight against them, and any dropped
guard steps past. What the models deliberately do NOT cover (DESIGN.md
§19): refill-rate interactions, the accepted init-on-miss self-heal
over-admission of a crashed migration's never-exported keys (bounded
separately per root), and wall-clock-dependent TTL arithmetic."""

from __future__ import annotations

from collections import namedtuple

from tools.drl_verify.extract import Facts

__all__ = ["MigrationWorld", "ConfigWorld", "ReservationWorld",
           "FederationWorld", "BreakerWorld", "READ_OPS",
           "MODELED_OPS", "all_worlds", "unmodeled_idempotent_ops",
           "CAP", "ENV"]

#: Idempotent ops that are pure reads — replay-safe by construction
#: (their server handlers mutate nothing; the wire fuzz pins replies).
READ_OPS = frozenset({"OP_PEEK", "OP_PING", "OP_METRICS",
                      "OP_PLACEMENT", "OP_AUDIT"})

#: Idempotent ops whose replay safety is *explored*: each maps to the
#: world whose dup_* labels exercise it. Adding an op to
#: _IDEMPOTENT_OPS without extending this table fails verification.
MODELED_OPS = {
    "OP_PLACEMENT_ANNOUNCE": "migration",
    "OP_MIGRATE_PULL": "migration",
    "OP_MIGRATE_PUSH": "migration",
    "OP_CONFIG": "config",
    "OP_RESERVE": "reservation",
    "OP_SETTLE": "reservation",
    "OP_FED_LEASE": "federation",
    "OP_FED_RENEW": "federation",
    "OP_FED_RECLAIM": "federation",
}


def unmodeled_idempotent_ops(facts: Facts) -> "list[str]":
    return sorted(op for op in facts.idempotent_ops
                  if op not in READ_OPS and op not in MODELED_OPS)


#: One key, CAP tokens, no refill: the over-admission bounds are then
#: exact — see each world's ``_post_checks``.
CAP = 2
ENV = 1


# ===========================================================================
# Migration world
# ===========================================================================

MigState = namedtuple("MigState", [
    "se", "de", "ce",        # adopted epochs: src, dst, client (0|1)
    "h",                     # src handoff: None | (export, envelope_left)
    "tomb",                  # src local-expiry tombstone for epoch 1
    "applied",               # dst import ledger: applied batch ids
    "acked",                 # coordinator's view of applied batches
    "db", "sb",              # balances (-1 = no table entry yet)
    "g",                     # total granted tokens
    "eb",                    # envelope tokens minted (export episodes)
    "co",                    # coordinator: idle|pulled|cdst|done|aborted
    "att", "cr",             # attempt (1|2), coordinator crashed
    "px",                    # coordinator's pulled bucket row (-1 = none)
    "pr",                    # coordinator's pulled copy carries the res row
    "rsrc", "rstash", "rdst",  # reservation row: src ledger/stash/dst
    "ssrc", "sdst",          # settled-record flags per ledger
    "rf", "og",              # refunds issued, dst outstanding gauge
    "rff",                   # reservation stash forfeited (expiry abort)
    "fresh", "res0",         # root flags: key untouched / res existed
])


class MigrationWorld:
    """Product of the two placement machines under the adversarial
    scheduler. Batch 0 of the handoff carries the bucket row, batch 1
    the reservation row — so a partially-pushed, aborted, retried
    migration exercises the exactly-once ledger the way PR 6's shipped
    bug did."""

    name = "migration"
    invariants = ("no-double-admit", "epoch-monotonic",
                  "idempotent-replay", "abort-restores-old-epoch",
                  "settle-dedup", "res-survives-migration",
                  "outstanding-conserved", "same-epoch-map-immutable")

    def __init__(self, facts: Facts) -> None:
        self.f = facts

    def init_states(self):
        # Roots cover every pre-migration traffic history: spent 0..CAP
        # plus the never-touched key (init-on-miss at first acquire),
        # each with and without an outstanding reservation.
        for sb in list(range(CAP + 1)) + [-1]:
            for res in (True, False):
                yield MigState(
                    se=0, de=0, ce=0, h=None, tomb=False,
                    applied=frozenset(), acked=frozenset(),
                    db=-1, sb=sb, g=(CAP - sb) if sb >= 0 else 0,
                    eb=0, co="idle", att=1, cr=False, px=-1, pr=False,
                    rsrc=res, rstash=False, rdst=False,
                    ssrc=False, sdst=False, rf=0, og=0, rff=False,
                    fresh=sb < 0, res0=res)

    def _bound(self, s: MigState) -> int:
        # Grants ≤ CAP + minted envelopes — DESIGN.md §12's epsilon
        # with budget = ENV and no fill term. A never-touched key adds
        # one accepted init-on-miss budget: a crashed migration whose
        # handoff expired can re-mint the key's FIRST budget on both
        # sides (nothing was exported, so nothing was debited — the
        # reference's init-on-miss self-heal posture, documented as
        # out of scope in DESIGN.md §19).
        return CAP * (2 if s.fresh else 1) + s.eb

    # -- action alphabet ----------------------------------------------------
    def labels(self, s: MigState):
        out = []
        if not s.cr:
            out.append("crash")
            if s.co == "idle" and not s.tomb:
                out.append("pull")
            if s.co == "pulled":
                for b in (0, 1):
                    if b not in s.acked:
                        out.append(f"push_{b}")
                if s.acked == frozenset((0, 1)):
                    out.append("commit_dst")
                out.append("coord_abort")
            if s.co == "cdst":
                out.append("commit_src")
            if s.co == "aborted" and s.att == 1:
                out.append("retry")
        if s.h is not None:
            out.append("expire")
        # Network duplication — the idempotent-replay probes.
        if s.h is not None or s.tomb:
            out.append("dup_pull")
        for b in (0, 1):
            if b in s.applied:
                out.append(f"dup_push_{b}")
        if s.de == 1:
            out += ["dup_commit_dst", "twin_announce_dst"]
        if s.se == 1:
            out.append("dup_commit_src")
        if s.se == 1 or s.de == 1:
            out += ["stale_announce_src", "stale_announce_dst"]
        # Client traffic: acquires, a placement refresh, settles
        # (relayed — deliverable to either node) and their replays.
        if s.g < self._bound(s) + 1:   # one step past the bound suffices
            out.append("acquire")
        if s.ce < s.de:
            out.append("refresh")
        if s.res0:
            out += ["settle_src", "settle_dst"]
            if s.ssrc:
                out.append("dup_settle_src")
            if s.sdst:
                out.append("dup_settle_dst")
        return out

    # -- transition semantics ----------------------------------------------
    def apply(self, s: MigState, label: str):
        f = self.f
        viols: list = []
        before = s

        def dup_changed(op: str, what: str) -> None:
            viols.append((
                "idempotent-replay",
                f"replayed {op} frame changed state: {what} "
                f"(classified idempotent at {f.remote_file}:"
                f"{f.idempotent_ops.get(op, 0)})", op))

        if label == "pull":
            s = self._pull(s)._replace(
                co="pulled", acked=frozenset())
            if s.pr:
                s = s._replace(rsrc=False, rstash=True)

        elif label == "dup_pull":
            # The coordinator ignores the dup reply, so px/pr keep the
            # original pull's content — and an illegitimate re-mint
            # does NOT grow the envelope bound (eb), so the grants it
            # enables land past it.
            if s.h is not None:
                if not f.pull_cached:
                    s = self._pull(s)._replace(px=s.px, pr=s.pr,
                                               eb=s.eb)
                    dup_changed("OP_MIGRATE_PULL",
                                "re-exported instead of serving the "
                                "cached handoff — a second source "
                                "debit and envelope")
            elif s.tomb:
                if not f.pull_tombstone_guard:
                    s = self._pull(s)._replace(px=s.px, pr=s.pr,
                                               eb=s.eb, tomb=False)
                    dup_changed("OP_MIGRATE_PULL",
                                "re-exported after a local expiry "
                                "abort (tombstone ignored) — the "
                                "aborted export is charged again")

        elif label.startswith("push_") or label.startswith("dup_push_"):
            dup = label.startswith("dup_")
            b = int(label[-1])
            if b in s.applied:
                if not f.push_dedup:
                    s = self._apply_batch(s, b)
                    if dup and s != before:
                        dup_changed("OP_MIGRATE_PUSH",
                                    f"batch {b} imported twice — the "
                                    "(epoch, batch) dedup is gone")
            else:
                s = self._apply_batch(s, b)._replace(
                    applied=s.applied | {b})
            if not dup:
                s = s._replace(acked=s.acked | {b})

        elif label == "commit_dst":
            s = s._replace(de=1, co="cdst")

        elif label == "commit_src":
            # Commit unparks: handoff dropped, stashed rows live at the
            # destination now, tombstone cleared.
            s = s._replace(se=1, h=None, rstash=False, tomb=False,
                           co="done")
            if s.res0 and not s.rff and not (s.ssrc or s.sdst) \
                    and not s.rdst and not s.rsrc:
                viols.append((
                    "res-survives-migration",
                    "migration committed but the outstanding "
                    "reservation row reached no ledger — its settle "
                    "answers 'unknown' and the hold is silently lost",
                    "lost-row"))

        elif label in ("dup_commit_dst", "dup_commit_src"):
            pass  # same-epoch same-map re-announce: idempotent always

        elif label in ("stale_announce_src", "stale_announce_dst"):
            node = "se" if label.endswith("src") else "de"
            if getattr(s, node) == 1 and not f.announce_stale_guard:
                s = s._replace(**{node: 0})
                dup_changed("OP_PLACEMENT_ANNOUNCE",
                            "a stale epoch-0 announce was adopted "
                            "over epoch 1")

        elif label == "twin_announce_dst":
            if not f.announce_conflict_guard:
                viols.append((
                    "same-epoch-map-immutable",
                    "a conflicting placement map was adopted at an "
                    "already-committed epoch — split-brain slot "
                    "ownership (guard at "
                    f"{f.announce_conflict_guard.file}:"
                    f"{f.announce_conflict_guard.line} missing)",
                    "twin"))
                return None, viols

        elif label == "coord_abort":
            s = self._src_abort(s, viols, tombstone=False)
            if f.abort_resets_push_ledger:
                s = s._replace(applied=frozenset())
            # The destination half of the abort: imported reservation
            # rows leave the dst ledger again (their surviving home is
            # the restored source stash / the retry's re-export).
            if f.abort_drops_imported_res and s.rdst:
                s = s._replace(rdst=False, og=max(0, s.og - 1))
            s = s._replace(co="aborted")

        elif label == "expire":
            s = self._src_abort(s, viols, tombstone=True)

        elif label == "retry":
            s = s._replace(att=2, co="idle", acked=frozenset(),
                           px=-1, pr=False)

        elif label == "crash":
            s = s._replace(cr=True)

        elif label == "acquire":
            s = self._acquire(s, viols)

        elif label == "refresh":
            s = s._replace(ce=max(s.ce, s.de))

        elif label in ("settle_src", "settle_dst"):
            s = self._settle(s, at_src=label.endswith("src"))

        elif label in ("dup_settle_src", "dup_settle_dst"):
            if not f.settle_dedup:
                dup_changed("OP_SETTLE",
                            "replayed settle answered 'unknown' "
                            "instead of replaying the recorded "
                            "reconciliation — the settled-rid record "
                            "is gone")

        else:  # pragma: no cover - label/apply drift is a checker bug
            raise AssertionError(f"unknown label {label!r}")

        self._post_checks(before, s, viols)
        return s, viols

    # -- helpers ------------------------------------------------------------
    def _pull(self, s: MigState) -> MigState:
        """Export + park + source debit (placement.pull): the envelope
        is withheld from the export and stays as the source's
        authoritative residual; an entry-less key exports no row."""
        if s.sb < 0:
            return s._replace(h=(-1, 0), px=-1, pr=s.rsrc)
        env = min(ENV, s.sb)
        return s._replace(h=(s.sb - env, env), sb=env,
                          px=s.sb - env, pr=s.rsrc, eb=s.eb + env)

    def _apply_batch(self, s: MigState, b: int) -> MigState:
        if b == 0:
            if s.px < 0:
                return s  # no bucket row in this attempt's export
            # Saturating import: a fresh key initializes full and
            # CAP - export is debited away, landing exactly export.
            db = s.px if s.db < 0 else max(0, s.db - (CAP - s.px))
            return s._replace(db=db)
        if s.pr:
            if s.rdst or s.sdst:
                if self.f.restore_skip_known:
                    return s
                return s._replace(og=s.og + 1)  # gauge double-count
            return s._replace(rdst=True, og=s.og + 1)
        return s

    def _src_abort(self, s: MigState, viols: list,
                   tombstone: bool) -> MigState:
        had_stash = s.rstash
        s = s._replace(h=None, tomb=tombstone)
        if not had_stash:
            return s
        if tombstone:
            # Expiry abort: the coordinator is presumed dead and the
            # commit may already have reached the destination, so the
            # FIXED code forfeits the stash (conservative — settles
            # answer 'unknown'). The pre-fix code restored it, double-
            # homing the rid: the model follows the extracted fact and
            # the settle-dedup invariant catches the regression.
            if self.f.expiry_abort_forfeits:
                return s._replace(rstash=False, rff=True)
            return s._replace(rstash=False, rsrc=True)
        if self.f.abort_restores_reservations:
            return s._replace(rstash=False, rsrc=True)
        s = s._replace(rstash=False)
        viols.append((
            "abort-restores-old-epoch",
            "coordinator abort dropped the handoff but did not "
            "restore the exported reservation rows — the hold "
            "vanished with the dead migration (restore at "
            f"{self.f.abort_restores_reservations.file}:"
            f"{self.f.abort_restores_reservations.line} missing)",
            "res-stash"))
        return s

    def _acquire(self, s: MigState, viols: list) -> MigState:
        if s.ce == 0:
            if s.h is not None:                      # parked: envelope
                export, env = s.h
                if env > 0:
                    return s._replace(h=(export, env - 1), g=s.g + 1)
                return s
            if s.se == 1:                            # moved: chase once
                return s._replace(ce=1)
            sb = CAP if s.sb < 0 else s.sb           # init-on-miss
            if sb > 0:
                return s._replace(sb=sb - 1, g=s.g + 1)
            return s._replace(sb=sb)
        if s.de == 1:
            if s.db < 0:
                # Init-on-miss at the NEW owner: legitimate only when
                # the committed attempt exported no bucket row. If a
                # row was exported and the destination still has no
                # entry, the exactly-once import silently dropped it —
                # the PR-6 over-admission bug class, caught here.
                if s.px >= 0:
                    viols.append((
                        "no-double-admit",
                        "destination served init-on-miss at full "
                        "capacity for a key whose bucket row WAS "
                        "exported — the import ledger silently "
                        "dropped the retried batch (abort must reset "
                        "the per-epoch dedup set: "
                        f"{self.f.abort_resets_push_ledger.file}:"
                        f"{self.f.abort_resets_push_ledger.line})",
                        "dropped-import"))
                return s._replace(db=CAP - 1, g=s.g + 1)
            if s.db > 0:
                return s._replace(db=s.db - 1, g=s.g + 1)
            return s
        return s

    def _settle(self, s: MigState, at_src: bool) -> MigState:
        if at_src:
            if s.h is not None:        # parked: settle defers (retried)
                return s
            if s.se == 1:              # moved: client re-routes
                return s._replace(ce=1)
            if s.ssrc:
                return s
            if s.rsrc:
                return s._replace(rsrc=False, ssrc=True, rf=s.rf + 1)
            return s                   # unknown rid: counted no-op
        # At dst: the placement gate rejects until dst owns the tenant.
        if s.de != 1:
            return s
        if s.sdst:
            return s
        if s.rdst:
            return s._replace(rdst=False, sdst=True, rf=s.rf + 1,
                              og=max(0, s.og - 1))
        return s

    def _post_checks(self, old: MigState, new: MigState,
                     viols: list) -> None:
        if new.g > self._bound(new):
            viols.append((
                "no-double-admit",
                f"granted {new.g} tokens against a bound of "
                f"{self._bound(new)} (CAP {CAP} + envelopes {new.eb}"
                f"{' + accepted first-touch budget' if new.fresh else ''}"
                ") — DESIGN.md §12 envelope epsilon exceeded",
                "bound"))
        if new.se < old.se or new.de < old.de:
            viols.append((
                "epoch-monotonic",
                "an observer's adopted placement epoch went backwards "
                f"(src {old.se}->{new.se}, dst {old.de}->{new.de}); "
                f"stale-announce guard at "
                f"{self.f.announce_stale_guard.file}:"
                f"{self.f.announce_stale_guard.line}", "epoch"))
        if new.rf > 1:
            viols.append((
                "settle-dedup",
                f"{new.rf} refunds issued for one reservation id — a "
                "relayed/replayed settle reconciled twice", "refunds"))
        rows = 1 if new.rdst else 0
        if new.og != rows:
            viols.append((
                "outstanding-conserved",
                f"destination outstanding gauge {new.og} != live rows "
                f"{rows} — a re-delivered restore double-counted the "
                "hold", "gauge"))


# ===========================================================================
# Config world
# ===========================================================================

CfgState = namedtuple("CfgState", [
    "v",          # committed config version (0..2)
    "staged",     # staged (version, rule) pairs, frozenset
    "rules",      # committed forwarding map, tuple of (old, new)
    "balA", "balB", "balC",  # -1 = table untouched (init-on-miss full)
    "exported",   # tables whose rebase export ran, frozenset
    "cph",        # mid-commit micro-phase: None | "gated" | "rebased"
    "ccl",        # client's cached config
    "g",          # total granted
])

_SNAP2 = (("A", "C"),)   # the v2 adopt snapshot's rule set


class ConfigWorld:
    """One node's ConfigState (two-phase mutation, adopt, the serving
    gate) against a stale-cached client. ``commit1_a``/``commit1_b``
    split the commit into its gate-flip and rebase halves in whichever
    order the extracted ``commit_gate_first`` fact says the code runs
    them — the adversary interleaves acquires in between."""

    name = "config"
    invariants = ("config-version-monotonic", "config-rebase-order",
                  "same-version-rule-immutable", "idempotent-replay")

    def __init__(self, facts: Facts) -> None:
        self.f = facts

    def init_states(self):
        for spent in range(CAP + 1):
            yield CfgState(v=0, staged=frozenset(), rules=(),
                           balA=CAP - spent, balB=-1, balC=-1,
                           exported=frozenset(), cph=None, ccl="A",
                           g=spent)

    def labels(self, s: CfgState):
        if s.cph is not None:
            return ["commit1_b", "acquire"]
        out = []
        if s.v == 0 and (1, "AB") not in s.staged:
            out.append("prepare1")
        if (1, "AB") in s.staged:
            out += ["commit1_a", "abort1", "prepare_twin",
                    "dup_prepare1"]
        if s.v >= 1:
            out += ["dup_commit1", "stale_adopt0", "stale_prepare1"]
        if s.v < 2:
            out.append("adopt2")
        else:
            out.append("dup_adopt2")
        if s.g < CAP + 2:
            out.append("acquire")
        return out

    def apply(self, s: CfgState, label: str):
        f = self.f
        viols: list = []
        before = s

        def dup_changed(what: str) -> None:
            viols.append((
                "idempotent-replay",
                "replayed OP_CONFIG frame changed state: " + what +
                f" (classified idempotent at {f.remote_file}:"
                f"{f.idempotent_ops.get('OP_CONFIG', 0)})",
                "OP_CONFIG"))

        if label == "prepare1":
            s = s._replace(staged=s.staged | {(1, "AB")})
        elif label == "dup_prepare1":
            pass  # same rule at same version: idempotent by contract
        elif label == "stale_prepare1":
            if not f.prepare_stale_guard:
                s = s._replace(staged=s.staged | {(1, "AB")})
                viols.append((
                    "config-version-monotonic",
                    "a stale prepare (version already committed past) "
                    "was accepted instead of raising StaleConfigError "
                    f"(guard at {f.prepare_stale_guard.file}:"
                    f"{f.prepare_stale_guard.line})", "stale-prepare"))
        elif label == "prepare_twin":
            if not f.prepare_conflict_guard:
                viols.append((
                    "same-version-rule-immutable",
                    "a conflicting rule was staged over an existing "
                    "one at the same version — two coordinators' "
                    "mutations silently merged (guard at "
                    f"{f.prepare_conflict_guard.file}:"
                    f"{f.prepare_conflict_guard.line})", "twin"))
                return None, viols
        elif label == "abort1":
            s = s._replace(staged=s.staged - {(1, "AB")})
        elif label == "commit1_a":
            if s.v >= 1:
                pass  # stale commit: version <= committed -> no-op
            elif f.commit_gate_first:
                s = s._replace(rules=s.rules + (("A", "B"),), v=1,
                               staged=s.staged - {(1, "AB")},
                               cph="gated")
            else:
                s = self._rebase(s)._replace(cph="rebased")
        elif label == "commit1_b":
            if s.cph == "gated":
                s = self._rebase(s)._replace(cph=None)
            else:
                s = s._replace(rules=s.rules + (("A", "B"),), v=1,
                               staged=s.staged - {(1, "AB")},
                               cph=None)
        elif label == "dup_commit1":
            if not f.commit_idempotent_guard:
                s = self._rebase(s)
                if s != before:
                    dup_changed("the rebase ran a second time")
        elif label == "adopt2":
            s = s._replace(v=2, rules=_SNAP2)
        elif label == "dup_adopt2":
            pass  # version <= committed: no-op
        elif label == "stale_adopt0":
            if not f.adopt_stale_guard:
                s = s._replace(v=0, rules=())
        elif label == "acquire":
            s = self._acquire(s, viols)
        else:  # pragma: no cover
            raise AssertionError(f"unknown label {label!r}")

        if s.v < before.v:
            viols.append((
                "config-version-monotonic",
                f"committed config version went backwards "
                f"({before.v} -> {s.v}); adopt stale-guard at "
                f"{f.adopt_stale_guard.file}:"
                f"{f.adopt_stale_guard.line}", "version"))
        return s, viols

    def _rebase(self, s: CfgState) -> CfgState:
        spent = CAP - (CAP if s.balA < 0 else s.balA)
        balB = max(0, (CAP if s.balB < 0 else s.balB) - spent)
        return s._replace(balB=balB, exported=s.exported | {"A"})

    def _acquire(self, s: CfgState, viols: list) -> CfgState:
        cfg = s.ccl
        fwd = dict(s.rules)
        seen = set()
        while cfg in fwd and cfg not in seen:
            seen.add(cfg)
            cfg = fwd[cfg]
        if cfg != s.ccl:
            return s._replace(ccl=cfg)   # one chase, then cached
        bal_field = "bal" + cfg
        bal = getattr(s, bal_field)
        bal = CAP if bal < 0 else bal
        if bal <= 0:
            return s._replace(**{bal_field: bal})
        if cfg in s.exported:
            viols.append((
                "config-rebase-order",
                f"a grant landed on retired table {cfg} AFTER its "
                "balance was exported by the rebase — the spent carry "
                "missed it (the gate must flip before the export; "
                f"order fact at {self.f.commit_gate_first.file}:"
                f"{self.f.commit_gate_first.line})", "rebase-order"))
        return s._replace(**{bal_field: bal - 1, "g": s.g + 1})


# ===========================================================================
# Reservation world
# ===========================================================================

ResState = namedtuple("ResState", [
    "out", "set_", "exp",    # row outstanding / settled-recorded / expired
    "tb", "kb",              # tenant / key balances (0..CAP)
    "debt", "dcre", "dcol",  # tenant debt, created, collected (0..3)
    "og",                    # outstanding gauge
    "stash", "dstash",       # exported row flag, exported debt amount
    "tag_seen",              # tagged debt delivery seen at this ledger
    "restored",              # a restore delivery has been processed
    "rf",                    # refunds issued for the rid
])


class ReservationWorld:
    """One ledger, one rid, estimate 1 token: reserve/settle/expire
    with debt pay-down, plus the migration export/restore lane with
    tagged debt rows and duplicate restore deliveries."""

    name = "reservation"
    invariants = ("settle-dedup", "debt-conserved",
                  "outstanding-conserved", "idempotent-replay")

    def __init__(self, facts: Facts) -> None:
        self.f = facts

    def init_states(self):
        for tb in range(CAP + 1):
            yield ResState(out=False, set_=False, exp=False,
                           tb=tb, kb=CAP, debt=0, dcre=0, dcol=0,
                           og=0, stash=False, dstash=0, tag_seen=False,
                           restored=False, rf=0)

    def labels(self, s: ResState):
        out = ["reserve"]
        if s.out:
            out += ["settle_refund", "settle_debt", "expire"]
        if s.out or s.debt:
            if not s.stash and not s.dstash:
                out.append("export")
        if s.set_:
            out.append("dup_settle")
        if s.out or s.set_:
            out.append("dup_reserve")
        if s.stash or s.dstash:
            out.append("restore")
        if s.restored:
            out.append("dup_restore")
        return out

    def apply(self, s: ResState, label: str):
        f = self.f
        viols: list = []

        def dup_changed(op: str, what: str) -> None:
            viols.append((
                "idempotent-replay",
                f"replayed {op} frame changed state: {what} "
                f"(classified idempotent at {f.remote_file}:"
                f"{f.idempotent_ops.get(op, 0)})", op))

        if label in ("reserve", "dup_reserve"):
            if s.out or s.set_:
                if not f.reserve_dedup:
                    ns = self._collect_debt(s)
                    if ns.debt < 1 and ns.tb >= 1 and ns.kb >= 1:
                        ns = ns._replace(tb=ns.tb - 1, kb=ns.kb - 1,
                                         og=ns.og + 1)
                    if ns != s:
                        dup_changed("OP_RESERVE",
                                    "the estimate was debited a second "
                                    "time — the duplicate-rid probe is "
                                    "gone")
                    s = ns
            else:
                s = self._collect_debt(s)
                if s.debt < 1 and s.tb >= 1 and s.kb >= 1:
                    s = s._replace(tb=s.tb - 1, kb=s.kb - 1, out=True,
                                   og=s.og + 1)
        elif label == "settle_refund":
            s = s._replace(out=False, og=s.og - 1, set_=True,
                           rf=s.rf + 1, tb=min(CAP, s.tb + 1),
                           kb=min(CAP, s.kb + 1))
        elif label == "settle_debt":
            s = s._replace(out=False, og=s.og - 1, set_=True,
                           kb=max(0, s.kb - 1))
            if s.tb >= 1:
                s = s._replace(tb=s.tb - 1)
            else:
                s = s._replace(debt=min(3, s.debt + 1),
                               dcre=min(3, s.dcre + 1))
        elif label == "dup_settle":
            if not f.settle_dedup:
                dup_changed("OP_SETTLE",
                            "replayed settle answered 'unknown' "
                            "instead of replaying the recorded "
                            "reconciliation")
        elif label == "expire":
            s = s._replace(out=False, og=s.og - 1, set_=True, exp=True)
        elif label == "export":
            s = s._replace(dstash=s.debt, debt=0)
            if s.out:
                s = s._replace(out=False, og=s.og - 1, stash=True)
        elif label in ("restore", "dup_restore"):
            dup = label == "dup_restore"
            if s.stash or dup:
                if s.out or s.set_:
                    if not f.restore_skip_known:
                        s = s._replace(og=s.og + 1)
                elif s.stash:
                    s = s._replace(out=True, og=s.og + 1)
                s = s._replace(stash=False)
            if s.dstash or dup:
                if s.tag_seen:
                    if not f.debt_tag_dedup and s.dstash:
                        s = s._replace(debt=min(3, s.debt + s.dstash))
                elif s.dstash:
                    s = s._replace(debt=min(3, s.debt + s.dstash),
                                   tag_seen=True)
            if not dup:
                s = s._replace(restored=True)
        else:  # pragma: no cover
            raise AssertionError(f"unknown label {label!r}")

        rows = 1 if s.out else 0
        if s.og != rows:
            viols.append((
                "outstanding-conserved",
                f"outstanding gauge {s.og} != live rows {rows} — a "
                "re-delivered restore double-counted the hold "
                f"(skip-known guard at {f.restore_skip_known.file}:"
                f"{f.restore_skip_known.line})", "gauge"))
        if s.rf > 1:
            viols.append((
                "settle-dedup",
                f"{s.rf} refunds issued for one rid", "refunds"))
        # Exported debt counts as in flight until its tagged delivery
        # lands; later copies of the same tag are duplicates, not value.
        if s.debt + (0 if s.tag_seen else s.dstash) \
                != s.dcre - s.dcol:
            viols.append((
                "debt-conserved",
                f"tenant debt {s.debt} (+{s.dstash} exported) != "
                f"created {s.dcre} - collected {s.dcol} — a "
                "re-delivered debt row applied twice (tag dedup at "
                f"{f.debt_tag_dedup.file}:{f.debt_tag_dedup.line})",
                "debt"))
        return s, viols

    def _collect_debt(self, s: ResState) -> ResState:
        if s.debt >= 1:
            pay = min(s.debt, s.tb)
            s = s._replace(tb=s.tb - pay, debt=s.debt - pay,
                           dcol=min(3, s.dcol + pay))
        return s


# ===========================================================================
# Federation world
# ===========================================================================

FedState = namedtuple("FedState", [
    "lh",       # home holds the lease
    "le",       # current lease epoch at home (= grants issued)
    "lr",       # region's adopted lease epoch (0 = no lease)
    "hs",       # home ticks since last renewal (lease TTL clock)
    "rs",       # region ticks since last renewal (its own mono clock)
    "rb",       # region slice balance (config persistence: minted once)
    "minted",   # the slice bucket has been minted
    "eb",       # degraded-envelope balance
    "em",       # envelope episodes minted
    "deg",      # region serving its degraded envelope
    "adm",      # region admitted total (monotonic)
    "rep",      # admitted total the home has seen reported
    "hb",       # home global bucket balance
    "d",        # home-side debt (charge the bucket could not cover)
    "hxc",      # expired-lease record's conservative charge (-1 = none)
    "hxr",      # expired-lease record's reported-at-expiry total
    "exp",      # a home-side expiry has happened for the current term
    "rcl",      # a reclaim has been recorded
    "ref",      # heal refunds issued (at most one per lease id)
    "skew",     # a wall-clock skew fault is active
])

#: Lease TTL in model ticks; the slice is CAP tokens, no refill — the
#: bounds are then equalities at the boundary like every other world.
FED_TTL = 2


class FederationWorld:
    """One home :class:`FederationLedger` and one region agent under
    the adversarial WAN: lease / renew / reclaim with duplication,
    stale replies, independent monotonic clocks on both ends (the two
    ``*_tick`` labels — a partition is simply the scheduler ticking
    one side without delivering a renew), wall-clock skew, home-side
    expiry with the conservative fully-spent charge, region-side
    expiry into the degraded envelope, and heal reconciliation. The
    slice bucket is minted ONCE (a re-lease under the same config
    re-mints nothing — the OP_CONFIG rebase carries spent balances),
    so ``adm <= CAP + em·ENV`` is exact."""

    name = "federation"
    invariants = ("fed-lease-monotonic", "fed-no-skew-extension",
                  "fed-global-bound", "fed-reclaim-idempotent",
                  "idempotent-replay")

    def __init__(self, facts: Facts) -> None:
        self.f = facts

    def init_states(self):
        # Roots: with and without an active skew fault from the start
        # (skew may also arrive mid-trace via the label).
        for skew in (False, True):
            yield FedState(
                lh=False, le=0, lr=0, hs=0, rs=0, rb=0, minted=False,
                eb=0, em=0, deg=False, adm=0, rep=0, hb=CAP, d=0,
                hxc=-1, hxr=0, exp=False, rcl=False, ref=0, skew=skew)

    def labels(self, s: FedState):
        out = []
        if not s.lh and s.le < 2 and not s.rcl:
            out.append("lease")         # first lease / post-heal fresh id
        if s.le >= 1:
            out.append("dup_lease")
        if s.lr >= 2:
            out.append("stale_reply")
        if s.lr > 0:
            out += ["renew", "reclaim"]
        if s.lr > 0 or s.exp:
            # A duplicate WAN delivery does not care what the region
            # currently believes — a post-expiry replay re-enters the
            # home's heal path, where the popped record keeps the
            # refund at-most-once.
            out.append("dup_renew")
        if s.rcl:
            out.append("dup_reclaim")
        if s.lh:
            out.append("home_tick")
        if s.lr > 0 and s.rs <= FED_TTL:
            out.append("region_tick")
        if s.adm < CAP + 2 * ENV:
            out.append("admit")
        if not s.skew:
            out.append("skew")
        return out

    def apply(self, s: FedState, label: str):
        f = self.f
        viols: list = []
        before = s

        def dup_changed(op: str, what: str) -> None:
            viols.append((
                "idempotent-replay",
                f"replayed {op} frame changed state: {what} "
                f"(classified idempotent at {f.remote_file}:"
                f"{f.idempotent_ops.get(op, 0)})", op))

        if label == "lease":
            epoch = s.le + 1
            # ref tracks heal refunds PER LEASE ID (the invariant's
            # unit): a fresh grant is a fresh id, whose own single
            # heal is legitimate.
            s = s._replace(lh=True, le=epoch, lr=epoch, hs=0, rs=0,
                           deg=False, exp=False, ref=0)
            if not s.minted:
                # First lease mints the slice bucket; a re-lease under
                # the same config re-mints NOTHING (the regional
                # bucket's spent state persists — config identity).
                s = s._replace(rb=CAP, minted=True)

        elif label == "dup_lease":
            if not f.fed_lease_dedup:
                # The recorded-grant replay is gone: the replayed
                # frame re-runs the grant body — a new epoch, the old
                # lease's term restarted, a second conservative
                # charge staged. Visible state change on a replay.
                ns = s._replace(le=min(3, s.le + 1),
                                lr=min(3, s.le + 1), hs=0)
                if ns != s:
                    dup_changed("OP_FED_LEASE",
                                "the grant body ran a second time — "
                                "a fresh epoch and term were minted "
                                "for a replayed lease_id")
                s = ns

        elif label == "stale_reply":
            # An out-of-order WAN reply carrying epoch lr-1 reaches
            # the region's adoption path.
            if not f.fed_adopt_epoch_guard:
                s = s._replace(lr=s.lr - 1)

        elif label == "home_tick":
            hs = min(FED_TTL + 1, s.hs + 1)
            s = s._replace(hs=hs)
            if hs >= FED_TTL:
                if f.fed_expiry_monotonic or not s.skew:
                    s = self._home_expire(s, viols)
                # else: the wall-based expiry comparison is skewed —
                # the lease silently outlives its TTL (checked below).

        elif label == "region_tick":
            rs = min(FED_TTL + 1, s.rs + 1)
            s = s._replace(rs=rs)
            if rs >= FED_TTL and s.lr > 0 and not s.deg:
                # Region-side monotonic expiry: degrade to the
                # envelope — one fresh envelope budget per episode.
                s = s._replace(deg=True, eb=ENV, em=min(2, s.em + 1))

        elif label == "renew":
            if s.lh:
                delta = s.adm - s.rep
                s = self._charge(s, delta)._replace(
                    rep=s.adm, hs=0, rs=0, deg=False)
            else:
                # A renew reaching an expired lease is the HEAL path;
                # the reply tells the region to take a fresh lease.
                s = self._heal(s, viols)._replace(lr=0)

        elif label == "dup_renew":
            # Re-delivery of the last processed report: monotonic
            # totals make its delta max(0, rep − rep) = 0 — absorbing
            # by construction. The TTL re-arm is its only effect (the
            # same effect any renew has). A re-delivered POST-EXPIRY
            # renew re-enters the heal path — where the popped record
            # is what keeps the refund at-most-once.
            if s.lh:
                s = s._replace(hs=0)
            else:
                s = self._heal(s, viols)._replace(lr=0)

        elif label in ("reclaim", "dup_reclaim"):
            dup = label == "dup_reclaim"
            if dup:
                # The live recorded-reclaim replay: zero side effects.
                # (Its absence is pinned by the at-most-once unit
                # audit in tests/test_federation.py; the model's
                # double-refund class is the heal-record leak below.)
                pass
            elif s.lh:
                delta = s.adm - s.rep
                s = self._charge(s, delta)._replace(
                    rep=s.adm, lh=False, lr=0, rcl=True)
            else:
                s = self._heal(s, viols)._replace(lr=0, rcl=True)

        elif label == "admit":
            if s.deg:
                if s.eb > 0:
                    s = s._replace(eb=s.eb - 1, adm=s.adm + 1)
            elif s.lr > 0 and s.rb > 0:
                s = s._replace(rb=s.rb - 1, adm=s.adm + 1)

        elif label == "skew":
            s = s._replace(skew=True)

        else:  # pragma: no cover - label/apply drift is a checker bug
            raise AssertionError(f"unknown label {label!r}")

        self._post_checks(before, s, viols)
        return s, viols

    # -- helpers ------------------------------------------------------------
    def _charge(self, s: FedState, delta: int) -> FedState:
        if delta <= 0:
            return s
        short = max(0, delta - s.hb)
        return s._replace(hb=max(0, s.hb - delta),
                          d=min(6, s.d + short))

    def _home_expire(self, s: FedState, viols: list) -> FedState:
        """The home's monotonic lease expiry: the unreported slice
        entitlement is presumed FULLY SPENT (conservative) and charged;
        the heal refund reconciles the true total later."""
        charge = max(0, CAP - s.rep) if self.f.fed_conservative_spent \
            else 0
        s = self._charge(s, charge)._replace(
            lh=False, hxc=charge, hxr=s.rep, exp=True)
        if not self.f.fed_conservative_spent:
            accounted = (CAP - s.hb) + s.d
            if accounted < CAP:
                viols.append((
                    "fed-global-bound",
                    "home expired an unreachable region's lease "
                    f"with only {accounted}/{CAP} tokens accounted — "
                    "the slice must be presumed fully spent until "
                    "reclaim-or-expiry reconciles (conservative "
                    "charge at "
                    f"{self.f.fed_conservative_spent.file}:"
                    f"{self.f.fed_conservative_spent.line} missing)",
                    "conservative"))
        return s

    def _heal(self, s: FedState, viols: list) -> FedState:
        """A late renew/reclaim reconciling an expired lease's
        conservative charge: refund = charge − true unreported delta
        (never negative — the charge was an upper bound). At most one
        refund per lease id: the record must POP."""
        if s.hxc < 0:
            return s   # unknown lease id: counted no-op
        true_delta = max(0, s.adm - s.hxr)
        refund = max(0, s.hxc - true_delta)
        extra = max(0, true_delta - s.hxc)
        ns = self._charge(s, extra)._replace(
            hb=min(CAP, s.hb + refund))
        if refund > 0:
            ns = ns._replace(ref=min(2, ns.ref + 1))
        if self.f.fed_heal_once:
            ns = ns._replace(hxc=-1)
        return ns

    def _post_checks(self, old: FedState, new: FedState,
                     viols: list) -> None:
        if 0 < new.lr < old.lr:
            viols.append((
                "fed-lease-monotonic",
                f"the region's adopted lease epoch went backwards "
                f"({old.lr} -> {new.lr}): a stale out-of-order WAN "
                "reply rolled the applied slice config back (epoch "
                f"guard at {self.f.fed_adopt_epoch_guard.file}:"
                f"{self.f.fed_adopt_epoch_guard.line})", "epoch"))
        if new.lh and new.hs > FED_TTL:
            viols.append((
                "fed-no-skew-extension",
                f"the lease outlived its TTL ({new.hs} ticks > "
                f"{FED_TTL}) under a wall-clock skew fault — expiry "
                "must be keyed on the MONOTONIC clock "
                f"({self.f.fed_expiry_monotonic.file}:"
                f"{self.f.fed_expiry_monotonic.line})", "skew"))
        if new.adm > CAP + new.em * ENV:
            viols.append((
                "fed-global-bound",
                f"region admitted {new.adm} tokens against a slice of "
                f"{CAP} + {new.em} envelope episode(s) x {ENV} — the "
                "partition envelope bound is exceeded", "bound"))
        if new.ref > 1:
            viols.append((
                "fed-reclaim-idempotent",
                f"{new.ref} heal refunds issued for one lease id — "
                "the expired-lease record must pop at the first "
                f"reconciliation ({self.f.fed_heal_once.file}:"
                f"{self.f.fed_heal_once.line})", "refunds"))


# ===========================================================================
# Breaker world
# ===========================================================================

BrState = namedtuple("BrState", [
    "st",     # closed | open | half_open
    "fl",     # consecutive closed-state failures (0..THRESH)
    "pi",     # probe slot held
    "oa",     # ticks since opened (saturating)
    "pa",     # ticks since probe granted (saturating)
    "outp",   # unsettled probes outstanding (0..2)
])

THRESH = 2   # failure_threshold in the model
TO = 2       # recovery_timeout in ticks


class BreakerWorld:
    """The breaker machine rebuilt from the extracted transition table:
    the model takes exactly the edges the ``_transition`` call sites
    encode, so a rewired transition is a rewired model — and a violated
    contract."""

    name = "breaker"
    invariants = ("breaker-single-probe",
                  "breaker-failure-never-closes",
                  "breaker-opens-at-threshold", "breaker-recloses",
                  "breaker-no-wedge")

    def __init__(self, facts: Facts) -> None:
        self.f = facts
        self.edges = facts.breaker_edges

    def init_states(self):
        yield BrState(st="closed", fl=0, pi=False, oa=0, pa=0, outp=0)

    def labels(self, s: BrState):
        out = ["tick", "allow"]
        if s.st == "closed":
            out += ["fail", "success"]
        if s.outp >= 1:
            out += ["probe_success", "probe_failure", "probe_abandon"]
        return out

    def _edge(self, frm: str, event: str) -> "str | None":
        for f, e, t in self.edges:
            if e == event and f in (frm, "*"):
                return t
        return None

    def apply(self, s: BrState, label: str):
        f = self.f
        viols: list = []

        if label == "tick":
            s = s._replace(oa=min(TO, s.oa + 1),
                           pa=min(TO, s.pa + 1) if s.pi else s.pa)

        elif label == "fail":
            s = s._replace(fl=min(THRESH, s.fl + 1))
            if s.fl >= THRESH:
                to = self._edge("closed", "failure")
                if to:
                    s = s._replace(st=to, oa=0, fl=0)

        elif label == "success":
            s = s._replace(fl=0)

        elif label == "allow":
            if s.st == "open" and s.oa >= TO:
                to = self._edge("open", "timeout")
                if to:
                    s = s._replace(st=to, pi=True, pa=0,
                                   outp=min(2, s.outp + 1))
            elif s.st == "half_open":
                if not s.pi:
                    s = s._replace(pi=True, pa=0,
                                   outp=min(2, s.outp + 1))
                elif not f.breaker_single_probe_guard:
                    s = s._replace(pa=0, outp=min(2, s.outp + 1))
                elif s.pa >= TO and f.breaker_probe_reclaim:
                    # Abandoned slot reclaimed after a full recovery
                    # window: the old holder is written off (its late
                    # settle is out of model scope) and a new holder
                    # probes — still one live probe per window.
                    s = s._replace(pa=0, outp=1)

        elif label == "probe_success":
            s = s._replace(outp=s.outp - 1, pi=False)
            if s.st == "half_open":
                to = self._edge("half_open", "success")
                if to:
                    s = s._replace(st=to, fl=0)
                else:
                    viols.append((
                        "breaker-recloses",
                        "a successful half-open probe did not re-close "
                        "the breaker — the node stays quarantined "
                        "after proving healthy (transitions extracted "
                        f"from {f.breaker_file})", "recloses"))

        elif label == "probe_failure":
            s = s._replace(outp=s.outp - 1, pi=False)
            if s.st == "half_open":
                to = self._edge("half_open", "failure")
                if to:
                    s = s._replace(st=to,
                                   oa=0 if to == "open" else s.oa)
                if to == "closed":
                    viols.append((
                        "breaker-failure-never-closes",
                        "a FAILED half-open probe re-closed the "
                        "breaker — traffic floods a node that just "
                        "failed its health probe (transitions "
                        f"extracted from {f.breaker_file})",
                        "fail-close"))

        elif label == "probe_abandon":
            s = s._replace(outp=s.outp - 1)   # cancelled, never settled

        else:  # pragma: no cover
            raise AssertionError(f"unknown label {label!r}")

        if s.outp > 1:
            viols.append((
                "breaker-single-probe",
                f"{s.outp} unsettled half-open probes in flight — the "
                "single-probe admission gate is gone (guard at "
                f"{f.breaker_single_probe_guard.file}:"
                f"{f.breaker_single_probe_guard.line})", "probes"))
        if s.fl >= THRESH and s.st == "closed":
            viols.append((
                "breaker-opens-at-threshold",
                f"{THRESH} consecutive failures left the breaker "
                "CLOSED — a dead node keeps eating traffic "
                f"(transitions extracted from {f.breaker_file})",
                "threshold"))
        if s.st == "open" and s.oa >= TO \
                and self._edge("open", "timeout") is None:
            viols.append((
                "breaker-no-wedge",
                "recovery timeout elapsed but no OPEN -> HALF_OPEN "
                "transition exists — the node is quarantined forever",
                "open-wedge"))
        if s.st == "half_open" and s.pi and s.outp == 0 \
                and s.pa >= TO and not f.breaker_probe_reclaim:
            viols.append((
                "breaker-no-wedge",
                "an abandoned probe slot is never reclaimed — allow() "
                "answers reject forever (reclaim guard at "
                f"{f.breaker_probe_reclaim.file}:"
                f"{f.breaker_probe_reclaim.line})", "probe-wedge"))
        return s, viols


class ProductWorld:
    """The asynchronous product of two worlds: every interleaving of
    their action alphabets (``left:`` / ``right:`` label prefixes).
    migration × config is the ISSUE-14 adversary 'concurrent reshape
    AND live limit mutation': the exploration proves every invariant
    of both machines holds under arbitrary interleaving of the other's
    control plane — and it is where the state count earns the word
    'product'."""

    def __init__(self, left, right) -> None:
        self.left, self.right = left, right
        self.name = f"{left.name}x{right.name}"
        self.invariants = tuple(dict.fromkeys(
            left.invariants + right.invariants))

    def init_states(self):
        rights = list(self.right.init_states())
        for ls in self.left.init_states():
            for rs in rights:
                yield (ls, rs)

    def labels(self, s):
        return ([f"left:{l}" for l in self.left.labels(s[0])]
                + [f"right:{l}" for l in self.right.labels(s[1])])

    def apply(self, s, label):
        side, _, inner = label.partition(":")
        if side == "left":
            ns, viols = self.left.apply(s[0], inner)
            return (None if ns is None else (ns, s[1])), viols
        ns, viols = self.right.apply(s[1], inner)
        return (None if ns is None else (s[0], ns)), viols


def all_worlds(facts: Facts, *, include_product: bool = True) -> list:
    worlds = [MigrationWorld(facts), ConfigWorld(facts),
              ReservationWorld(facts), FederationWorld(facts),
              BreakerWorld(facts)]
    if include_product:
        worlds.append(ProductWorld(MigrationWorld(facts),
                                   ConfigWorld(facts)))
    return worlds
