"""Model extraction — the live code is the spec.

drl-verify does NOT keep a hand-written copy of the protocol rules it
checks: every behavioral fact the models depend on is extracted from
the implementation via ``ast`` at check time, so a refactor that drops
a guard *changes the model* and the exploration finds the resulting
violation (with a trace), instead of a stale hand-model silently
passing. The extraction surface (docs/DESIGN.md §19):

- ``runtime/remote.py`` — the ``_IDEMPOTENT_OPS`` /
  ``_NON_IDEMPOTENT_OPS`` classification (which wire ops the client may
  replay post-send; every idempotent op must have a replay model).
- ``runtime/placement.py`` — the epoch state machine's guards: the
  stale-announce raise, the conflicting-twin raise, the pull cache, the
  expiry-abort tombstone, the push batch dedup, and the abort's push-
  ledger reset + reservation-stash restore.
- ``runtime/liveconfig.py`` — the config-version machine's guards: the
  stale prepare/adopt raises, commit idempotency, the staged-twin
  conflict raise, and the gate-flips-before-rebase statement order.
- ``runtime/reservations.py`` — the ledger's dedup probes: duplicate
  reserve, recorded settle, restore-skips-known-rid, and the
  per-(tag, tenant) debt dedup.
- ``runtime/federation.py`` — the WAN lease machine's guards: the
  duplicate-lease grant replay, the region's forward-only slice-epoch
  adoption, the MONOTONIC (never wall) expiry clock, the
  conservative fully-spent charge at expiry, and the heal record's
  at-most-once pop.
- ``utils/resilience.py`` — the breaker transition table (every
  ``self._transition(...)`` call site with its guarding state) plus the
  single-probe and probe-reclaim guards in ``allow``.

A missing CLASS or METHOD is an :class:`ExtractionError` (the checker
is blind — exit 2, never a silent 'clean'); a missing GUARD inside a
found method is a *fact* (``False``) that the model faithfully adopts —
and the exploration then produces the counterexample that guard exists
to prevent. Each fact carries file:line provenance for findings.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib

__all__ = ["Facts", "ExtractionError", "extract_facts",
           "extract_placement", "extract_liveconfig",
           "extract_reservations", "extract_federation",
           "extract_breaker", "extract_op_sets"]


class ExtractionError(RuntimeError):
    """An extraction anchor (class/method/assignment) is gone: the
    checker cannot see the code it models. Loud by design."""


@dataclasses.dataclass
class Fact:
    """One extracted boolean fact with provenance."""

    present: bool
    file: str
    line: int

    def __bool__(self) -> bool:
        return self.present


@dataclasses.dataclass
class Facts:
    """Everything the worlds consume (see module docstring)."""

    # remote.py — op name -> line of the classification set.
    idempotent_ops: "dict[str, int]"
    non_idempotent_ops: "dict[str, int]"
    remote_file: str

    # placement.py — NodePlacementState guards.
    announce_stale_guard: Fact      # stale epoch announce raises
    announce_conflict_guard: Fact   # same-epoch different-map raises
    pull_cached: Fact               # re-delivered pull serves the cache
    pull_tombstone_guard: Fact      # post-expiry-abort pull refuses
    push_dedup: Fact                # (epoch, batch) applied-set dedup
    abort_resets_push_ledger: Fact  # _abort pops the target epoch's set
    abort_restores_reservations: Fact  # _abort restores the res stash
    expiry_abort_forfeits: Fact     # expiry abort does NOT restore it
    abort_drops_imported_res: Fact  # dst abort drops imported rows

    # liveconfig.py — ConfigState guards.
    prepare_stale_guard: Fact
    prepare_conflict_guard: Fact    # staged twin at same version raises
    commit_idempotent_guard: Fact   # version <= committed -> no-op
    adopt_stale_guard: Fact         # stale adopt snapshot -> no-op
    commit_gate_first: Fact         # gate flip precedes the rebase

    # reservations.py — ReservationLedger dedup probes.
    reserve_dedup: Fact             # duplicate reserve replays decision
    settle_dedup: Fact              # settled-rid map replays the result
    restore_skip_known: Fact        # restore skips an already-known rid
    debt_tag_dedup: Fact            # tagged debt applies once per tag

    # federation.py — FederationLedger / RegionFederation guards.
    fed_lease_dedup: Fact           # duplicate lease_id replays the grant
    fed_adopt_epoch_guard: Fact     # region adopts slice epochs forward-only
    fed_expiry_monotonic: Fact      # expire() reads the MONOTONIC clock,
    #                                 never the wall clock (skew immunity)
    fed_conservative_spent: Fact    # expiry charges the unreported slice
    #                                 entitlement (fully-spent presumption)
    fed_heal_once: Fact             # heal POPS the expired record (at most
    #                                 one refund per lease id)

    # resilience.py — CircuitBreaker.
    breaker_edges: "frozenset[tuple[str, str, str]]"  # (from, event, to)
    breaker_single_probe_guard: Fact  # allow() rejects while in flight
    breaker_probe_reclaim: Fact       # abandoned slot reclaimed on time
    breaker_file: str


# -- shared AST helpers ------------------------------------------------------

def _parse(path: pathlib.Path) -> ast.Module:
    try:
        return ast.parse(path.read_text())
    except (OSError, SyntaxError) as exc:
        raise ExtractionError(f"cannot parse {path}: {exc!r}") from exc


def _class(tree: ast.Module, name: str, path: pathlib.Path) -> ast.ClassDef:
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == name:
            return node
    raise ExtractionError(f"class {name} not found in {path}")


def _method(cls: ast.ClassDef, name: str,
            path: pathlib.Path) -> "ast.FunctionDef | ast.AsyncFunctionDef":
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            return node
    raise ExtractionError(
        f"method {cls.name}.{name} not found in {path}")


def _src(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


def _find_fact(fn: ast.AST, file: str, *needles: str,
               node_type: type = ast.AST) -> Fact:
    """A fact holds when some node of ``node_type`` inside ``fn``
    unparses to text containing EVERY needle. Line = the matching node
    (guard present) or the method header (guard absent — the site the
    refactor would have to restore)."""
    for node in ast.walk(fn):
        if not isinstance(node, node_type):
            continue
        text = _src(node)
        if text and all(n in text for n in needles):
            return Fact(True, file, getattr(node, "lineno", fn.lineno))
    return Fact(False, file, fn.lineno)


def _all_facts(file: str, *facts: Fact) -> Fact:
    """Conjunction: the combined fact holds only when EVERY site does;
    the provenance line is the first missing site's (the one a revert
    would have to restore), else the first site's."""
    for f in facts:
        if not f.present:
            return f
    return facts[0]


def _find_if_test(fn: ast.AST, file: str, *needles: str) -> Fact:
    """Like :func:`_find_fact` restricted to ``If`` CONDITIONS — for
    guards whose needle text also appears as ordinary statements in
    the surrounding branches (matching a whole If's body would keep
    the fact alive after the guard itself is deleted)."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.If):
            continue
        text = _src(node.test)
        if text and all(n in text for n in needles):
            return Fact(True, file, node.lineno)
    return Fact(False, file, fn.lineno)


# -- remote.py: the idempotency classification -------------------------------

def extract_op_sets(remote_py: pathlib.Path
                    ) -> "tuple[dict[str, int], dict[str, int]]":
    """``{op_name: line}`` for both classification sets. Reuses the
    drl-check extractor (one parser, two checkers — they cannot
    drift apart)."""
    from tools.drl_check import wire_conformance

    sets = wire_conformance._remote_op_sets(remote_py)
    out = []
    for name in ("_IDEMPOTENT_OPS", "_NON_IDEMPOTENT_OPS"):
        if name not in sets:
            raise ExtractionError(f"{name} not found in {remote_py}")
        members, line = sets[name]
        out.append({m: line for m in members})
    return out[0], out[1]


# -- placement.py ------------------------------------------------------------

def extract_placement(placement_py: pathlib.Path, rel: str) -> dict:
    tree = _parse(placement_py)
    cls = _class(tree, "NodePlacementState", placement_py)
    announce = _method(cls, "announce", placement_py)
    abort = _method(cls, "_abort", placement_py)
    pull = _method(cls, "pull", placement_py)
    push = _method(cls, "push", placement_py)
    return {
        "announce_stale_guard": _find_fact(
            announce, rel, "pmap.epoch < self.pmap.epoch",
            "StalePlacementError", node_type=ast.If),
        "announce_conflict_guard": _find_fact(
            announce, rel, "pmap.epoch == self.pmap.epoch",
            "StalePlacementError", node_type=ast.If),
        "pull_cached": _find_fact(
            pull, rel, "self._handoffs.get(target_epoch)"),
        "pull_tombstone_guard": _find_fact(
            pull, rel, "in self._aborted_epochs", node_type=ast.If),
        "push_dedup": _find_fact(
            push, rel, "batch in applied", node_type=ast.If),
        "abort_resets_push_ledger": _find_fact(
            abort, rel, "self._applied.pop(target_epoch"),
        # The FULL coordinator-abort restore (rows + debts) — needle is
        # the whole call so the forfeit branch's debt-only
        # restore_rows([], ...) cannot keep this fact alive.
        "abort_restores_reservations": _find_fact(
            abort, rel, "restore_rows(*h.res_stash)"),
        # BOTH expiry paths (gate() and bulk_gate()) must forfeit the
        # reservation stash: restoring under a slow commit double-homes
        # the rid and a retried settle refunds on both sides — the
        # settle-dedup counterexample this PR's fix closed. ANDed so a
        # revert of EITHER call site drops the fact (and the model
        # then re-derives the counterexample).
        "expiry_abort_forfeits": _all_facts(
            rel,
            _find_fact(_method(cls, "gate", placement_py), rel,
                       "self._abort(", "restore_reservations=False",
                       node_type=ast.Call),
            _find_fact(_method(cls, "bulk_gate", placement_py), rel,
                       "self._abort(", "restore_reservations=False",
                       node_type=ast.Call)),
        # The destination half of the same fix: an abort must drop the
        # reservation rows its pushes imported for the aborted epoch.
        "abort_drops_imported_res": _find_fact(
            abort, rel, "self._imported_res.pop(target_epoch"),
    }


# -- liveconfig.py -----------------------------------------------------------

def extract_liveconfig(liveconfig_py: pathlib.Path, rel: str) -> dict:
    tree = _parse(liveconfig_py)
    cls = _class(tree, "ConfigState", liveconfig_py)
    prepare = _method(cls, "_prepare", liveconfig_py)
    commit = _method(cls, "_commit", liveconfig_py)
    adopt = _method(cls, "_adopt", liveconfig_py)

    # Statement order inside _commit: the serving gate must flip BEFORE
    # the rebase exports the old table (DESIGN.md §13 — the over-
    # admission epsilon depends on it). Compare first-occurrence lines.
    gate_line = rebase_line = None
    for node in ast.walk(commit):
        if (gate_line is None and isinstance(node, ast.Assign)
                and any("self.rules[" in _src(t) for t in node.targets)):
            gate_line = node.lineno
        if (rebase_line is None and isinstance(node, ast.Await)
                and "_rebase_state" in _src(node)):
            rebase_line = node.lineno
    gate_first = (gate_line is not None and rebase_line is not None
                  and gate_line < rebase_line)

    return {
        "prepare_stale_guard": _find_fact(
            prepare, rel, "version <= self.version", "StaleConfigError",
            node_type=ast.If),
        "prepare_conflict_guard": _find_fact(
            prepare, rel, "staged != rule", node_type=ast.If),
        "commit_idempotent_guard": _find_fact(
            commit, rel, "version <= self.version", node_type=ast.If),
        "adopt_stale_guard": _find_fact(
            adopt, rel, "version <= self.version", node_type=ast.If),
        "commit_gate_first": Fact(gate_first, rel,
                                  gate_line or commit.lineno),
    }


# -- reservations.py ---------------------------------------------------------

def extract_reservations(reservations_py: pathlib.Path, rel: str) -> dict:
    tree = _parse(reservations_py)
    cls = _class(tree, "ReservationLedger", reservations_py)
    reserve = _method(cls, "reserve", reservations_py)
    settle = _method(cls, "settle", reservations_py)
    restore = _method(cls, "restore_rows", reservations_py)
    return {
        "reserve_dedup": _find_fact(
            reserve, rel, "self._duplicate_reserve("),
        "settle_dedup": _find_fact(
            settle, rel, "self._settled.get(rid)"),
        "restore_skip_known": _find_fact(
            restore, rel, "rid in self._entries", "rid in self._settled",
            node_type=ast.If),
        "debt_tag_dedup": _find_fact(
            restore, rel, "(tag, tenant) in seen", node_type=ast.If),
    }


# -- federation.py -----------------------------------------------------------

def extract_federation(federation_py: pathlib.Path, rel: str) -> dict:
    tree = _parse(federation_py)
    ledger = _class(tree, "FederationLedger", federation_py)
    region = _class(tree, "RegionFederation", federation_py)
    lease = _method(ledger, "lease", federation_py)
    expire = _method(ledger, "expire", federation_py)
    heal = _method(ledger, "_heal", federation_py)
    adopt = _method(region, "_adopt", federation_py)

    # The monotonic-TTL contract is a NEGATIVE fact too: expire() must
    # read self._clock AND must not read self._wall — a refactor that
    # swaps the clock source silently re-opens the WAN-skew lease
    # extension the whole design exists to prevent.
    uses_clock = _find_fact(expire, rel, "self._clock(",
                            node_type=ast.Call)
    uses_wall = _find_fact(expire, rel, "self._wall(",
                           node_type=ast.Call)
    expiry_monotonic = Fact(
        bool(uses_clock) and not bool(uses_wall), rel,
        uses_wall.line if uses_wall else uses_clock.line)

    return {
        "fed_lease_dedup": _find_fact(
            lease, rel, "self._duplicate_lease(", node_type=ast.Call),
        "fed_adopt_epoch_guard": _find_if_test(
            adopt, rel, "epoch <= lease.epoch"),
        "fed_expiry_monotonic": expiry_monotonic,
        "fed_conservative_spent": _find_fact(
            expire, rel, "self._conservative_charge(",
            node_type=ast.Call),
        "fed_heal_once": _find_fact(
            heal, rel, "self._expired.pop(lease_id",
            node_type=ast.Call),
    }


# -- resilience.py: the breaker transition table -----------------------------

_STATE_NAMES = {"CLOSED": "closed", "OPEN": "open",
                "HALF_OPEN": "half_open"}


def _breaker_edges_in(fn: ast.AST, event: str
                      ) -> "set[tuple[str, str, str]]":
    """Every ``self._transition(self.X)`` call with the nearest
    enclosing ``self._state == self.Y`` condition as the source state
    (``*`` when unconditioned — e.g. ``allow``'s OPEN->HALF_OPEN flip
    happens after the state was already tested by the surrounding
    branch structure)."""
    edges: set[tuple[str, str, str]] = set()

    def walk(node: ast.AST, ctx: str) -> None:
        if isinstance(node, ast.If):
            new_ctx = ctx
            text = _src(node.test)
            for const, name in _STATE_NAMES.items():
                if f"self._state == self.{const}" in text:
                    new_ctx = name
            for child in node.body:
                walk(child, new_ctx)
            for child in node.orelse:
                walk(child, ctx)
            return
        if isinstance(node, ast.Call) and \
                _src(node.func).endswith("._transition") and node.args:
            target = _src(node.args[0])
            for const, name in _STATE_NAMES.items():
                if f"self.{const}" == target:
                    edges.add((ctx, event, name))
            return
        for child in ast.iter_child_nodes(node):
            walk(child, ctx)

    walk(fn, "*")
    return edges


def extract_breaker(resilience_py: pathlib.Path, rel: str) -> dict:
    tree = _parse(resilience_py)
    cls = _class(tree, "CircuitBreaker", resilience_py)
    allow = _method(cls, "allow", resilience_py)
    succ = _method(cls, "record_success", resilience_py)
    fail = _method(cls, "record_failure", resilience_py)
    edges = (_breaker_edges_in(allow, "timeout")
             | _breaker_edges_in(succ, "success")
             | _breaker_edges_in(fail, "failure"))
    # The single-probe guard: allow() must answer reject while a probe
    # is in flight; the reclaim guard: ONLY inside its recovery window
    # (an abandoned slot frees itself — no reject-forever wedge). Both
    # match If CONDITIONS: the same attribute names appear as plain
    # assignments elsewhere in allow(), which must not keep the facts
    # alive after the guards are deleted.
    single = _find_if_test(allow, rel, "self._probe_inflight")
    reclaim = _find_if_test(allow, rel, "self._probe_started")
    return {
        "breaker_edges": frozenset(edges),
        "breaker_single_probe_guard": single,
        "breaker_probe_reclaim": reclaim,
    }


# -- the one entry point -----------------------------------------------------

def extract_facts(root: pathlib.Path) -> Facts:
    pkg = root / "distributedratelimiting" / "redis_tpu"
    remote = pkg / "runtime" / "remote.py"
    placement = pkg / "runtime" / "placement.py"
    liveconfig = pkg / "runtime" / "liveconfig.py"
    reservations = pkg / "runtime" / "reservations.py"
    federation = pkg / "runtime" / "federation.py"
    resilience = pkg / "utils" / "resilience.py"

    def rel(p: pathlib.Path) -> str:
        try:
            return str(p.resolve().relative_to(root.resolve()))
        except ValueError:
            return str(p)

    idem, non_idem = extract_op_sets(remote)
    return Facts(
        idempotent_ops=idem,
        non_idempotent_ops=non_idem,
        remote_file=rel(remote),
        **extract_placement(placement, rel(placement)),
        **extract_liveconfig(liveconfig, rel(liveconfig)),
        **extract_reservations(reservations, rel(reservations)),
        **extract_federation(federation, rel(federation)),
        **extract_breaker(resilience, rel(resilience)),
        breaker_file=rel(resilience),
    )
