"""Cross-language lock-order analyzer (the second drl-verify leg).

Builds ONE static lock-acquisition graph spanning both halves of the
stack and fails on cycles:

- **Python** (``distributedratelimiting/``): every ``with`` /
  ``async with`` on a lock-shaped expression (``*lock*``, ``*gate*``)
  is an acquisition; a lock's identity is ``py:<Class>.<attr>`` (or
  ``py:<module>.<name>`` for locals). While a lock is held, lexically
  nested acquisitions AND calls to functions that themselves acquire a
  lock at top level become edges. Call resolution is deliberately
  conservative — noisy resolution would drown real cycles in
  same-name coincidences:

  - ``self.method(...)`` resolves within the caller's class hierarchy
    (its own class and AST-visible ancestors); a resolved callee that
    takes the SAME attribute the caller already holds is same-object
    re-entrancy (the RLock pattern ``now_ticks_checked`` uses), not an
    ordering edge.
  - other calls resolve by bare name only when exactly ONE class in
    the corpus defines a lock-acquiring method of that name
    (``pull``/``push`` -> the placement control lock, ``announce`` ->
    the config lock, ...); ambiguous names contribute no edge.
  - calls to ``fe_*``/``dir_*`` ABI entry points bridge into the C
    half: the edge targets whatever lock classes that C function
    takes.

- **C** (``native/frontend.cc``): lock classes are identified by the
  mutex TYPE in ``std::lock_guard<T>`` / ``std::unique_lock<T>``
  declarations (``c:FeMutex`` is the shard connection mutex,
  ``c:T0SpinMutex`` the tier-0 slice lock) — renaming a guard variable
  cannot blind the extractor. A guard is held to the end of its brace
  block; a guard declared while another is live is an edge. Call edges
  propagate one hop, so a handler holding the shard mutex that calls
  ``t0_local_try`` (takes the slice lock) yields the documented
  ``FeMutex -> T0SpinMutex`` order.

Second rule, same scan: the ``fe_t0_retire`` all-slices combined
section — the ONE place multiple slice locks are held together — must
take them in canonical container order (forward iteration over the
partition vector). A reversed sweep, a *second* multi-slice section
anywhere else, or a scalar nested same-class acquisition fails
``slice-sweep-order``: two combined sections with different orders is
exactly how the shard-vs-pump deadlock would ship.

Findings reuse drl-check's :class:`Finding` (file:line on every edge
of a reported cycle)."""

from __future__ import annotations

import ast
import pathlib
import re

from tools.drl_check.common import Finding, iter_py_files, rel

__all__ = ["check", "build_graph", "LockGraph", "py_summaries",
           "py_summaries_from_source", "c_lock_summaries"]

_LOCKISH = ("lock", "gate")


class LockGraph:
    """Nodes are lock identities; edges carry provenance."""

    def __init__(self) -> None:
        self.nodes: set[str] = set()
        #: (src, dst) -> (file, line, note)
        self.edges: dict[tuple[str, str], tuple[str, int, str]] = {}

    def add(self, src: str, dst: str, file: str, line: int,
            note: str) -> None:
        if src == dst:
            return
        self.nodes.add(src)
        self.nodes.add(dst)
        self.edges.setdefault((src, dst), (file, line, note))

    def cycles(self) -> "list[list[str]]":
        """Every elementary cycle, canonicalized (rotation-minimal,
        found from its minimal node only). The graph is tiny; simple
        DFS is plenty."""
        adj: dict[str, list[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, []).append(b)
        out: list[list[str]] = []

        def dfs(start: str, node: str, path: list[str]) -> None:
            for nxt in adj.get(node, ()):
                if nxt == start:
                    out.append(path[:])
                elif nxt not in path and nxt > start:
                    dfs(start, nxt, path + [nxt])

        for node in sorted(adj):
            dfs(node, node, [node])
        return out


# ===========================================================================
# Python half
# ===========================================================================

def _expr_text(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


def _is_lockish(text: str) -> bool:
    low = text.lower()
    return any(t in low for t in _LOCKISH)


class _PyFn:
    """Per-function lock summary."""

    def __init__(self, qualname: str, cls: "str | None",
                 module: str) -> None:
        self.qualname = qualname
        self.cls = cls
        self.module = module
        self.name = qualname.rsplit(".", 1)[-1]
        #: top-level acquisitions: (lock_id, attr_name, file, line)
        self.direct: "list[tuple[str, str, str, int]]" = []
        #: (outer_lock_id, inner_lock_id, file, line)
        self.held_acquires: "list[tuple[str, str, str, int]]" = []
        #: (outer_lock_id, outer_attr, callee, selfcall, file, line)
        self.held_calls: "list[tuple[str, str, str, bool, str, int]]" \
            = []


class _PyVisitor(ast.NodeVisitor):
    def __init__(self, module: str, file: str) -> None:
        self.module = module
        self.file = file
        self.cls: "str | None" = None
        self.fns: "list[_PyFn]" = []
        self.bases: "dict[str, list[str]]" = {}
        self._fn: "_PyFn | None" = None
        self._held: "list[tuple[str, str]]" = []   # (lock_id, attr)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev, self.cls = self.cls, node.name
        self.bases[node.name] = [
            b.id if isinstance(b, ast.Name) else _expr_text(b)
            .rsplit(".", 1)[-1]
            for b in node.bases]
        self.generic_visit(node)
        self.cls = prev

    def _visit_fn(self, node) -> None:
        prev_fn, prev_held = self._fn, self._held
        qual = (f"{self.cls}.{node.name}" if self.cls else node.name)
        self._fn = _PyFn(qual, self.cls, self.module)
        self._held = []
        self.fns.append(self._fn)
        self.generic_visit(node)
        self._fn, self._held = prev_fn, prev_held

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def _lock_of(self, text: str) -> "tuple[str, str]":
        attr = text.split(".")[-1].split("(")[0]
        scope = (self.cls if text.startswith("self.") and self.cls
                 else self.module)
        return f"py:{scope}.{attr}", attr

    def _visit_with(self, node) -> None:
        fn = self._fn
        locks = []
        for item in node.items:
            text = _expr_text(item.context_expr)
            if _is_lockish(text):
                locks.append(self._lock_of(text))
        if fn is None or not locks:
            self.generic_visit(node)
            return
        for lk, attr in locks:
            if self._held:
                fn.held_acquires.append(
                    (self._held[-1][0], lk, self.file, node.lineno))
            else:
                fn.direct.append((lk, attr, self.file, node.lineno))
        self._held.extend(locks)
        for child in node.body:
            self.visit(child)
        del self._held[len(self._held) - len(locks):]

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    def visit_Call(self, node: ast.Call) -> None:
        if self._fn is not None and self._held:
            name, selfcall = "", False
            if isinstance(node.func, ast.Attribute):
                name = node.func.attr
                selfcall = (isinstance(node.func.value, ast.Name)
                            and node.func.value.id == "self")
            elif isinstance(node.func, ast.Name):
                name = node.func.id
            if name:
                outer_id, outer_attr = self._held[-1]
                self._fn.held_calls.append(
                    (outer_id, outer_attr, name, selfcall,
                     self.file, node.lineno))
        self.generic_visit(node)


def py_summaries_from_source(source: str, module: str, file: str
                             ) -> "tuple[list, dict]":
    v = _PyVisitor(module, file)
    v.visit(ast.parse(source))
    return v.fns, v.bases


def py_summaries(root: pathlib.Path) -> "tuple[list, dict]":
    fns: list = []
    bases: dict = {}
    for py in iter_py_files(root / "distributedratelimiting"):
        try:
            f, b = py_summaries_from_source(py.read_text(), py.stem,
                                            rel(py, root))
        except SyntaxError:
            continue
        fns.extend(f)
        bases.update(b)
    return fns, bases


def _ancestors(cls: str, bases: "dict[str, list[str]]") -> "set[str]":
    out, todo = {cls}, list(bases.get(cls, ()))
    while todo:
        b = todo.pop()
        if b not in out:
            out.add(b)
            todo.extend(bases.get(b, ()))
    return out


# ===========================================================================
# C half
# ===========================================================================

_C_SIG_RE = re.compile(
    r"^[A-Za-z_][\w:<>,\*&\s]*?\b([A-Za-z_]\w*)\s*\($")
_C_GUARD_RE = re.compile(
    r"std::(?:lock_guard|unique_lock)\s*<\s*([A-Za-z_]\w*)\s*>")
_C_VEC_GUARD_RE = re.compile(
    r"std::vector\s*<\s*std::unique_lock\s*<\s*([A-Za-z_]\w*)\s*>")
_C_CALL_RE = re.compile(r"\b([A-Za-z_]\w*)\s*\(")


class _CFn:
    def __init__(self, name: str, line: int) -> None:
        self.name = name
        self.line = line
        self.direct: "list[tuple[str, int]]" = []      # (class, line)
        self.held_acquires: "list[tuple[str, str, int]]" = []
        self.held_calls: "list[tuple[str, str, int]]" = []
        self.multi: "list[tuple[str, int, str]]" = []  # combined sects


def c_lock_summaries(cc: pathlib.Path) -> "dict[str, _CFn]":
    """Scan one C++ translation unit: function extents by brace depth
    (multi-line signatures included), guard declarations by mutex
    type, combined (vector-of-unique_lock) sections with their loop
    source text."""
    out: dict[str, _CFn] = {}
    depth = 0
    base_depth = 0   # open `namespace {` / `extern "C" {` wrappers
    fn: "_CFn | None" = None
    pending: "tuple[str, int] | None" = None   # (name, line) pre-'{'
    held: "list[tuple[str, int]]" = []   # (class, depth at declaration)
    vec_types: dict[str, str] = {}       # vector var name -> class
    last_for = ""    # most recent loop header (sweep-order evidence)
    for lineno, raw in enumerate(cc.read_text().splitlines(), 1):
        line = raw.split("//")[0]
        stripped = line.strip()
        if fn is None and depth == base_depth and "{" in stripped \
                and (stripped.startswith("namespace")
                     or stripped.startswith('extern "C"')):
            base_depth += 1
        elif fn is None and depth == base_depth:
            # A signature may span lines: remember the name at the
            # opening paren, arm the function body at the first '{'
            # (unless a ';' lands first — that was a prototype).
            if pending is None and stripped and "(" in stripped \
                    and not stripped.startswith(("#", "}",
                                                 "namespace")):
                m = _C_SIG_RE.match(re.sub(r"\(.*$", "(", stripped))
                if m and "=" not in stripped.split("(")[0]:
                    pending = (m.group(1), lineno)
            if pending is not None:
                brace, semi = line.find("{"), line.find(";")
                if brace >= 0 and (semi < 0 or brace < semi):
                    fn = _CFn(pending[0], pending[1])
                    pending = None
                elif semi >= 0:
                    pending = None
        if fn is not None:
            if re.search(r"\bfor\s*\(", line):
                last_for = stripped
            m = _C_VEC_GUARD_RE.search(line)
            if m:
                var = line.split(">")[-1].strip().rstrip(";").split(
                    " ")[-1]
                vec_types[var] = m.group(1)
            for var, klass in list(vec_types.items()):
                if f"{var}.emplace_back" in line \
                        or f"{var}.push_back" in line:
                    # Evidence = the acquiring line PLUS its enclosing
                    # loop header (a reversed iterator usually lives in
                    # the `for (...)`, not on the emplace line).
                    src = raw.strip()
                    if last_for and last_for not in src:
                        src = f"{last_for} | {src}"
                    fn.multi.append((klass, lineno, src))
            m = _C_GUARD_RE.search(line)
            if m and "vector" not in line:
                klass = m.group(1)
                if held:
                    fn.held_acquires.append(
                        (held[-1][0], klass, lineno))
                else:
                    fn.direct.append((klass, lineno))
                # Declaration depth includes braces OPENED EARLIER ON
                # THIS LINE: `if (x) { std::lock_guard<M> g(m); }`
                # lives one level deeper than the line's start, so the
                # net-zero brace count releases it at end of line
                # instead of holding it for the rest of the function.
                prefix = line[:m.start()]
                held.append((klass, depth + prefix.count("{")
                             - prefix.count("}")))
            if held:
                for cm in _C_CALL_RE.finditer(line):
                    name = cm.group(1)
                    if name not in ("lock_guard", "unique_lock",
                                    "vector", "emplace_back",
                                    "push_back"):
                        fn.held_calls.append(
                            (held[-1][0], name, lineno))
        depth += line.count("{") - line.count("}")
        base_depth = min(base_depth, max(depth, 0))
        held = [(c, d) for c, d in held if d <= depth]
        if fn is not None and depth <= base_depth:
            out.setdefault(fn.name, fn)
            fn = None
            held = []
            vec_types = {}
            last_for = ""
    if fn is not None:
        out.setdefault(fn.name, fn)
    return out


# ===========================================================================
# the combined graph + rules
# ===========================================================================

def build_graph(root: pathlib.Path,
                frontend: "pathlib.Path | None" = None,
                py_fns: "list | None" = None,
                py_bases: "dict | None" = None
                ) -> "tuple[LockGraph, dict]":
    frontend = frontend or (root / "native" / "frontend.cc")
    if py_fns is None:
        py_fns, py_bases = py_summaries(root)
    py_bases = py_bases or {}
    c_fns = c_lock_summaries(frontend) if frontend.exists() else {}
    c_file = rel(frontend, root)

    graph = LockGraph()
    #: bare name -> lock-acquiring functions (for call resolution).
    by_name: dict[str, list] = {}
    for fn in py_fns:
        if fn.direct:
            by_name.setdefault(fn.name, []).append(fn)

    for fn in py_fns:
        for lk, _attr, _f, _ln in fn.direct:
            graph.nodes.add(lk)
        for outer, inner, f, ln in fn.held_acquires:
            graph.add(outer, inner, f, ln,
                      f"nested acquisition in {fn.qualname}")
        for outer, outer_attr, callee, selfcall, f, ln in \
                fn.held_calls:
            if callee.startswith(("fe_", "dir_")) and callee in c_fns:
                cfn = c_fns[callee]
                for klass, cl in cfn.direct:
                    graph.add(outer, f"c:{klass}", f, ln,
                              f"{fn.qualname} calls {callee} (takes "
                              f"{klass} at {c_file}:{cl})")
                for klass, cl, _src in cfn.multi:
                    graph.add(outer, f"c:{klass}", f, ln,
                              f"{fn.qualname} calls {callee} "
                              f"(all-slices section at {c_file}:{cl})")
                continue
            targets = by_name.get(callee, ())
            if selfcall:
                # Resolve inside the class hierarchy; a callee taking
                # the SAME attribute is same-object re-entrancy (the
                # RLock pattern), not an ordering edge.
                hierarchy = _ancestors(fn.cls or "", py_bases)
                targets = [t for t in targets
                           if t.cls in hierarchy]
            elif len({t.cls or t.module for t in targets}) != 1:
                continue   # ambiguous bare name: no edge
            for target in targets:
                if target is fn:
                    continue
                for lk, attr, tf, tl in target.direct:
                    if selfcall and attr == outer_attr:
                        continue
                    graph.add(outer, lk, f, ln,
                              f"{fn.qualname} calls "
                              f"{target.qualname} (takes {lk} at "
                              f"{tf}:{tl})")

    for name, cfn in c_fns.items():
        for klass, _ln in cfn.direct:
            graph.nodes.add(f"c:{klass}")
        for outer, inner, ln in cfn.held_acquires:
            graph.add(f"c:{outer}", f"c:{inner}", c_file, ln,
                      f"nested acquisition in {name}()")
        for outer, callee, ln in cfn.held_calls:
            target = c_fns.get(callee)
            if target is None or target is cfn:
                continue
            for klass, tl in target.direct:
                graph.add(f"c:{outer}", f"c:{klass}", c_file, ln,
                          f"{name}() calls {callee}() (takes {klass} "
                          f"at {c_file}:{tl})")
            for klass, tl, _src in target.multi:
                graph.add(f"c:{outer}", f"c:{klass}", c_file, ln,
                          f"{name}() calls {callee}() (all-slices "
                          f"section at {c_file}:{tl})")
    return graph, c_fns


def check_graph(graph: LockGraph) -> "list[Finding]":
    findings: list[Finding] = []
    for cyc in graph.cycles():
        related = []
        for a, b in zip(cyc, cyc[1:] + cyc[:1]):
            f, ln, note = graph.edges[(a, b)]
            related.append((f, ln, f"{a} -> {b}: {note}"))
        f0, l0, _ = related[0]
        findings.append(Finding(
            "lock-cycle",
            "lock acquisition cycle: " + " -> ".join(cyc + [cyc[0]])
            + " — two paths taking these locks in opposite order "
            "deadlock under contention",
            f0, l0, tuple(related)))
    return findings


#: THE sanctioned all-slices combined section (named, not inferred
#: from file order): the fe_t0_retire config-retire sweep. Any other
#: multi-slice section is a finding — even if fe_t0_retire's own
#: sweep was refactored away meanwhile.
SANCTIONED_SWEEP = "fe_t0_retire"


def check_sweeps(c_fns: "dict[str, _CFn]",
                 c_file: str) -> "list[Finding]":
    findings: list[Finding] = []
    multi_sites = [(name, klass, ln, src)
                   for name, cfn in c_fns.items()
                   for klass, ln, src in cfn.multi]
    for name, klass, ln, src in multi_sites:
        if re.search(r"rbegin|\brend\b|reverse", src):
            findings.append(Finding(
                "slice-sweep-order",
                f"{name}() takes all {klass} slice locks in "
                f"NON-canonical order ({src!r}) — the documented "
                "all-slices sweep acquires in forward container "
                "order; any second ordering deadlocks against it",
                c_file, ln))
    sanctioned = [(name, klass, ln, src)
                  for name, klass, ln, src in multi_sites
                  if name == SANCTIONED_SWEEP]
    for name, klass, ln, _src in multi_sites:
        if name == SANCTIONED_SWEEP:
            continue
        related = tuple(
            (c_file, sl, f"the documented sweep: {sn}()")
            for sn, _sk, sl, _ss in sanctioned)
        findings.append(Finding(
            "slice-sweep-order",
            f"{name}() holds multiple {klass} slice locks combined "
            f"— only the documented {SANCTIONED_SWEEP}() sweep may "
            "do this; a second multi-slice section can order-race "
            "the first",
            c_file, ln, related))
    for name, cfn in c_fns.items():
        for outer, inner, ln in cfn.held_acquires:
            if outer == inner:
                findings.append(Finding(
                    "slice-sweep-order",
                    f"{name}() acquires a second {inner} while one "
                    "is already held — unordered multi-lock section "
                    "outside the documented all-slices sweep",
                    c_file, ln))
    return findings


def check(root: pathlib.Path,
          frontend: "pathlib.Path | None" = None) -> "list[Finding]":
    frontend = frontend or (root / "native" / "frontend.cc")
    graph, c_fns = build_graph(root, frontend)
    findings = check_graph(graph)
    findings += check_sweeps(c_fns, rel(frontend, root))
    return sorted(findings, key=lambda f: (f.rule, f.file, f.line))
