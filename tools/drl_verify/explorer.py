"""Bounded explicit-state exploration with counterexample traces.

The worlds (:mod:`tools.drl_verify.machines`) are deterministic labeled
transition systems: ``init_states()`` gives the roots, ``labels(s)``
the enabled actions, ``apply(s, label)`` the successor plus any
invariant violations the transition itself detects (monotonicity,
replay-divergence, budget bounds are all edge properties). The
explorer runs breadth-first, so the FIRST trace found for a violation
class is already the shortest; a greedy deletion pass then drops every
action the violation does not actually need (re-executing the
remainder from the root each time), which is what turns a 14-step
schedule into the 4-step story a human reads.

Bounds are explicit and LOUD: ``max_states`` / ``max_depth`` caps are
reported in the result so a truncated exploration can never read as an
exhaustive one (the ISSUE-14 contract: caps are logged, never silently
applied)."""

from __future__ import annotations

import dataclasses
from collections import deque

__all__ = ["Violation", "ExploreResult", "explore", "minimize_trace",
           "replay_trace"]


@dataclasses.dataclass
class Violation:
    """One invariant violation with its (minimized) counterexample."""

    world: str
    invariant: str
    detail: str
    trace: "tuple[str, ...]"   # action labels root -> violating action
    root: object               # the initial state the trace starts from
    key: str = ""              # the violation class key (dedup + names)

    def format(self) -> str:
        steps = "\n".join(f"    {i + 1}. {label}"
                          for i, label in enumerate(self.trace))
        return (f"[{self.world}] invariant '{self.invariant}' violated: "
                f"{self.detail}\n  counterexample "
                f"({len(self.trace)} steps):\n{steps}")


@dataclasses.dataclass
class ExploreResult:
    world: str
    states: int
    transitions: int
    depth: int
    violations: "list[Violation]"
    truncated_states: bool = False
    truncated_depth: bool = False
    invariants: "tuple[str, ...]" = ()

    @property
    def truncated(self) -> bool:
        return self.truncated_states or self.truncated_depth


def explore(world, *, max_states: int = 200_000,
            max_depth: int = 64) -> ExploreResult:
    """BFS over ``world``. Collects the first (shortest) violation per
    ``(invariant, detail-key)`` class, minimized. Exploration continues
    past a violating edge's SOURCE state but does not expand the
    violating successor (one bad state explains itself; its successors
    would only repeat the story)."""
    roots = list(world.init_states())
    seen: "dict[object, tuple[object, str] | None]" = {
        s: None for s in roots}
    queue = deque((s, 0) for s in roots)
    violations: "dict[tuple[str, str], Violation]" = {}
    transitions = 0
    depth_reached = 0
    truncated_states = truncated_depth = False

    def trace_to(state: object) -> "tuple[list[str], object]":
        labels: list[str] = []
        cur = state
        while seen[cur] is not None:
            prev, label = seen[cur]
            labels.append(label)
            cur = prev
        labels.reverse()
        return labels, cur

    while queue:
        state, depth = queue.popleft()
        depth_reached = max(depth_reached, depth)
        if depth >= max_depth:
            truncated_depth = True
            continue
        for label in world.labels(state):
            nxt, viols = world.apply(state, label)
            transitions += 1
            bad = False
            for inv, detail, key in viols:
                bad = True
                vkey = (inv, key)
                if vkey not in violations:
                    prefix, root = trace_to(state)
                    trace = tuple(prefix + [label])
                    trace = minimize_trace(world, root, trace, inv, key)
                    violations[vkey] = Violation(
                        world.name, inv, detail, trace, root, key)
            if bad or nxt is None or nxt in seen:
                continue
            if len(seen) >= max_states:
                truncated_states = True
                continue
            seen[nxt] = (state, label)
            queue.append((nxt, depth + 1))

    return ExploreResult(
        world=world.name, states=len(seen), transitions=transitions,
        depth=depth_reached,
        violations=sorted(violations.values(),
                          key=lambda v: (v.invariant, v.detail)),
        truncated_states=truncated_states,
        truncated_depth=truncated_depth,
        invariants=tuple(getattr(world, "invariants", ())),
    )


def replay_trace(world, root, trace: "tuple[str, ...]"
                 ) -> "tuple[str, str, str] | None":
    """Re-execute ``trace`` from ``root``; returns the first violation
    tuple the final action produces (``None`` when the schedule is not
    even executable — a label disabled along the way — or ends clean).
    Intermediate violations don't count: a minimized trace must put its
    violation at the END, where the generated replay test asserts."""
    state = root
    for i, label in enumerate(trace):
        if label not in world.labels(state):
            return None
        state, viols = world.apply(state, label)
        if i < len(trace) - 1:
            if viols or state is None:
                return None
    return viols[0] if viols else None


def minimize_trace(world, root, trace: "tuple[str, ...]",
                   invariant: str, key: str) -> "tuple[str, ...]":
    """Greedy single-deletion minimization: drop any action whose
    removal still reproduces the SAME (invariant, key) violation at the
    end of the schedule. BFS already gives the shortest path through
    the state graph; this removes actions that were merely on the way
    (a dup delivery, an unrelated acquire)."""
    labels = list(trace)
    changed = True
    while changed:
        changed = False
        for i in range(len(labels) - 1):  # never drop the final action
            cand = tuple(labels[:i] + labels[i + 1:])
            viol = replay_trace(world, root, cand)
            if viol is not None and viol[0] == invariant \
                    and viol[2] == key:
                labels = list(cand)
                changed = True
                break
    return tuple(labels)
