"""Wire/ABI conformance: diff the two protocol implementations.

``runtime/wire.py`` is the normative spec (docs/DESIGN.md §10);
``native/frontend.cc`` re-implements the hot subset in C. This analyzer
extracts a wire model from each side and diffs them:

- **Constants** (``wire-const``): every opcode / response kind / flag
  bit / version / size bound the C side mirrors must exist in the
  Python module with the same value. The C side deliberately names only
  the ops it fast-paths (everything else is passthrough — Python stays
  the authority), so the strict direction is C → Python.
- **Frame layouts** (``wire-layout``): the C parser's hand-written
  offset arithmetic for the keyed-request tail, the decision/error
  replies, and the trace tail must match the ``struct`` formats that
  define them in Python (field order, width, total size).
- **Endianness** (``wire-endian``): every ``struct.Struct`` format in
  ``wire.py`` must pin little-endian (``<``) — the C side assumes an LE
  host and does raw ``memcpy``.
- **Dispatch coverage** (``wire-dispatch``): every ``OP_*`` constant in
  ``wire.py`` must have a dispatch reference in ``runtime/server.py`` —
  an op no handler answers is dead protocol surface (file:line on both
  sides).
- **ctypes ABI** (``abi-export``): every ``fe_*``/``dir_*`` symbol the
  loader (``utils/native.py``) binds must be exported by the
  corresponding ``.cc``, and vice versa — a symbol on one side only is
  either a binding that can never resolve or dead C surface nothing
  feature-detects.
- **Retry-safety classification** (``wire-idempotency``): every
  ``OP_*`` constant in ``wire.py`` must be explicitly classified in
  exactly one of ``_IDEMPOTENT_OPS`` / ``_NON_IDEMPOTENT_OPS`` in
  ``runtime/remote.py`` (file:line on both sides). The idempotent set
  is the client's post-send retry whitelist — an op missing from BOTH
  sets is a deliberate-looking accident: nobody decided whether a
  retry after an ambiguous failure can double-apply it, and a future
  op silently defaults to whatever the author forgot to think about.
- **Transport-mode flags** (``transport-flag``): the io_uring transport
  selector ``fe_start_sharded2`` takes (``kUringOff`` / ``kUringOn`` /
  ``kUringSqpoll`` in C; ``URING_OFF`` / ``URING_ON`` /
  ``URING_SQPOLL`` in ``utils/native.py``) must exist on both sides
  with equal values — a drift here silently starts the wrong transport
  (an operator asking for SQPOLL getting plain uring, or uring getting
  epoll) with no error anywhere.
- **Tenant-extension fallthrough** (``wire-hier``): the hierarchical
  frames (``OP_ACQUIRE_H``, ``BULK_KIND_HBUCKET``) carry a tenant
  extension the C parser does not speak, so they MUST reach the Python
  lane: the bulk parser's ``kind > BULK_KIND_FWINDOW`` gate must exist,
  the scalar switch must not case-list ``OP_ACQUIRE_H`` (a case there
  would parse the frame as the flat keyed shape and silently drop the
  tenant level), the HBUCKET kind value must sit above the C fast
  lane's gate and inside the 2-bit kind field, and ``wire.py`` must
  define the extension pieces (``_HIER_TAIL``) the rule is pinning.
"""

from __future__ import annotations

import ast
import pathlib
import re
import struct as struct_mod

from tools.drl_check.common import (
    Finding,
    const_eval_c,
    const_eval_py,
    rel,
)

__all__ = ["check", "check_wire", "check_abi", "check_dispatch",
           "check_idempotency", "check_transport_flags",
           "extract_py_model", "extract_c_model"]


# -- Python-side model ------------------------------------------------------

class PyWireModel:
    def __init__(self) -> None:
        self.constants: dict[str, tuple[int, int]] = {}   # name -> (value, line)
        self.structs: dict[str, tuple[str, int]] = {}     # name -> (fmt, line)

    def struct_size(self, name: str) -> int | None:
        if name not in self.structs:
            return None
        return struct_mod.calcsize(self.structs[name][0])

    def field_offsets(self, name: str) -> "list[tuple[str, int]] | None":
        """Per-field (format char, byte offset) of a struct format."""
        if name not in self.structs:
            return None
        fmt = self.structs[name][0]
        body = fmt[1:] if fmt[:1] in "<>=!@" else fmt
        prefix = fmt[:1] if fmt[:1] in "<>=!@" else ""
        out: list[tuple[str, int]] = []
        seen = ""
        for ch in body:
            out.append((ch, struct_mod.calcsize(prefix + seen)))
            seen += ch
        return out


def extract_py_model(wire_py: pathlib.Path) -> PyWireModel:
    tree = ast.parse(wire_py.read_text())
    model = PyWireModel()
    struct_sizes: dict[str, int] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        value = node.value
        if (isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and value.func.attr == "Struct"
                and value.args
                and isinstance(value.args[0], ast.Constant)
                and isinstance(value.args[0].value, str)):
            fmt = value.args[0].value
            model.structs[target.id] = (fmt, node.lineno)
            struct_sizes[target.id] = struct_mod.calcsize(fmt)
            continue
        const = const_eval_py(value, struct_sizes)
        if const is not None:
            model.constants[target.id] = (const, node.lineno)
    return model


# -- C-side model -----------------------------------------------------------

class CWireModel:
    def __init__(self) -> None:
        self.constants: dict[str, tuple[int, int]] = {}
        self.text = ""
        self.lines: list[str] = []

    def line_of(self, offset: int) -> int:
        return self.text.count("\n", 0, offset) + 1


_C_CONST_RE = re.compile(
    r"constexpr\s+(?:uint8_t|uint16_t|uint32_t|uint64_t|int|size_t|double)"
    r"\s+(\w+)\s*=\s*([^;]+);")


def extract_c_model(frontend_cc: pathlib.Path) -> CWireModel:
    model = CWireModel()
    model.text = frontend_cc.read_text()
    model.lines = model.text.splitlines()
    for m in _C_CONST_RE.finditer(model.text):
        value = const_eval_c(m.group(2))
        if value is not None:
            model.constants[m.group(1)] = (value, model.line_of(m.start()))
    return model


# -- the conformance diff ---------------------------------------------------

#: C names whose Python counterpart has a different spelling. Everything
#: matching _MIRRORED_PREFIX maps by identity.
_C_TO_PY = {
    "kVersion": "PROTOCOL_VERSION",
    "kMaxFrame": "MAX_FRAME",
    "kBodyOff": "_BODY_OFF",
    "kTraceTail": "TRACE_TAIL_LEN",
    # Native bulk lane (round 8): the C parser/encoder's head widths and
    # flag bits mirror wire.py's private bulk-layout names.
    "kBulkReqHead": "BULK_REQ_HEAD_LEN",
    "kBulkRespHead": "BULK_RESP_HEAD_LEN",
    "kBulkFlagRemaining": "_FLAG_WITH_REMAINING",
    "kBulkFlagChained": "_FLAG_CHAINED",
    "kBulkKindMask": "_KIND_MASK",
    "kBulkKindShift": "_KIND_SHIFT",
}
_MIRRORED_PREFIX = re.compile(
    r"^(OP_|RESP_|TRACE_FLAG$|STATS_FLAG_|BULK_FLAG_|BULK_KIND_)")

#: The wire.py names C hard-codes via the mapped k-constants; used for
#: the Python-side existence direction of the diff.
_PY_FROM_C = set(_C_TO_PY.values())


def _diff_constants(py: PyWireModel, c: CWireModel, wire_rel: str,
                    cc_rel: str) -> list[Finding]:
    findings: list[Finding] = []
    for c_name, (c_val, c_line) in sorted(c.constants.items()):
        py_name = _C_TO_PY.get(c_name)
        if py_name is None:
            if not _MIRRORED_PREFIX.match(c_name):
                continue  # internal C tunable (kMaxConnOut, kT0Probe, …)
            py_name = c_name
        if py_name not in py.constants:
            findings.append(Finding(
                "wire-const",
                f"{c_name} = {c_val} mirrors wire constant {py_name!r}, "
                f"which {wire_rel} does not define",
                cc_rel, c_line,
                ((wire_rel, 1, f"no assignment to {py_name}"),)))
            continue
        py_val, py_line = py.constants[py_name]
        if py_val != c_val:
            findings.append(Finding(
                "wire-const",
                f"{c_name} = {c_val} disagrees with {py_name} = {py_val} "
                f"({wire_rel}:{py_line})",
                cc_rel, c_line,
                ((wire_rel, py_line,
                  f"python side defines {py_name} = {py_val}"),)))
    return findings


def _check_endianness(py: PyWireModel, wire_rel: str) -> list[Finding]:
    findings = []
    for name, (fmt, line) in sorted(py.structs.items()):
        if not fmt.startswith("<"):
            findings.append(Finding(
                "wire-endian",
                f"struct format {name} = {fmt!r} does not pin "
                "little-endian ('<'); frontend.cc memcpy-decodes assuming "
                "an LE wire", wire_rel, line))
    return findings


def _c_region(c: CWireModel, start_pat: str, end_pat: str
              ) -> tuple[str, int] | None:
    """Text between two regex anchors, plus the start line number."""
    m = re.search(start_pat, c.text)
    if m is None:
        return None
    m_end = re.search(end_pat, c.text[m.end():])
    end = m.end() + (m_end.start() if m_end else len(c.text) - m.end())
    return c.text[m.start():end], c.line_of(m.start())


def _layout_checks(py: PyWireModel, c: CWireModel, wire_rel: str,
                   cc_rel: str) -> list[Finding]:
    """Cross-check frontend.cc's hand-written offset arithmetic against
    the struct formats that define the layouts in wire.py."""
    findings: list[Finding] = []

    def mismatch(line: int, msg: str, py_struct: str) -> None:
        py_line = py.structs.get(py_struct, ("", 1))[1]
        findings.append(Finding(
            "wire-layout", msg, cc_rel, line,
            ((wire_rel, py_line,
              f"layout defined by {py_struct} = "
              f"{py.structs.get(py_struct, ('?',))[0]!r}"),)))

    # 1. Keyed-request frame: [u16 klen][key][i32 count][f64 a][f64 b].
    keyed = py.struct_size("_KEYED")
    acq = py.struct_size("_ACQ_TAIL")
    region = _c_region(c, r"case OP_ACQUIRE:", r"case OP_PING:")
    if region and keyed is not None and acq is not None:
        text, base = region
        m = re.search(
            r"len\s*!=\s*kBodyOff\s*\+\s*(\d+)\s*\+\s*size_t\(klen\)"
            r"\s*\+\s*(\d+)", text)
        if m is None:
            mismatch(base, "cannot find the keyed-request length check "
                     "(kBodyOff + <keyed> + klen + <tail>) in the "
                     "OP_ACQUIRE case", "_ACQ_TAIL")
        else:
            c_keyed, c_tail = int(m.group(1)), int(m.group(2))
            at_line = base + text.count("\n", 0, m.start())
            if c_keyed != keyed:
                mismatch(at_line,
                         f"keyed header width {c_keyed} != "
                         f"struct.calcsize(_KEYED) = {keyed}", "_KEYED")
            if c_tail != acq:
                mismatch(at_line,
                         f"request tail width {c_tail} != "
                         f"struct.calcsize(_ACQ_TAIL) = {acq}", "_ACQ_TAIL")
        # Field reads: rd_i32(kp + klen), rd_f64(kp + klen + 4 / + 12).
        expected = py.field_offsets("_ACQ_TAIL") or []
        type_of = {"i": "rd_i32", "d": "rd_f64", "I": "rd_u32",
                   "H": "rd_u16", "Q": "rd_u64"}
        reads = [(m.group(1), int(m.group(2) or 0),
                  base + text.count("\n", 0, m.start()))
                 for m in re.finditer(
                     r"(rd_\w+)\(\s*\w+\s*\+\s*klen(?:\s*\+\s*(\d+))?\s*\)",
                     text)]
        want = [(type_of.get(ch, "?"), off) for ch, off in expected]
        got = [(fn, off) for fn, off, _ in reads]
        if want != got:
            at = reads[0][2] if reads else base
            mismatch(at,
                     f"keyed-request tail reads {got} do not match "
                     f"_ACQ_TAIL field layout {want}", "_ACQ_TAIL")

    # 2. Decision reply: [u8 granted][f64 remaining] == _DECISION.
    decision = py.struct_size("_DECISION")
    region = _c_region(c, r"std::string encode_decision",
                       r"std::string encode_empty")
    if region and decision is not None:
        text, base = region
        m = re.search(r"kBodyOff\s*\+\s*(\d+)", text)
        if m is None or int(m.group(1)) != decision:
            got = "absent" if m is None else m.group(1)
            at = base if m is None else base + text.count("\n", 0, m.start())
            mismatch(at,
                     f"encode_decision payload width {got} != "
                     f"struct.calcsize(_DECISION) = {decision}", "_DECISION")

    # 3. Error reply: [u16 mlen][msg] — header width mirrors _KEYED.
    region = _c_region(c, r"std::string encode_error", r"struct Item")
    if region and keyed is not None:
        text, base = region
        m = re.search(r"kBodyOff\s*\+\s*(\d+)\s*\+\s*mlen", text)
        if m is None or int(m.group(1)) != keyed:
            got = "absent" if m is None else m.group(1)
            at = base if m is None else base + text.count("\n", 0, m.start())
            mismatch(at,
                     f"encode_error length-prefix width {got} != "
                     f"struct.calcsize(_KEYED) = {keyed}", "_KEYED")

    # 4. Bulk request head: [u8 flags][f64 a][f64 b][u32 n] — the native
    # bulk lane's hand-written reads in handle_bulk_frame must match
    # _BULK_REQ_HEAD's field table (the head-size constant itself is
    # covered by the kBulkReqHead ↔ BULK_REQ_HEAD_LEN diff above).
    bulk_fields = py.field_offsets("_BULK_REQ_HEAD")
    region = _c_region(c, r"bool handle_bulk_frame", r"void drain_parked")
    if region and bulk_fields is not None:
        text, base = region
        reads = [(m.group(1), int(m.group(2)),
                  base + text.count("\n", 0, m.start()))
                 for m in re.finditer(
                     r"(rd_f64|rd_u32)\(p \+ (\d+)\)", text)]
        want = [("rd_f64" if ch == "d" else "rd_u32", off)
                for ch, off in bulk_fields if ch in "dI"]
        got = [(fn, off) for fn, off, _ in reads]
        if want != got:
            at = reads[0][2] if reads else base
            mismatch(at,
                     f"bulk-request head reads {got} do not match "
                     f"_BULK_REQ_HEAD field layout {want}",
                     "_BULK_REQ_HEAD")

    # 5. Trace tail: [u64 hi][u64 lo][u64 parent][u8 flags] — the C parse
    # memcpys at fixed offsets that must match _TRACE_TAIL's field table.
    tail_fields = py.field_offsets("_TRACE_TAIL")
    region = _c_region(c, r"if \(traced\) \{", r"if \(op == OP_ACQUIRE")
    if region and tail_fields is not None:
        text, base = region
        got_offsets = sorted(
            int(m.group(1) or 0) for m in re.finditer(
                r"std::memcpy\(&it\.tr_\w+,\s*tp(?:\s*\+\s*(\d+))?,\s*8\)",
                text))
        flag_reads = [int(m.group(1))
                      for m in re.finditer(r"tp\[(\d+)\]", text)]
        want_q = sorted(off for ch, off in tail_fields if ch == "Q")
        want_b = [off for ch, off in tail_fields if ch == "B"]
        if got_offsets != want_q or sorted(set(flag_reads)) != want_b:
            mismatch(base,
                     f"trace-tail parse offsets u64@{got_offsets} "
                     f"flags@{sorted(set(flag_reads))} do not match "
                     f"_TRACE_TAIL layout u64@{want_q} flags@{want_b}",
                     "_TRACE_TAIL")
    return findings


def _hier_checks(py: PyWireModel, c: CWireModel, wire_rel: str,
                 cc_rel: str) -> list[Finding]:
    """``wire-hier``: pin the tenant extension's Python-lane
    fallthrough (see module doc). The hierarchical frames are the one
    wire surface the C side deliberately does NOT mirror — this rule
    is what keeps that deliberate, not accidental."""
    findings: list[Finding] = []
    missing = [n for n in ("OP_ACQUIRE_H", "BULK_KIND_HBUCKET")
               if n not in py.constants]
    if "_HIER_TAIL" not in py.structs:
        missing.append("_HIER_TAIL")
    if missing:
        return [Finding(
            "wire-hier",
            f"wire.py no longer defines {', '.join(missing)} — the "
            "tenant-extension surface this rule pins is gone (remove "
            "the rule only with the feature)",
            wire_rel, 1, ((cc_rel, 1, "C fallthrough pinned here"),))]
    hb, hb_line = py.constants["BULK_KIND_HBUCKET"]
    fw = c.constants.get("BULK_KIND_FWINDOW")
    if fw is not None and hb <= fw[0]:
        findings.append(Finding(
            "wire-hier",
            f"BULK_KIND_HBUCKET = {hb} does not sit above the C bulk "
            f"fast lane's kind gate (BULK_KIND_FWINDOW = {fw[0]}, "
            f"{cc_rel}:{fw[1]}) — HBUCKET frames would parse as a flat "
            "kind and silently drop the tenant level",
            wire_rel, hb_line, ((cc_rel, fw[1], "C kind gate bound"),)))
    mask = py.constants.get("_KIND_MASK")
    shift = py.constants.get("_KIND_SHIFT")
    if mask is not None and shift is not None \
            and hb > (mask[0] >> shift[0]):
        findings.append(Finding(
            "wire-hier",
            f"BULK_KIND_HBUCKET = {hb} does not fit the kind field "
            f"(_KIND_MASK >> _KIND_SHIFT = {mask[0] >> shift[0]}) — "
            "the flag bits cannot encode it",
            wire_rel, hb_line, ((wire_rel, mask[1], "_KIND_MASK"),)))
    m = re.search(r"kind\s*>\s*BULK_KIND_FWINDOW\s*\)\s*return false",
                  c.text)
    if m is None:
        anchor = re.search(r"bool handle_bulk_frame", c.text)
        at = c.line_of(anchor.start()) if anchor else 1
        findings.append(Finding(
            "wire-hier",
            "handle_bulk_frame no longer routes kinds past "
            "BULK_KIND_FWINDOW to the Python lane (`kind > "
            "BULK_KIND_FWINDOW) return false` gate missing) — HBUCKET "
            "frames would be misparsed in C instead of served by "
            "wire.py", cc_rel, at,
            ((wire_rel, hb_line, "BULK_KIND_HBUCKET defined here"),)))
    m = re.search(r"case\s+OP_ACQUIRE_H\s*:", c.text)
    if m is not None:
        findings.append(Finding(
            "wire-hier",
            "frontend.cc case-lists OP_ACQUIRE_H in a switch — the C "
            "parser does not speak the tenant extension, so the op "
            "must stay on the default (passthrough) arm; a real C fast "
            "path must mirror the full tenant tail layout first and "
            "retire this rule deliberately",
            cc_rel, c.line_of(m.start()),
            ((wire_rel, py.constants["OP_ACQUIRE_H"][1],
              "OP_ACQUIRE_H defined here"),)))
    return findings


# -- ctypes ABI cross-check -------------------------------------------------

_PY_SYMBOL_RE = re.compile(r"^(fe_|dir_)\w+$")
# A C export: return type then the symbol then '(' at (possibly indented)
# line start, inside an extern "C" region.
_C_DEF_RE = re.compile(
    r"^[ \t]*(?:[A-Za-z_][\w:<>]*[*\s]+)+((?:fe_|dir_)\w+)\s*\(",
    re.MULTILINE)


def _py_bound_symbols(native_py: pathlib.Path) -> dict[str, int]:
    """Every ``lib.fe_*`` / ``lib.dir_*`` attribute the ctypes loader
    touches (binding ``argtypes``/``restype`` or calling) → first line."""
    tree = ast.parse(native_py.read_text())
    symbols: dict[str, int] = {}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Attribute)
                and _PY_SYMBOL_RE.match(node.attr)):
            symbols.setdefault(node.attr, node.lineno)
    return symbols


def _c_exported_symbols(cc: pathlib.Path) -> dict[str, tuple[int, bool]]:
    """extern-"C" definitions → (line, conditional) where ``conditional``
    marks symbols inside ``#ifdef DRL_WITH_PYTHON`` (present only in
    builds with CPython headers — the loader feature-detects them)."""
    text = cc.read_text()
    # Track the DRL_WITH_PYTHON conditional spans (no nesting in-tree).
    cond_spans: list[tuple[int, int]] = []
    start = None
    depth = 0
    for m in re.finditer(r"^[ \t]*#[ \t]*(ifdef|ifndef|if|endif)\b.*$",
                         text, re.MULTILINE):
        directive = m.group(1)
        if directive in ("ifdef", "ifndef", "if"):
            if start is None and "DRL_WITH_PYTHON" in m.group(0) \
                    and directive == "ifdef":
                start = m.end()
                depth = 1
            elif start is not None:
                depth += 1
        elif directive == "endif" and start is not None:
            depth -= 1
            if depth == 0:
                cond_spans.append((start, m.start()))
                start = None
    out: dict[str, tuple[int, bool]] = {}
    for m in _C_DEF_RE.finditer(text):
        line = text.count("\n", 0, m.start()) + 1
        conditional = any(s <= m.start() < e for s, e in cond_spans)
        out.setdefault(m.group(1), (line, conditional))
    return out


def check_abi(native_py: pathlib.Path, cc_files: "list[pathlib.Path]",
              root: pathlib.Path) -> list[Finding]:
    findings: list[Finding] = []
    bound = _py_bound_symbols(native_py)
    exported: dict[str, tuple[str, int, bool]] = {}
    for cc in cc_files:
        cc_rel = rel(cc, root)
        for name, (line, cond) in _c_exported_symbols(cc).items():
            exported.setdefault(name, (cc_rel, line, cond))
    py_rel = rel(native_py, root)
    for name, line in sorted(bound.items()):
        if name not in exported:
            findings.append(Finding(
                "abi-export",
                f"ctypes binds {name!r} but no native source exports it "
                "— the binding can never resolve (or resolves against a "
                "stale binary)", py_rel, line,
                tuple((rel(cc, root), 1, "searched this file")
                      for cc in cc_files)))
    for name, (cc_rel, line, _cond) in sorted(exported.items()):
        if name not in bound:
            findings.append(Finding(
                "abi-export",
                f"native export {name!r} has no ctypes binding in "
                f"{py_rel} — dead ABI surface nothing feature-detects",
                cc_rel, line, ((py_rel, 1, "no lib.<symbol> reference"),)))
    return findings


# -- transport-mode flag cross-check ----------------------------------------

#: fe_start_sharded2's uring_mode values: C constexpr name → the
#: utils/native.py module constant that must mirror it. Pinned BOTH
#: directions — a value drift or a missing side silently starts the
#: wrong transport (no error: the C side would just run a mode the
#: Python caller didn't mean).
_TRANSPORT_FLAGS = {
    "kUringOff": "URING_OFF",
    "kUringOn": "URING_ON",
    "kUringSqpoll": "URING_SQPOLL",
}


def _py_module_constants(py_file: pathlib.Path) -> dict[str, tuple[int, int]]:
    """Module-level integer assignments → (value, line)."""
    tree = ast.parse(py_file.read_text())
    out: dict[str, tuple[int, int]] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        value = const_eval_py(node.value, {})
        if value is not None:
            out[target.id] = (value, node.lineno)
    return out


def check_transport_flags(native_py: pathlib.Path,
                          frontend_cc: pathlib.Path,
                          root: pathlib.Path) -> list[Finding]:
    """``transport-flag``: the uring transport-mode trio must exist on
    both sides of the ctypes boundary with equal values."""
    c = extract_c_model(frontend_cc)
    py_consts = _py_module_constants(native_py)
    py_rel = rel(native_py, root)
    cc_rel = rel(frontend_cc, root)
    findings: list[Finding] = []
    for c_name, py_name in sorted(_TRANSPORT_FLAGS.items()):
        c_has = c_name in c.constants
        py_has = py_name in py_consts
        if not c_has and not py_has:
            findings.append(Finding(
                "transport-flag",
                f"transport mode pair {c_name}/{py_name} is defined on "
                "neither side — the fe_start_sharded2 mode contract is "
                "gone; retire this rule deliberately if the transport "
                "knob was removed", cc_rel, 1, ((py_rel, 1, "searched"),)))
            continue
        if not c_has:
            py_val, py_line = py_consts[py_name]
            findings.append(Finding(
                "transport-flag",
                f"{py_name} = {py_val} has no C counterpart {c_name} in "
                f"{cc_rel} — fe_start_sharded2 would receive a mode the "
                "C side never interprets", py_rel, py_line,
                ((cc_rel, 1, f"no constexpr {c_name}"),)))
            continue
        if not py_has:
            c_val, c_line = c.constants[c_name]
            findings.append(Finding(
                "transport-flag",
                f"{c_name} = {c_val} has no Python counterpart "
                f"{py_name} in {py_rel} — callers cannot name this "
                "transport mode", cc_rel, c_line,
                ((py_rel, 1, f"no assignment to {py_name}"),)))
            continue
        c_val, c_line = c.constants[c_name]
        py_val, py_line = py_consts[py_name]
        if c_val != py_val:
            findings.append(Finding(
                "transport-flag",
                f"{c_name} = {c_val} disagrees with {py_name} = "
                f"{py_val} ({py_rel}:{py_line}) — fe_start_sharded2 "
                "would start a different transport than the caller "
                "asked for", cc_rel, c_line,
                ((py_rel, py_line,
                  f"python side defines {py_name} = {py_val}"),)))
    return findings


# -- op dispatch coverage ---------------------------------------------------

def _server_op_references(server_py: pathlib.Path) -> dict[str, int]:
    """Every ``wire.OP_*`` attribute the server module reads → first
    line. Attribute access is the dispatch idiom throughout server.py
    (comparisons, membership sets, handler branches)."""
    tree = ast.parse(server_py.read_text())
    refs: dict[str, int] = {}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Attribute)
                and node.attr.startswith("OP_")
                and isinstance(node.value, ast.Name)
                and node.value.id == "wire"):
            refs.setdefault(node.attr, node.lineno)
    return refs


def check_dispatch(wire_py: pathlib.Path, server_py: pathlib.Path,
                   root: pathlib.Path) -> list[Finding]:
    """``wire-dispatch``: every ``OP_*`` constant wire.py defines must
    be referenced by the server's dispatch (runtime/server.py). An op
    without a handler is dead protocol surface — a client can emit a
    frame the fleet answers only with 'unknown op', which reads as an
    old-peer latch, not the bug it is."""
    py = extract_py_model(wire_py)
    refs = _server_op_references(server_py)
    wire_rel = rel(wire_py, root)
    server_rel = rel(server_py, root)
    findings: list[Finding] = []
    for name, (value, line) in sorted(py.constants.items()):
        if not name.startswith("OP_"):
            continue
        if name not in refs:
            findings.append(Finding(
                "wire-dispatch",
                f"{name} = {value} has no dispatch reference in "
                f"{server_rel} — a frame carrying it is dead protocol "
                "surface (answered 'unknown op')",
                wire_rel, line,
                ((server_rel, 1, f"no wire.{name} reference"),)))
    return findings


# -- retry-safety classification --------------------------------------------

_IDEMPOTENCY_SETS = ("_IDEMPOTENT_OPS", "_NON_IDEMPOTENT_OPS")


def _remote_op_sets(remote_py: pathlib.Path
                    ) -> "dict[str, tuple[dict[str, int], int]]":
    """The two classification sets in remote.py: ``{set_name:
    ({op_name: line}, assignment_line)}``. Members are the ``wire.OP_*``
    attributes inside the (frozen)set literal the name is assigned."""
    tree = ast.parse(remote_py.read_text())
    out: dict[str, tuple[dict[str, int], int]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name) \
                or target.id not in _IDEMPOTENCY_SETS:
            continue
        members: dict[str, int] = {}
        for sub in ast.walk(node.value):
            if (isinstance(sub, ast.Attribute)
                    and sub.attr.startswith("OP_")
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "wire"):
                members.setdefault(sub.attr, sub.lineno)
        out[target.id] = (members, node.lineno)
    return out


def check_idempotency(wire_py: pathlib.Path, remote_py: pathlib.Path,
                      root: pathlib.Path) -> list[Finding]:
    """``wire-idempotency``: every ``OP_*`` in wire.py appears in
    exactly one of remote.py's ``_IDEMPOTENT_OPS`` /
    ``_NON_IDEMPOTENT_OPS``. In one set = someone decided whether a
    post-send retry may replay it; in neither = the decision was never
    made (and the op silently defaults to retry-unsafe); in both = the
    two halves of the classification disagree."""
    py = extract_py_model(wire_py)
    sets = _remote_op_sets(remote_py)
    wire_rel = rel(wire_py, root)
    remote_rel = rel(remote_py, root)
    findings: list[Finding] = []
    missing_sets = [s for s in _IDEMPOTENCY_SETS if s not in sets]
    if missing_sets:
        return [Finding(
            "wire-idempotency",
            f"remote.py does not define {', '.join(missing_sets)} — the "
            "explicit retry-safety classification is gone",
            remote_rel, 1, ((wire_rel, 1, "ops defined here"),))]
    for name, (value, line) in sorted(py.constants.items()):
        if not name.startswith("OP_"):
            continue
        homes = [s for s in _IDEMPOTENCY_SETS if name in sets[s][0]]
        if len(homes) == 1:
            continue
        if not homes:
            findings.append(Finding(
                "wire-idempotency",
                f"{name} = {value} is classified in neither "
                "_IDEMPOTENT_OPS nor _NON_IDEMPOTENT_OPS — decide "
                "whether a post-send retry may replay it and say so "
                "explicitly",
                wire_rel, line,
                tuple((remote_rel, sets[s][1], f"{s} defined here")
                      for s in _IDEMPOTENCY_SETS)))
        else:
            findings.append(Finding(
                "wire-idempotency",
                f"{name} = {value} appears in BOTH _IDEMPOTENT_OPS and "
                "_NON_IDEMPOTENT_OPS — the classification contradicts "
                "itself",
                wire_rel, line,
                tuple((remote_rel, sets[s][0][name], f"member of {s}")
                      for s in _IDEMPOTENCY_SETS)))
    return findings


# -- entry points -----------------------------------------------------------

def check_wire(wire_py: pathlib.Path, frontend_cc: pathlib.Path,
               root: pathlib.Path) -> list[Finding]:
    py = extract_py_model(wire_py)
    c = extract_c_model(frontend_cc)
    wire_rel = rel(wire_py, root)
    cc_rel = rel(frontend_cc, root)
    findings = _diff_constants(py, c, wire_rel, cc_rel)
    findings += _check_endianness(py, wire_rel)
    findings += _layout_checks(py, c, wire_rel, cc_rel)
    findings += _hier_checks(py, c, wire_rel, cc_rel)
    return findings


def check(root: pathlib.Path) -> list[Finding]:
    pkg = root / "distributedratelimiting" / "redis_tpu"
    findings = check_wire(pkg / "runtime" / "wire.py",
                          root / "native" / "frontend.cc", root)
    findings += check_dispatch(pkg / "runtime" / "wire.py",
                               pkg / "runtime" / "server.py", root)
    findings += check_idempotency(pkg / "runtime" / "wire.py",
                                  pkg / "runtime" / "remote.py", root)
    findings += check_abi(pkg / "utils" / "native.py",
                          [root / "native" / "frontend.cc",
                           root / "native" / "directory.cc"], root)
    findings += check_transport_flags(pkg / "utils" / "native.py",
                                      root / "native" / "frontend.cc",
                                      root)
    return findings
