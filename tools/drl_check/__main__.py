"""CLI: ``python -m tools.drl_check [--json] [--only ANALYZER]``.

Exit status: 0 = clean, 1 = findings, 2 = analyzer crash (a bug in the
checker itself, never silently swallowed into a fake 'clean')."""

from __future__ import annotations

import argparse
import collections
import json
import pathlib
import sys

from tools.drl_check import (
    build_freshness,
    concurrency_lint,
    flight_kinds,
    jax_lint,
    metric_names,
    stale_suppression,
    wire_conformance,
)

_ANALYZERS = {
    "wire": wire_conformance.check,
    "concurrency": concurrency_lint.check,
    "jax": jax_lint.check,
    "freshness": build_freshness.check,
    "metrics": metric_names.check,
    "flightkinds": flight_kinds.check,
    "suppressions": stale_suppression.check,
}


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        prog="drl-check",
        description="repo-specific wire/ABI conformance + concurrency "
                    "and JAX hot-path lints (see tools/drl_check)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings on stdout")
    parser.add_argument("--only", choices=sorted(_ANALYZERS),
                        action="append",
                        help="run only this analyzer (repeatable)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: inferred from this "
                             "package's location)")
    args = parser.parse_args(argv)

    root = pathlib.Path(args.root) if args.root else \
        pathlib.Path(__file__).resolve().parents[2]
    selected = args.only or sorted(_ANALYZERS)

    findings = []
    for name in selected:
        try:
            findings += _ANALYZERS[name](root)
        except Exception as exc:  # noqa: BLE001 — checker bug: loud, rc 2
            print(f"drl-check: analyzer {name!r} crashed: {exc!r}",
                  file=sys.stderr)
            return 2

    if args.json:
        print(json.dumps([{
            "rule": f.rule, "file": f.file, "line": f.line,
            "message": f.message,
            "related": [list(r) for r in f.related],
        } for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
        if findings:
            by_rule = collections.Counter(f.rule for f in findings)
            summary = ", ".join(f"{n} {rule}"
                                for rule, n in sorted(by_rule.items()))
            print(f"drl-check: {len(findings)} finding"
                  f"{'s' if len(findings) != 1 else ''} ({summary})")
        else:
            print(f"drl-check: clean ({', '.join(selected)})")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
