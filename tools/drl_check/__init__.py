"""drl-check — repo-specific static conformance and lint suite.

The stack keeps four mirrored implementations of one agreement: the
Python wire codecs (``runtime/wire.py``, the normative protocol spec —
see docs/DESIGN.md §10), the C parser (``native/frontend.cc``), the
ctypes ABI bindings (``utils/native.py``), and the jitted JAX kernels
(``ops/``). Runtime fuzz tests exercise the agreement; this package
checks it *statically*, so drift is a failed ``make check`` instead of
a production misparse. Four analyzers:

- :mod:`.wire_conformance` — extracts the wire model (opcodes, flag
  bits, frame layouts, version gates) from ``wire.py`` via ``ast`` and
  from ``frontend.cc`` via constant/offset parsing, diffs the two,
  requires every ``OP_*`` constant to have a server dispatch handler
  (``wire-dispatch``), and cross-checks every ``fe_*``/``dir_*`` symbol
  the ctypes loader binds against the C exports.
- :mod:`.concurrency_lint` — AST checks for the asyncio/thread races
  this repo has actually shipped fixes for: blocking calls in
  ``async def``, locks held across ``await``, loop-affine calls from
  sync code, and unguarded ``loop.close()`` after a timed join.
- :mod:`.jax_lint` — JAX hot-path hygiene in ``ops/`` and
  ``runtime/store.py``: Python branches on traced values, per-call
  ``jax.jit`` re-wrapping, unhashable static arguments.
- :mod:`.build_freshness` — verifies ``native/build/*.so.hash``
  sidecars against the current source hashes, so analysis results are
  never reported against a binary built from different source.
- :mod:`.metric_names` — the autonomous controller's sensor
  subscriptions (``SENSOR_SERIES`` in ``runtime/controller.py``) must
  each resolve to a registered metric family in the registry that
  emits it; a renamed family is a failed check, not a silently blinded
  control loop.
- :mod:`.flight_kinds` — every flight-recorder ``record(kind)`` call
  and ``frames(kind=)`` filter must use a kind from
  ``REGISTERED_KINDS`` (``utils/flight_recorder.py``); a typo'd kind
  fails silently (the filter matches nothing), so it fails here
  instead.
- :mod:`.stale_suppression` — a ``# drl-check: ok(<rule>)`` whose rule
  no longer fires at that site (or names an unknown/non-suppressible
  rule) is itself a finding: dead suppressions read as protection they
  don't provide and pre-excuse future regressions.

The protocol-level counterpart — model checking the epoch/config/
reservation/breaker state machines plus the cross-language lock-order
analyzer — lives in :mod:`tools.drl_verify` (``make verify-model``).

Run ``python -m tools.drl_check`` (exit 0 = clean); suppress a
deliberate exception with ``# drl-check: ok(<rule>)`` on (or one line
above) the flagged line, with a reason.
"""

from __future__ import annotations

from tools.drl_check.common import Finding  # re-export for consumers

__all__ = ["Finding", "run_all"]


def run_all(repo_root=None) -> "list[Finding]":
    """Run every analyzer against the live tree; returns all findings
    (empty = clean)."""
    import pathlib

    from tools.drl_check import (
        build_freshness,
        concurrency_lint,
        flight_kinds,
        jax_lint,
        metric_names,
        stale_suppression,
        wire_conformance,
    )

    root = pathlib.Path(repo_root) if repo_root else (
        pathlib.Path(__file__).resolve().parents[2])
    findings: list[Finding] = []
    findings += wire_conformance.check(root)
    findings += concurrency_lint.check(root)
    findings += jax_lint.check(root)
    findings += build_freshness.check(root)
    findings += metric_names.check(root)
    findings += flight_kinds.check(root)
    findings += stale_suppression.check(root)
    return findings
