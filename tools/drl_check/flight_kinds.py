"""``flight-kind`` — every flight-recorder frame kind must be
registered.

The flight recorder's ``frames(kind=...)`` filter is how audits read
the evidence ring (the controller's action-log assertions, the chaos
soaks' breaker checks). A typo'd kind on EITHER side fails silently:
``record("flsh", ...)`` produces frames no filter finds, and
``frames(kind="contoller")`` matches nothing — the audit assertion
passes vacuously. This rule extracts :data:`REGISTERED_KINDS` from
``utils/flight_recorder.py`` via ``ast`` (the table is the anchor; its
absence is a loud error, never a vacuous pass) and cross-checks every
recorder ``record("<kind>", ...)`` call and every
``frames(kind="<kind>")`` filter across the package AND the tests —
file:line on both sides.

Receiver discipline keeps unrelated ``record()`` methods (histograms,
profiling sessions) out: the first argument must be a string literal
and the receiver expression must be recorder-shaped
(``*recorder*``/``*rec*``/``flight``). ``frames(kind=...)`` is matched
by attribute name with a string-literal kind (``np.argsort(...,
kind="stable")`` has no ``frames`` attribute and never matches).

Suppress a deliberately foreign kind with
``# drl-check: ok(flight-kind)``."""

from __future__ import annotations

import ast
import pathlib

from tools.drl_check.common import (
    Finding,
    Suppressions,
    iter_py_files,
    rel,
)

__all__ = ["check", "check_sources", "registered_kinds"]

_RECORDERISH = ("recorder", "rec", "flight", "fr")


def registered_kinds(flight_recorder_py: pathlib.Path
                     ) -> "tuple[frozenset[str], int]":
    """Extract ``REGISTERED_KINDS`` (+ its line) from the live module
    source. A missing/empty table raises — the rule must never pass
    vacuously because a refactor moved the anchor."""
    tree = ast.parse(flight_recorder_py.read_text())
    for node in tree.body:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target]
                   if isinstance(node, ast.AnnAssign) else [])
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "REGISTERED_KINDS":
                kinds = {
                    k.value for k in ast.walk(node.value)
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
                if not kinds:
                    raise RuntimeError(
                        "REGISTERED_KINDS is empty in "
                        f"{flight_recorder_py}")
                return frozenset(kinds), node.lineno
    raise RuntimeError(
        f"REGISTERED_KINDS not found in {flight_recorder_py} — the "
        "flight-kind rule's anchor is gone")


def _recorder_shaped(expr: ast.AST) -> bool:
    try:
        text = ast.unparse(expr).lower()
    except Exception:
        return False
    last = text.split(".")[-1]
    return any(t in last for t in _RECORDERISH) \
        or "flight" in text


def _kind_sites(source: str) -> "list[tuple[str, int, str]]":
    """(kind, line, site-kind) for record()/frames() literal kinds."""
    out = []
    tree = ast.parse(source)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute):
            continue
        if node.func.attr == "record" and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str) \
                and _recorder_shaped(node.func.value):
            out.append((node.args[0].value, node.lineno, "record"))
        elif node.func.attr == "frames":
            for kw in node.keywords:
                if kw.arg != "kind":
                    continue
                if isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    out.append((kw.value.value, node.lineno,
                                "frames(kind=)"))
                elif isinstance(kw.value, (ast.Tuple, ast.List)):
                    # frames(kind=("slo", "audit")) — each element is
                    # checked on its own line so one typo'd member of
                    # a multi-kind filter is still caught.
                    for elt in kw.value.elts:
                        if isinstance(elt, ast.Constant) \
                                and isinstance(elt.value, str):
                            out.append((elt.value, node.lineno,
                                        "frames(kind=)"))
    return out


def check_sources(sources: "list[tuple[str, str]]",
                  kinds: "frozenset[str]",
                  table_file: str, table_line: int) -> "list[Finding]":
    """``sources`` is ``[(path, text), ...]``."""
    findings = []
    for path, text in sources:
        supp = Suppressions(text)
        try:
            sites = _kind_sites(text)
        except SyntaxError:
            continue
        for kind, line, what in sites:
            if kind in kinds or supp.suppressed(line, "flight-kind"):
                continue
            findings.append(Finding(
                "flight-kind",
                f"{what} uses unregistered frame kind {kind!r} — a "
                "typo here fails silently (the filter matches "
                "nothing); add it to REGISTERED_KINDS or fix the "
                "spelling",
                path, line,
                ((table_file, table_line,
                  "the registered-kinds table"),)))
    return sorted(findings, key=lambda f: (f.file, f.line))


def check(root: pathlib.Path) -> "list[Finding]":
    fr = (root / "distributedratelimiting" / "redis_tpu" / "utils"
          / "flight_recorder.py")
    kinds, table_line = registered_kinds(fr)
    sources = []
    for base in ("distributedratelimiting", "tests"):
        d = root / base
        if d.exists():
            for py in iter_py_files(d):
                sources.append((rel(py, root), py.read_text()))
    return check_sources(sources, kinds, rel(fr, root), table_line)
