"""Stale-binary gate: conformance results must describe the binary that
is actually loaded.

``utils/native.py`` stamps every successful build with a
``<name>.so.hash`` sidecar holding the sha256 of the source it was
compiled from, and refuses to load a binary whose sidecar disagrees
with the current source (rebuild-on-load). This check enforces the same
invariant *statically* for every built artifact — production,
``build/asan/``, and ``build/tsan/`` — so ``make check`` cannot report
a clean conformance diff for ``frontend.cc`` while the ``.so`` under
test was built from a different revision of it.

Rule ``stale-binary``: a ``.so`` exists whose sidecar is missing or
records a hash other than the current source's. (No ``.so`` at all is
fine — the loader builds on first import.)
"""

from __future__ import annotations

import hashlib
import pathlib

from tools.drl_check.common import Finding, rel

__all__ = ["check", "check_native_dir"]

#: artifact name → source it must be built from.
_ARTIFACTS = {
    "_directory.so": "directory.cc",
    "_frontend.so": "frontend.cc",
}


def _sha256(path: pathlib.Path) -> str:
    return hashlib.sha256(path.read_bytes()).hexdigest()


def check_native_dir(native: pathlib.Path,
                     root: pathlib.Path) -> list[Finding]:
    findings: list[Finding] = []
    build = native / "build"
    if not build.exists():
        return findings
    for so_name, src_name in _ARTIFACTS.items():
        src = native / src_name
        if not src.exists():
            continue
        src_hash = _sha256(src)
        for so in sorted(build.rglob(so_name)):
            sidecar = so.with_name(so.name + ".hash")
            if not sidecar.exists():
                findings.append(Finding(
                    "stale-binary",
                    f"{rel(so, root)} has no source-hash sidecar — it "
                    "cannot be proven to match the current "
                    f"{src_name}; rebuild (make -C native, or delete "
                    "the .so and let the loader rebuild)",
                    rel(so, root), 1,
                    ((rel(src, root), 1, f"current sha256 {src_hash[:12]}…"),
                     )))
                continue
            recorded = sidecar.read_text().strip()
            if recorded != src_hash:
                findings.append(Finding(
                    "stale-binary",
                    f"{rel(so, root)} was built from "
                    f"{src_name}@{recorded[:12]}… but the tree has "
                    f"{src_hash[:12]}… — analysis of the source does "
                    "not describe this binary; rebuild before trusting "
                    "either", rel(so, root), 1,
                    ((rel(src, root), 1,
                      f"current sha256 {src_hash[:12]}…"),)))
    return findings


def check(root: pathlib.Path) -> list[Finding]:
    return check_native_dir(root / "native", root)
