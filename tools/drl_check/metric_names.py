"""metric-name — the controller's sensor subscriptions must name real
series.

The autonomous control plane (``runtime/controller.py``) drives
actuators from metric series it never emits itself: the server's and
the cluster client's :class:`MetricsRegistry` families. A rename on the
emitting side — ``requests_served`` becoming ``requests_answered`` in a
refactor — would not fail any test; the controller's sensor would just
read zero forever and the loop would go quietly blind. This analyzer
makes that drift a failed ``make check`` instead:

- the controller declares its subscriptions in the module-level
  ``SENSOR_SERIES`` tuple (full OpenMetrics names, ``drl_`` prefix);
- every registration site repo-wide is extracted via ``ast`` — the
  ``counter``/``gauge``/``histogram``/``labeled_gauges``/
  ``labeled_counters`` calls (exact family names) and
  ``register_numeric_dict`` calls (prefix families whose per-key
  suffixes are dynamic);
- each subscribed name must resolve to a registered family: exact
  match, or ``<prefix>_…`` under a dict family. A miss is one finding
  at the subscription element's line, with the nearest registered
  family's registration site as the other side of the diff.

Suppress a deliberate exception (e.g. a series produced by an external
scraper) with ``# drl-check: ok(metric-name)`` on the tuple element.
"""

from __future__ import annotations

import ast
import difflib
import pathlib

from tools.drl_check.common import (
    Finding,
    Suppressions,
    iter_py_files,
    rel,
)

__all__ = ["check", "check_sources"]

#: Default namespace every registry in this repo uses
#: (MetricsRegistry.NAMESPACE) — full names are ``drl_<family>``.
_NAMESPACE = "drl"

_EXACT_METHODS = frozenset({"counter", "gauge", "histogram",
                            "labeled_gauges", "labeled_counters"})
#: Module-level tuples holding series subscriptions: the controller's
#: sensors and the SLO watchdog's sample sources (utils/slo.py) — both
#: consume series they never emit, so both drift the same way.
_SUBSCRIPTION_NAMES = ("SENSOR_SERIES", "SLO_SERIES")


def controller_subscriptions(path: pathlib.Path
                             ) -> list[tuple[str, int]]:
    """``(series_name, line)`` per element of the controller's
    subscription tuple(s)."""
    tree = ast.parse(path.read_text(), filename=str(path))
    out: list[tuple[str, int]] = []
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name):
                targets = [node.target.id]
            value = node.value
        else:
            continue
        if not any(t in _SUBSCRIPTION_NAMES for t in targets):
            continue
        if isinstance(value, (ast.Tuple, ast.List)):
            for elt in value.elts:
                if isinstance(elt, ast.Constant) \
                        and isinstance(elt.value, str):
                    out.append((elt.value, elt.lineno))
    return out


def registered_families(py_files: "list[pathlib.Path]"
                        ) -> tuple[dict[str, tuple[pathlib.Path, int]],
                                   dict[str, tuple[pathlib.Path, int]]]:
    """Scan registration call sites: returns ``(exact, prefixes)`` maps
    of full (``drl_``-prefixed) family name → first registration site.
    ``prefixes`` holds ``register_numeric_dict`` families, whose sample
    names extend the prefix per snapshot key at scrape time."""
    exact: dict[str, tuple[pathlib.Path, int]] = {}
    prefixes: dict[str, tuple[pathlib.Path, int]] = {}
    for path in py_files:
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            method = node.func.attr
            name = f"{_NAMESPACE}_{node.args[0].value}"
            if method in _EXACT_METHODS:
                exact.setdefault(name, (path, node.lineno))
            elif method == "register_numeric_dict":
                prefixes.setdefault(name, (path, node.lineno))
    return exact, prefixes


def check_sources(subscription_paths, py_files: "list[pathlib.Path]",
                  root: pathlib.Path) -> list[Finding]:
    """``subscription_paths`` is one path or a sequence of paths, each
    scanned for ``_SUBSCRIPTION_NAMES`` tuples; every element of every
    tuple must resolve against the registration sites in ``py_files``."""
    if isinstance(subscription_paths, pathlib.Path):
        subscription_paths = [subscription_paths]
    exact, prefixes = registered_families(py_files)
    findings: list[Finding] = []
    for sub_path in subscription_paths:
        subs = controller_subscriptions(sub_path)
        suppress = Suppressions(sub_path.read_text())
        for name, line in subs:
            if suppress.suppressed(line, "metric-name"):
                continue
            if name in exact or name in prefixes:
                continue
            if any(name.startswith(prefix + "_") for prefix in prefixes):
                continue
            all_families = sorted(exact) + sorted(prefixes)
            related: list[tuple[str, int, str]] = []
            near = difflib.get_close_matches(name, all_families, n=1,
                                             cutoff=0.0)
            if near:
                site = exact.get(near[0]) or prefixes[near[0]]
                related.append((rel(site[0], root), site[1],
                                f"nearest registered family: {near[0]}"))
            findings.append(Finding(
                rule="metric-name",
                message=(f"subscriber declares series {name!r} but no "
                         "MetricsRegistry registration emits it — the "
                         "sensor would read zero forever"),
                file=rel(sub_path, root),
                line=line,
                related=tuple(related),
            ))
    return findings


def check(root: pathlib.Path) -> list[Finding]:
    pkg = root / "distributedratelimiting" / "redis_tpu"
    subscribers = [pkg / "runtime" / "controller.py",
                   pkg / "utils" / "slo.py"]
    subscribers = [p for p in subscribers if p.exists()]
    if not subscribers:
        return []  # shim trees (CLI tests) carry no subscribers
    py_files = iter_py_files(root / "distributedratelimiting")
    return check_sources(subscribers, py_files, root)
