"""Shared plumbing for the drl-check analyzers: findings, suppression
comments, and safe constant evaluation for the two source languages."""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re

__all__ = [
    "Finding", "Suppressions", "const_eval_py", "const_eval_c",
    "rel", "iter_py_files", "KNOWN_RULES", "INLINE_SUPPRESSIBLE",
]

#: Every rule name any checker in this repo can emit (drl-check AND
#: drl-verify's lock-order leg). The ``stale-suppression`` rule flags
#: a ``# drl-check: ok(<rule>)`` naming anything else — a typo'd rule
#: name suppresses nothing and rots silently.
KNOWN_RULES = frozenset({
    # wire/ABI conformance
    "wire-const", "wire-layout", "wire-endian", "wire-hier",
    "wire-dispatch", "wire-idempotency", "abi-export",
    # concurrency lint
    "async-blocking", "lock-across-await", "task-off-loop",
    "unguarded-loop-close", "swallowed-exception",
    # JAX hot-path lint
    "traced-branch", "jit-rewrap", "jit-static-unhashable",
    "jit-f64", "jit-closed-scalar",
    # build freshness / metrics / flight recorder
    "stale-binary", "metric-name", "flight-kind",
    # drl-verify lock-order leg
    "lock-cycle", "slice-sweep-order",
    # drl-xla compiled-artifact conformance (python -m tools.drl_xla)
    "xla-purity", "xla-donation", "xla-retrace", "xla-budget",
    "xla-stale-ledger",
    # this meta-rule itself (ok(stale-suppression) is the escape hatch)
    "stale-suppression",
})

#: Rules whose analyzers actually consult inline suppression comments.
#: Naming any OTHER known rule in an ok(...) is dead by construction —
#: the analyzer never reads the comment — and stale-suppression says
#: so instead of letting the comment imply protection it doesn't have.
INLINE_SUPPRESSIBLE = frozenset({
    "async-blocking", "lock-across-await", "task-off-loop",
    "unguarded-loop-close", "swallowed-exception",
    "traced-branch", "jit-rewrap", "jit-static-unhashable",
    "jit-f64", "jit-closed-scalar",
    "metric-name", "flight-kind",
    # Honored by drl-xla at the kernel's def line. xla-stale-ledger is
    # deliberately NOT suppressible: a stale ledger is a freshness bug,
    # not a judgment call.
    "xla-purity", "xla-donation", "xla-retrace", "xla-budget",
})


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer hit. ``related`` carries the other side of a
    cross-language diff (file, line, note) so a conformance error names
    BOTH locations."""

    rule: str
    message: str
    file: str
    line: int
    related: tuple[tuple[str, int, str], ...] = ()

    def format(self) -> str:
        out = [f"{self.file}:{self.line}: error[{self.rule}]: "
               f"{self.message}"]
        for f, ln, note in self.related:
            out.append(f"    {f}:{ln}: {note}")
        return "\n".join(out)


#: ``# drl-check: ok(rule[, rule])`` (Python) / ``// drl-check: ok(rule)``
#: (C++) — suppresses matching rules on the same line or the line below
#: (i.e. the comment may sit on its own line directly above the code).
_SUPPRESS_RE = re.compile(
    r"(?:#|//)\s*drl-check:\s*ok\(\s*([\w\-, ]+?)\s*\)")


class Suppressions:
    """Per-file map of suppression comments."""

    def __init__(self, text: str) -> None:
        self._by_line: dict[int, set[str]] = {}
        for i, line in enumerate(text.splitlines(), start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")}
                self._by_line.setdefault(i, set()).update(rules)

    def suppressed(self, line: int, rule: str) -> bool:
        for ln in (line, line - 1):
            if rule in self._by_line.get(ln, ()):
                return True
        return False


def rel(path: pathlib.Path, root: pathlib.Path) -> str:
    """Repo-relative path when possible (stable finding identity)."""
    try:
        return str(path.resolve().relative_to(root.resolve()))
    except ValueError:
        return str(path)


def iter_py_files(root: pathlib.Path) -> "list[pathlib.Path]":
    return sorted(p for p in root.rglob("*.py")
                  if "__pycache__" not in p.parts)


# -- constant evaluation ----------------------------------------------------

def const_eval_py(node: ast.AST,
                  struct_sizes: "dict[str, int] | None" = None) -> int | None:
    """Evaluate a module-level constant expression: int literals, the
    arithmetic the wire module actually uses (``1 << 20``, ``0b10000``),
    and ``<struct_name>.size`` when ``struct_sizes`` knows the struct."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = const_eval_py(node.operand, struct_sizes)
        return None if inner is None else -inner
    if isinstance(node, ast.BinOp):
        left = const_eval_py(node.left, struct_sizes)
        right = const_eval_py(node.right, struct_sizes)
        if left is None or right is None:
            return None
        ops = {ast.LShift: lambda a, b: a << b,
               ast.RShift: lambda a, b: a >> b,
               ast.BitOr: lambda a, b: a | b,
               ast.BitAnd: lambda a, b: a & b,
               ast.Add: lambda a, b: a + b,
               ast.Sub: lambda a, b: a - b,
               ast.Mult: lambda a, b: a * b}
        fn = ops.get(type(node.op))
        return None if fn is None else fn(left, right)
    if (struct_sizes is not None and isinstance(node, ast.Attribute)
            and node.attr == "size" and isinstance(node.value, ast.Name)
            and node.value.id in struct_sizes):
        return struct_sizes[node.value.id]
    return None


_C_CONST_ALLOWED = re.compile(r"^[0-9a-fA-FxX\s()<>|&+\-*uUlL]+$")


def const_eval_c(expr: str) -> int | None:
    """Evaluate a C constant initializer (``1u << 20``, ``0x80``, plain
    ints). Strips integer suffixes, then evaluates an allow-listed
    arithmetic expression — anything else returns ``None``."""
    expr = expr.strip()
    if not _C_CONST_ALLOWED.match(expr):
        return None
    cleaned = re.sub(r"(?<=[0-9a-fA-F])[uUlL]+", "", expr)
    try:
        value = eval(compile(cleaned, "<c-const>", "eval"),  # noqa: S307
                     {"__builtins__": {}}, {})
    except Exception:
        return None
    return value if isinstance(value, int) else None
