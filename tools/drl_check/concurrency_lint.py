"""Concurrency lint: AST checks for the asyncio/thread bug classes this
repo has actually shipped fixes for (the pump-alive use-after-free in
PR 1, the loop/thread shutdown ordering audits since).

Rules:

- ``async-blocking`` — a blocking call (``time.sleep``, ``subprocess``,
  ``concurrent.futures``-style ``.result()``, thread ``.join()``)
  inside an ``async def``: it stalls the whole event loop, which on the
  serving path stalls every connection.
- ``lock-across-await`` — a *synchronous* lock (``threading.Lock`` et
  al., recognized by name) held across an ``await``: any other task
  needing the lock on the same loop deadlocks; any thread needing it
  blocks for the full await.
- ``task-off-loop`` — ``create_task`` / ``ensure_future`` /
  ``call_soon`` / ``call_later`` / ``call_at`` from a synchronous
  function: loop-affine APIs that are only safe on the loop thread.
  Sync code reached from another thread must use
  ``call_soon_threadsafe`` (never flagged). Functions that call
  ``asyncio.get_running_loop()`` are exempt — it raises off-loop, so it
  IS the affinity guard. Functions that are loop-thread-only by design
  (timer callbacks, ``call_soon_threadsafe`` targets) annotate with
  ``# drl-check: ok(task-off-loop)``.
- ``unguarded-loop-close`` — ``loop.close()`` after a *timed*
  ``thread.join()`` with no ``is_alive()`` guard: if the join timed
  out, the loop thread is still running and close() either raises or
  hands the running thread a closed loop (the use-after-free class
  fixed for the native pump in PR 1; ``cluster.py`` carries the model
  guard).
- ``swallowed-exception`` — an ``except Exception:`` (or bare
  ``except:``) in ``runtime/`` whose handler neither logs, raises,
  replies an error, nor touches a failure counter: the class of
  invisible partition the chaos-plane PR dug out of ``cluster.py``
  (a down node vanished into ``pass``). A handler counts as VISIBLE
  when its body raises, calls anything log/warn-shaped, routes an
  error onward (``_reply``/``_send``/``fe_fail``/``set_exception``),
  or bumps a counter-shaped attribute (``…_failures``, ``…_errors``,
  ``shed``, …). Deliberate swallows (observer-bug shields) annotate
  ``# drl-check: ok(swallowed-exception)`` with their reason.
"""

from __future__ import annotations

import ast
import pathlib

from tools.drl_check.common import (
    Finding,
    Suppressions,
    iter_py_files,
    rel,
)

__all__ = ["check", "check_file", "check_source"]

#: Dotted-call suffixes that block the loop. ``.result``/``.join`` are
#: receiver-gated below (too many innocent methods share the names).
_BLOCKING_CALLS = {
    ("time", "sleep"),
    ("os", "system"),
    ("os", "popen"),
    ("subprocess", "run"),
    ("subprocess", "call"),
    ("subprocess", "check_call"),
    ("subprocess", "check_output"),
    ("socket", "create_connection"),
}
_LOCKISH = ("lock", "gate", "mutex", "sem")
_THREADISH = ("thread", "pump", "worker")
_LOOP_AFFINE = {"create_task", "call_soon", "call_later", "call_at"}

#: swallowed-exception: call-name fragments that make a handler visible
#: (logging in any spelling) …
_VISIBLE_CALLISH = ("log", "warn", "print")
#: … exact call names that route the failure onward instead of eating it …
_VISIBLE_ROUTES = {"_reply", "_send", "fe_fail", "set_exception",
                   "encode_response", "dump", "auto_dump",
                   "_note_node_error", "_note_scrape_error"}
#: … and attribute-name fragments that count as a failure metric.
_COUNTERISH = ("failure", "error", "shed", "retr", "timeout",
               "suppressed", "evicted", "cancelled", "dropped")


def _dotted(node: ast.AST) -> tuple[str, ...]:
    """('time', 'sleep') for ``time.sleep`` — best effort, '' for
    non-name parts."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    parts.append(node.id if isinstance(node, ast.Name) else "")
    return tuple(reversed(parts))


def _expr_text(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


class _FnVisitor(ast.NodeVisitor):
    """Per-function-scope analysis; nested defs get their own scope (a
    sync helper nested in an async def is not 'in' the async def)."""

    def __init__(self, path: str, supp: Suppressions,
                 runtime_scope: bool = False) -> None:
        self.path = path
        self.supp = supp
        self.runtime_scope = runtime_scope  # swallowed-exception on/off
        self.findings: list[Finding] = []
        self._stack: list[ast.AST] = []  # enclosing function nodes

    # -- scope plumbing
    def _in_async(self) -> bool:
        return bool(self._stack) and isinstance(self._stack[-1],
                                                ast.AsyncFunctionDef)

    def _in_sync_fn(self) -> bool:
        return bool(self._stack) and isinstance(self._stack[-1],
                                                ast.FunctionDef)

    @staticmethod
    def _loop_guarded(fn: ast.AST) -> bool:
        """True when the function calls ``get_running_loop()`` in its own
        scope: that call raises off the loop thread, so a sync function
        holding its result is proven loop-affine (nested defs guard
        themselves, not the enclosing scope)."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if (isinstance(n, ast.Call)
                    and _dotted(n.func)[-1] == "get_running_loop"):
                return True
            stack.extend(ast.iter_child_nodes(n))
        return False

    def _emit(self, rule: str, line: int, message: str) -> None:
        if not self.supp.suppressed(line, rule):
            self.findings.append(Finding(rule, message, self.path, line))

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()
        self._check_loop_close(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()
        self._check_loop_close(node)

    # -- rules
    def visit_Call(self, node: ast.Call) -> None:
        dotted = _dotted(node.func)
        name = dotted[-1]
        recv = ".".join(dotted[:-1]).lower()
        if self._in_async():
            if dotted[-2:] in _BLOCKING_CALLS:
                self._emit("async-blocking", node.lineno,
                           f"blocking call {'.'.join(dotted)}() inside "
                           "'async def' stalls the event loop — await an "
                           "async equivalent or use asyncio.to_thread")
            elif name == "result" and len(dotted) > 1 \
                    and (node.args or node.keywords):
                # A timeout argument marks the blocking
                # concurrent.futures wait; a bare .result() on a
                # done-checked asyncio future is a non-blocking read.
                self._emit("async-blocking", node.lineno,
                           f"{'.'.join(dotted)}(timeout) blocks inside "
                           "'async def' — wrap the future with "
                           "asyncio.wrap_future and await it")
            elif name == "join" and any(t in recv for t in _THREADISH):
                self._emit("async-blocking", node.lineno,
                           f"{'.'.join(dotted)}() joins a thread inside "
                           "'async def' — use asyncio.to_thread(x.join,…)")
        if self._in_sync_fn() and not self._loop_guarded(self._stack[-1]):
            if name in _LOOP_AFFINE:
                self._emit("task-off-loop", node.lineno,
                           f"loop-affine {'.'.join(dotted)}() in a "
                           "synchronous function: only safe on the loop "
                           "thread — use call_soon_threadsafe from other "
                           "threads, or annotate if this function is "
                           "loop-thread-only by design")
            elif dotted[-2:] in {("asyncio", "ensure_future"),
                                 ("asyncio", "create_task")}:
                self._emit("task-off-loop", node.lineno,
                           f"{'.'.join(dotted)}() in a synchronous "
                           "function creates a task off-loop — same "
                           "affinity contract as loop.create_task")
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        lockish = any(
            any(t in _expr_text(item.context_expr).lower()
                for t in _LOCKISH)
            for item in node.items)
        if lockish and self._in_async():
            awaits = [n for n in self._body_walk(node)
                      if isinstance(n, ast.Await)]
            if awaits:
                self._emit("lock-across-await", node.lineno,
                           "synchronous lock held across 'await' (first "
                           f"await at line {awaits[0].lineno}): tasks "
                           "needing it deadlock the loop; use "
                           "asyncio.Lock or release before awaiting")
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if self.runtime_scope and self._swallows(node):
            self._emit(
                "swallowed-exception", node.lineno,
                "'except Exception' swallows the failure with no log, "
                "metric, raise, or error routing — a partition here is "
                "invisible; log it (utils/log.py), bump a counter, or "
                "annotate the deliberate shield")
        self.generic_visit(node)

    @staticmethod
    def _swallows(node: ast.ExceptHandler) -> bool:
        """True for an Exception-wide handler whose body makes the
        failure invisible (no raise / log-ish call / error routing /
        counter-shaped attribute write)."""
        t = node.type
        wide = (t is None
                or (isinstance(t, ast.Name)
                    and t.id in ("Exception", "BaseException")))
        if not wide:
            return False
        for n in ast.walk(node):
            if isinstance(n, ast.Raise):
                return False
            if isinstance(n, ast.Call):
                name = _dotted(n.func)[-1]
                lowered = ".".join(_dotted(n.func)).lower()
                if (name in _VISIBLE_ROUTES
                        or any(t in lowered for t in _VISIBLE_CALLISH)):
                    return False
            if isinstance(n, (ast.AugAssign, ast.Assign)):
                targets = ([n.target] if isinstance(n, ast.AugAssign)
                           else n.targets)
                for tgt in targets:
                    if (isinstance(tgt, ast.Attribute)
                            and any(c in tgt.attr.lower()
                                    for c in _COUNTERISH)):
                        return False
        return True

    @staticmethod
    def _body_walk(node: ast.With):
        """Walk the with-body without descending into nested defs (an
        await inside a nested async def is not held-across)."""
        stack = list(node.body)
        while stack:
            n = stack.pop()
            yield n
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                stack.extend(ast.iter_child_nodes(n))

    def _check_loop_close(self, fn: ast.AST) -> None:
        closes: list[ast.Call] = []
        timed_join = False
        guarded = False
        # Own scope only: nested defs run their own check — walking into
        # them would double-report their close/join pairs up the stack.
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(n))
            if isinstance(n, ast.Call) and isinstance(n.func,
                                                      ast.Attribute):
                recv = _expr_text(n.func.value).lower()
                if n.func.attr == "close" and "loop" in recv:
                    closes.append(n)
                elif n.func.attr == "join" and (n.args or n.keywords) \
                        and any(t in recv for t in _THREADISH):
                    # Receiver-gated like async-blocking: a timed THREAD
                    # join, not str.join(parts)/b"".join(...).
                    timed_join = True
            if isinstance(n, ast.Attribute) and n.attr == "is_alive":
                guarded = True
        if closes and timed_join and not guarded:
            for call in closes:
                self._emit(
                    "unguarded-loop-close", call.lineno,
                    "loop.close() after a timed thread join with no "
                    "is_alive() guard: a timed-out join leaves the loop "
                    "thread running — closing under it raises or "
                    "use-after-frees (guard like cluster.py aclose)")


def check_source(source: str, path: str,
                 runtime_scope: "bool | None" = None) -> list[Finding]:
    if runtime_scope is None:
        # swallowed-exception is scoped to the serving runtime — the
        # layer whose invisible failures ARE outages. Models, utils,
        # and tools keep their deliberate broad catches unflagged.
        runtime_scope = "runtime" in pathlib.PurePath(path).parts
    tree = ast.parse(source)
    visitor = _FnVisitor(path, Suppressions(source), runtime_scope)
    visitor.visit(tree)
    return sorted(visitor.findings, key=lambda f: f.line)


def check_file(py: pathlib.Path, root: pathlib.Path) -> list[Finding]:
    return check_source(py.read_text(), rel(py, root))


def check(root: pathlib.Path) -> list[Finding]:
    pkg = root / "distributedratelimiting"
    findings: list[Finding] = []
    for py in iter_py_files(pkg):
        findings += check_file(py, root)
    return findings
