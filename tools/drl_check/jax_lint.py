"""JAX hot-path lint over the kernel layer (``ops/`` and
``runtime/store.py``).

Rules:

- ``traced-branch`` — a Python-level ``if``/``while`` on a value derived
  from a *traced* (non-static) parameter inside a jitted function:
  either a ``ConcretizationTypeError`` at trace time or, worse, a branch
  baked in at trace time that silently stops tracking the runtime value.
  Shape/dtype/None tests are exempt (static under jit by construction).
- ``jit-rewrap`` — ``jax.jit(...)`` called inside a function body: every
  call builds a fresh wrapper whose cache is thrown away, so the kernel
  re-traces (and re-compiles) per call. Decorate at module level or
  cache the wrapper (``lru_cache``-style builders are exempt).
- ``jit-static-unhashable`` — a parameter named static (via
  ``static_argnames``/``static_argnums``) whose default is a mutable
  literal (list/dict/set): static args key the jit cache by hash, so the
  first call raises ``TypeError: unhashable``; even when callers always
  override, the default documents an illegal call.
- ``jit-f64`` — a 64-bit dtype (``float64``/``double``/``int64``/
  ``complex128``, as an attribute, an ``astype`` target, or a ``dtype=``
  keyword) inside a jitted hot path: the state plane is 32-bit by
  contract, and with x64 disabled the promotion is silently *clamped* —
  the source lies about the artifact. This is the AST layer of a
  two-layer check: drl-xla's ``xla-purity`` verifies the compiled jaxpr
  carries no 64-bit values (``python -m tools.drl_xla``), so a
  violation is named at both the source line and the artifact.
- ``jit-closed-scalar`` — a jitted function *nested* in another
  function closes over an enclosing local/parameter: the value is baked
  into the trace, so each rebuild (or each distinct value, via the
  surrounding builder) re-traces and re-compiles — the retrace-per-cost
  leak drl-xla's ``xla-retrace`` probes on the compiled side.
  ``lru_cache``'d builders are exempt (intentional per-config
  specialization with a bounded cache), as are closed-over helper
  functions/classes.
"""

from __future__ import annotations

import ast
import pathlib

from tools.drl_check.common import Finding, Suppressions, rel

__all__ = ["check", "check_file", "check_source"]


def _dotted(node: ast.AST) -> tuple[str, ...]:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    parts.append(node.id if isinstance(node, ast.Name) else "")
    return tuple(reversed(parts))


def _is_jit_ref(node: ast.AST) -> bool:
    """``jax.jit`` or bare ``jit`` (the conventional import alias)."""
    d = _dotted(node)
    return d[-1] == "jit" and (len(d) == 1 or d[-2] in ("jax", ""))


class _JitSpec:
    """Static-parameter model of one jitted function."""

    def __init__(self, fn: ast.AST) -> None:
        self.fn = fn
        self.static_names: set[str] = set()
        self.static_nums: set[int] = set()

    def resolve_static(self) -> set[str]:
        args = self.fn.args
        names = set(self.static_names)
        positional = [a.arg for a in (args.posonlyargs + args.args)]
        for i in self.static_nums:
            if 0 <= i < len(positional):
                names.add(positional[i])
        return names


def _jit_spec_from_decorators(fn: ast.AST) -> _JitSpec | None:
    """Recognize ``@jax.jit``, ``@jit``, ``@jax.jit(...)``, and
    ``@(functools.)partial(jax.jit, ...)``."""
    for dec in fn.decorator_list:
        if _is_jit_ref(dec):
            return _JitSpec(fn)
        if isinstance(dec, ast.Call):
            is_partial = _dotted(dec.func)[-1] == "partial" and dec.args \
                and _is_jit_ref(dec.args[0])
            if not (is_partial or _is_jit_ref(dec.func)):
                continue
            spec = _JitSpec(fn)
            for kw in dec.keywords:
                if kw.arg == "static_argnames":
                    for el in ast.walk(kw.value):
                        if isinstance(el, ast.Constant) \
                                and isinstance(el.value, str):
                            spec.static_names.add(el.value)
                elif kw.arg == "static_argnums":
                    for el in ast.walk(kw.value):
                        if isinstance(el, ast.Constant) \
                                and isinstance(el.value, int):
                            spec.static_nums.add(el.value)
            return spec
    return None


#: Wrappers under which a traced name stays static/legal in a branch
#: test: shape metadata, type tests, None tests, Python-int casts of
#: shape components.
_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize"}
_STATIC_CALLS = {"len", "isinstance", "hasattr", "getattr", "callable",
                 "int", "bool", "float", "str", "type"}


def _branch_uses_traced(test: ast.AST, traced: set[str]) -> str | None:
    """The first traced parameter the branch test reads as a VALUE (not
    through a static wrapper), or None."""

    def scan(node: ast.AST) -> str | None:
        if isinstance(node, ast.Attribute) and node.attr in _SHAPE_ATTRS:
            return None  # x.shape / x.ndim … — static under jit
        if isinstance(node, ast.Call):
            name = _dotted(node.func)[-1]
            if name in _STATIC_CALLS:
                return None  # len(x), isinstance(x, …) — static
        if isinstance(node, ast.Compare) and any(
                isinstance(op, (ast.Is, ast.IsNot))
                for op in node.ops):
            return None  # `x is None` — identity, static
        if isinstance(node, ast.Name) and node.id in traced:
            return node.id
        for child in ast.iter_child_nodes(node):
            hit = scan(child)
            if hit is not None:
                return hit
        return None

    return scan(test)


#: 64-bit dtype spellings that have no business on the 32-bit state
#: plane. Matched as attribute names (``jnp.float64``), ``astype``
#: string targets, and ``dtype=`` keyword constants.
_WIDE_DTYPE_NAMES = frozenset({
    "float64", "double", "int64", "uint64", "complex128",
})


def _wide_dtype_use(node: ast.AST) -> str | None:
    """The wide dtype this node introduces, or None."""
    if isinstance(node, ast.Attribute) and node.attr in _WIDE_DTYPE_NAMES:
        return node.attr
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "astype":
            for arg in node.args[:1]:
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str) \
                        and arg.value in _WIDE_DTYPE_NAMES:
                    return arg.value
        for kw in node.keywords:
            if kw.arg == "dtype" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str) \
                    and kw.value.value in _WIDE_DTYPE_NAMES:
                return kw.value.value
    return None


#: Enclosing-function shapes that legitimately build-and-return a jitted
#: callable (the result is cached by the caller / a lru_cache).
_BUILDER_DECORATORS = {"lru_cache", "cache", "cached_property"}


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str, supp: Suppressions) -> None:
        self.path = path
        self.supp = supp
        self.findings: list[Finding] = []
        self._fn_stack: list[ast.AST] = []

    def _emit(self, rule: str, line: int, message: str) -> None:
        if not self.supp.suppressed(line, rule):
            self.findings.append(Finding(rule, message, self.path, line))

    def _visit_fn(self, node: ast.AST) -> None:
        spec = _jit_spec_from_decorators(node)
        if spec is not None:
            self._check_jitted(node, spec)
            if self._fn_stack:
                self._check_closed_scalar(node, spec)
        self._fn_stack.append(node)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Call(self, node: ast.Call) -> None:
        if _is_jit_ref(node.func) and self._fn_stack:
            fn = self._fn_stack[-1]
            decorated = {_dotted(d.func if isinstance(d, ast.Call) else d
                                 )[-1]
                         for d in getattr(fn, "decorator_list", [])}
            if not decorated & _BUILDER_DECORATORS:
                self._emit(
                    "jit-rewrap", node.lineno,
                    "jax.jit(...) called inside a function body: each "
                    "call builds a fresh wrapper and re-traces — "
                    "decorate at module level, or cache the built "
                    "wrapper (lru_cache'd builders are exempt)")
        self.generic_visit(node)

    def _check_jitted(self, fn: ast.AST, spec: _JitSpec) -> None:
        static = spec.resolve_static()
        args = fn.args
        all_params = [a.arg for a in (args.posonlyargs + args.args
                                      + args.kwonlyargs)]
        traced = {p for p in all_params if p not in static}

        # jit-static-unhashable: mutable default on a static parameter.
        pos = args.posonlyargs + args.args
        defaults = [None] * (len(pos) - len(args.defaults)) \
            + list(args.defaults)
        pairs = list(zip(pos, defaults)) \
            + list(zip(args.kwonlyargs, args.kw_defaults))
        for arg, default in pairs:
            if arg.arg in static and isinstance(
                    default, (ast.List, ast.Dict, ast.Set)):
                self._emit(
                    "jit-static-unhashable", default.lineno,
                    f"static argument {arg.arg!r} defaults to a mutable "
                    "literal: static args key the jit cache by hash, so "
                    "calls relying on the default raise TypeError — use "
                    "a hashable default (tuple / frozen config / None)")

        # traced-branch: Python control flow on a traced value.
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                hit = _branch_uses_traced(node.test, traced)
                if hit is not None:
                    kind = ("while" if isinstance(node, ast.While)
                            else "if")
                    self._emit(
                        "traced-branch", node.lineno,
                        f"Python-level '{kind}' on traced parameter "
                        f"{hit!r} inside a jitted function: branches "
                        "must be jnp.where / lax.cond / lax.select (or "
                        f"mark {hit!r} static if it is config, at the "
                        "cost of a cache entry per value)")

        # jit-f64: a 64-bit dtype reaching a jitted hot path.
        for node in ast.walk(fn):
            wide = _wide_dtype_use(node)
            if wide is not None:
                self._emit(
                    "jit-f64", node.lineno,
                    f"64-bit dtype {wide!r} in a jitted hot path: the "
                    "state plane is 32-bit by contract, and with x64 "
                    "disabled this promotion is silently clamped to "
                    "32-bit — the source no longer describes the "
                    "artifact (compiled-side twin: xla-purity in "
                    "`python -m tools.drl_xla` checks the jaxpr)")

    def _check_closed_scalar(self, fn: ast.AST, spec: _JitSpec) -> None:
        """jit-closed-scalar: a nested jitted function reading an
        enclosing function's local/parameter bakes that value into the
        trace — a retrace per rebuild (and per distinct value through
        the builder). Cached builders and closed-over callables are the
        two legitimate shapes; everything else is flagged."""
        for enclosing in self._fn_stack:
            decorated = {_dotted(d.func if isinstance(d, ast.Call) else d
                                 )[-1]
                         for d in getattr(enclosing, "decorator_list", [])}
            if decorated & _BUILDER_DECORATORS:
                return
        outer_bound: set[str] = set()
        outer_callables: set[str] = set()
        for enclosing in self._fn_stack:
            a = enclosing.args
            outer_bound.update(x.arg for x in (a.posonlyargs + a.args
                                               + a.kwonlyargs))
            for node in ast.walk(enclosing):
                if node is fn or isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)) and node is not enclosing:
                    outer_callables.add(getattr(node, "name", ""))
                    continue
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Store):
                    outer_bound.add(node.id)
        a = fn.args
        own: set[str] = {x.arg for x in (a.posonlyargs + a.args
                                         + a.kwonlyargs)}
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Store):
                own.add(node.id)
        reported: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.id in outer_bound and node.id not in own and \
                    node.id not in outer_callables and \
                    node.id not in reported:
                reported.add(node.id)
                self._emit(
                    "jit-closed-scalar", node.lineno,
                    f"jitted function {fn.name!r} closes over "
                    f"{node.id!r} from the enclosing function: the "
                    "value is baked into the trace, so the kernel "
                    "re-traces per rebuild/per distinct value — pass "
                    "it as an operand, mark it static, or cache the "
                    "builder with lru_cache (compiled-side twin: "
                    "xla-retrace in `python -m tools.drl_xla`)")


def check_source(source: str, path: str) -> list[Finding]:
    tree = ast.parse(source)
    visitor = _Visitor(path, Suppressions(source))
    visitor.visit(tree)
    return sorted(visitor.findings, key=lambda f: f.line)


def check_file(py: pathlib.Path, root: pathlib.Path) -> list[Finding]:
    return check_source(py.read_text(), rel(py, root))


def check(root: pathlib.Path) -> list[Finding]:
    """Scan the jit-heavy layers: every ``ops/`` module plus the device
    store (``runtime/store.py``), per the hot-path inventory."""
    pkg = root / "distributedratelimiting" / "redis_tpu"
    paths = sorted((pkg / "ops").glob("*.py")) + [pkg / "runtime" /
                                                  "store.py"]
    findings: list[Finding] = []
    for py in paths:
        if py.exists():
            findings += check_file(py, root)
    return findings
