"""``stale-suppression`` — dead ``# drl-check: ok(...)`` comments are
findings.

A suppression comment is a standing claim: "the named rule fires here,
and we accept it for this reason". When a refactor removes the code
that fired — or the comment names a rule that never existed — the
claim rots: the comment now suppresses NOTHING, but it still reads as
protection, and the next real finding at that site is silently eaten
the day the code regresses into firing again. Three failure shapes,
all flagged:

- **unknown rule** — ``ok(task-of-loop)`` (typo'd / renamed rule):
  suppresses nothing anywhere.
- **non-suppressible rule** — ``ok(wire-const)``: that analyzer never
  consults inline comments (see ``INLINE_SUPPRESSIBLE`` in common.py),
  so the comment is dead by construction.
- **stale** — the named rule IS suppressible but no longer fires at
  this site: re-run the owning analyzer on the file with every
  suppression comment neutralized (same line count, so line numbers
  hold) and require a finding of that rule at the comment's line or
  the line below (the comment's coverage).

Escape hatch: a comment whose rule list includes ``stale-suppression``
is exempt (it declares "keep me even while dormant" — e.g. a rule
that fires only on some platforms).

``xla-*`` rules are validated for spelling/suppressibility here but
their staleness is NOT re-checked — those analyzers read compiled
artifacts, which this AST-level pass cannot re-run. drl-xla audits its
own suppressions (``python -m tools.drl_xla``)."""

from __future__ import annotations

import pathlib

from tools.drl_check.common import (
    INLINE_SUPPRESSIBLE,
    KNOWN_RULES,
    _SUPPRESS_RE,
    Finding,
    iter_py_files,
    rel,
)

__all__ = ["check", "check_source_entries", "suppression_comments"]


def _neutralize(text: str) -> str:
    """Disarm every suppression THE SAME regex recognizes (one shared
    pattern in common.py — a private copy here once drifted on
    whitespace and falsely staled live comments). Line count and
    character positions are preserved, so re-run findings keep their
    line numbers."""
    return _SUPPRESS_RE.sub(
        lambda m: m.group(0).replace("ok(", "xx(", 1), text)


def suppression_comments(text: str) -> "list[tuple[int, list[str]]]":
    out = []
    for i, line in enumerate(text.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out.append((i, [r.strip() for r in m.group(1).split(",")]))
    return out


def _raw_findings(path: str, text: str) -> "list":
    """Every suppressible analyzer's findings for this file with the
    suppression comments neutralized."""
    from tools.drl_check import concurrency_lint, jax_lint

    neutral = _neutralize(text)
    findings = []
    try:
        findings += concurrency_lint.check_source(neutral, path)
        findings += jax_lint.check_source(neutral, path)
    except SyntaxError:
        return []
    return findings


def _metric_name_fires(root: pathlib.Path, path: str,
                       line: int) -> bool:
    """Would metric-name fire at ``line`` of THIS file with the
    suppression neutralized? The rule only ever consults controller.py
    — a metric-name suppression anywhere else is dead by location (and
    must not be exonerated by a coincidental line-number collision
    with a controller.py finding)."""
    import tempfile

    from tools.drl_check import metric_names

    if pathlib.PurePath(path).name != "controller.py":
        return False
    controller = (root / "distributedratelimiting" / "redis_tpu"
                  / "runtime" / "controller.py")
    if not controller.exists():
        return False
    neutral = _neutralize(controller.read_text())
    with tempfile.TemporaryDirectory() as td:
        mutated = pathlib.Path(td) / "controller.py"
        mutated.write_text(neutral)
        try:
            findings = metric_names.check_sources(
                mutated,
                [p for p in iter_py_files(
                    root / "distributedratelimiting")
                 if p.name != "controller.py"],
                root)
        except Exception:
            return False
    return any(f.line in (line, line + 1) for f in findings)


def _flight_kind_fires(root: pathlib.Path, path: str, text: str,
                       line: int) -> bool:
    from tools.drl_check import flight_kinds

    fr = (root / "distributedratelimiting" / "redis_tpu" / "utils"
          / "flight_recorder.py")
    try:
        kinds, table_line = flight_kinds.registered_kinds(fr)
        findings = flight_kinds.check_sources(
            [(path, _neutralize(text))], kinds,
            rel(fr, root), table_line)
    except Exception:
        return False
    return any(f.line in (line, line + 1) for f in findings)


def check_source_entries(root: pathlib.Path, path: str,
                         text: str) -> "list[Finding]":
    findings: list[Finding] = []
    comments = suppression_comments(text)
    if not comments:
        return findings
    raw = None   # computed lazily, once per file
    for line, rules in comments:
        if "stale-suppression" in rules:
            continue   # the declared keep-while-dormant escape hatch
        for rule in rules:
            if rule not in KNOWN_RULES:
                findings.append(Finding(
                    "stale-suppression",
                    f"suppression names unknown rule {rule!r} — it "
                    "suppresses nothing (typo, or the rule was "
                    "renamed); fix or delete the comment",
                    path, line))
                continue
            if rule not in INLINE_SUPPRESSIBLE:
                findings.append(Finding(
                    "stale-suppression",
                    f"rule {rule!r} never honors inline suppression "
                    "comments — this ok(...) is dead by construction "
                    "and reads as protection it does not provide",
                    path, line))
                continue
            if rule.startswith("xla-"):
                # Compile-level rules: drl-check cannot re-trace a
                # kernel to test staleness. drl-xla audits its own
                # xla-* suppressions (apply_suppressions emits the
                # stale-suppression finding there).
                continue
            if rule == "metric-name":
                fires = _metric_name_fires(root, path, line)
            elif rule == "flight-kind":
                fires = _flight_kind_fires(root, path, text, line)
            else:
                if raw is None:
                    raw = _raw_findings(path, text)
                fires = any(f.rule == rule and f.line in (line, line + 1)
                            for f in raw)
            if not fires:
                findings.append(Finding(
                    "stale-suppression",
                    f"suppressed rule {rule!r} no longer fires at "
                    "this site — the code it excused is gone; delete "
                    "the comment so a future regression here is "
                    "LOUD, not silently pre-excused",
                    path, line))
    return findings


def check(root: pathlib.Path) -> "list[Finding]":
    findings: list[Finding] = []
    for py in iter_py_files(root / "distributedratelimiting"):
        findings += check_source_entries(root, rel(py, root),
                                         py.read_text())
    native = root / "native"
    if native.exists():
        for cc in sorted(native.glob("*.cc")):
            findings += check_source_entries(root, rel(cc, root),
                                             cc.read_text())
    return sorted(findings, key=lambda f: (f.file, f.line))
