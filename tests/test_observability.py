"""The unified observability plane (ISSUE 2).

Covers, layer by layer:

- ``LatencyHistogram`` edge cases: quantiles at exact bucket boundaries,
  ``reset()`` identity preservation under a live holder, ``record_bulk``
  vs per-decision parity, the running ``sum_s``.
- OpenMetrics rendering (``MetricsRegistry``): escaping, label sets,
  counter ``_total`` suffixing, cumulative histogram buckets with a
  mandatory ``+Inf``, empty-registry exposition, snapshot-dict adoption.
- ``HeavyHitters`` space-saving sketch: bounded memory, the overcount/
  error contract, batched feeding.
- ``FlightRecorder``: ring bound, parseable JSONL dumps, trigger rate
  limiting.
- The serving integration, acceptance criteria of the issue: a
  ``curl``-able ``/metrics`` endpoint and the ``OP_METRICS`` wire op on
  BOTH the asyncio and native front-end servers, per-stage latency
  decomposition in stats and exposition, ``cluster_metrics()``
  aggregating two live nodes, and a forced degraded-mode window leaving
  a parseable flight-recorder dump.
"""

from __future__ import annotations

import asyncio
import json
import math

import pytest

from distributedratelimiting.redis_tpu.runtime.cluster import (
    ClusterBucketStore,
)
from distributedratelimiting.redis_tpu.runtime.remote import RemoteBucketStore
from distributedratelimiting.redis_tpu.runtime.server import BucketStoreServer
from distributedratelimiting.redis_tpu.runtime.store import (
    BucketStore,
    DeviceBucketStore,
    InProcessBucketStore,
)
from distributedratelimiting.redis_tpu.utils.flight_recorder import (
    FlightRecorder,
)
from distributedratelimiting.redis_tpu.utils.heavy_hitters import HeavyHitters
from distributedratelimiting.redis_tpu.utils.metrics import (
    LatencyHistogram,
    LimiterMetrics,
    MetricsRegistry,
    aggregate_openmetrics,
    parse_openmetrics,
)
from distributedratelimiting.redis_tpu.utils.native import load_frontend_lib


def run(coro):
    return asyncio.run(coro)


async def _http_get(host: str, port: int, path: str,
                    accept: str | None = None) -> tuple[int, bytes]:
    status, body, _ = await _http_get_full(host, port, path, accept)
    return status, body


async def _http_get_full(host: str, port: int, path: str,
                         accept: str | None = None
                         ) -> tuple[int, bytes, str]:
    reader, writer = await asyncio.open_connection(host, port)
    req = f"GET {path} HTTP/1.1\r\nHost: test\r\n"
    if accept is not None:
        req += f"Accept: {accept}\r\n"
    writer.write((req + "\r\n").encode())
    await writer.drain()
    data = await reader.read()
    writer.close()
    try:
        await writer.wait_closed()
    except (ConnectionResetError, BrokenPipeError):
        pass
    head, _, body = data.partition(b"\r\n\r\n")
    status = int(head.split()[1])
    ctype = ""
    for line in head.decode("latin-1").split("\r\n"):
        if line.lower().startswith("content-type:"):
            ctype = line.split(":", 1)[1].strip()
    return status, body, ctype


# -- LatencyHistogram edge cases --------------------------------------------

class TestLatencyHistogram:
    def test_empty_quantiles_are_zero(self):
        h = LatencyHistogram()
        assert h.quantile(0.5) == 0.0
        assert h.p99 == 0.0

    def test_exemplars_render_and_parse(self):
        """A traced observation renders as an OpenMetrics exemplar on
        its bucket line; the aggregation parser strips it; the plain
        rendering suppresses it; reset clears it."""
        h = LatencyHistogram()
        h.record(0.004)
        h.record(0.004, trace_id="cafe" * 8)
        reg = MetricsRegistry()
        reg.histogram("x_seconds", "test", lambda: h)
        text = reg.render()
        ex_lines = [ln for ln in text.splitlines() if " # {" in ln]
        assert len(ex_lines) == 1
        assert 'trace_id="' + "cafe" * 8 + '"' in ex_lines[0]
        # value 2 (cumulative count) precedes the exemplar annotation
        assert ex_lines[0].split(" # ")[0].endswith(" 2")
        # the parser (aggregation path) reads the sample value cleanly
        _, samples = parse_openmetrics(text)
        bucket = [v for n, l, v in samples if n == "drl_x_seconds_bucket"
                  and v == 2.0]
        assert bucket
        assert " # {" not in reg.render(exemplars=False)
        # exemplar() annotates without counting
        h2 = LatencyHistogram()
        h2.exemplar(0.01, "beef" * 8)
        assert h2.total == 0 and h2.exemplars
        h2.reset()
        assert h2.exemplars is None

    def test_exemplar_strip_spares_label_values_containing_hash(self):
        """Hot keys are user-controlled label values: a key containing
        ' # ' must survive the exemplar strip (only the annotation
        AFTER the label set's closing brace drops)."""
        text = ('# TYPE drl_hot_key_count gauge\n'
                'drl_hot_key_count{key="tenant # 7"} 12\n'
                'drl_x_bucket{le="0.01",key="a # b"} 5'
                ' # {trace_id="cafe"} 0.003 1.5\n'
                '# EOF\n')
        _, samples = parse_openmetrics(text)
        by_name = {n: (dict(l), v) for n, l, v in samples}
        assert by_name["drl_hot_key_count"] == (
            {"key": "tenant # 7"}, 12.0)
        assert by_name["drl_x_bucket"] == (
            {"le": "0.01", "key": "a # b"}, 5.0)

    def test_quantile_at_exact_bucket_boundaries(self):
        """A sample recorded exactly on a bucket's upper edge must read
        back as an upper bound within one bucket width (the documented
        +25% quantile error), never below the true value."""
        h = LatencyHistogram()
        for k in (0, 1, 7, 40, LatencyHistogram.N_BUCKETS - 2):
            h.reset()
            v = LatencyHistogram.MIN_S * (LatencyHistogram.BASE ** k)
            h.record(v)
            q = h.quantile(1.0)
            assert q >= v * (1 - 1e-9), (k, v, q)
            assert q <= v * LatencyHistogram.BASE * (1 + 1e-9), (k, v, q)

    def test_min_and_overflow_buckets(self):
        h = LatencyHistogram()
        h.record(0.0)           # <= MIN_S clamps into bucket 0
        h.record(-1.0)          # pathological negative: bucket 0, no raise
        h.record(1e9)           # far past the table: overflow bucket
        assert h.counts[0] == 2
        assert h.counts[-1] == 1
        assert h.quantile(1.0) == h.bucket_upper_bounds()[-1]

    def test_quantile_cdf_boundary(self):
        """q landing exactly on a cumulative boundary reads the bucket
        that completes the mass, not the next one."""
        h = LatencyHistogram()
        for _ in range(50):
            h.record(2e-6)   # one bucket
        for _ in range(50):
            h.record(1e-3)   # a later bucket
        assert h.quantile(0.5) < 1e-3   # exactly half the mass
        assert h.quantile(0.51) > 1e-3

    def test_reset_preserves_identity_under_live_holder(self):
        """Holders capture the histogram object (MicroBatcher does at
        construction): reset must zero IN PLACE, never swap the object."""
        h = LatencyHistogram()

        class Holder:
            def __init__(self, hist):
                self.hist = hist

            def observe(self, s):
                self.hist.record(s)

        holder = Holder(h)
        holder.observe(1e-3)
        assert h.total == 1
        h.reset()
        assert h.total == 0 and h.sum_s == 0.0
        holder.observe(2e-3)  # records through the held reference...
        assert h.total == 1   # ...and is visible in the original
        assert holder.hist is h

    def test_sum_tracks_recorded_seconds(self):
        h = LatencyHistogram()
        h.record(0.25)
        h.record(0.75)
        assert h.sum_s == pytest.approx(1.0)

    def test_record_bulk_vs_per_decision_parity(self):
        """One record_bulk(n, granted) must leave the counters exactly
        where n record_decision calls do; latency intentionally differs —
        bulk records ONE sample (the whole call's), per-decision n."""
        bulk, single = LimiterMetrics(), LimiterMetrics()
        bulk.record_bulk(10, 7, latency_s=1e-3)
        for i in range(10):
            single.record_decision(i < 7, latency_s=1e-3)
        assert bulk.decisions == single.decisions == 10
        assert bulk.grants == single.grants == 7
        assert bulk.denials == single.denials == 3
        assert bulk.denial_rate == single.denial_rate
        assert bulk.acquire_latency.total == 1
        assert single.acquire_latency.total == 10

    def test_bucket_bounds_match_quantile_convention(self):
        bounds = LatencyHistogram.bucket_upper_bounds()
        assert len(bounds) == LatencyHistogram.N_BUCKETS
        assert bounds[0] == LatencyHistogram.MIN_S
        assert bounds[5] == pytest.approx(
            LatencyHistogram.MIN_S * LatencyHistogram.BASE ** 5)


# -- OpenMetrics rendering ---------------------------------------------------

class TestOpenMetricsRendering:
    def test_empty_registry_renders_eof_only(self):
        assert MetricsRegistry().render() == "# EOF\n"

    def test_counter_gets_total_suffix_and_gauge_does_not(self):
        reg = MetricsRegistry()
        reg.counter("reqs", "requests", lambda: 5)
        reg.gauge("depth", "queue depth", lambda: 2.5)
        text = reg.render()
        assert "# TYPE drl_reqs counter" in text
        assert "drl_reqs_total 5" in text
        assert "drl_depth 2.5" in text
        assert text.endswith("# EOF\n")

    def test_label_escaping(self):
        reg = MetricsRegistry()
        reg.gauge("g", "h", lambda: 1,
                  labels={"key": 'a"b\\c\nd'})
        text = reg.render()
        assert 'key="a\\"b\\\\c\\nd"' in text
        # and it round-trips through the parser
        _, samples = parse_openmetrics(text)
        assert samples[0][1] == (("key", 'a"b\\c\nd'),)

    def test_label_sets_share_one_family(self):
        reg = MetricsRegistry()
        for stage in ("queue", "flush"):
            h = LatencyHistogram()
            h.record(1e-3)
            reg.histogram("stage_seconds", "stages",
                          lambda h=h: h, labels={"stage": stage})
        text = reg.render()
        assert text.count("# TYPE drl_stage_seconds histogram") == 1
        assert 'stage="queue"' in text and 'stage="flush"' in text

    def test_histogram_cumulative_buckets_and_inf(self):
        reg = MetricsRegistry()
        h = LatencyHistogram()
        h.record(2e-6)
        h.record(2e-6)
        h.record(1e9)  # overflow bucket
        reg.histogram("lat_seconds", "latency", lambda: h)
        text = reg.render()
        _, samples = parse_openmetrics(text)
        buckets = [(dict(lbl)["le"], v) for name, lbl, v in samples
                   if name == "drl_lat_seconds_bucket"]
        assert buckets[-1][0] == "+Inf"
        assert buckets[-1][1] == 3  # cumulative: everything
        values = [v for _, v in buckets]
        assert values == sorted(values)  # cdf is monotone
        count = [v for name, _, v in samples
                 if name == "drl_lat_seconds_count"]
        assert count == [3]
        sums = [v for name, _, v in samples
                if name == "drl_lat_seconds_sum"]
        assert sums[0] == pytest.approx(1e9 + 4e-6)

    def test_histogram_none_skipped(self):
        reg = MetricsRegistry()
        reg.histogram("absent_seconds", "maybe", lambda: None)
        assert "absent_seconds_bucket" not in reg.render()

    def test_numeric_dict_adoption(self):
        reg = MetricsRegistry()
        reg.register_numeric_dict(
            "store", "store metrics",
            lambda: {"launches": 4, "occupancy": 0.5,
                     "name": "skipped", "flag": True, "nested": {}},
            counters={"launches"})
        text = reg.render()
        assert "drl_store_launches_total 4" in text
        assert "drl_store_occupancy 0.5" in text
        assert "skipped" not in text and "nested" not in text
        assert "drl_store_flag" not in text  # bools are not numbers here

    def test_dynamic_labeled_gauges(self):
        reg = MetricsRegistry()
        series = [({"key": "a"}, 3.0), ({"key": "b"}, 1.0)]
        reg.labeled_gauges("hot", "hot keys", lambda: series)
        text = reg.render()
        assert 'drl_hot{key="a"} 3' in text
        assert 'drl_hot{key="b"} 1' in text

    def test_broken_reader_does_not_kill_scrape(self):
        reg = MetricsRegistry()
        reg.gauge("bad", "raises", lambda: 1 / 0)
        reg.gauge("good", "fine", lambda: 7)
        text = reg.render()
        assert "drl_good 7" in text and "drl_bad" not in text

    def test_type_conflict_rejected(self):
        reg = MetricsRegistry()
        reg.counter("x", "h", lambda: 1)
        with pytest.raises(ValueError):
            reg.gauge("x", "h", lambda: 1)

    def test_aggregate_openmetrics(self):
        reg_a, reg_b = MetricsRegistry(), MetricsRegistry()
        reg_a.counter("reqs", "r", lambda: 10)
        reg_b.counter("reqs", "r", lambda: 32)
        merged = aggregate_openmetrics([reg_a.render(), reg_b.render()])
        assert "drl_reqs_total 42" in merged
        assert 'drl_reqs_total{node="0"} 10' in merged
        assert 'drl_reqs_total{node="1"} 32' in merged
        assert "# TYPE drl_reqs counter" in merged
        assert merged.endswith("# EOF\n")

    def test_aggregate_families_stay_contiguous(self):
        """OpenMetrics forbids interleaving families: every family's
        samples (aggregated + per-node) must form one contiguous block
        after its single # TYPE line."""
        regs = []
        for v in (1, 2):
            reg = MetricsRegistry()
            reg.counter("alpha", "a", lambda v=v: v)
            reg.gauge("beta", "b", lambda v=v: v * 10)
            regs.append(reg)
        merged = aggregate_openmetrics([r.render() for r in regs])
        lines = [l for l in merged.splitlines() if l != "# EOF"]
        fam_of = []
        for line in lines:
            name = line.split(None, 2)[2].split()[0] if \
                line.startswith("# TYPE") else line.split("{")[0].split()[0]
            fam_of.append("alpha" if "alpha" in name else "beta")
        # one contiguous run per family → exactly one transition
        transitions = sum(1 for a, b in zip(fam_of, fam_of[1:]) if a != b)
        assert transitions == 1, lines
        assert merged.count("# TYPE drl_alpha counter") == 1
        assert merged.count("# TYPE drl_beta gauge") == 1


# -- HeavyHitters ------------------------------------------------------------

class TestHeavyHitters:
    def test_exact_when_under_capacity(self):
        hh = HeavyHitters(k=8)
        for _ in range(5):
            hh.offer("a")
        hh.offer("b", 3)
        top = hh.top()
        assert top[0] == ("a", 5.0, 0.0)
        assert top[1] == ("b", 3.0, 0.0)
        assert hh.offered == 8.0

    def test_bounded_memory_and_error_contract(self):
        hh = HeavyHitters(k=4)
        # A true heavy hitter among a long cold tail.
        for i in range(200):
            hh.offer(f"cold{i}")
            if i % 2 == 0:
                hh.offer("hot")
        assert len(hh) <= 4
        top = hh.top()
        hot = next(t for t in top if t[0] == "hot")
        # Space-saving: reported count ≥ true count, overshoot ≤ error.
        assert hot[1] >= 100
        assert hot[1] - hot[2] <= 100

    def test_offer_many_matches_offers_for_small_batches(self):
        a, b = HeavyHitters(k=16), HeavyHitters(k=16)
        keys = ["x"] * 5 + ["y"] * 3 + ["z"]
        a.offer_many(keys)
        for k in keys:
            b.offer(k)
        assert dict((k, c) for k, c, _ in a.top()) == \
            dict((k, c) for k, c, _ in b.top())
        assert a.offered == b.offered == 9.0

    def test_offer_many_truncation_keeps_offered_honest(self):
        hh = HeavyHitters(k=2, batch_top=2)
        hh.offer_many(["a", "a", "b", "c", "d"])  # c, d truncated
        assert hh.offered == 5.0
        assert len(hh) <= 2

    def test_reset(self):
        hh = HeavyHitters(k=2)
        hh.offer("a")
        hh.reset()
        assert len(hh) == 0 and hh.offered == 0.0
        assert hh.snapshot()["top"] == []


# -- FlightRecorder ----------------------------------------------------------

class TestFlightRecorder:
    def test_ring_is_bounded(self, tmp_path):
        rec = FlightRecorder(capacity=8, dump_dir=str(tmp_path))
        for i in range(50):
            rec.record("flush", n=i)
        assert len(rec.frames()) == 8
        assert rec.frames()[0]["n"] == 42  # oldest surviving frame
        assert rec.frames_recorded == 50

    def test_dump_is_parseable_jsonl(self, tmp_path):
        rec = FlightRecorder(capacity=4, dump_dir=str(tmp_path))
        rec.record("flush", n=1, wall_ms=0.5, error=None)
        rec.record("t0_sync", keys=3, failures=1)
        path = rec.dump("unit_test", {"note": "hello"})
        assert path is not None
        lines = [json.loads(line) for line in open(path)]
        assert lines[0]["kind"] == "header"
        assert lines[0]["reason"] == "unit_test"
        assert lines[0]["note"] == "hello"
        assert [f["kind"] for f in lines[1:]] == ["flush", "t0_sync"]
        assert rec.dumps_written == 1
        assert rec.last_dump_path == path

    def test_auto_dump_rate_limited(self, tmp_path):
        rec = FlightRecorder(capacity=4, dump_dir=str(tmp_path),
                             min_dump_interval_s=3600.0)
        rec.record("flush", n=1)
        assert rec.auto_dump("streak") is not None
        assert rec.auto_dump("streak") is None  # suppressed
        assert rec.dumps_suppressed == 1
        assert rec.dump("operator") is not None  # explicit bypasses

    def test_unwritable_dir_fails_soft(self):
        rec = FlightRecorder(capacity=4,
                             dump_dir="/nonexistent-dir-for-test")
        rec.record("flush", n=1)
        assert rec.dump("x") is None  # no raise on the serving path


# -- Serving integration: asyncio server ------------------------------------

class TestAsyncioServerExposition:
    @pytest.mark.jax_backend
    def test_metrics_op_http_and_stage_decomposition(self):
        async def body():
            backing = DeviceBucketStore(n_slots=1 << 10)
            srv = BucketStoreServer(backing, metrics_port=0)
            await srv.start()
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            try:
                for i in range(60):
                    await store.acquire(f"user{i % 5}", 1, 1000.0, 10.0)
                # OP_METRICS on the wire
                text = await store.metrics()
                assert text.endswith("# EOF\n")
                assert "drl_serving_latency_seconds_bucket" in text
                for stage in ("queue", "flush", "reply"):
                    assert (f'drl_stage_latency_seconds_bucket{{stage='
                            f'"{stage}"') in text, stage
                assert "drl_store_launches_total" in text
                assert 'drl_hot_key_count{key="user0"}' in text
                # the same bytes over plain HTTP (the curl path)
                status, http_body = await _http_get(
                    srv.host, srv.metrics_port, "/metrics")
                assert status == 200
                assert b"drl_serving_latency_seconds_bucket" in http_body
                status, _ = await _http_get(srv.host, srv.metrics_port,
                                            "/nope")
                assert status == 404
                # Content negotiation: scrapers that Accept openmetrics
                # get the full ctype; everyone else gets Prometheus
                # text 0.0.4 (and no exemplar annotations).
                _, _, ctype = await _http_get_full(
                    srv.host, srv.metrics_port, "/metrics",
                    accept="application/openmetrics-text; version=1.0.0")
                assert ctype == MetricsRegistry.CONTENT_TYPE
                _, _, ctype = await _http_get_full(
                    srv.host, srv.metrics_port, "/metrics")
                assert ctype == BucketStoreServer.PLAIN_CONTENT_TYPE
                _, _, ctype = await _http_get_full(
                    srv.host, srv.metrics_port, "/metrics",
                    accept="text/plain")
                assert ctype == BucketStoreServer.PLAIN_CONTENT_TYPE
                # stats carries the decomposition numerically
                stats = await store.stats()
                stages = stats["stages"]
                assert {"queue", "flush", "reply"} <= set(stages)
                for s in ("queue", "flush", "reply"):
                    assert stages[s]["samples"] > 0
                assert stats["hot_keys"]["tracked"] == 5
                # reset opens a fresh window for every stage histogram
                await store.stats(reset=True)
                stats2 = await store.stats()
                assert stats2.get("stages", {}).get(
                    "queue", {"samples": 0})["samples"] == 0
            finally:
                await store.aclose()
                await srv.aclose()
                await backing.aclose()

        run(body())

    @pytest.mark.jax_backend
    def test_stats_flight_dump_trigger(self, tmp_path):
        async def body():
            backing = DeviceBucketStore(n_slots=1 << 10)
            srv = BucketStoreServer(backing, flight_dir=str(tmp_path))
            await srv.start()
            # Per-request framing: the scalar lane rides the micro-
            # batcher, whose flush observer is what feeds the recorder.
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            try:
                await store.acquire("k", 1, 100.0, 10.0)
                stats = await store.stats(dump_flight=True)
                path = stats["flight_recorder"]["last_dump_path"]
                assert path is not None
                lines = [json.loads(line) for line in open(path)]
                assert lines[0]["kind"] == "header"
                assert any(f["kind"] == "flush" for f in lines[1:])
            finally:
                await store.aclose()
                await srv.aclose()
                await backing.aclose()

        run(body())

    def test_sema_releases_and_probes_not_counted_as_hot_keys(self):
        """OP_SEMA's count is a signed delta: releases (<0) and probes
        (0) are not admission demand and must not feed the sketch (a
        balanced acquire/release stream would double-weight its keys)."""
        from distributedratelimiting.redis_tpu.runtime import wire

        async def body():
            srv = BucketStoreServer(InProcessBucketStore())
            acq = wire.encode_request(1, wire.OP_SEMA, "sema-key", 1,
                                      10.0, 0.0)[4:]
            rel = wire.encode_request(2, wire.OP_SEMA, "sema-key", -1,
                                      0.0, 0.0)[4:]
            probe = wire.encode_request(3, wire.OP_SEMA, "sema-key", 0,
                                        10.0, 0.0)[4:]
            await srv.handle_frame_body(acq)
            await srv.handle_frame_body(rel)
            await srv.handle_frame_body(probe)
            top = srv.heavy_hitters.top()
            assert top == [("sema-key", 1.0, 0.0)], top

        run(body())

    def test_http_flight_trigger_is_rate_limited(self, tmp_path):
        async def body():
            srv = BucketStoreServer(InProcessBucketStore(),
                                    metrics_port=0,
                                    flight_dir=str(tmp_path))
            await srv.start()
            try:
                srv.flight_recorder.record("flush", n=1)
                status, body1 = await _http_get(
                    srv.host, srv.metrics_port, "/flight")
                assert status == 200
                first = json.loads(body1)
                assert first["dumped"] and not first["suppressed"]
                status, body2 = await _http_get(
                    srv.host, srv.metrics_port, "/flight")
                second = json.loads(body2)
                # within min_dump_interval_s: suppressed, no new file —
                # an unauthenticated peer cannot disk-fill through here.
                assert second["dumped"] is None and second["suppressed"]
            finally:
                await srv.aclose()

        run(body())

    def test_observability_off_still_exposes_latency(self):
        async def body():
            srv = BucketStoreServer(InProcessBucketStore(),
                                    observability=False)
            await srv.start()
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            try:
                await store.acquire("k", 1, 100.0, 10.0)
                assert srv.heavy_hitters is None
                assert srv.flight_recorder is None
                text = await store.metrics()
                assert "drl_serving_latency_seconds_bucket" in text
                assert "drl_hot_key_count" not in text
                stats = await store.stats()
                assert "hot_keys" not in stats
            finally:
                await store.aclose()
                await srv.aclose()

        run(body())


# -- Serving integration: native front-end ----------------------------------

_LIB = load_frontend_lib()
native_only = pytest.mark.skipif(
    _LIB is None, reason="native front-end library unavailable")
tier0_native_only = pytest.mark.skipif(
    _LIB is None or not getattr(_LIB, "has_tier0", False),
    reason="native front-end library (with tier-0 ABI) unavailable")


@native_only
def test_native_server_metrics_and_stage_decomposition():
    async def body():
        srv = BucketStoreServer(InProcessBucketStore(),
                                native_frontend=True, metrics_port=0)
        await srv.start()
        assert srv._native is not None
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
        try:
            for i in range(150):
                await store.acquire(f"key{i % 4}", 1, 1e6, 1e6)
            text = await store.metrics()
            assert "drl_native_frontend 1" in text
            for stage in ("native_queue", "native_exec"):
                assert (f'drl_stage_latency_seconds_bucket{{stage='
                        f'"{stage}"') in text, stage
            assert 'drl_hot_key_count{key="key0"}' in text
            stats = await store.stats()
            st = stats["stages"]
            assert st["native_queue"]["samples"] > 0
            assert st["native_exec"]["samples"] > 0
            # serving covers queue + exec: its p99 can't be below either
            # stage's p50 by construction (same windows, same samples).
            assert stats["serving_p99_ms"] >= st["native_queue"]["p50_ms"]
            # the HTTP endpoint serves beside the native wire listener
            status, http_body = await _http_get(srv.host,
                                                srv.metrics_port,
                                                "/metrics")
            assert status == 200
            assert b'stage="native_exec"' in http_body
            # reset clears the C-side stage windows too
            await store.stats(reset=True)
            stats2 = await store.stats()
            assert "native_queue" not in stats2.get("stages", {})
        finally:
            await store.aclose()
            await srv.aclose()

    run(body())


class _OutageStore(InProcessBucketStore):
    """Backing store whose device-touching paths fail on demand (the
    r04/r05 outage mode as the front-end sees it — test_tier0's rig)."""

    def __init__(self):
        super().__init__()
        self.fail = False

    def _check(self):
        if self.fail:
            raise RuntimeError("simulated device outage")

    async def acquire_many(self, *a, **kw):
        self._check()
        return await super().acquire_many(*a, **kw)

    async def debit_many(self, *a, **kw):
        self._check()
        return await super().debit_many(*a, **kw)


@tier0_native_only
def test_flight_recorder_dumps_on_forced_degraded_mode(tmp_path):
    """Acceptance criterion: a forced degraded-mode window (tier-0 sync
    pump failing against a dead store) must leave a parseable JSONL dump
    on disk, written by the sync-failure-streak trigger."""
    from distributedratelimiting.redis_tpu.runtime.native_frontend import (
        Tier0Config,
    )

    async def body():
        backing = _OutageStore()
        cfg = Tier0Config(sync_interval_s=0.01, min_budget=8.0,
                          max_stale_s=10.0)
        srv = BucketStoreServer(backing, native_frontend=True,
                                native_tier0=cfg,
                                flight_dir=str(tmp_path))
        await srv.start()
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
        try:
            # Warm: install the replica, let one healthy sync land.
            for _ in range(50):
                await store.acquire("hot", 1, 10000.0, 1e-9)
            await asyncio.sleep(0.05)
            # With tier-0 armed the exposition carries its gauges and
            # the pump-fed hot-key series (acceptance criterion).
            text = await store.metrics()
            assert "drl_tier0_hits_total" in text
            assert "drl_tier0_last_sync_age_s" in text
            assert 'drl_hot_key_count{key="hot"}' in text
            backing.fail = True
            # Keep tier-0 granting locally so every sync round has
            # harvested amounts to fail on.
            deadline = asyncio.get_running_loop().time() + 5.0
            dumped = None
            while asyncio.get_running_loop().time() < deadline:
                for _ in range(20):
                    await store.acquire("hot", 1, 10000.0, 1e-9)
                await asyncio.sleep(0.03)
                rec = srv.flight_recorder
                if rec is not None and rec.dumps_written:
                    dumped = rec.last_dump_path
                    break
            assert dumped is not None, "degraded streak never dumped"
            lines = [json.loads(line) for line in open(dumped)]
            assert lines[0]["kind"] == "header"
            assert lines[0]["reason"] == "t0_sync_streak"
            syncs = [f for f in lines[1:] if f["kind"] == "t0_sync"]
            assert syncs, lines[1:]
            assert any(f["failures"] for f in syncs)
            assert max(f["streak"] for f in syncs) >= 1
        finally:
            backing.fail = False
            await store.aclose()
            await srv.aclose()

    run(body())


# -- Cluster aggregation -----------------------------------------------------

def test_cluster_metrics_aggregates_two_nodes():
    async def body():
        servers = []
        for _ in range(2):
            s = BucketStoreServer(InProcessBucketStore())
            await s.start()
            servers.append(s)
        cluster = ClusterBucketStore(
            addresses=[(s.host, s.port) for s in servers])
        try:
            keys = [f"ck{i}" for i in range(64)]
            res = await cluster.acquire_many(keys, [1] * 64, 1000.0, 10.0)
            assert res.granted.all()
            text = await cluster.cluster_metrics()
            lines = text.splitlines()
            agg = [l for l in lines
                   if l.startswith("drl_requests_served_total ")]
            n0 = [l for l in lines
                  if l.startswith('drl_requests_served_total{node="0"}')]
            n1 = [l for l in lines
                  if l.startswith('drl_requests_served_total{node="1"}')]
            assert agg and n0 and n1
            assert float(agg[0].split()[-1]) == pytest.approx(
                float(n0[0].split()[-1]) + float(n1[0].split()[-1]))
            # both nodes actually served a sub-batch (crc32 spreads 64
            # keys across 2 nodes with overwhelming probability)
            assert float(n0[0].split()[-1]) >= 1
            assert float(n1[0].split()[-1]) >= 1
            assert text.endswith("# EOF\n")
        finally:
            await cluster.aclose()
            for s in servers:
                await s.aclose()

    run(body())


# -- MicroBatcher stage instrumentation --------------------------------------

def test_batcher_queue_stage_and_flush_observer():
    """The queue-stage histogram records the oldest member's wait once
    per flush; the observer sees (n, wall, error, trace_id) including
    failures — the flight recorder's feed contract (trace_id is None
    whenever no member of the flush was sampled)."""
    from distributedratelimiting.redis_tpu.runtime.batcher import (
        MicroBatcher,
    )

    async def body():
        qhist = LatencyHistogram()
        seen: list[tuple] = []

        async def flush(reqs):
            await asyncio.sleep(0.001)
            return [r * 2 for r in reqs]

        mb = MicroBatcher(flush, max_batch=8, queue_latency=qhist,
                          flush_observer=lambda *a: seen.append(a))
        out = await asyncio.gather(*(mb.submit(i) for i in range(8)))
        assert out == [i * 2 for i in range(8)]
        await mb.aclose()
        assert qhist.total >= 1
        assert seen and seen[0][0] == 8 and seen[0][2] is None
        assert seen[0][1] >= 0.001
        assert seen[0][3] is None  # untraced flush: no elected trace

        async def bad_flush(reqs):
            raise RuntimeError("boom")

        seen.clear()
        mb2 = MicroBatcher(bad_flush, max_batch=4,
                           flush_observer=lambda *a: seen.append(a))
        with pytest.raises(RuntimeError):
            await mb2.submit(1)
        await mb2.aclose()
        assert seen and seen[0][2] is not None
        assert "boom" in seen[0][2]

        # An observer that itself raises must not fail a flush that
        # succeeded (nor be re-invoked on a phantom error path).
        calls = []

        def exploding_observer(n, dt, err, trace_id=None):
            calls.append(err)
            raise ValueError("observer bug")

        mb3 = MicroBatcher(flush, max_batch=4,
                           flush_observer=exploding_observer)
        assert await mb3.submit(21) == 42  # result survives the observer
        await mb3.aclose()
        assert calls == [None]  # called once, success-shaped

    run(body())


@pytest.mark.jax_backend
def test_flush_error_triggers_degraded_entry_dump(tmp_path):
    """The store-side degraded trigger without any native dependency:
    a failing flush fires the observer, which records the frame and
    auto-dumps through the attached recorder."""
    store = DeviceBucketStore(n_slots=64)
    rec = FlightRecorder(capacity=16, dump_dir=str(tmp_path))
    store.metrics.flight_recorder = rec
    store._flush_observer(64, 0.002, None)
    store._flush_observer(64, 0.1, "RuntimeError('device gone')")
    assert rec.dumps_written == 1
    lines = [json.loads(line) for line in open(rec.last_dump_path)]
    assert lines[0]["reason"] == "flush_error"
    assert [f["kind"] for f in lines[1:]] == ["flush", "flush"]
    assert lines[-1]["error"] is not None
