"""Concurrency (held-permit) limiter tests — permits return on dispose.

Covers the semaphore kernel, all three stores, the limiter contract
(queueing, cancellation-with-permit-return, dispose), and multi-instance
sharing over the wire.
"""

import asyncio

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.models.concurrency import (
    ConcurrencyLimiter,
)
from distributedratelimiting.redis_tpu.models.options import (
    ConcurrencyLimiterOptions,
)
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.queueing import (
    QueueProcessingOrder,
)
from distributedratelimiting.redis_tpu.runtime.remote import RemoteBucketStore
from distributedratelimiting.redis_tpu.runtime.server import BucketStoreServer
from distributedratelimiting.redis_tpu.runtime.store import (
    DeviceBucketStore,
    InProcessBucketStore,
)


def run(coro):
    return asyncio.run(coro)


def device_store():
    return DeviceBucketStore(n_slots=64, counter_slots=8, clock=ManualClock(),
                             max_batch=64)


class TestSemaKernel:
    def test_acquire_until_full_then_release(self):
        import jax.numpy as jnp

        from distributedratelimiting.redis_tpu.ops import kernels as K

        state = K.init_sema_state(8)

        def op(state, slot, delta, limit):
            packed = np.full((4, 8), -1, np.int32)
            packed[1] = 0
            packed[2] = 0
            packed[3] = 1
            packed[0, 0] = slot
            packed[1, 0] = delta
            packed[2, 0] = limit
            state, out = K.sema_batch_packed(state, jnp.asarray(packed))
            o = np.asarray(out)
            return state, bool(o[0, 0] > 0.5), float(o[1, 0])

        state, ok, after = op(state, 3, 2, 3)
        assert ok and after == 2
        state, ok, after = op(state, 3, 2, 3)   # 2+2 > 3
        assert not ok and after == 2
        state, ok, after = op(state, 3, 1, 3)
        assert ok and after == 3
        state, ok, after = op(state, 3, -2, 0)  # release always applies
        assert ok and after == 1
        state, ok, after = op(state, 3, -9, 0)  # over-release clamps at 0
        assert ok and after == 0

    def test_batch_duplicates_never_over_admit(self):
        import jax.numpy as jnp

        from distributedratelimiting.redis_tpu.ops import kernels as K

        state = K.init_sema_state(8)
        packed = np.full((4, 8), -1, np.int32)
        packed[1] = 0
        packed[2] = 0
        packed[3] = 1
        # Five +1 acquires for the same slot, limit 3.
        packed[0, :5] = 2
        packed[1, :5] = 1
        packed[2, :5] = 3
        state, out = K.sema_batch_packed(state, jnp.asarray(packed))
        o = np.asarray(out)
        assert o[0, :5].sum() == 3
        assert int(np.asarray(state.active)[2]) == 3


@pytest.mark.parametrize("make_store", [InProcessBucketStore, device_store])
class TestStoreSemantics:
    def test_limit_enforced_and_released(self, make_store):
        store = make_store()
        assert store.concurrency_acquire_blocking("s", 2, 3).granted
        assert not store.concurrency_acquire_blocking("s", 2, 3).granted
        store.concurrency_release_blocking("s", 2)
        assert store.concurrency_acquire_blocking("s", 3, 3).granted

    def test_keys_are_independent(self, make_store):
        store = make_store()
        assert store.concurrency_acquire_blocking("a", 3, 3).granted
        assert store.concurrency_acquire_blocking("b", 3, 3).granted


class TestConcurrencyLimiter:
    def test_lease_dispose_returns_permits(self):
        lim = ConcurrencyLimiter(
            ConcurrencyLimiterOptions(permit_limit=2, instance_name="c1"),
            InProcessBucketStore())
        l1 = lim.acquire(1)
        l2 = lim.acquire(1)
        assert l1.is_acquired and l2.is_acquired
        assert not lim.acquire(1).is_acquired
        l1.dispose()
        assert lim.acquire(1).is_acquired
        l1.dispose()  # double-dispose is a no-op, not an over-release
        assert not lim.acquire(1).is_acquired

    def test_context_manager_releases(self):
        lim = ConcurrencyLimiter(
            ConcurrencyLimiterOptions(permit_limit=1, instance_name="c2"),
            InProcessBucketStore())
        with lim.acquire(1) as lease:
            assert lease.is_acquired
            assert not lim.acquire(1).is_acquired
        assert lim.acquire(1).is_acquired

    def test_over_limit_raises_and_zero_probe(self):
        lim = ConcurrencyLimiter(
            ConcurrencyLimiterOptions(permit_limit=2, instance_name="c3"),
            InProcessBucketStore())
        with pytest.raises(ValueError):
            lim.acquire(3)
        assert lim.acquire(0).is_acquired          # permits available
        hold = lim.acquire(2)
        assert not lim.acquire(0).is_acquired      # none left
        hold.dispose()

    def test_async_waiters_drain_on_release(self):
        async def main():
            lim = ConcurrencyLimiter(
                ConcurrencyLimiterOptions(permit_limit=1, queue_limit=4,
                                          instance_name="c4"),
                InProcessBucketStore())
            first = await lim.acquire_async(1)
            waiter = asyncio.create_task(lim.acquire_async(1))
            await asyncio.sleep(0.01)
            assert not waiter.done()
            await first.release_async()
            lease = await asyncio.wait_for(waiter, 2.0)
            assert lease.is_acquired
            await lease.release_async()
            assert lim.available_permits() == 1
            await lim.aclose()

        run(main())

    def test_cancelled_waiter_returns_queued_slot(self):
        async def main():
            lim = ConcurrencyLimiter(
                ConcurrencyLimiterOptions(permit_limit=1, queue_limit=1,
                                          instance_name="c5"),
                InProcessBucketStore())
            first = await lim.acquire_async(1)
            waiter = asyncio.create_task(lim.acquire_async(1))
            await asyncio.sleep(0.01)
            waiter.cancel()
            with pytest.raises(asyncio.CancelledError):
                await waiter
            # The cancelled waiter's queue slot is free again.
            waiter2 = asyncio.create_task(lim.acquire_async(1))
            await asyncio.sleep(0.01)
            await first.release_async()
            lease = await asyncio.wait_for(waiter2, 2.0)
            assert lease.is_acquired
            # Permits were never stranded on the cancelled waiter.
            await lease.release_async()
            assert lim.available_permits() == 1
            await lim.aclose()

        run(main())

    def test_dispose_fails_queued_waiters(self):
        async def main():
            lim = ConcurrencyLimiter(
                ConcurrencyLimiterOptions(permit_limit=1, queue_limit=3,
                                          instance_name="c6"),
                InProcessBucketStore())
            first = await lim.acquire_async(1)
            waiter = asyncio.create_task(lim.acquire_async(1))
            await asyncio.sleep(0.01)
            await lim.aclose()
            lease = await asyncio.wait_for(waiter, 2.0)
            assert not lease.is_acquired
            del first

        run(main())

    def test_newest_first_evicts_oldest_waiter(self):
        async def main():
            lim = ConcurrencyLimiter(
                ConcurrencyLimiterOptions(
                    permit_limit=1, queue_limit=1,
                    queue_processing_order=QueueProcessingOrder.NEWEST_FIRST,
                    instance_name="c7"),
                InProcessBucketStore())
            first = await lim.acquire_async(1)
            w1 = asyncio.create_task(lim.acquire_async(1))
            await asyncio.sleep(0.01)
            w2 = asyncio.create_task(lim.acquire_async(1))
            await asyncio.sleep(0.01)
            assert not (await asyncio.wait_for(w1, 2.0)).is_acquired
            await first.release_async()
            assert (await asyncio.wait_for(w2, 2.0)).is_acquired
            await lim.aclose()

        run(main())

    def test_cancel_after_grant_releases_permits(self):
        """A waiter cancelled after the drain granted it (future resolved,
        awaiting task not yet resumed) must release the held permits —
        otherwise the semaphore's capacity shrinks forever."""
        async def main():
            lim = ConcurrencyLimiter(
                ConcurrencyLimiterOptions(permit_limit=1, queue_limit=4,
                                          instance_name="c8"),
                InProcessBucketStore())
            first = await lim.acquire_async(1)
            waiter = asyncio.create_task(lim.acquire_async(1))
            await asyncio.sleep(0.01)
            # release_async drains synchronously on this loop: the waiter's
            # future is resolved with a held lease before we regain control.
            await first.release_async()
            waiter.cancel()  # cancel before the waiter task resumes
            with pytest.raises(asyncio.CancelledError):
                await waiter
            await asyncio.sleep(0.05)  # let the compensating release run
            assert lim.available_permits() == 1
            await lim.aclose()

        run(main())

    def test_cancel_midflight_fast_path_releases_grant(self):
        """A cancel landing while the fast-path store acquire is in flight
        must not leak the grant the store goes on to make."""
        class SlowStore(InProcessBucketStore):
            async def concurrency_acquire(self, key, delta, limit,
                                          ttl_s=86400.0):
                await asyncio.sleep(0.05)
                return await super().concurrency_acquire(key, delta, limit,
                                                         ttl_s)

        async def main():
            lim = ConcurrencyLimiter(
                ConcurrencyLimiterOptions(permit_limit=2, queue_limit=4,
                                          instance_name="c9"),
                SlowStore())
            t = asyncio.create_task(lim.acquire_async(2))
            await asyncio.sleep(0.01)  # t is awaiting the shielded store op
            t.cancel()
            with pytest.raises(asyncio.CancelledError):
                await t
            await asyncio.sleep(0.2)  # store op completes; release runs
            assert lim.available_permits() == 2
            await lim.aclose()

        run(main())

    def test_sync_acquire_does_not_overtake_oldest_first_waiters(self):
        """The sync path applies the same queue-fairness gate as async:
        with a parked OLDEST_FIRST waiter, acquire() fails fast even when
        the store has free permits."""
        async def main():
            lim = ConcurrencyLimiter(
                ConcurrencyLimiterOptions(permit_limit=2, queue_limit=4,
                                          instance_name="c10"),
                InProcessBucketStore())
            a = await lim.acquire_async(1)
            w = asyncio.create_task(lim.acquire_async(2))  # parks: only 1 free
            await asyncio.sleep(0.01)
            assert not w.done()
            lease = lim.acquire(1)  # 1 permit IS free, but a waiter is ahead
            assert not lease.is_acquired
            await a.release_async()  # 2 free -> waiter drains
            assert (await asyncio.wait_for(w, 2.0)).is_acquired
            await lim.aclose()

        run(main())


class TestDistributedConcurrency:
    def test_two_instances_share_one_semaphore_over_tcp(self):
        async def main():
            async with BucketStoreServer(InProcessBucketStore()) as srv:
                store_a = RemoteBucketStore(address=(srv.host, srv.port))
                store_b = RemoteBucketStore(address=(srv.host, srv.port))
                lim_a = ConcurrencyLimiter(
                    ConcurrencyLimiterOptions(permit_limit=2,
                                              instance_name="shared"),
                    store_a)
                lim_b = ConcurrencyLimiter(
                    ConcurrencyLimiterOptions(permit_limit=2,
                                              instance_name="shared"),
                    store_b)
                try:
                    la = await lim_a.acquire_async(1)
                    lb = await lim_b.acquire_async(1)
                    assert la.is_acquired and lb.is_acquired
                    # Global limit reached across both instances.
                    assert not (await lim_a.acquire_async(1)).is_acquired
                    await la.release_async()
                    assert (await lim_b.acquire_async(1)).is_acquired
                finally:
                    await lim_a.aclose()
                    await lim_b.aclose()
                    await store_a.aclose()
                    await store_b.aclose()

        run(main())


class TestCrossInstanceWakeup:
    def test_waiter_wakes_on_other_instances_release(self):
        """Regression: a waiter parked on instance B must wake when
        instance A releases — there is no cross-instance signal, so B's
        retry poll is the only wakeup path."""

        async def main():
            backing = InProcessBucketStore()
            lim_a = ConcurrencyLimiter(
                ConcurrencyLimiterOptions(permit_limit=1, queue_limit=2,
                                          instance_name="x",
                                          retry_period_s=0.02),
                backing)
            lim_b = ConcurrencyLimiter(
                ConcurrencyLimiterOptions(permit_limit=1, queue_limit=2,
                                          instance_name="x",
                                          retry_period_s=0.02),
                backing)
            held = await lim_a.acquire_async(1)
            waiter = asyncio.create_task(lim_b.acquire_async(1))
            await asyncio.sleep(0.05)
            assert not waiter.done()
            await held.release_async()   # release on A — B must poll it up
            lease = await asyncio.wait_for(waiter, 3.0)
            assert lease.is_acquired
            await lease.release_async()
            await lim_a.aclose()
            await lim_b.aclose()

        run(main())


class TestProbeIsReadOnly:
    def test_probe_allocates_nothing_on_device_store(self):
        store = device_store()
        # Zero-delta probe of an unknown key: no directory slot, no device
        # state — a monitoring poll must not create or TTL-refresh slots.
        res = store.concurrency_acquire_blocking("never-used", 0, 5)
        assert res.granted and res.remaining == 0.0
        assert store._sema_dir.lookup("never-used") is None

    def test_probe_does_not_refresh_ttl(self):
        import numpy as np

        clock = ManualClock()
        store = DeviceBucketStore(n_slots=64, counter_slots=8, clock=clock,
                                  max_batch=64)
        store.concurrency_acquire_blocking("k", 1, 5)
        store.concurrency_release_blocking("k", 1)
        ts_after_release = int(np.asarray(store._semas.last_ts)[
            store._sema_dir.lookup("k")])
        clock.advance_seconds(100.0)
        store.concurrency_acquire_blocking("k", 0, 5)  # probe
        ts_after_probe = int(np.asarray(store._semas.last_ts)[
            store._sema_dir.lookup("k")])
        assert ts_after_probe == ts_after_release

    def test_probe_does_not_create_inprocess_entry(self):
        store = InProcessBucketStore()
        store.concurrency_acquire_blocking("ghost", 0, 5)
        assert "ghost" not in store._semas


class TestSpuriousRelease:
    @pytest.mark.parametrize("make_store", [InProcessBucketStore, device_store])
    def test_release_of_unknown_key_allocates_nothing(self, make_store):
        store = make_store()
        store.concurrency_release_blocking("never-acquired", 3)
        if isinstance(store, DeviceBucketStore):
            assert store._sema_dir.lookup("never-acquired") is None
        else:
            assert "never-acquired" not in store._semas
        # And the semaphore still behaves normally afterwards.
        assert store.concurrency_acquire_blocking("never-acquired", 2, 3).granted
        assert not store.concurrency_acquire_blocking("never-acquired", 2, 3).granted


class TestBulkSemaphore:
    """concurrency_acquire_many — the packed bulk path the native
    front-end batches OP_SEMA frames into."""

    def test_mixed_batch_with_duplicates_serializes_in_order(self):
        async def main():
            store = device_store()
            try:
                keys = ["a", "a", "a", "b", "a", "b"]
                deltas = [2, 2, 2, 1, -2, 0]
                # limit 4: a gets 2+2 then denies the third; the release
                # afterward applies; b's probe sees its own held count.
                res = await store.concurrency_acquire_many(keys, deltas, 4)
                assert res.granted.tolist() == [True, True, False, True,
                                                True, True]
                # post-release state: a holds 2, b holds 1
                r = await store.concurrency_acquire("a", 2, 4)
                assert r.granted and r.remaining == pytest.approx(4.0)
            finally:
                await store.aclose()

        run(main())

    def test_unknown_key_release_and_probe_allocate_nothing(self):
        async def main():
            store = device_store()
            try:
                res = await store.concurrency_acquire_many(
                    ["ghost", "phantom"], [-3, 0], 5)
                assert res.granted.tolist() == [True, True]
                assert res.remaining.tolist() == [0.0, 0.0]
                assert store._sema_dir.lookup("ghost") is None
                assert store._sema_dir.lookup("phantom") is None
            finally:
                await store.aclose()

        run(main())

    def test_matches_scalar_path_on_distinct_keys(self):
        # Exactness contract: bulk decisions equal the scalar path's
        # whenever in-call keys are distinct (duplicates serialize
        # conservatively — covered by the mixed-batch test above).
        async def main():
            bulk = device_store()
            scalar = device_store()
            try:
                rng = np.random.default_rng(7)
                keys = [f"k{i}" for i in range(50)]
                deltas = [int(rng.integers(-2, 4)) for _ in range(50)]
                # Seed both stores with identical held state first.
                seed = [(k, 2) for k in keys[::3]]
                await bulk.concurrency_acquire_many(
                    [k for k, _ in seed], [d for _, d in seed], 6)
                for k, d in seed:
                    await scalar.concurrency_acquire(k, d, 6)
                res = await bulk.concurrency_acquire_many(keys, deltas, 6)
                for i, (k, d) in enumerate(zip(keys, deltas)):
                    if d >= 0:
                        r = await scalar.concurrency_acquire(k, d, 6)
                        assert res.granted[i] == r.granted, i
                        assert res.remaining[i] == pytest.approx(
                            r.remaining), i
                    else:
                        await scalar.concurrency_release(k, -d)
                        assert bool(res.granted[i]) is True
            finally:
                await bulk.aclose()
                await scalar.aclose()

        run(main())

    def test_over_release_with_acquire_same_batch_keeps_the_permit(self):
        """Regression: the kernel clamps a slot's NET batch delta at
        zero, so an over-release plus a granted acquire in one packed
        dispatch would lose the permit — such rows must serialize."""
        async def main():
            store = device_store()
            try:
                await store.concurrency_acquire("k", 2, 4)
                res = await store.concurrency_acquire_many(
                    ["k", "k"], [-5, 1], 4)
                assert res.granted.tolist() == [True, True]
                # Serial semantics: release clamps to 0 held, acquire
                # lands 1. The store must still hold that permit.
                r = await store.concurrency_acquire("k", 0, 4)
                assert r.remaining == pytest.approx(1.0)
            finally:
                await store.aclose()

        run(main())

    def test_duplicate_acquires_report_serialized_remaining(self):
        """Regression: each duplicate acquire row's `remaining` is its
        own serialized post-op count, not the post-batch total."""
        async def main():
            store = device_store()
            try:
                res = await store.concurrency_acquire_many(
                    ["k", "k", "k"], [1, 1, 1], 10)
                assert res.granted.all()
                assert res.remaining.tolist() == [1.0, 2.0, 3.0]
            finally:
                await store.aclose()

        run(main())

    def test_denied_duplicate_rows_report_possible_counts(self):
        """Regression: a denied row's `remaining` must sum only APPLIED
        earlier demand — not denied demand — so it can never read a held
        count above the limit."""
        async def main():
            store = device_store()
            try:
                res = await store.concurrency_acquire_many(
                    ["k", "k", "k"], [3, 3, 3], 4)
                assert res.granted.tolist() == [True, False, False]
                assert res.remaining.tolist() == [3.0, 3.0, 3.0]
            finally:
                await store.aclose()

        run(main())

    def test_per_row_limits(self):
        async def main():
            store = device_store()
            try:
                res = await store.concurrency_acquire_many(
                    ["a", "b", "c"], [2, 2, 2], [1, 2, 3])
                assert res.granted.tolist() == [False, True, True]
            finally:
                await store.aclose()

        run(main())
