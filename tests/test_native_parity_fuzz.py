"""Differential fuzz: the native front-end vs the asyncio server.

The native front-end's contract is that it serves the exact v4 wire
protocol the asyncio server serves — same decisions, same remainings,
same error shapes — with only the transport machinery swapped. This
fuzz drives an identical randomized op sequence (buckets, windows,
fixed windows, semaphores, probes, releases, bulk frames, pings, stats
resets) against BOTH server halves over real sockets, each backed by an
InProcessBucketStore on its own ManualClock advanced in lockstep, and
asserts reply-for-reply equality. Sequential (depth-1) driving keeps
both sides deterministic.
"""

from __future__ import annotations

import asyncio
import json
import struct

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.runtime import wire
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.remote import RemoteBucketStore
from distributedratelimiting.redis_tpu.runtime.server import BucketStoreServer
from distributedratelimiting.redis_tpu.runtime.store import InProcessBucketStore
from distributedratelimiting.redis_tpu.utils.native import load_frontend_lib

_LIB = load_frontend_lib()
pytestmark = pytest.mark.skipif(
    _LIB is None,
    reason="native front-end library unavailable (no compiler?)")

#: uring arms need a live ring: kernel support AND no seccomp gate AND
#: a binary with the uring ABI. Arms skip loudly otherwise — the epoll
#: arms still run, so parity is never silently untested.
_URING_OK = bool(_LIB is not None and getattr(_LIB, "has_uring", False)
                 and _LIB.fe_uring_available())
_URING_SKIP = pytest.mark.skipif(
    not _URING_OK, reason="io_uring unavailable on this host "
    "(kernel, seccomp, or stale binary) — uring parity arm skipped")


def _uring_arm(*vals):
    return pytest.param(*vals, marks=_URING_SKIP)


# -- raw-socket helpers for the byte-level bulk differential ----------------

async def _start_pair(tier0=False, shards=1, uring=None):
    """One asyncio server and one native server over identical
    InProcess stores on lockstep manual clocks. ``shards`` sizes the
    native side's SO_REUSEPORT shard group (round 11): the fuzz drives
    ONE connection, which lives its whole life on whichever shard the
    kernel picked — the per-connection order contract is shard-local,
    so replies must stay byte-identical at any shard count. ``uring``
    swaps the native side's transport (round 16): the reply bytes are
    the spec, so every arm must pass unchanged on either transport."""
    clocks = [ManualClock(), ManualClock()]
    servers = [
        BucketStoreServer(InProcessBucketStore(clock=clocks[0]),
                          native_frontend=False),
        BucketStoreServer(InProcessBucketStore(clock=clocks[1]),
                          native_frontend=True, native_tier0=tier0,
                          native_shards=shards, native_uring=uring),
    ]
    for s in servers:
        await s.start()
    if uring in ("on", "sqpoll"):
        # The arm must actually test the ring: a silent per-shard
        # fallback here would green the uring parity without running it.
        assert servers[1]._native.uring_shards == \
            servers[1]._native.n_shards
    conns = [await asyncio.open_connection(s.host, s.port)
             for s in servers]
    return clocks, servers, conns


async def _close_pair(servers, conns):
    for _r, w in conns:
        w.close()
        try:
            await w.wait_closed()
        except (ConnectionResetError, BrokenPipeError):
            pass
    for s in servers:
        await s.aclose()


async def _read_reply(conn) -> bytes:
    r, _w = conn
    hdr = await asyncio.wait_for(r.readexactly(4), 10.0)
    (ln,) = struct.unpack("<I", hdr)
    return hdr + await asyncio.wait_for(r.readexactly(ln), 10.0)


async def _roundtrip(conn, frame: bytes) -> bytes:
    _r, w = conn
    w.write(frame)
    await w.drain()
    return await _read_reply(conn)


def _random_bulk_frame(rng, seq: int) -> bytes:
    """One randomized ACQUIRE_MANY frame: random key blobs (duplicates
    and non-UTF-8 bytes included), random counts (zero-permit probes
    in), all three table kinds, both remaining modes, and a trace tail
    on a sampled minority (wire flags bit 4)."""
    nk = int(rng.integers(1, 28))
    pool = [b"k%d" % rng.integers(0, 8) for _ in range(nk)]
    if rng.random() < 0.25:
        # byte-identity keys: invalid UTF-8 must rate-limit under its
        # own stable identity on BOTH lanes, never error the frame
        pool[0] = bytes(rng.integers(0, 256, int(rng.integers(1, 12)),
                                     dtype=np.uint8).tolist())
    counts = rng.integers(0, 4, nk)
    if rng.random() < 0.3:
        # Weighted-cost arm (ISSUE 10 satellite): heavy-tailed N-token
        # costs beside the unit/probe mix — both lanes must agree on
        # multi-token boundary decisions byte for byte too.
        heavy = rng.integers(0, nk + 1)
        counts[:heavy] = rng.integers(1, 3000, heavy)
    kind = int(rng.integers(0, 3))
    with_rem = bool(rng.integers(0, 2))
    trace = None
    if rng.random() < 0.3:
        trace = (int(rng.integers(1, 1 << 62)),
                 int(rng.integers(1, 1 << 62)),
                 int(rng.integers(1, 1 << 62)), 1)
    return wire.encode_bulk_request(
        seq, pool, counts, 10.0, 1.0, with_remaining=with_rem,
        kind=kind, trace=trace)


@pytest.mark.parametrize(
    "seed,tier0,shards,uring",
    [(5, False, 1, None),
     (29, False, 1, None),
     (5, True, 1, None),
     (5, False, 4, None),
     (29, True, 4, None),
     # round 16: the SAME seeds over the uring transport — multishot
     # recv rechunks arbitrarily, so the chained/malformed ordering
     # contract is exercised under a different segmentation than epoll
     # ever produces, and the replies must not move a byte.
     _uring_arm(5, False, 4, "on"),
     _uring_arm(29, True, 4, "on"),
     _uring_arm(5, True, 1, "sqpoll")])
def test_bulk_frames_reply_byte_identical(seed, tier0, shards, uring):
    """Randomized ACQUIRE_MANY frames — duplicates, probes, hostile
    keys, trace tails, every kind, chained chunks, malformed shapes —
    must produce byte-identical replies from the native bulk lane and
    the asyncio server. (tier0=True arms the cache at capacity 10 <
    min_budget, so tier-0 must stay semantically invisible; shards=4
    runs the same contract against the multi-shard front-end — the
    chained-chunk parking and error ordering are per-connection state
    and must behave identically on whichever shard accepts.)"""
    async def main():
        clocks, servers, conns = await _start_pair(tier0=tier0,
                                                   shards=shards,
                                                   uring=uring)
        rng = np.random.default_rng(seed)
        try:
            for step in range(150):
                frame = _random_bulk_frame(rng, step)
                roll = rng.random()
                if roll < 0.1:
                    # Malformed: truncate the body and re-stamp the
                    # length prefix — both servers must answer the same
                    # routable error (wire.py is the authority on both).
                    cut = int(rng.integers(1, 8))
                    body = frame[4:]
                    if len(body) > cut + 7:
                        body = body[:-cut]
                    frame = struct.pack("<I", len(body)) + body
                    replies = [await _roundtrip(cn, frame)
                               for cn in conns]
                    assert replies[0] == replies[1], step
                elif roll < 0.25:
                    # Chained pair: chunk 2 must decide after chunk 1 on
                    # both lanes (the asyncio bulk_tail contract; the
                    # native lane parks the chained frame in C). Chunk 1
                    # is sometimes MALFORMED: its error reply must still
                    # come back BEFORE the chained successor's verdict —
                    # the chain follows it onto the Python lane.
                    if rng.random() < 0.3:
                        cut = int(rng.integers(1, 6))
                        body = frame[4:]
                        if len(body) > cut + 7:
                            body = body[:-cut]
                        frame = struct.pack("<I", len(body)) + body
                    f2 = wire.encode_bulk_request(
                        10000 + step, [b"c0", b"c1", b"c0"], [1, 1, 1],
                        10.0, 1.0, chained=True)
                    for cn in conns:
                        cn[1].write(frame + f2)
                        await cn[1].drain()
                    r1 = [await _read_reply(cn) for cn in conns]
                    r2 = [await _read_reply(cn) for cn in conns]
                    assert r1[0] == r1[1], step
                    assert r2[0] == r2[1], step
                else:
                    replies = [await _roundtrip(cn, frame)
                               for cn in conns]
                    assert replies[0] == replies[1], step
                if rng.random() < 0.2:
                    dt = float(rng.uniform(0.0, 2.0))
                    for c in clocks:
                        c.advance_seconds(dt)
        finally:
            await _close_pair(servers, conns)

    asyncio.run(main())


@pytest.mark.parametrize("uring", [None, _uring_arm("on")])
def test_bulk_gated_rows_byte_identical(uring):
    """Placement-MOVED and retired-config bulk frames answer the exact
    same routable errors from both lanes (frame-level gates; the native
    lane answers them via fe_send + fe_bulk_discard) — on either
    transport."""
    async def main():
        _clocks, servers, conns = await _start_pair(uring=uring)
        try:
            # Live-config mutation on both: retire (50, 1) -> (80, 2).
            for payload in ({"prepare": {"kind": "bucket",
                                         "old": [50.0, 1.0],
                                         "new": [80.0, 2.0]},
                             "version": 1},
                            {"commit": 1}):
                frame = wire.encode_request(900, wire.OP_CONFIG,
                                            key=json.dumps(payload))
                rs = [await _roundtrip(cn, frame) for cn in conns]
                assert rs[0] == rs[1]
            frame = wire.encode_bulk_request(7, [b"a", b"b", b"a"],
                                             [1, 2, 1], 50.0, 1.0)
            rs = [await _roundtrip(cn, frame) for cn in conns]
            assert rs[0] == rs[1]
            assert b"config moved" in rs[0]
            # Current-config frames still decide normally.
            frame = wire.encode_bulk_request(8, [b"a", b"b"], [1, 1],
                                             80.0, 2.0)
            rs = [await _roundtrip(cn, frame) for cn in conns]
            assert rs[0] == rs[1]
            assert rs[0][9] == wire.RESP_BULK
            # Placement map: half the slots belong to node 1 — frames
            # touching them answer the frame-level MOVED error.
            ann = {"map": {"epoch": 1, "n_slots": 16,
                           "slot_owner": [0, 1] * 8, "overrides": {}},
                   "node_id": 0}
            frame = wire.encode_request(901, wire.OP_PLACEMENT_ANNOUNCE,
                                        key=json.dumps(ann))
            rs = [await _roundtrip(cn, frame) for cn in conns]
            assert rs[0] == rs[1]
            rng = np.random.default_rng(3)
            saw_moved = saw_bulk = False
            for step in range(40):
                nk = int(rng.integers(1, 12))
                pool = [b"m%d" % rng.integers(0, 64) for _ in range(nk)]
                frame = wire.encode_bulk_request(
                    1000 + step, pool, [1] * nk, 80.0, 2.0)
                rs = [await _roundtrip(cn, frame) for cn in conns]
                assert rs[0] == rs[1], step
                if b"placement moved" in rs[0]:
                    saw_moved = True
                elif rs[0][9] == wire.RESP_BULK:
                    saw_bulk = True
            assert saw_moved and saw_bulk
        finally:
            await _close_pair(servers, conns)

    asyncio.run(main())


# tier0=True runs the same fuzz with the tier-0 admission cache armed:
# at the fuzz's capacity (10) every key sits below the default
# min_budget confidence gate, so tier-0 must be semantically INVISIBLE —
# identical replies, never a locally-guessed decision.
@pytest.mark.parametrize(
    "seed,tier0,shards,uring",
    [(11, False, 1, None),
     (23, False, 1, None),
     (47, False, 1, None),
     (11, True, 1, None),
     (47, True, 1, None),
     (23, False, 4, None),
     (11, True, 4, None),
     # round 16: scalar/chained/hierarchical mix over the uring
     # transport, same seeds as the epoll arms above.
     _uring_arm(23, False, 4, "on"),
     _uring_arm(11, True, 4, "on"),
     _uring_arm(47, False, 1, "sqpoll")])
def test_native_and_asyncio_servers_answer_identically(seed, tier0,
                                                       shards, uring):
    async def main():
        clocks = [ManualClock(), ManualClock()]
        servers = [
            BucketStoreServer(InProcessBucketStore(clock=clocks[0]),
                              native_frontend=False),
            BucketStoreServer(InProcessBucketStore(clock=clocks[1]),
                              native_frontend=True, native_tier0=tier0,
                              native_shards=shards, native_uring=uring),
        ]
        for s in servers:
            await s.start()
        if uring in ("on", "sqpoll"):
            assert servers[1]._native.uring_shards == \
                servers[1]._native.n_shards
        stores = [RemoteBucketStore(address=(s.host, s.port),
                                    coalesce_requests=False)
                  for s in servers]
        rng = np.random.default_rng(seed)
        try:
            for step in range(300):
                op = rng.integers(0, 9)
                key = f"k{rng.integers(0, 6)}"
                # Weighted-cost mix (ISSUE 10 satellite): mostly small
                # counts, a heavy arm near/over the boundary — grant
                # edges must match for N-token costs too.
                count = (int(rng.integers(0, 4)) if rng.random() < 0.7
                         else int(rng.integers(1, 10)))
                if op == 0:      # token bucket acquire / zero-probe
                    rs = [await st.acquire(key, count, 10.0, 1.0)
                          for st in stores]
                    assert rs[0].granted == rs[1].granted, step
                    assert rs[0].remaining == pytest.approx(
                        rs[1].remaining), step
                elif op == 1:    # sliding window
                    rs = [await st.window_acquire(key, count, 8.0, 30.0)
                          for st in stores]
                    assert rs[0].granted == rs[1].granted, step
                    assert rs[0].remaining == pytest.approx(
                        rs[1].remaining), step
                elif op == 2:    # fixed window
                    rs = [await st.fixed_window_acquire(key, count, 8.0,
                                                        30.0)
                          for st in stores]
                    assert rs[0].granted == rs[1].granted, step
                    assert rs[0].remaining == pytest.approx(
                        rs[1].remaining), step
                elif op == 3:    # semaphore acquire
                    rs = [await st.concurrency_acquire(key, count, 5)
                          for st in stores]
                    assert rs[0].granted == rs[1].granted, step
                    assert rs[0].remaining == pytest.approx(
                        rs[1].remaining), step
                elif op == 4:    # semaphore release (incl. over-release)
                    for st in stores:
                        await st.concurrency_release(key, count + 1)
                elif op == 5:    # bulk frame (native lane since round 8)
                    nk = int(rng.integers(1, 25))
                    keys = [f"k{rng.integers(0, 6)}" for _ in range(nk)]
                    counts = [int(c) for c in rng.integers(0, 4, nk)]
                    rs = [await st.acquire_many(keys, counts, 10.0, 1.0)
                          for st in stores]
                    assert (rs[0].granted == rs[1].granted).all(), step
                    np.testing.assert_allclose(rs[0].remaining,
                                               rs[1].remaining, rtol=1e-6)
                elif op == 6:    # decaying global counter sync
                    rs = [await st.sync_counter(key, float(count), 1.0)
                          for st in stores]
                    assert rs[0].global_score == pytest.approx(
                        rs[1].global_score), step
                elif op == 8:    # hierarchical tenant → key (OP_ACQUIRE_H)
                    tenant = f"t{rng.integers(0, 3)}"
                    rs = [await st.acquire_hierarchical(
                        tenant, key, count, 30.0, 1.0, 10.0, 1.0)
                          for st in stores]
                    assert rs[0].granted == rs[1].granted, step
                    assert rs[0].remaining == pytest.approx(
                        rs[1].remaining), step
                else:            # ping + clock advance in lockstep
                    for st in stores:
                        await st.ping()
                    dt = float(rng.uniform(0.0, 2.0))
                    for c in clocks:
                        c.advance_seconds(dt)
            # Both histograms observed the same number of samples.
            stats = [await st.stats() for st in stores]
            assert (stats[0]["requests_served"]
                    == stats[1]["requests_served"]), stats
        finally:
            for st in stores:
                await st.aclose()
            for s in servers:
                await s.aclose()

    asyncio.run(main())
