"""Differential fuzz: the native front-end vs the asyncio server.

The native front-end's contract is that it serves the exact v4 wire
protocol the asyncio server serves — same decisions, same remainings,
same error shapes — with only the transport machinery swapped. This
fuzz drives an identical randomized op sequence (buckets, windows,
fixed windows, semaphores, probes, releases, bulk frames, pings, stats
resets) against BOTH server halves over real sockets, each backed by an
InProcessBucketStore on its own ManualClock advanced in lockstep, and
asserts reply-for-reply equality. Sequential (depth-1) driving keeps
both sides deterministic.
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.remote import RemoteBucketStore
from distributedratelimiting.redis_tpu.runtime.server import BucketStoreServer
from distributedratelimiting.redis_tpu.runtime.store import InProcessBucketStore
from distributedratelimiting.redis_tpu.utils.native import load_frontend_lib

pytestmark = pytest.mark.skipif(
    load_frontend_lib() is None,
    reason="native front-end library unavailable (no compiler?)")


# tier0=True runs the same fuzz with the tier-0 admission cache armed:
# at the fuzz's capacity (10) every key sits below the default
# min_budget confidence gate, so tier-0 must be semantically INVISIBLE —
# identical replies, never a locally-guessed decision.
@pytest.mark.parametrize("seed,tier0", [(11, False), (23, False),
                                        (47, False), (11, True),
                                        (47, True)])
def test_native_and_asyncio_servers_answer_identically(seed, tier0):
    async def main():
        clocks = [ManualClock(), ManualClock()]
        servers = [
            BucketStoreServer(InProcessBucketStore(clock=clocks[0]),
                              native_frontend=False),
            BucketStoreServer(InProcessBucketStore(clock=clocks[1]),
                              native_frontend=True, native_tier0=tier0),
        ]
        for s in servers:
            await s.start()
        stores = [RemoteBucketStore(address=(s.host, s.port),
                                    coalesce_requests=False)
                  for s in servers]
        rng = np.random.default_rng(seed)
        try:
            for step in range(300):
                op = rng.integers(0, 8)
                key = f"k{rng.integers(0, 6)}"
                count = int(rng.integers(0, 4))
                if op == 0:      # token bucket acquire / zero-probe
                    rs = [await st.acquire(key, count, 10.0, 1.0)
                          for st in stores]
                    assert rs[0].granted == rs[1].granted, step
                    assert rs[0].remaining == pytest.approx(
                        rs[1].remaining), step
                elif op == 1:    # sliding window
                    rs = [await st.window_acquire(key, count, 8.0, 30.0)
                          for st in stores]
                    assert rs[0].granted == rs[1].granted, step
                    assert rs[0].remaining == pytest.approx(
                        rs[1].remaining), step
                elif op == 2:    # fixed window
                    rs = [await st.fixed_window_acquire(key, count, 8.0,
                                                        30.0)
                          for st in stores]
                    assert rs[0].granted == rs[1].granted, step
                    assert rs[0].remaining == pytest.approx(
                        rs[1].remaining), step
                elif op == 3:    # semaphore acquire
                    rs = [await st.concurrency_acquire(key, count, 5)
                          for st in stores]
                    assert rs[0].granted == rs[1].granted, step
                    assert rs[0].remaining == pytest.approx(
                        rs[1].remaining), step
                elif op == 4:    # semaphore release (incl. over-release)
                    for st in stores:
                        await st.concurrency_release(key, count + 1)
                elif op == 5:    # bulk frame (passthrough on native)
                    keys = [f"k{rng.integers(0, 6)}" for _ in range(17)]
                    counts = [1] * 17
                    rs = [await st.acquire_many(keys, counts, 10.0, 1.0)
                          for st in stores]
                    assert (rs[0].granted == rs[1].granted).all(), step
                    np.testing.assert_allclose(rs[0].remaining,
                                               rs[1].remaining, rtol=1e-6)
                elif op == 6:    # decaying global counter sync
                    rs = [await st.sync_counter(key, float(count), 1.0)
                          for st in stores]
                    assert rs[0].global_score == pytest.approx(
                        rs[1].global_score), step
                else:            # ping + clock advance in lockstep
                    for st in stores:
                        await st.ping()
                    dt = float(rng.uniform(0.0, 2.0))
                    for c in clocks:
                        c.advance_seconds(dt)
            # Both histograms observed the same number of samples.
            stats = [await st.stats() for st in stores]
            assert (stats[0]["requests_served"]
                    == stats[1]["requests_served"]), stats
        finally:
            for st in stores:
                await st.aclose()
            for s in servers:
                await s.aclose()

    asyncio.run(main())
