"""Chaos plane: deterministic fault injection, at-most-once retries,
per-node circuit breakers, and cluster failover with degraded fallback.

The load-bearing guarantees under test:

- **Schedule determinism**: the same seed reproduces the same fault
  schedule — the realized event log equals the injector's pure-function
  preview, occurrence for occurrence.
- **At-most-once admission** (the differential test): every retried
  ACQUIRE replays against a serial model — with one unique key per
  logical request, no key is ever executed twice no matter which phase
  the failure struck.
- **Deadline shedding**: a server whose own queueing consumed the
  client's budget sheds the request unexecuted (typed, counted,
  exposed); pre-deadline peers answer a routable error and the client
  latches stamping off.
- **Breakers + degraded failover** (the seeded soak): a down node trips
  its breaker, its keyspace serves from the local fair-share envelope
  with over-admission inside the epsilon bound, the healthy node is
  untouched, the breaker re-closes after the fault window, and teardown
  strands nothing.
"""

from __future__ import annotations

import asyncio

import pytest

from distributedratelimiting.redis_tpu.models.approximate import (
    headroom_budget,
)
from distributedratelimiting.redis_tpu.runtime import wire
from distributedratelimiting.redis_tpu.runtime.cluster import (
    ClusterBucketStore,
    NodeUnavailableError,
)
from distributedratelimiting.redis_tpu.runtime.remote import (
    RemoteBucketStore,
    StoreTimeoutError,
)
from distributedratelimiting.redis_tpu.runtime.server import BucketStoreServer
from distributedratelimiting.redis_tpu.runtime.store import (
    InProcessBucketStore,
)
from distributedratelimiting.redis_tpu.utils import faults
from distributedratelimiting.redis_tpu.utils.faults import (
    FaultInjector,
    FaultRule,
)
from distributedratelimiting.redis_tpu.utils.flight_recorder import (
    FlightRecorder,
)
from distributedratelimiting.redis_tpu.utils.resilience import (
    BreakerConfig,
    CircuitBreaker,
    RetryPolicy,
)


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    yield
    faults.uninstall()


# -- fault injector: determinism --------------------------------------------

_RULES = {
    "client.connect": (FaultRule("reset", probability=0.3),
                       FaultRule("delay", probability=0.2,
                                 delay_s=0.001, jitter_s=0.002)),
    "server.dispatch": (FaultRule("error", probability=0.15, after=10,
                                  until=60, max_faults=5),),
}


class TestInjectorDeterminism:
    def test_same_seed_same_schedule(self):
        a = FaultInjector(7, _RULES).schedule_preview("client.connect", 200)
        b = FaultInjector(7, _RULES).schedule_preview("client.connect", 200)
        assert a == b and len(a) > 0

    def test_different_seed_different_schedule(self):
        a = FaultInjector(7, _RULES).schedule_preview("client.connect", 200)
        b = FaultInjector(8, _RULES).schedule_preview("client.connect", 200)
        assert a != b

    def test_live_decisions_equal_preview(self):
        inj = FaultInjector(42, _RULES)
        for _ in range(120):
            inj.decide("client.connect")
        for _ in range(80):
            inj.decide("server.dispatch")
        for seam in _RULES:
            realized = [e for e in inj.events if e.seam == seam]
            preview = inj.schedule_preview(seam,
                                           inj.occurrence_count(seam))
            assert realized == preview

    def test_occurrence_windows_and_caps(self):
        inj = FaultInjector(1, {"s": (FaultRule("reset", probability=1.0,
                                                after=3, until=6),)})
        fired = [inj.decide("s") is not None for _ in range(10)]
        assert fired == [False] * 3 + [True] * 3 + [False] * 4
        inj2 = FaultInjector(1, {"s": (FaultRule("reset", probability=1.0,
                                                 max_faults=2),)})
        assert sum(inj2.decide("s") is not None for _ in range(10)) == 2

    def test_interleaving_does_not_shift_seams(self):
        # Per-seam rng streams: a seam's schedule is a pure function of
        # ITS occurrence index, however other seams interleave.
        lone = FaultInjector(9, _RULES)
        for _ in range(50):
            lone.decide("client.connect")
        mixed = FaultInjector(9, _RULES)
        for i in range(50):
            mixed.decide("server.dispatch")  # interleaved noise
            mixed.decide("client.connect")
        assert ([e for e in lone.events if e.seam == "client.connect"]
                == [e for e in mixed.events
                    if e.seam == "client.connect"])


# -- resilience primitives ---------------------------------------------------

class TestCircuitBreaker:
    def _clocked(self, **kw):
        t = [0.0]
        br = CircuitBreaker(BreakerConfig(**kw), clock=lambda: t[0])
        return br, t

    def test_trips_after_threshold_and_recovers(self):
        br, t = self._clocked(failure_threshold=3, recovery_timeout_s=1.0)
        for _ in range(2):
            br.record_failure()
        assert br.state == CircuitBreaker.CLOSED
        br.record_failure()
        assert br.state == CircuitBreaker.OPEN and br.opens == 1
        assert br.allow() == "reject" and br.quarantined()
        t[0] = 1.5
        assert not br.quarantined()
        assert br.allow() == "probe"          # half-open: one probe slot
        assert br.allow() == "reject"         # second caller sheds
        br.record_success()
        assert br.state == CircuitBreaker.CLOSED

    def test_half_open_failure_reopens(self):
        br, t = self._clocked(failure_threshold=1, recovery_timeout_s=0.5)
        br.record_failure()
        t[0] = 1.0
        assert br.allow() == "probe"
        br.record_failure()
        assert br.state == CircuitBreaker.OPEN and br.opens == 2

    def test_success_resets_consecutive_failures(self):
        br, _ = self._clocked(failure_threshold=2)
        br.record_failure()
        br.record_success()
        br.record_failure()
        assert br.state == CircuitBreaker.CLOSED

    def test_abandoned_probe_slot_is_reclaimed(self):
        # A holder cancelled mid-probe must not wedge the node in
        # reject-forever: release_probe frees the slot immediately, and
        # even without it the slot self-reclaims after a recovery
        # window.
        br, t = self._clocked(failure_threshold=1, recovery_timeout_s=1.0)
        br.record_failure()
        t[0] = 1.5
        assert br.allow() == "probe"
        br.release_probe()                 # cancelled holder, explicit
        assert br.allow() == "probe"       # slot immediately available
        # Leak it this time (no release, no verdict):
        assert br.allow() == "reject"
        t[0] = 3.0                         # a recovery window passes
        assert br.allow() == "probe"       # reclaimed, not wedged
        br.record_success()
        assert br.state == CircuitBreaker.CLOSED

    def test_transition_listener(self):
        seen = []
        br = CircuitBreaker(BreakerConfig(failure_threshold=1,
                                          recovery_timeout_s=0.0),
                            clock=lambda: 0.0,
                            on_transition=lambda o, n: seen.append((o, n)))
        br.record_failure()
        br.allow()
        br.record_success()
        assert seen == [("closed", "open"), ("open", "half_open"),
                        ("half_open", "closed")]


class TestRetryPolicy:
    def test_delay_growth_cap_and_jitter_bounds(self):
        import random

        p = RetryPolicy(max_attempts=5, base_delay_s=0.1, max_delay_s=0.5,
                        multiplier=2.0, jitter=0.5)
        rng = random.Random(0)
        for attempt, raw in ((1, 0.1), (2, 0.2), (3, 0.4), (4, 0.5)):
            for _ in range(20):
                d = p.delay_s(attempt, rng)
                assert raw * 0.5 <= d <= raw
        assert p.max_total_delay_s() == pytest.approx(0.1 + 0.2 + 0.4 + 0.5)

    def test_validation(self):
        with pytest.raises(ValueError):
            RetryPolicy(max_attempts=0)
        with pytest.raises(ValueError):
            RetryPolicy(jitter=1.5)


# -- wire: the deadline tail --------------------------------------------------

class TestDeadlineTail:
    def test_roundtrip_and_strip_order(self):
        frame = wire.encode_request(5, wire.OP_ACQUIRE, "k", 1, 10.0, 1.0,
                                    deadline_s=0.25)
        body = frame[4:]
        assert body[5] & wire.DEADLINE_FLAG
        plain, ddl = wire.strip_deadline(body)
        assert ddl == 0.25
        # The stripped body is byte-identical to an unstamped frame.
        bare = wire.encode_request(5, wire.OP_ACQUIRE, "k", 1, 10.0, 1.0)
        assert plain == bare[4:]

    def test_with_trace_tail_trace_rides_last(self):
        frame = wire.encode_request(
            5, wire.OP_ACQUIRE, "k", 1, 10.0, 1.0,
            trace=(1, 2, 3, 1), deadline_s=0.5)
        body = frame[4:]
        stripped, tctx = wire.strip_trace(body)
        assert tctx is not None and tctx.trace_hi == 1
        plain, ddl = wire.strip_deadline(stripped)
        assert ddl == 0.5
        seq, op, key, count, a, b = wire.decode_request(plain)
        assert (seq, op, key, count, a, b) == (5, wire.OP_ACQUIRE, "k",
                                               1, 10.0, 1.0)

    def test_old_server_answers_routable_unknown_op(self):
        frame = wire.encode_request(5, wire.OP_ACQUIRE, "k", 1, 10.0, 1.0,
                                    deadline_s=0.25)
        with pytest.raises(wire.RemoteStoreError, match="unknown op"):
            wire.decode_request(frame[4:])

    def test_truncated_tail_raises(self):
        frame = wire.encode_request(5, wire.OP_PING, deadline_s=1.0)
        body = frame[4:5] + bytes([frame[9] ]) + b""  # mangled short body
        body = frame[4:10]  # header only, flag set, tail missing
        with pytest.raises(wire.RemoteStoreError, match="truncated"):
            wire.strip_deadline(body)


# -- client resilience over a live wire --------------------------------------

class CountingStore(InProcessBucketStore):
    """Backing store that logs every executed acquire — the serial-model
    side of the at-most-once differential."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.acquires: list[str] = []

    async def acquire(self, key, count, capacity, fill_rate_per_sec):
        self.acquires.append(key)
        return await super().acquire(key, count, capacity,
                                     fill_rate_per_sec)


class TestClientResilience:
    def test_connect_reset_retried_and_counted(self):
        async def main():
            faults.install(FaultInjector(3, {
                "client.connect": (FaultRule("reset", probability=1.0,
                                             max_faults=2),)}))
            async with BucketStoreServer(InProcessBucketStore()) as srv:
                store = RemoteBucketStore(
                    address=(srv.host, srv.port), coalesce_requests=False,
                    retry_policy=RetryPolicy(max_attempts=4,
                                             base_delay_s=0.005),
                    reconnect_backoff_base_s=0.005, resilience_seed=1)
                try:
                    assert (await store.acquire("k", 1, 5.0, 1.0)).granted
                    assert store.resilience_stats()["retries"] == 2
                finally:
                    await store.aclose()

        run(main())

    def test_timeout_is_typed_and_never_retried(self):
        async def main():
            faults.install(FaultInjector(3, {
                "server.dispatch": (FaultRule("blackhole",
                                              probability=1.0),)}))
            backing = CountingStore()
            async with BucketStoreServer(backing) as srv:
                store = RemoteBucketStore(
                    address=(srv.host, srv.port), coalesce_requests=False,
                    request_timeout_s=0.15, resilience_seed=1)
                try:
                    with pytest.raises(StoreTimeoutError):
                        await store.acquire("k", 1, 5.0, 1.0)
                    # Typed: still an asyncio.TimeoutError for old catches.
                    assert issubclass(StoreTimeoutError,
                                      asyncio.TimeoutError)
                    stats = store.resilience_stats()
                    assert stats["timeouts"] == 1
                    assert stats["retries"] == 0  # sent ⇒ never replayed
                finally:
                    await store.aclose()
            assert backing.acquires == []  # blackholed before the store

        run(main())

    def test_per_call_timeout_override(self):
        async def main():
            faults.install(FaultInjector(3, {
                "server.dispatch": (FaultRule("stall", probability=1.0,
                                              delay_s=0.4),)}))
            async with BucketStoreServer(InProcessBucketStore()) as srv:
                store = RemoteBucketStore(
                    address=(srv.host, srv.port),
                    request_timeout_s=30.0)  # default would hang 30s
                try:
                    t0 = asyncio.get_running_loop().time()
                    with pytest.raises(StoreTimeoutError):
                        await store.acquire("k", 1, 5.0, 1.0,
                                            timeout_s=0.1)
                    assert asyncio.get_running_loop().time() - t0 < 2.0
                finally:
                    await store.aclose()

        run(main())

    def test_post_send_failure_not_retried_for_admission(self):
        # A connection reset AFTER the frame was written may or may not
        # have executed server-side: the client must surface the error,
        # not replay the ACQUIRE.
        async def main():
            faults.install(FaultInjector(3, {
                "client.write": (FaultRule("reset", probability=1.0,
                                           after=1, max_faults=1),)}))
            backing = CountingStore()
            async with BucketStoreServer(backing) as srv:
                store = RemoteBucketStore(
                    address=(srv.host, srv.port), coalesce_requests=False,
                    reconnect_backoff_base_s=0.005, resilience_seed=1)
                try:
                    assert (await store.acquire("w0", 1, 5.0, 1.0)).granted
                    with pytest.raises(ConnectionError):
                        await store.acquire("w1", 1, 5.0, 1.0)
                    assert store.resilience_stats()["retries"] == 0
                    # Next use reconnects and serves.
                    assert (await store.acquire("w2", 1, 5.0, 1.0)).granted
                finally:
                    await store.aclose()
            assert backing.acquires.count("w1") == 0  # never reached

        run(main())

    def test_partial_frame_drops_cleanly_no_misparse(self):
        async def main():
            faults.install(FaultInjector(3, {
                "client.write": (FaultRule("partial_frame",
                                           probability=1.0, after=1,
                                           max_faults=1),)}))
            async with BucketStoreServer(InProcessBucketStore()) as srv:
                store = RemoteBucketStore(
                    address=(srv.host, srv.port), coalesce_requests=False,
                    reconnect_backoff_base_s=0.005, resilience_seed=1)
                try:
                    assert (await store.acquire("p0", 1, 5.0, 1.0)).granted
                    with pytest.raises(ConnectionError):
                        await store.acquire("p1", 1, 5.0, 1.0)
                    # The torn frame neither wedged the server nor
                    # poisoned the next connection.
                    assert (await store.acquire("p2", 1, 5.0, 1.0)).granted
                finally:
                    await store.aclose()

        run(main())


class TestDeadlinePropagation:
    def test_server_sheds_expired_work_unexecuted(self):
        async def main():
            faults.install(FaultInjector(3, {
                "server.dispatch": (FaultRule("delay", probability=1.0,
                                              delay_s=0.2),)}))
            backing = CountingStore()
            async with BucketStoreServer(backing) as srv:
                store = RemoteBucketStore(
                    address=(srv.host, srv.port), coalesce_requests=False,
                    propagate_deadlines=True, request_timeout_s=0.08)
                try:
                    with pytest.raises(StoreTimeoutError):
                        await store.acquire("k", 1, 5.0, 1.0)
                    await asyncio.sleep(0.25)  # let the server catch up
                    assert srv.requests_shed == 1
                    # The shed is visible on the metrics plane too.
                    assert ("drl_requests_shed_total 1"
                            in srv.registry.render())
                finally:
                    await store.aclose()
            assert backing.acquires == []  # shed BEFORE the store

        run(main())

    def test_pre_deadline_peer_latches_stamping_off(self):
        # A fake old server: answers any bit-6-flagged op with the
        # routable "unknown op" error (exactly what decode_request
        # raises there) and serves bare frames normally.
        async def main():
            flagged = 0

            async def old_server(reader, writer):
                nonlocal flagged
                while True:
                    body = await wire.read_frame(reader)
                    if body is None:
                        break
                    seq = int.from_bytes(body[1:5], "little")
                    if body[5] & wire.DEADLINE_FLAG:
                        flagged += 1
                        resp = wire.encode_response(
                            seq, wire.RESP_ERROR,
                            f"unknown op {body[5]}")
                    else:
                        resp = wire.encode_response(
                            seq, wire.RESP_DECISION, True, 1.0)
                    writer.write(resp)
                    await writer.drain()
                writer.close()

            server = await asyncio.start_server(old_server, "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            store = RemoteBucketStore(
                address=("127.0.0.1", port), coalesce_requests=False,
                propagate_deadlines=True)
            try:
                res = await store.acquire("k", 1, 5.0, 1.0)
                assert res.granted  # latched off + re-sent bare
                assert store._peer_deadlines is False
                assert flagged == 1
                # Subsequent requests go bare first time (no re-probe).
                await store.acquire("k", 1, 5.0, 1.0)
                assert flagged == 1
            finally:
                await store.aclose()
                server.close()
                await server.wait_closed()

        run(main())


class TestAttemptLatch:
    """The attempt-counter tail's old-peer posture (ISSUE 20): its
    latch is INDEPENDENT of the deadline latch — a peer that chokes on
    one tail must not cost the client the other."""

    @staticmethod
    def _old_server(reject_flags: int):
        """A fake old server rejecting any frame whose op byte carries
        one of ``reject_flags`` with the routable "unknown op" error
        (exactly what decode_request raises there), serving the rest."""
        state = {"flagged": 0}

        async def handler(reader, writer):
            while True:
                body = await wire.read_frame(reader)
                if body is None:
                    break
                seq = int.from_bytes(body[1:5], "little")
                if body[5] & reject_flags:
                    state["flagged"] += 1
                    resp = wire.encode_response(
                        seq, wire.RESP_ERROR, f"unknown op {body[5]}")
                else:
                    resp = wire.encode_response(
                        seq, wire.RESP_DECISION, True, 1.0)
                writer.write(resp)
                await writer.drain()
            writer.close()

        return handler, state

    def test_attempt_rejecting_peer_keeps_deadline_stamping(self):
        # One seeded connect reset forces a retry, so the re-send
        # carries BOTH the attempt and deadline tails; the peer rejects
        # only the attempt tail → that latch alone flips, and deadline
        # stamping survives for the connection.
        async def main():
            handler, state = self._old_server(wire.ATTEMPT_FLAG)
            server = await asyncio.start_server(handler, "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            faults.install(FaultInjector(3, {
                "client.connect": (FaultRule("reset", probability=1.0,
                                             max_faults=1),)}))
            store = RemoteBucketStore(
                address=("127.0.0.1", port), coalesce_requests=False,
                propagate_deadlines=True,
                retry_policy=RetryPolicy(max_attempts=4,
                                         base_delay_s=0.005),
                reconnect_backoff_base_s=0.005, resilience_seed=1)
            try:
                res = await store.acquire("k", 1, 5.0, 1.0)
                assert res.granted  # attempt latched off, re-sent
                assert store._peer_attempts is False
                assert store._peer_deadlines is True  # independent
                assert state["flagged"] == 1
            finally:
                await store.aclose()
                server.close()
                await server.wait_closed()

        run(main())

    def test_both_tail_rejections_peel_newest_first(self):
        # A peer predating BOTH dialects: the attempt tail (newest,
        # innermost) sheds first, then the deadline tail, and the bare
        # third send is served — two rejected probes total.
        async def main():
            handler, state = self._old_server(
                wire.ATTEMPT_FLAG | wire.DEADLINE_FLAG)
            server = await asyncio.start_server(handler, "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            faults.install(FaultInjector(3, {
                "client.connect": (FaultRule("reset", probability=1.0,
                                             max_faults=1),)}))
            store = RemoteBucketStore(
                address=("127.0.0.1", port), coalesce_requests=False,
                propagate_deadlines=True,
                retry_policy=RetryPolicy(max_attempts=4,
                                         base_delay_s=0.005),
                reconnect_backoff_base_s=0.005, resilience_seed=1)
            try:
                res = await store.acquire("k", 1, 5.0, 1.0)
                assert res.granted
                assert store._peer_attempts is False
                assert store._peer_deadlines is False
                assert state["flagged"] == 2
            finally:
                await store.aclose()
                server.close()
                await server.wait_closed()

        run(main())

    def test_bare_rejection_undoes_both_latches(self):
        # The peer rejects EVERYTHING: the base op is what it doesn't
        # speak, the tails were never the problem — both latches must
        # roll back before the error surfaces, so the next call still
        # stamps.
        async def main():
            handler, state = self._old_server(0xFF)
            server = await asyncio.start_server(handler, "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            faults.install(FaultInjector(3, {
                "client.connect": (FaultRule("reset", probability=1.0,
                                             max_faults=1),)}))
            store = RemoteBucketStore(
                address=("127.0.0.1", port), coalesce_requests=False,
                propagate_deadlines=True,
                retry_policy=RetryPolicy(max_attempts=4,
                                         base_delay_s=0.005),
                reconnect_backoff_base_s=0.005, resilience_seed=1)
            try:
                with pytest.raises(wire.RemoteStoreError,
                                   match="unknown op"):
                    await store.acquire("k", 1, 5.0, 1.0)
                assert store._peer_attempts is True
                assert store._peer_deadlines is True
                assert state["flagged"] == 3  # stamped, ddl-only, bare
            finally:
                await store.aclose()
                server.close()
                await server.wait_closed()

        run(main())


# -- the at-most-once differential -------------------------------------------

class TestAtMostOnceDifferential:
    def test_retried_acquires_never_double_execute(self):
        """One unique key per logical request: the serial model says
        each key may execute AT MOST once, whatever the fault schedule
        did to connects, reads, or dispatch. A replayed ACQUIRE would
        show up as a key with two executions."""

        async def main():
            faults.install(FaultInjector(1234, {
                "client.connect": (FaultRule("reset", probability=0.5),),
                "client.read": (FaultRule("reset", probability=0.10),),
            }))
            backing = CountingStore()
            async with BucketStoreServer(backing) as srv:
                store = RemoteBucketStore(
                    address=(srv.host, srv.port), coalesce_requests=False,
                    retry_policy=RetryPolicy(max_attempts=4,
                                             base_delay_s=0.003),
                    reconnect_backoff_base_s=0.003, resilience_seed=5,
                    request_timeout_s=2.0)
                n = 120
                outcomes: dict[str, str] = {}
                try:
                    for i in range(n):
                        key = f"d{i}"
                        try:
                            res = await store.acquire(key, 1, 1.0, 1e-9)
                            outcomes[key] = ("granted" if res.granted
                                             else "denied")
                        except (ConnectionError, OSError,
                                wire.RemoteStoreError):
                            outcomes[key] = "error"
                finally:
                    await store.aclose()

            retries = store.resilience_stats()["retries"]
            assert retries > 0, "the schedule must actually retry"
            # Serial-model replay: every key executes at most once …
            from collections import Counter

            per_key = Counter(backing.acquires)
            doubled = {k: c for k, c in per_key.items() if c > 1}
            assert doubled == {}, f"double-executed keys: {doubled}"
            # … and every client-observed GRANT maps to exactly one
            # execution of its key (capacity 1, fill ~0: the model
            # grants each key's single execution).
            for key, outcome in outcomes.items():
                if outcome == "granted":
                    assert per_key[key] == 1

        run(main())


# -- cluster breakers + degraded failover ------------------------------------

class FlakyNode(InProcessBucketStore):
    """In-process node whose store ops can be failed on demand."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.fail = False

    def _check(self):
        if self.fail:
            raise ConnectionError("injected node outage")

    async def acquire(self, *a, **kw):
        self._check()
        return await super().acquire(*a, **kw)

    async def acquire_many(self, *a, **kw):
        self._check()
        return await super().acquire_many(*a, **kw)

    async def sync_counter(self, *a, **kw):
        self._check()
        return await super().sync_counter(*a, **kw)


class TestClusterBreakers:
    def _cluster(self, n=2, **kw):
        nodes = [FlakyNode() for _ in range(n)]
        kw.setdefault("breaker", BreakerConfig(failure_threshold=3,
                                               recovery_timeout_s=0.15))
        return ClusterBucketStore(stores=nodes, **kw), nodes

    def test_breaker_opens_and_sheds_fast_without_fallback(self):
        async def main():
            store, nodes = self._cluster()
            nodes[1].fail = True  # "hot" routes to node 1
            for _ in range(3):
                with pytest.raises(ConnectionError):
                    await store.acquire("hot", 1, 100.0, 1.0)
            # Breaker open: typed shed, no node I/O.
            calls_before = nodes[1].fail
            with pytest.raises(NodeUnavailableError):
                await store.acquire("hot", 1, 100.0, 1.0)
            assert store.shed == 1
            assert store.node_errors[1] == 3
            # The healthy node's keyspace is untouched.
            assert (await store.acquire("alpha", 1, 100.0, 1.0)).granted
            st = await store.stats()
            assert st["resilience"]["breakers"][1]["state"] == "open"
            assert st["resilience"]["breakers"][0]["state"] == "closed"
            await store.aclose()
            assert calls_before

        run(main())

    def test_degraded_fallback_serves_quarantined_keyspace(self):
        async def main():
            cap = 40.0
            store, nodes = self._cluster(degraded_fallback=True,
                                         degraded_fraction=0.5)
            nodes[1].fail = True
            # Every failure (and then every breaker-open rejection)
            # serves from the local fair-share envelope instead of
            # erroring: availability over accuracy.
            grants = 0
            for _ in range(60):
                res = await store.acquire("hot", 1, cap, 1e-9)
                grants += res.granted
            budget = headroom_budget(cap, fraction=0.5, min_budget=1.0)
            assert 0 < grants <= budget  # bounded by the shared formula
            assert store.degraded_decisions == 60
            # Node recovers → probe re-closes the breaker → degraded
            # state is discarded and the authoritative bucket serves.
            nodes[1].fail = False
            await asyncio.sleep(0.2)  # recovery window elapses
            res = await store.acquire("hot", 1, cap, 1e-9)
            assert res.granted  # authoritative (fresh bucket: full cap)
            st = await store.stats()
            assert st["resilience"]["breakers"][1]["state"] == "closed"
            assert st["resilience"]["degraded_keys"] == 0  # cleared
            await store.aclose()

        run(main())

    def test_bulk_rows_degrade_per_node(self):
        async def main():
            store, nodes = self._cluster(degraded_fallback=True,
                                         partial_failures="deny")
            nodes[1].fail = True
            keys = ["alpha", "hot", "d", "beta"]  # 0,1,0,1
            res = await store.acquire_many(keys, [1, 1, 1, 1], 1000.0,
                                           1.0)
            assert res.granted[0] and res.granted[2]  # node 0: exact
            assert res.granted[1] and res.granted[3]  # node 1: degraded
            assert store.degraded_decisions == 2
            await store.aclose()

        run(main())

    def test_sync_counter_gets_error_not_fake_result(self):
        # The approximate limiter owns its degraded mode: it must see
        # the failure, never a fabricated sync result.
        async def main():
            store, nodes = self._cluster(degraded_fallback=True)
            nodes[1].fail = True
            with pytest.raises(ConnectionError):
                await store.sync_counter("hot", 5.0, 1.0)
            await store.aclose()

        run(main())

    def test_metrics_registry_exposes_breaker_retry_shed(self):
        async def main():
            store, nodes = self._cluster(degraded_fallback=False)
            nodes[1].fail = True
            for _ in range(3):
                with pytest.raises(ConnectionError):
                    await store.acquire("hot", 1, 10.0, 1.0)
            with pytest.raises(NodeUnavailableError):
                await store.acquire("hot", 1, 10.0, 1.0)
            text = store.metrics_registry().render()
            assert 'drl_cluster_node_errors_total{node="1"} 3' in text
            assert 'drl_cluster_breaker_state{node="1"} 2' in text
            assert 'drl_cluster_breaker_opens_total{node="1"} 1' in text
            assert "drl_cluster_shed_total 1" in text
            assert "drl_cluster_degraded_decisions_total 0" in text
            await store.aclose()

        run(main())

    def test_breaker_events_hit_flight_recorder(self):
        async def main(tmp):
            rec = FlightRecorder(64, dump_dir=tmp, name="cluster")
            store, nodes = self._cluster(flight_recorder=rec)
            nodes[1].fail = True
            for _ in range(3):
                with pytest.raises(ConnectionError):
                    await store.acquire("hot", 1, 10.0, 1.0)
            kinds = [f["kind"] for f in rec.frames()]
            assert "node_error" in kinds and "breaker" in kinds
            assert rec.dumps_written == 1  # breaker_open auto-dump
            assert "breaker_open" in rec.last_dump_path
            await store.aclose()

        import tempfile

        with tempfile.TemporaryDirectory() as tmp:
            run(main(tmp))


# -- the seeded chaos soak ----------------------------------------------------

class TestChaosSoak:
    SEED = 20260803

    RULES = {
        "client.connect": (
            FaultRule("reset", probability=0.15),
            FaultRule("delay", probability=0.2, delay_s=0.001,
                      jitter_s=0.002),
        ),
        "client.read": (FaultRule("reset", probability=0.02),),
        "server.dispatch": (
            FaultRule("delay", probability=0.05, delay_s=0.002,
                      jitter_s=0.002),
        ),
    }

    def test_soak_invariants(self):
        """Live 2-node TCP topology through a healthy → node-down →
        recovered schedule, with seeded connection/dispatch chaos the
        whole way. Asserts the acceptance invariants: bounded
        over-admission, visible errors, breaker recovery, schedule
        determinism, no stranded futures, clean aclose."""

        async def main():
            inj = FaultInjector(self.SEED, self.RULES)
            faults.install(inj)
            backing0 = InProcessBucketStore()
            backing1 = FlakyNode()
            srv0 = BucketStoreServer(backing0)
            srv1 = BucketStoreServer(backing1)
            await srv0.start()
            await srv1.start()
            cap_hot = 40.0
            cluster = ClusterBucketStore(
                addresses=[(srv0.host, srv0.port),
                           (srv1.host, srv1.port)],
                breaker=BreakerConfig(failure_threshold=3,
                                      recovery_timeout_s=0.25),
                degraded_fallback=True, degraded_fraction=0.5,
                coalesce_requests=False,
                request_timeout_s=1.0,
                retry_policy=RetryPolicy(max_attempts=3,
                                         base_delay_s=0.004),
                reconnect_backoff_base_s=0.004,
                resilience_seed=self.SEED,
            )
            hot_grants = 0
            alpha_ok = 0
            alpha_n = 0

            async def drive(n: int):
                nonlocal hot_grants, alpha_ok, alpha_n
                for i in range(n):
                    try:
                        r = await cluster.acquire("hot", 1, cap_hot, 1e-9)
                        hot_grants += r.granted
                    except (ConnectionError, OSError, StoreTimeoutError,
                            wire.RemoteStoreError):
                        pass  # counted server-side; availability asserted
                        # via alpha below
                    alpha_n += 1
                    try:
                        r = await cluster.acquire("alpha", 1, 1e6, 1.0)
                        alpha_ok += r.granted
                    except (ConnectionError, OSError, StoreTimeoutError,
                            wire.RemoteStoreError):
                        pass

            try:
                # Phase A: healthy (chaos still jitters connects/reads).
                await drive(50)
                # Phase B: node 1 down hard — its keyspace must fail
                # over to the degraded envelope, node 0 keeps serving.
                backing1.fail = True
                await drive(100)
                st = await cluster.stats()
                assert st["resilience"]["breakers"][1]["opens"] >= 1
                assert st["resilience"]["node_errors"][1] > 0
                # Phase C: node recovers; the half-open probe re-closes.
                backing1.fail = False
                await asyncio.sleep(0.3)
                await drive(50)
                st = await cluster.stats()
                assert st["resilience"]["breakers"][1]["state"] == "closed"

                # Over-admission: authoritative grants ≤ cap; each
                # degraded episode adds at most one fair-share budget.
                budget = headroom_budget(cap_hot, fraction=0.5,
                                         min_budget=1.0)
                episodes = st["resilience"]["breakers"][1]["opens"] + 1
                assert hot_grants <= cap_hot + budget * episodes
                assert hot_grants >= 10  # availability: it kept serving
                # Healthy node barely noticed (only client-side chaos).
                assert alpha_ok >= alpha_n * 0.7

                # Schedule determinism: realized == pure-function preview.
                for seam in self.RULES:
                    realized = [e for e in inj.events if e.seam == seam]
                    assert realized == inj.schedule_preview(
                        seam, inj.occurrence_count(seam))
                # And an identically-seeded injector would do it again.
                twin = FaultInjector(self.SEED, self.RULES)
                for seam in self.RULES:
                    assert (twin.schedule_preview(
                        seam, inj.occurrence_count(seam))
                        == inj.schedule_preview(
                            seam, inj.occurrence_count(seam)))

                # No stranded futures on any node client.
                for node in cluster.nodes:
                    assert node._pending == {}
            finally:
                await cluster.aclose()
                await srv0.aclose()
                await srv1.aclose()
                await backing0.aclose()
                await backing1.aclose()

            # Clean aclose: loops stopped, threads joined.
            for node in cluster.nodes:
                assert node._io_loop is None

        run(main())

    def test_soak_metrics_exposition_carries_resilience_families(self):
        """The fleet scrape (cluster_metrics) must carry the breaker /
        shed / retry families alongside the per-node store series."""

        async def main():
            backing = FlakyNode()
            async with BucketStoreServer(backing) as srv:
                cluster = ClusterBucketStore(
                    addresses=[(srv.host, srv.port)],
                    breaker=True, degraded_fallback=True,
                    coalesce_requests=False, request_timeout_s=0.5)
                try:
                    await cluster.acquire("k", 1, 100.0, 1.0)
                    text = await cluster.cluster_metrics()
                    assert "drl_cluster_breaker_state" in text
                    assert "drl_cluster_shed_total" in text
                    assert "drl_cluster_client_retries_total" in text
                    assert "drl_requests_served_total" in text  # node's
                    assert text.rstrip().endswith("# EOF")
                finally:
                    await cluster.aclose()

        run(main())
