"""Fixed-window limiter tests — window count resets at every boundary."""

import asyncio

import pytest

from distributedratelimiting.redis_tpu.models.fixed_window import (
    FixedWindowRateLimiter,
)
from distributedratelimiting.redis_tpu.models.options import FixedWindowOptions
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.remote import RemoteBucketStore
from distributedratelimiting.redis_tpu.runtime.server import BucketStoreServer
from distributedratelimiting.redis_tpu.runtime.store import (
    DeviceBucketStore,
    InProcessBucketStore,
)


def run(coro):
    return asyncio.run(coro)


def device_store(clock):
    return DeviceBucketStore(n_slots=64, counter_slots=8, clock=clock,
                             max_batch=64)


@pytest.mark.parametrize("make_store", [InProcessBucketStore, device_store])
class TestFixedWindowStore:
    def test_resets_at_boundary_not_gradually(self, make_store):
        clock = ManualClock()
        store = make_store(clock)
        for _ in range(3):
            assert store.fixed_window_acquire_blocking("k", 1, 3.0, 1.0).granted
        assert not store.fixed_window_acquire_blocking("k", 1, 3.0, 1.0).granted
        # Mid-window: still denied (fixed window does NOT slide open).
        clock.advance_seconds(0.9)
        assert not store.fixed_window_acquire_blocking("k", 1, 3.0, 1.0).granted
        # Past the boundary: full limit again (the classic boundary reset).
        clock.advance_seconds(0.2)
        for _ in range(3):
            assert store.fixed_window_acquire_blocking("k", 1, 3.0, 1.0).granted

    def test_differs_from_sliding_at_boundary(self, make_store):
        clock = ManualClock()
        store = make_store(clock)
        # Exhaust both variants in window 0...
        for _ in range(3):
            store.fixed_window_acquire_blocking("x", 1, 3.0, 1.0)
            store.window_acquire_blocking("x", 1, 3.0, 1.0)
        clock.advance_seconds(1.05)  # just past the boundary
        # Fixed admits a full burst; sliding still counts the trailing
        # window's consumption and denies.
        assert store.fixed_window_acquire_blocking("x", 3, 3.0, 1.0).granted
        assert not store.window_acquire_blocking("x", 3, 3.0, 1.0).granted


class TestFixedWindowLimiter:
    def test_contract_and_retry_after(self):
        clock = ManualClock()
        lim = FixedWindowRateLimiter(
            FixedWindowOptions(permit_limit=2, window_s=1.0,
                               instance_name="fw"),
            InProcessBucketStore(clock=clock))
        assert lim.acquire(2).is_acquired
        denied = lim.acquire(1)
        assert not denied.is_acquired
        assert denied.retry_after == 1.0
        with pytest.raises(ValueError):
            lim.acquire(3)
        clock.advance_seconds(1.1)
        assert lim.acquire(2).is_acquired

    def test_async_over_tcp(self):
        async def main():
            clock = ManualClock()
            async with BucketStoreServer(
                    InProcessBucketStore(clock=clock)) as srv:
                store = RemoteBucketStore(address=(srv.host, srv.port))
                lim = FixedWindowRateLimiter(
                    FixedWindowOptions(permit_limit=2, window_s=1.0,
                                       instance_name="fw2"),
                    store)
                try:
                    assert (await lim.acquire_async(2)).is_acquired
                    assert not (await lim.acquire_async(1)).is_acquired
                    clock.advance_seconds(1.1)  # server clock is authority
                    assert (await lim.acquire_async(1)).is_acquired
                finally:
                    await lim.aclose()
                    await store.aclose()

        run(main())
