"""Device-resident fingerprint directory: kernels + store.

The probe/insert/TTL design and its disclosed trade-offs live in
``ops/fp_directory.py``; the store integration in ``runtime/fp_store.py``.
Differential anchor: `FingerprintBucketStore` must decide exactly like
`InProcessBucketStore` under a shared manual clock."""

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from distributedratelimiting.redis_tpu.ops import fp_directory as F
from distributedratelimiting.redis_tpu.ops import kernels as K
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.fp_store import (
    FingerprintBucketStore,
    fingerprints,
)
from distributedratelimiting.redis_tpu.runtime.store import (
    DeviceBucketStore,
    InProcessBucketStore,
)


def run(coro):
    return asyncio.run(coro)


class TestFingerprints:
    def test_native_and_python_agree(self):
        # The pure-Python FNV fallback must match the native pass
        # bit-for-bit — fingerprints persist in tables and checkpoints.
        from distributedratelimiting.redis_tpu.runtime import fp_store

        keys = ["a", "user:42", "ключ-🔑", "", "x" * 300]
        got = fingerprints(keys)
        for i, k in enumerate(keys):
            h = fp_store._fp64_py(k)
            assert got[i, 0] == h & 0xFFFFFFFF
            assert got[i, 1] == h >> 32

    def test_never_empty_sentinel(self):
        fps = fingerprints([f"k{i}" for i in range(1000)])
        assert ((fps != 0).any(axis=1)).all()


def _resolve(fp, keys, valid=None, probe_window=8, rounds=4):
    k = jnp.asarray(fingerprints(keys))
    v = jnp.ones((len(keys),), bool) if valid is None else jnp.asarray(valid)
    return F.fp_resolve_core(fp, k, v, probe_window=probe_window,
                             rounds=rounds)


class TestResolveKernel:
    def test_insert_then_hit_same_slot(self):
        fp = F.init_fp_table(64)
        out1 = _resolve(fp, ["alpha", "beta", "gamma"])
        assert np.asarray(out1.resolved).all()
        out2 = _resolve(out1.fp, ["gamma", "alpha", "beta"])
        s1 = np.asarray(out1.slots)
        s2 = np.asarray(out2.slots)
        assert s2[0] == s1[2] and s2[1] == s1[0] and s2[2] == s1[1]

    def test_distinct_keys_distinct_slots(self):
        fp = F.init_fp_table(256)
        keys = [f"k{i}" for i in range(100)]
        out = _resolve(fp, keys)
        slots = np.asarray(out.slots)
        assert np.asarray(out.resolved).all()
        assert len(np.unique(slots)) == 100

    def test_in_batch_duplicates_share_slot(self):
        fp = F.init_fp_table(64)
        out = _resolve(fp, ["dup", "x", "dup", "dup"])
        slots = np.asarray(out.slots)
        assert slots[0] == slots[2] == slots[3] != slots[1]

    def test_padding_rows_do_not_insert(self):
        fp = F.init_fp_table(64)
        out = _resolve(fp, ["a", "b"], valid=np.array([True, False]))
        assert int((np.asarray(out.fp) != 0).any(-1).sum()) == 1
        assert np.asarray(out.slots)[1] == -1

    def test_window_pressure_reports_unresolved(self):
        # 4-slot table, window 4: the 5th distinct key cannot be placed.
        fp = F.init_fp_table(4)
        out = _resolve(fp, [f"k{i}" for i in range(6)], probe_window=4)
        res = np.asarray(out.resolved)
        assert res.sum() == 4
        assert (np.asarray(out.slots)[~res] == -1).all()

    def test_sweep_frees_cells_for_reuse(self):
        fp = F.init_fp_table(4)
        state = K.init_bucket_state(4)
        out = _resolve(fp, [f"k{i}" for i in range(4)], probe_window=4)
        # Touch the buckets so exists=True and TTL applies.
        state, _, _ = K.acquire_core(
            state, out.slots, jnp.ones((4,), jnp.int32),
            jnp.ones((4,), bool), jnp.int32(0), jnp.float32(5.0),
            jnp.float32(1.0 / 1024.0))
        far = 10_000_000  # way past time-to-full TTL
        fp2, state2, n_freed = F.fp_sweep_expired(
            out.fp, state, jnp.int32(far), jnp.float32(5.0),
            jnp.float32(1.0 / 1024.0))
        assert int(n_freed) == 4
        out2 = _resolve(fp2, ["fresh1", "fresh2"], probe_window=4)
        assert np.asarray(out2.resolved).all()

    def test_peek_does_not_insert(self):
        fp = F.init_fp_table(64)
        state = K.init_bucket_state(64)
        k = jnp.asarray(fingerprints(["ghost"]))
        est = F.fp_peek_batch(fp, state, k, jnp.ones((1,), bool),
                              jnp.int32(0), jnp.float32(7.0),
                              jnp.float32(0.0), probe_window=8)
        assert float(np.asarray(est)[0]) == 7.0  # full bucket on miss
        assert int((np.asarray(fp) != 0).any(-1).sum()) == 0

    def test_migrate_preserves_state(self):
        fp = F.init_fp_table(8)
        state = K.init_bucket_state(8)
        keys = [f"k{i}" for i in range(6)]
        out = _resolve(fp, keys, probe_window=8)
        tokens = jnp.asarray(np.arange(8, dtype=np.float32))
        state = K.BucketState(tokens, state.last_ts,
                              jnp.ones((8,), bool))
        new_fp = F.init_fp_table(16)
        new_state = K.init_bucket_state(16)
        kpair = out.fp[np.asarray(out.slots)]
        new_fp, new_state, placed = F.fp_migrate_chunk(
            new_fp, new_state, kpair, tokens[out.slots],
            state.last_ts[out.slots], state.exists[out.slots],
            jnp.ones((6,), bool), probe_window=8)
        assert np.asarray(placed).all()
        re = _resolve(new_fp, keys, probe_window=8)
        old_tokens = np.asarray(tokens)[np.asarray(out.slots)]
        new_tokens = np.asarray(new_state.tokens)[np.asarray(re.slots)]
        np.testing.assert_allclose(new_tokens, old_tokens)


class TestTableInvariants:
    def test_churn_preserves_uniqueness_and_findability(self):
        # Property check over random insert/expire/re-insert churn: every
        # live fingerprint occupies exactly ONE cell (duplicate cells
        # would let one key's consumption split across buckets), and
        # every live key resolves to its cell within the probe window
        # (full-window scans make TTL clears safe — this is the claim).
        rng = np.random.default_rng(17)
        clock = ManualClock()
        store = FingerprintBucketStore(n_slots=256, clock=clock,
                                       probe_window=8)
        table = store._table(5.0, 1.0)
        pool = [f"c{i}" for i in range(120)]

        async def churn():
            for cycle in range(6):
                batch = [pool[j] for j in rng.integers(0, len(pool), 80)]
                await store.acquire_many(batch, [1] * 80, 5.0, 1.0)
                clock.advance_seconds(rng.choice([0.5, 2.0, 3600.0]))
                store.sweep_all()
            fp = np.asarray(table.fp)
            live = fp[(fp != 0).any(-1)]
            # Uniqueness: no fingerprint occupies two cells.
            packed = live[:, 0].astype(np.uint64) << 32 | live[:, 1]
            assert len(np.unique(packed)) == len(packed)
            # Findability: re-resolving every live fingerprint hits
            # (insert-free peek must see full table coverage).
            from distributedratelimiting.redis_tpu.ops import (
                fp_directory as F,
            )
            import jax.numpy as jnp

            out = F.fp_resolve_core(
                jnp.asarray(fp), jnp.asarray(live),
                jnp.ones((len(live),), bool),
                probe_window=table.probe_window, rounds=1)
            assert np.asarray(out.resolved).all()
            slots = np.asarray(out.slots)
            assert len(np.unique(slots)) == len(slots)
            await store.aclose()

        run(churn())


class TestFingerprintStore:
    def test_capacity_enforced_async_path(self):
        async def main():
            store = FingerprintBucketStore(n_slots=256, clock=ManualClock())
            got = [(await store.acquire("k", 1, 3.0, 1.0)).granted
                   for _ in range(5)]
            assert got == [True] * 3 + [False] * 2
            await store.aclose()

        run(main())

    def test_refill_over_time(self):
        async def main():
            clock = ManualClock()
            store = FingerprintBucketStore(n_slots=256, clock=clock)
            for _ in range(3):
                assert (await store.acquire("k", 1, 3.0, 1.0)).granted
            assert not (await store.acquire("k", 1, 3.0, 1.0)).granted
            clock.advance_seconds(2.0)
            assert (await store.acquire("k", 2, 3.0, 1.0)).granted
            await store.aclose()

        run(main())

    def test_bulk_matches_host_directory_store(self):
        # Same kernel core, different directory: the fingerprint store
        # must decide bit-identically to the host-directory device store
        # (including the documented CONSERVATIVE in-batch duplicate rule,
        # which an exact serial oracle intentionally differs from).
        async def main():
            clock = ManualClock()
            store = FingerprintBucketStore(n_slots=1024, clock=clock)
            oracle = DeviceBucketStore(n_slots=1024, clock=clock)
            rng = np.random.default_rng(7)
            keys = [f"k{i}" for i in rng.integers(0, 40, 300)]
            counts = rng.integers(0, 4, 300).tolist()
            got = await store.acquire_many(keys, counts, 5.0, 1.0)
            want = await oracle.acquire_many(keys, counts, 5.0, 1.0)
            np.testing.assert_array_equal(got.granted, want.granted)
            np.testing.assert_allclose(got.remaining, want.remaining,
                                       atol=1e-4)
            await store.aclose()
            await oracle.aclose()

        run(main())

    def test_bulk_verdict_only_matches_host_directory_store(self):
        # The with_remaining=False path ships bit-packed verdicts (the
        # u8[K, 2, B//8] bit-planes) — its grants must equal both the
        # host-directory store's and its own with_remaining=True path
        # (same kernel, different result encoding).
        async def main():
            clock = ManualClock()
            store = FingerprintBucketStore(n_slots=1024, clock=clock)
            full = FingerprintBucketStore(n_slots=1024, clock=clock)
            oracle = DeviceBucketStore(n_slots=1024, clock=clock)
            rng = np.random.default_rng(11)
            keys = [f"k{i}" for i in rng.integers(0, 40, 300)]
            counts = rng.integers(0, 4, 300).tolist()
            got = await store.acquire_many(keys, counts, 5.0, 1.0,
                                           with_remaining=False)
            ref = await full.acquire_many(keys, counts, 5.0, 1.0)
            want = await oracle.acquire_many(keys, counts, 5.0, 1.0,
                                             with_remaining=False)
            assert got.remaining is None
            np.testing.assert_array_equal(got.granted, want.granted)
            np.testing.assert_array_equal(got.granted, ref.granted)
            await store.aclose()
            await full.aclose()
            await oracle.aclose()

        run(main())

    def test_bulk_negative_count_stays_valid_row(self):
        # pack_fp12 clamps counts on BOTH sides: a negative ask must stay
        # a valid row (kernel grants count<=0 like every other path), not
        # wrap into uint32 sign-bit range and read as a padding row.
        async def main():
            clock = ManualClock()
            store = FingerprintBucketStore(n_slots=256, clock=clock)
            res = await store.acquire_many(["neg", "pos"], [-1, 1],
                                           5.0, 0.0)
            assert bool(res.granted[0]) and bool(res.granted[1])
            # The clamped ask consumed nothing: the bucket is still full,
            # so a full-capacity ask grants and the next one is denied —
            # i.e. the row resolved into a real bucket, not padding.
            res2 = await store.acquire_many(["neg", "neg"], [5, 1],
                                            5.0, 0.0,
                                            with_remaining=False)
            assert bool(res2.granted[0]) and not bool(res2.granted[1])
            await store.aclose()

        run(main())

    def test_bulk_verdict_only_odd_max_batch(self):
        # max_batch not divisible by 8 cannot use bit-planes; the path
        # must fall back to the f32 fused result instead of crashing
        # (classic-store guard parity, store.py bits path).
        async def main():
            clock = ManualClock()
            store = FingerprintBucketStore(n_slots=1024, max_batch=60,
                                           clock=clock)
            oracle = DeviceBucketStore(n_slots=1024, clock=clock)
            rng = np.random.default_rng(13)
            # Distinct keys: the in-batch duplicate-serialization rule is
            # batch-boundary-dependent, and max_batch=60 chunks batches
            # differently from the oracle's default — duplicates would
            # legitimately diverge.
            keys = [f"k{i}" for i in range(150)]
            counts = rng.integers(0, 4, 150).tolist()
            got = await store.acquire_many(keys, counts, 5.0, 1.0,
                                           with_remaining=False)
            want = await oracle.acquire_many(keys, counts, 5.0, 1.0,
                                             with_remaining=False)
            assert got.remaining is None
            np.testing.assert_array_equal(got.granted, want.granted)
            await store.aclose()
            await oracle.aclose()

        run(main())

    def test_bulk_distinct_keys_match_exact_oracle(self):
        # With no in-call duplicates the decisions are exact — the serial
        # InProcess oracle applies directly.
        async def main():
            clock = ManualClock()
            store = FingerprintBucketStore(n_slots=1024, clock=clock)
            oracle = InProcessBucketStore(clock=clock)
            rng = np.random.default_rng(11)
            keys = [f"k{i}" for i in range(300)]
            counts = rng.integers(0, 7, 300).tolist()
            got = await store.acquire_many(keys, counts, 5.0, 1.0)
            want = await oracle.acquire_many(keys, counts, 5.0, 1.0)
            np.testing.assert_array_equal(got.granted, want.granted)
            np.testing.assert_allclose(got.remaining, want.remaining,
                                       atol=1e-4)
            await store.aclose()

        run(main())

    def test_bulk_duplicate_serialization(self):
        async def main():
            store = FingerprintBucketStore(n_slots=256, clock=ManualClock())
            res = await store.acquire_many(["hot"] * 8, [1] * 8, 5.0, 0.0)
            assert list(res.granted) == [True] * 5 + [False] * 3
            await store.aclose()

        run(main())

    def test_peek_and_blocking(self):
        store = FingerprintBucketStore(n_slots=256, clock=ManualClock())
        assert store.peek_blocking("fresh", 9.0, 1.0) == 9.0
        r = store.acquire_blocking("fresh", 4, 9.0, 1.0)
        assert r.granted and r.remaining == pytest.approx(5.0)
        assert store.peek_blocking("fresh", 9.0, 1.0) == 5.0
        run(store.aclose())

    def test_pressure_grows_table_and_keeps_state(self):
        async def main():
            clock = ManualClock()
            store = FingerprintBucketStore(n_slots=64, clock=clock,
                                           probe_window=8)
            table = store._table(5.0, 0.0)
            # Consume 2 of 5 on a marker key, then slam enough distinct
            # keys to exceed the probe windows → pressure → grow.
            assert (await store.acquire("marker", 2, 5.0, 0.0)).granted
            keys = [f"f{i}" for i in range(200)]
            res = await store.acquire_many(keys, [1] * 200, 5.0, 0.0)
            assert store.metrics.fp_unresolved > 0
            assert table.n_slots >= 128  # at least one doubling
            # Marker's consumption survived the device-side rehash.
            assert store.peek_blocking("marker", 5.0, 0.0) == 3.0
            # Deny-and-heal converges: each pressured call sweeps/grows,
            # so within a few retries every key is placeable and grants.
            for _ in range(3):
                res = await store.acquire_many(keys, [1] * 200, 5.0, 0.0)
                if res.granted.all():
                    break
            assert res.granted.all()
            assert table.n_slots >= 256
            await store.aclose()

        run(main())

    def test_snapshot_restore_roundtrip(self):
        async def main():
            clock = ManualClock()
            store = FingerprintBucketStore(n_slots=256, clock=clock)
            for i in range(10):
                await store.acquire(f"k{i}", 2, 5.0, 1.0)
            snap = store.snapshot()
            fresh = FingerprintBucketStore(n_slots=256, clock=ManualClock())
            fresh.restore(snap)
            res = await fresh.acquire_many(
                [f"k{i}" for i in range(10)], [4] * 10, 5.0, 1.0)
            assert not res.granted.any()  # 3 left of 5 per key
            await store.aclose()
            await fresh.aclose()

        run(main())

    def test_restore_replaces_legacy_wrapping_placement(self):
        # Pre-v2 snapshots placed entries at base = mix(fp) % n (wrapping
        # window). Restoring one must RE-PLACE entries through the
        # migrate kernel, not install the table verbatim — under today's
        # non-wrapping base = mix(fp) % (n - L + 1) most legacy positions
        # are invisible to the probe, and their state would silently
        # reset.
        async def main():
            from distributedratelimiting.redis_tpu.runtime.fp_store import (
                fingerprints,
            )

            n = 256
            keys = [f"legacy{i}" for i in range(20)]
            fps = fingerprints(keys)
            h = (fps[:, 0] * np.uint32(0x9E3779B1)) ^ fps[:, 1]
            base_old = h % np.uint32(n)
            fp_tab = np.zeros((n, 2), np.uint32)
            tokens = np.zeros((n,), np.float32)
            last_ts = np.zeros((n,), np.int32)
            exists = np.zeros((n,), bool)
            for i, b in enumerate(base_old):
                assert not fp_tab[b].any(), "test keys must not collide"
                fp_tab[b] = fps[i]       # sparse table: old code placed
                tokens[b] = float(i)     # each key at its window's base
                exists[b] = True
            clock = ManualClock()
            store = FingerprintBucketStore(n_slots=n, clock=clock)
            store.acquire_blocking("warm", 1, 100.0, 0.0)  # make the table
            snap = store.snapshot()
            key0 = next(iter(snap["tables"]))
            legacy = {"fp": fp_tab, "probe_window": 16,  # no "placement":
                      "tokens": tokens, "last_ts": last_ts,  # a v1 form
                      "exists": exists}
            snap["tables"] = {key0: legacy}
            store.restore(snap)
            for i, k in enumerate(keys):
                got = store.peek_blocking(k, 100.0, 0.0)
                assert got == float(int(i)), (k, got, i)
            await store.aclose()

        run(main())

    def test_restore_adopts_snapshot_probe_window(self):
        # A key placed deep in a 16-cell window must stay visible after
        # restoring into a store configured with a narrower window — the
        # snapshot's geometry wins (else deep entries are orphaned and
        # their consumption forgotten).
        async def main():
            clock = ManualClock()
            store = FingerprintBucketStore(n_slots=256, clock=clock,
                                           probe_window=16)
            for i in range(40):
                await store.acquire(f"k{i}", 2, 5.0, 0.0)
            snap = store.snapshot()
            narrow = FingerprintBucketStore(n_slots=256, clock=ManualClock(),
                                            probe_window=4)
            narrow.restore(snap)
            assert narrow._table(5.0, 0.0).probe_window == 16
            res = await narrow.acquire_many(
                [f"k{i}" for i in range(40)], [4] * 40, 5.0, 0.0)
            assert not res.granted.any()  # consumption all remembered
            await store.aclose()
            await narrow.aclose()

        run(main())

    def test_cross_type_restore_rejected(self):
        async def main():
            host_store = DeviceBucketStore(n_slots=256, clock=ManualClock())
            await host_store.acquire("k", 1, 5.0, 1.0)
            snap = host_store.snapshot()
            fp_store = FingerprintBucketStore(n_slots=256,
                                              clock=ManualClock())
            with pytest.raises(ValueError, match="host key directory"):
                fp_store.restore(snap)
            fp_store2 = FingerprintBucketStore(n_slots=256,
                                               clock=ManualClock())
            await fp_store2.acquire("k", 1, 5.0, 1.0)
            snap2 = fp_store2.snapshot()
            host_store2 = DeviceBucketStore(n_slots=256, clock=ManualClock())
            with pytest.raises(ValueError, match="fingerprint"):
                host_store2.restore(snap2)
            for s in (host_store, fp_store, fp_store2, host_store2):
                await s.aclose()

        run(main())

    def test_aux_tiers_inherited(self):
        async def main():
            store = FingerprintBucketStore(n_slots=256, clock=ManualClock())
            # Windows, counters, semaphores ride the parent store.
            assert (await store.window_acquire("w", 1, 3.0, 10.0)).granted
            r = await store.sync_counter("c", 5.0, 0.0)
            assert r.global_score == pytest.approx(5.0)
            assert (await store.concurrency_acquire("s", 1, 2)).granted
            await store.concurrency_release("s", 1)
            await store.aclose()

        run(main())

    def test_window_tier_uses_fingerprint_directory(self):
        async def main():
            clock = ManualClock()
            store = FingerprintBucketStore(n_slots=256, clock=clock)
            # Sliding window limit 3 per 10s.
            got = [(await store.window_acquire("w", 1, 3.0, 10.0)).granted
                   for _ in range(5)]
            assert got == [True] * 3 + [False] * 2
            # Window table really is fingerprint-backed (no host dir).
            wt = store._wtable(3.0, 10.0)
            assert not hasattr(wt, "dir")
            assert int((np.asarray(wt.fp) != 0).any(-1).sum()) == 1
            # New window ⇒ interpolated estimate decays.
            clock.advance_seconds(15.0)
            assert (await store.window_acquire("w", 1, 3.0, 10.0)).granted
            await store.aclose()

        run(main())

    def test_window_bulk_matches_host_directory_store(self):
        async def main():
            clock = ManualClock()
            store = FingerprintBucketStore(n_slots=1024, clock=clock)
            oracle = DeviceBucketStore(n_slots=1024, clock=clock)
            rng = np.random.default_rng(5)
            keys = [f"w{i}" for i in rng.integers(0, 50, 300)]
            counts = rng.integers(0, 3, 300).tolist()
            for fixed in (False, True):
                got = await store.window_acquire_many(
                    keys, counts, 4.0, 10.0, fixed=fixed)
                want = await oracle.window_acquire_many(
                    keys, counts, 4.0, 10.0, fixed=fixed)
                np.testing.assert_array_equal(got.granted, want.granted)
                np.testing.assert_allclose(got.remaining, want.remaining,
                                           atol=1e-4)
            await store.aclose()
            await oracle.aclose()

        run(main())

    def test_window_bulk_verdict_only_matches_full_path(self):
        # The window-family bit-plane path (with_remaining=False through
        # fp_window_acquire_scan_fused_bits) must grant identically to
        # the f32 fused path and the host-directory oracle, for both
        # sliding and fixed windows.
        async def main():
            clock = ManualClock()
            rng = np.random.default_rng(17)
            keys = [f"w{i}" for i in rng.integers(0, 50, 300)]
            counts = rng.integers(0, 3, 300).tolist()
            for fixed in (False, True):
                store = FingerprintBucketStore(n_slots=1024, clock=clock)
                full = FingerprintBucketStore(n_slots=1024, clock=clock)
                oracle = DeviceBucketStore(n_slots=1024, clock=clock)
                got = await store.window_acquire_many(
                    keys, counts, 4.0, 10.0, fixed=fixed,
                    with_remaining=False)
                ref = await full.window_acquire_many(
                    keys, counts, 4.0, 10.0, fixed=fixed)
                want = await oracle.window_acquire_many(
                    keys, counts, 4.0, 10.0, fixed=fixed,
                    with_remaining=False)
                assert got.remaining is None
                np.testing.assert_array_equal(got.granted, ref.granted)
                np.testing.assert_array_equal(got.granted, want.granted)
                await store.aclose()
                await full.aclose()
                await oracle.aclose()

        run(main())

    def test_window_growth_preserves_state(self):
        async def main():
            clock = ManualClock()
            store = FingerprintBucketStore(n_slots=64, clock=clock,
                                           probe_window=8)
            wt = store._wtable(5.0, 60.0)
            # Consume 4 of 5 on a marker key, then flood distinct keys.
            r = await store.window_acquire_many(["wm"], [4], 5.0, 60.0)
            assert r.granted.all()
            keys = [f"wf{i}" for i in range(200)]
            for _ in range(4):
                res = await store.window_acquire_many(
                    keys, [1] * 200, 5.0, 60.0)
                if res.granted.all():
                    break
            assert res.granted.all()
            assert wt.n_slots >= 256
            # Marker's 4-of-5 consumption survived the window rehash.
            r2 = await store.window_acquire_many(["wm"], [2], 5.0, 60.0)
            assert not r2.granted.any()
            await store.aclose()

        run(main())

    def test_window_snapshot_roundtrip_and_cross_type(self):
        async def main():
            clock = ManualClock()
            store = FingerprintBucketStore(n_slots=256, clock=clock)
            await store.window_acquire("w", 3, 5.0, 60.0)
            snap = store.snapshot()
            fresh = FingerprintBucketStore(n_slots=256, clock=ManualClock())
            fresh.restore(snap)
            r = await fresh.window_acquire("w", 3, 5.0, 60.0)
            assert not r.granted  # 3 of 5 already consumed pre-snapshot
            host = DeviceBucketStore(n_slots=256, clock=ManualClock())
            with pytest.raises(ValueError, match="fingerprint"):
                host.restore(snap)
            await store.aclose()
            await fresh.aclose()
            await host.aclose()

        run(main())

    def test_concurrent_mixed_traffic_with_growth(self):
        # Race posture: async micro-batched acquires + blocking bulk calls
        # from threads + growth pressure, all against one table. The
        # donated-buffer discipline (launches under store._lock) must hold:
        # no "Array has been deleted", no lost state, aggregate
        # conservation (a cap-K key never grants more than K + refill).
        import threading

        async def main():
            store = FingerprintBucketStore(n_slots=128, clock=ManualClock(),
                                           probe_window=8)
            errors = []

            def bulk_worker(w):
                try:
                    keys = [f"b{w}-{i}" for i in range(150)]
                    for _ in range(3):
                        store.acquire_many_blocking(keys, [1] * 150, 5.0, 0.0)
                except Exception as exc:  # pragma: no cover
                    errors.append(exc)

            threads = [threading.Thread(target=bulk_worker, args=(w,))
                       for w in range(3)]
            for t in threads:
                t.start()
            granted_hot = 0
            for _ in range(40):
                r = await store.acquire("hot", 1, 10.0, 0.0)
                granted_hot += int(r.granted)
            for t in threads:
                t.join(timeout=120)
            assert not errors, errors
            assert granted_hot == 10  # cap-10, zero refill: exactly 10
            # Table grew under the 450-distinct-key pressure and survived.
            assert store._table(5.0, 0.0).n_slots > 128
            await store.aclose()

        run(main())

    def test_limiter_integration(self):
        from distributedratelimiting.redis_tpu.models.options import (
            TokenBucketOptions,
        )
        from distributedratelimiting.redis_tpu.models.token_bucket import (
            TokenBucketRateLimiter,
        )

        async def main():
            store = FingerprintBucketStore(n_slots=256, clock=ManualClock())
            limiter = TokenBucketRateLimiter(
                TokenBucketOptions(token_limit=3, tokens_per_period=1,
                                   instance_name="api"), store)
            got = [(await limiter.acquire_async(1)).is_acquired
                   for _ in range(5)]
            assert got == [True] * 3 + [False] * 2
            await store.aclose()

        run(main())
