"""Cluster store tests: client-side key sharding across N store nodes.

The Redis-Cluster shape of the reference's star topology (SURVEY.md §5.8)
— N shared-nothing store servers, clients routing key→node by stable
crc32. Per-key semantics must be exactly single-node semantics; failures
must degrade per node (invariant 9)."""

import asyncio

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.parallel.sharded_store import shard_of_key
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.cluster import ClusterBucketStore
from distributedratelimiting.redis_tpu.runtime.server import BucketStoreServer
from distributedratelimiting.redis_tpu.runtime.store import InProcessBucketStore


def run(coro):
    return asyncio.run(coro)


def make_cluster(n_nodes: int, clock=None, **kw):
    nodes = [InProcessBucketStore(clock=clock) for _ in range(n_nodes)]
    return ClusterBucketStore(stores=nodes, **kw), nodes


class TestConfig:
    def test_some_config_required(self):
        with pytest.raises(ValueError, match="stores, addresses, or urls"):
            ClusterBucketStore()

    def test_empty_cluster_rejected(self):
        with pytest.raises(ValueError, match="at least one node"):
            ClusterBucketStore(stores=[])

    def test_bad_partial_failures_rejected(self):
        with pytest.raises(ValueError, match="partial_failures"):
            make_cluster(2, partial_failures="ignore")

    def test_urls_build_remote_nodes(self):
        store = ClusterBucketStore(urls=["h1:1234", "h2:1234"])
        assert store.n_nodes == 2


class TestRouting:
    def test_same_key_same_node_capacity_enforced(self):
        # If routing ever moved a key between nodes, each node's fresh
        # bucket would re-grant; capacity holding proves stickiness.
        async def main():
            store, _ = make_cluster(4, clock=ManualClock())
            got = [(await store.acquire("user:1", 1, 3.0, 1.0)).granted
                   for _ in range(5)]
            assert got == [True] * 3 + [False] * 2

        run(main())

    def test_keys_spread_across_nodes(self):
        async def main():
            store, nodes = make_cluster(4, clock=ManualClock())
            for i in range(64):
                await store.acquire(f"k{i}", 1, 10.0, 1.0)
            touched = [len(n._buckets) for n in nodes]
            assert sum(touched) == 64
            assert all(t > 0 for t in touched)  # crc32 spreads 64 keys

        run(main())

    def test_routing_matches_shard_of_key(self):
        store, nodes = make_cluster(3)
        for key in ("a", "b", "user:42", "ключ"):
            assert store.node_of(key) is nodes[shard_of_key(key, 3)]

    def test_sync_counter_shared_across_clients(self):
        # The approximate algorithm's global counter is one key → one
        # node; two "client" calls must see each other's consumption.
        async def main():
            store, _ = make_cluster(4, clock=ManualClock())
            r1 = await store.sync_counter("api", 10.0, 0.0)
            r2 = await store.sync_counter("api", 5.0, 0.0)
            assert r2.global_score == pytest.approx(r1.global_score + 5.0)

        run(main())


class TestBulk:
    def test_bulk_matches_per_key_oracle(self):
        async def main():
            clock = ManualClock()
            store, _ = make_cluster(3, clock=clock)
            oracle = InProcessBucketStore(clock=clock)
            keys = [f"k{i % 7}" for i in range(40)]  # duplicates included
            counts = [(i % 3) + 1 for i in range(40)]
            got = await store.acquire_many(keys, counts, 5.0, 1.0)
            want = await oracle.acquire_many(keys, counts, 5.0, 1.0)
            np.testing.assert_array_equal(got.granted, want.granted)
            np.testing.assert_allclose(got.remaining, want.remaining)

        run(main())

    def test_duplicate_serialization_preserved(self):
        # Same key twice in one bulk call: stable split keeps arrival
        # order on the owning node, so the second request sees the first's
        # consumption (invariant 3 at batch granularity).
        async def main():
            store, _ = make_cluster(4, clock=ManualClock())
            res = await store.acquire_many(["dup", "dup"], [3, 3], 5.0, 1.0)
            assert list(res.granted) == [True, False]

        run(main())

    def test_window_bulk_and_fixed(self):
        async def main():
            clock = ManualClock()
            store, _ = make_cluster(3, clock=clock)
            oracle = InProcessBucketStore(clock=clock)
            keys = [f"w{i % 5}" for i in range(20)]
            counts = [1] * 20
            for fixed in (False, True):
                got = await store.window_acquire_many(
                    keys, counts, 3.0, 10.0, fixed=fixed)
                want = await oracle.window_acquire_many(
                    keys, counts, 3.0, 10.0, fixed=fixed)
                np.testing.assert_array_equal(got.granted, want.granted)

        run(main())

    def test_empty_bulk(self):
        async def main():
            store, _ = make_cluster(2)
            res = await store.acquire_many([], [], 5.0, 1.0)
            assert len(res) == 0

        run(main())

    def test_verdict_only_bulk(self):
        async def main():
            store, _ = make_cluster(2, clock=ManualClock())
            res = await store.acquire_many(
                ["a", "b", "c"], [1, 1, 99], 5.0, 1.0, with_remaining=False)
            assert list(res.granted) == [True, True, False]
            assert res.remaining is None

        run(main())

    def test_blocking_bulk_from_sync_context(self):
        store, _ = make_cluster(3, clock=ManualClock())
        res = store.acquire_many_blocking(
            [f"k{i}" for i in range(10)], [1] * 10, 5.0, 1.0)
        assert res.granted.all()
        run(store.aclose())


class TestOverTcp:
    def test_cluster_of_two_servers(self):
        async def main():
            clock = ManualClock()
            async with BucketStoreServer(InProcessBucketStore(clock=clock)) as a:
                async with BucketStoreServer(
                        InProcessBucketStore(clock=clock)) as b:
                    store = ClusterBucketStore(
                        addresses=[(a.host, a.port), (b.host, b.port)])
                    try:
                        # Single-key ops route and hold capacity.
                        got = [(await store.acquire("k", 1, 2.0, 1.0)).granted
                               for _ in range(3)]
                        assert got == [True, True, False]
                        # Bulk spans both servers.
                        keys = [f"k{i}" for i in range(32)]
                        res = await store.acquire_many(
                            keys, [1] * 32, 5.0, 1.0)
                        assert res.granted.all()
                        # Stats aggregate across nodes.
                        stats = await store.stats()
                        assert stats["n_nodes"] == 2
                        assert len(stats["nodes"]) == 2
                        # Coalescing collapses decisions into frames, so
                        # the frame count is load-dependent; both nodes
                        # must have served some.
                        assert all(s["requests_served"] > 0
                                   for s in stats["nodes"])
                        await store.ping()
                    finally:
                        await store.aclose()

        run(main())

    def test_partial_failure_deny_decides_live_nodes(self):
        async def main():
            clock = ManualClock()
            dead = BucketStoreServer(InProcessBucketStore(clock=clock))
            await dead.start()
            async with BucketStoreServer(
                    InProcessBucketStore(clock=clock)) as live:
                store = ClusterBucketStore(
                    addresses=[(dead.host, dead.port),
                               (live.host, live.port)],
                    partial_failures="deny", request_timeout_s=2.0)
                try:
                    keys = [f"k{i}" for i in range(24)]
                    routes = [shard_of_key(k, 2) for k in keys]
                    assert 0 in routes and 1 in routes
                    await dead.aclose()
                    res = await store.acquire_many(keys, [1] * 24, 5.0, 1.0)
                    for i, r in enumerate(routes):
                        assert res.granted[i] == (r == 1), (i, r)
                finally:
                    await store.aclose()

        run(main())

    def test_partial_failure_raise_propagates(self):
        async def main():
            dead = BucketStoreServer(InProcessBucketStore())
            await dead.start()
            async with BucketStoreServer(InProcessBucketStore()) as live:
                store = ClusterBucketStore(
                    addresses=[(dead.host, dead.port),
                               (live.host, live.port)],
                    request_timeout_s=2.0)
                try:
                    await dead.aclose()
                    keys = [f"k{i}" for i in range(24)]
                    with pytest.raises(Exception):
                        await store.acquire_many(keys, [1] * 24, 5.0, 1.0)
                finally:
                    await store.aclose()

        run(main())


class TestLimitersOnCluster:
    def test_exact_limiter_shares_bucket_through_cluster(self):
        from distributedratelimiting.redis_tpu.models.options import (
            TokenBucketOptions,
        )
        from distributedratelimiting.redis_tpu.models.token_bucket import (
            TokenBucketRateLimiter,
        )

        async def main():
            store, _ = make_cluster(3, clock=ManualClock())
            lims = [TokenBucketRateLimiter(
                TokenBucketOptions(token_limit=6, instance_name="shared"),
                store) for _ in range(2)]
            granted = 0
            for lim in lims:
                for _ in range(6):
                    granted += (await lim.acquire_async(1)).is_acquired
            assert granted == 6  # one bucket on one owning node, not two

        run(main())

    def test_approximate_limiter_syncs_through_cluster(self):
        # The approximate algorithm's global counter is one key → one
        # node; two limiter instances sharing the cluster must converge
        # on it exactly as against a single store.
        from distributedratelimiting.redis_tpu.models.approximate import (
            ApproximateTokenBucketRateLimiter,
        )
        from distributedratelimiting.redis_tpu.models.options import (
            ApproximateTokenBucketOptions,
        )

        async def main():
            store, _ = make_cluster(3, clock=ManualClock())
            opts = ApproximateTokenBucketOptions(
                token_limit=100, tokens_per_period=10,
                replenishment_period_s=3600.0, instance_name="approx")
            a = ApproximateTokenBucketRateLimiter(opts, store)
            b = ApproximateTokenBucketRateLimiter(opts, store)
            for _ in range(30):
                assert a.acquire(1).is_acquired
            await a.refresh()     # push a's 30 into the shared counter
            await b.refresh()     # b pulls the global score
            assert b._global_score == pytest.approx(30.0)
            await a.aclose()
            await b.aclose()

        run(main())


class TestComposition:
    def test_cluster_of_fingerprint_stores(self):
        # Node-type agnosticism: a cluster whose nodes are device stores
        # with the device-resident directory — two orthogonal tiers
        # composing (client-side sharding × in-kernel key resolution).
        from distributedratelimiting.redis_tpu.runtime.fp_store import (
            FingerprintBucketStore,
        )

        async def main():
            clock = ManualClock()
            nodes = [FingerprintBucketStore(n_slots=256, clock=clock)
                     for _ in range(2)]
            store = ClusterBucketStore(stores=nodes)
            keys = [f"k{i}" for i in range(50)]
            res = await store.acquire_many(keys, [2] * 50, 5.0, 0.0)
            assert res.granted.all()
            res2 = await store.acquire_many(keys, [4] * 50, 5.0, 0.0)
            assert not res2.granted.any()  # 3 left of 5 per key
            # Per-key stickiness through both tiers.
            got = [(await store.acquire("k0", 1, 5.0, 0.0)).granted
                   for _ in range(4)]
            assert got == [True] * 3 + [False]
            await store.aclose()

        run(main())


class TestCheckpoint:
    def test_snapshot_restore_roundtrip(self):
        async def main():
            clock = ManualClock()
            store, _ = make_cluster(3, clock=clock)
            for i in range(12):
                await store.acquire(f"k{i}", 2, 5.0, 1.0)
            snap = store.snapshot()

            fresh, _ = make_cluster(3, clock=clock)
            fresh.restore(snap)
            # Restored consumption is visible: 3 left of 5 per key.
            res = await fresh.acquire_many(
                [f"k{i}" for i in range(12)], [4] * 12, 5.0, 1.0)
            assert not res.granted.any()

        run(main())

    def test_restore_topology_mismatch_rejected(self):
        store, _ = make_cluster(2)
        other, _ = make_cluster(3)
        with pytest.raises(ValueError, match="n_nodes"):
            other.restore(store.snapshot())
