"""L0 unit/property tests for the pure bucket math.

The kernel logic is deterministic given injected time (SURVEY.md §4
implication (a)); each semantic invariant from SURVEY.md §2 gets a direct
test here.
"""

import jax.numpy as jnp
import numpy as np

from distributedratelimiting.redis_tpu.ops import bucket_math as bm

TPS = bm.TICKS_PER_SECOND


def f32(x):
    return jnp.asarray(x, jnp.float32)


def i32(x):
    return jnp.asarray(x, jnp.int32)


class TestElapsed:
    def test_forward(self):
        assert bm.elapsed_ticks(i32(100), i32(40)) == 60

    def test_clock_regression_clamped_to_zero(self):
        # Invariant 1: failover to a store whose clock is behind must not
        # mint/destroy tokens (RedisTokenBucketRateLimiter.cs:218).
        assert bm.elapsed_ticks(i32(40), i32(100)) == 0


class TestRefill:
    def test_lazy_refill_linear(self):
        # 2 tokens/s, 3 s elapsed, from 1 token → 7
        out = bm.refill(f32(1.0), i32(0), i32(3 * TPS), 100.0, 2.0 / TPS)
        assert np.isclose(float(out), 7.0)

    def test_refill_clamped_to_capacity(self):
        # Invariant 2: forward jump grants at most one full bucket (:221).
        out = bm.refill(f32(1.0), i32(0), i32(10**9), 100.0, 2.0 / TPS)
        assert float(out) == 100.0

    def test_refill_never_negative_elapsed(self):
        out = bm.refill(f32(5.0), i32(1000), i32(500), 100.0, 2.0 / TPS)
        assert float(out) == 5.0


class TestRefillAndDecrement:
    def test_all_or_nothing(self):
        # Invariant 4: request of N succeeds iff refilled >= N (:224-227).
        tokens, ts, granted = bm.refill_and_decrement(
            f32([5.0, 5.0]), i32([0, 0]), jnp.array([True, True]),
            i32(0), i32([5, 6]), 10.0, 1.0 / TPS,
        )
        assert list(np.asarray(granted)) == [True, False]
        assert np.allclose(np.asarray(tokens), [0.0, 5.0])

    def test_init_on_miss_full_bucket(self):
        # A missing key starts FULL (:210-215) — wiped store self-heals.
        tokens, ts, granted = bm.refill_and_decrement(
            f32([123.0]), i32([999]), jnp.array([False]),
            i32(5), i32([4]), 10.0, 1.0 / TPS,
        )
        assert bool(granted[0])
        assert float(tokens[0]) == 6.0
        assert int(ts[0]) == 5

    def test_zero_count_probe_consumes_nothing(self):
        tokens, _, granted = bm.refill_and_decrement(
            f32([3.0]), i32([0]), jnp.array([True]),
            i32(0), i32([0]), 10.0, 1.0 / TPS,
        )
        assert bool(granted[0])
        assert float(tokens[0]) == 3.0

    def test_conservation_property(self, rng):
        # Over a random op sequence on one key: balance always in
        # [0, capacity]; grants exactly account for decrements.
        cap, rate = 50.0, 8.0 / TPS
        tokens, ts, exists = f32(0.0), i32(0), jnp.array(True)
        now = 0
        for _ in range(200):
            now += int(rng.integers(0, 2 * TPS))
            count = int(rng.integers(0, 12))
            prev = float(bm.refill(tokens, ts, i32(now), cap, rate))
            tokens, ts, granted = bm.refill_and_decrement(
                tokens, ts, exists, i32(now), i32(count), cap, rate
            )
            t = float(tokens)
            assert 0.0 <= t <= cap
            if bool(granted):
                assert np.isclose(t, prev - count, atol=1e-3)
            else:
                assert np.isclose(t, prev, atol=1e-3)
                assert prev < count


class TestTtl:
    def test_time_to_full(self):
        # 100-cap bucket at 40 tokens, 2 tokens/s → 30 s to full.
        ttl = bm.time_to_full_ttl(f32(40.0), 100.0, 2.0 / TPS)
        assert int(ttl) == 30 * TPS

    def test_clamped_to_min_1s(self):
        ttl = bm.time_to_full_ttl(f32(100.0), 100.0, 2.0 / TPS)
        assert int(ttl) == bm.MIN_TTL_TICKS

    def test_clamped_to_max(self):
        ttl = bm.time_to_full_ttl(f32(0.0), 1e9, 1e-12)
        assert int(ttl) <= min(bm.MAX_TTL_TICKS, 2**31 - 1)


class TestDecayAndAdd:
    def test_decay_formula(self):
        # new_v = max(0, v - delta*decay) + count  (:258)
        v, p, ts = bm.decay_and_add(
            f32(10.0), f32(float(TPS)), i32(0), jnp.array(True),
            i32(2 * TPS), f32(3.0), 2.0 / TPS,
        )
        assert np.isclose(float(v), 10.0 - 4.0 + 3.0)

    def test_decay_floor_zero(self):
        v, _, _ = bm.decay_and_add(
            f32(1.0), f32(0.0), i32(0), jnp.array(True),
            i32(100 * TPS), f32(5.0), 2.0 / TPS,
        )
        assert float(v) == 5.0

    def test_ewma(self):
        # new_p = 0.8*p + 0.2*delta  (:260-262)
        _, p, _ = bm.decay_and_add(
            f32(0.0), f32(1000.0), i32(0), jnp.array(True),
            i32(500), f32(0.0), 1.0 / TPS,
        )
        assert np.isclose(float(p), 0.8 * 1000.0 + 0.2 * 500.0)

    def test_init_on_miss(self):
        v, p, ts = bm.decay_and_add(
            f32(99.0), f32(99.0), i32(7), jnp.array(False),
            i32(1000), f32(4.0), 1.0 / TPS,
        )
        assert float(v) == 4.0
        assert float(p) == 1000.0  # stale ts masked on miss; seed = elapsed-from-epoch
        assert int(ts) == 1000


class TestInstanceEstimate:
    def test_k_clients(self):
        # k clients syncing every period → observed interval ≈ period/k.
        period = 1 * TPS
        for k in (1, 2, 5, 20):
            est = bm.instance_count_estimate(period, f32(period / k))
            assert int(est) == k

    def test_floor_one(self):
        est = bm.instance_count_estimate(TPS, f32(100 * TPS))
        assert int(est) == 1


class TestAvailableTokens:
    def test_fair_share_formula(self):
        # ceil((limit - global)/instances) - local  (:37)
        avail = bm.available_tokens(100.0, f32(40.0), 4, f32(5.0))
        assert float(avail) == 10.0  # ceil(60/4)=15, minus 5

    def test_floor_zero(self):
        avail = bm.available_tokens(100.0, f32(100.0), 1, f32(50.0))
        assert float(avail) == 0.0


class TestRetryAfter:
    def test_corrected_dimension(self):
        # deficit / rate, NOT deficit * rate (reference defect, SURVEY §2).
        # 10-token deficit at 2 tokens/s → 5 s.
        t = bm.retry_after_ticks(f32(10.0), 2.0 / TPS)
        assert int(t) == 5 * TPS


class TestSlidingWindow:
    W = 10 * TPS

    def test_advance_same_window(self):
        p, c, i = bm.sliding_window_advance(
            f32(3.0), f32(4.0), i32(5), jnp.array(True), i32(5 * self.W + 1), self.W
        )
        assert (float(p), float(c), int(i)) == (3.0, 4.0, 5)

    def test_advance_one_window_rolls(self):
        p, c, i = bm.sliding_window_advance(
            f32(3.0), f32(4.0), i32(5), jnp.array(True), i32(6 * self.W), self.W
        )
        assert (float(p), float(c), int(i)) == (4.0, 0.0, 6)

    def test_advance_two_windows_zeros(self):
        p, c, i = bm.sliding_window_advance(
            f32(3.0), f32(4.0), i32(5), jnp.array(True), i32(8 * self.W), self.W
        )
        assert (float(p), float(c), int(i)) == (0.0, 0.0, 8)

    def test_estimate_interpolation(self):
        # Halfway through current window: est = curr + 0.5*prev.
        est = bm.sliding_window_estimate(
            f32(10.0), f32(4.0), i32(6), i32(6 * self.W + self.W // 2), self.W
        )
        assert np.isclose(float(est), 4.0 + 5.0)

    def test_acquire_grant_and_deny(self):
        p, c, i, g = bm.sliding_window_acquire(
            f32(0.0), f32(8.0), i32(0), jnp.array(True),
            i32(1), i32(2), 10.0, self.W,
        )
        assert bool(g) and float(c) == 10.0
        p, c, i, g = bm.sliding_window_acquire(
            p, c, i, jnp.array(True), i32(2), i32(1), 10.0, self.W
        )
        assert not bool(g) and float(c) == 10.0


class TestDuplicatePrefix:
    def test_prefix_counts_earlier_same_slot(self):
        slots = jnp.array([3, 7, 3, 3, 7])
        counts = jnp.array([2, 5, 1, 4, 1])
        valid = jnp.array([True] * 5)
        pref = np.asarray(bm.duplicate_prefix(slots, counts, valid))
        assert list(pref) == [0.0, 0.0, 2.0, 3.0, 5.0]

    def test_invalid_rows_excluded(self):
        slots = jnp.array([3, 3, 3])
        counts = jnp.array([2, 5, 1])
        valid = jnp.array([True, False, True])
        pref = np.asarray(bm.duplicate_prefix(slots, counts, valid))
        assert list(pref) == [0.0, 2.0, 2.0]

    def test_precision_at_large_batch_demand(self):
        # Accumulation must stay per-key: with total batch demand far past
        # 2^24 (float32 integer precision), a whole-batch running sum would
        # corrupt same-slot prefixes and could over-admit duplicates.
        rng = np.random.default_rng(7)
        b = 4096
        slots = rng.integers(0, b, b).astype(np.int32)
        slots[100] = slots[50]  # guarantee at least one duplicate pair
        counts = rng.integers(1, 20_000, b).astype(np.int32)  # total ~41M
        valid = np.ones(b, bool)
        pref = np.asarray(
            bm.duplicate_prefix(jnp.asarray(slots), jnp.asarray(counts),
                                jnp.asarray(valid))
        )
        # Exact per-request expectation in int64.
        expected = np.zeros(b, np.int64)
        seen: dict[int, int] = {}
        for i in range(b):
            expected[i] = seen.get(int(slots[i]), 0)
            seen[int(slots[i])] = expected[i] + int(counts[i])
        np.testing.assert_array_equal(pref.astype(np.int64), expected)
