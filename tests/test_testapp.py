"""TestApp harness integration tests (SURVEY.md §4: multi-instance behavior
is exercised by multiple client processes on localhost sharing one store).

These spawn real subprocesses — the completed version of the reference's
Orleans-localhost multi-silo trick (TestApp/Program.cs:37-104)."""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTAPP = os.path.join(REPO_ROOT, "examples", "testapp.py")


def _run(args, timeout):
    env = dict(os.environ, JAX_PLATFORMS="cpu", DRLT_FORCE_CPU_PLATFORM="1")
    return subprocess.run(
        [sys.executable, TESTAPP, *args],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True,
        timeout=timeout,
    )


def test_single_process_smoke():
    proc = _run(["single", "--seconds", "1.5"], timeout=60)
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    # Burst capacity admits immediately; refill adds ~15 more over 1.5s.
    assert report["granted"] >= 100
    assert report["syncs"] > 0


def test_bulk_demo():
    proc = _run(["bulk", "--n", "5000", "--keys", "2000"], timeout=120)
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    # Fresh buckets/windows with limit 100 and ≤ ~3 hits per key: all grant.
    assert report["bucket_granted"] == 5000
    assert report["window_granted"] == 5000
    assert report["bucket_decisions_per_sec"] > 0


def test_cluster_demo():
    proc = _run(["cluster", "--nodes", "3", "--n", "300"], timeout=120)
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["granted_all_nodes_up"] == 300
    assert sum(report["key_spread"]) == 300
    after = report["after_node0_killed"]
    # Node 0's keys deny, every live node's key still grants.
    assert after["granted"] == 300 - report["key_spread"][0]
    assert after["live_node_grants"] == after["granted"]


def test_multi_process_convergence():
    proc = _run(["convergence", "--instances", "2", "--seconds", "5"],
                timeout=120)
    assert proc.returncode == 0, proc.stderr
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["converged"], summary
    assert len(summary["per_worker"]) == 2
    # Every instance actually served traffic against the shared store.
    assert all(r["granted"] > 0 for r in summary["per_worker"])
    assert summary["steady_state_granted"] <= summary["steady_state_bound"]


def test_multi_process_convergence_device_backend():
    """The PRODUCTION topology end to end: N OS worker processes → TCP →
    a server fronting the device-resident store (kernel launches decide the
    sync traffic). Device here is jax's platform in the child env (CPU in
    CI, TPU under axon) — same code path either way."""
    proc = _run(["convergence", "--instances", "2", "--seconds", "6",
                 "--backend", "device"], timeout=240)
    assert proc.returncode == 0, proc.stderr
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["converged"], summary
    assert all(r["granted"] > 0 for r in summary["per_worker"])
    # The workers' shares really came from the shared device store: each
    # instance saw the other (estimate > 1 means syncs flowed both ways).
    assert any(r["instance_count_estimate"] >= 2
               for r in summary["per_worker"])
