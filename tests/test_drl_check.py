"""The checkers get checked: each drl-check analyzer must (a) pass the
live tree — the repo ships conformant — and (b) catch its seeded
divergence EXACTLY once, with the right rule and file:line. The seeded
fixtures mutate copies of the real sources, so the wire/ABI tests also
pin that the extractors still recognize the real files' shapes (a
refactor that blinds an extractor fails the seeded test, not just the
live one)."""

from __future__ import annotations

import pathlib
import textwrap

import pytest

from tools.drl_check import (
    build_freshness,
    concurrency_lint,
    jax_lint,
    metric_names,
    run_all,
    wire_conformance,
)

ROOT = pathlib.Path(__file__).resolve().parents[1]
WIRE = ROOT / "distributedratelimiting" / "redis_tpu" / "runtime" / "wire.py"
SERVER = (ROOT / "distributedratelimiting" / "redis_tpu" / "runtime"
          / "server.py")
NATIVE_PY = (ROOT / "distributedratelimiting" / "redis_tpu" / "utils"
             / "native.py")
FRONTEND = ROOT / "native" / "frontend.cc"
DIRECTORY = ROOT / "native" / "directory.cc"


# -- the live tree is clean -------------------------------------------------

def test_live_tree_is_clean():
    findings = run_all(ROOT)
    assert findings == [], "\n".join(f.format() for f in findings)


def test_extractors_see_the_real_sources():
    """Guard against vacuous cleanliness: the models must be richly
    populated from the real files, or a parse regression would read as
    'clean'."""
    py = wire_conformance.extract_py_model(WIRE)
    c = wire_conformance.extract_c_model(FRONTEND)
    assert len(py.constants) >= 20 and len(py.structs) >= 8
    assert {"OP_ACQUIRE", "RESP_DECISION", "kVersion",
            "kMaxFrame"} <= set(c.constants)
    bound = wire_conformance._py_bound_symbols(NATIVE_PY)
    assert len([s for s in bound if s.startswith("fe_")]) >= 15
    assert len([s for s in bound if s.startswith("dir_")]) >= 10


# -- seeded divergences: wire constants / layout / ABI ----------------------

def _mutated_frontend(tmp_path: pathlib.Path, old: str, new: str
                      ) -> pathlib.Path:
    text = FRONTEND.read_text()
    assert old in text, f"fixture anchor gone from frontend.cc: {old!r}"
    out = tmp_path / "frontend.cc"
    out.write_text(text.replace(old, new, 1))
    return out


def test_wire_constant_drift_fires_once(tmp_path):
    cc = _mutated_frontend(tmp_path,
                           "constexpr uint8_t OP_FWINDOW = 9;",
                           "constexpr uint8_t OP_FWINDOW = 77;")
    findings = wire_conformance.check_wire(WIRE, cc, tmp_path)
    assert [f.rule for f in findings] == ["wire-const"]
    f = findings[0]
    assert "OP_FWINDOW" in f.message and "77" in f.message
    assert f.file.endswith("frontend.cc")
    assert FRONTEND.read_text().splitlines()[f.line - 1].startswith(
        "constexpr uint8_t OP_FWINDOW")  # same line in the original
    # The other side of the diff names wire.py's definition.
    assert any("wire.py" in rf for rf, _, _ in f.related)


def test_wire_version_drift_fires(tmp_path):
    cc = _mutated_frontend(tmp_path, "constexpr uint8_t kVersion = 4;",
                           "constexpr uint8_t kVersion = 5;")
    findings = wire_conformance.check_wire(WIRE, cc, tmp_path)
    assert [f.rule for f in findings] == ["wire-const"]
    assert "PROTOCOL_VERSION" in findings[0].message


def test_wire_layout_drift_fires_once(tmp_path):
    # Shift the second f64 of the keyed-request tail: field order/width
    # no longer matches struct _ACQ_TAIL ("<idd").
    cc = _mutated_frontend(tmp_path, "it.b = rd_f64(kp + klen + 12);",
                           "it.b = rd_f64(kp + klen + 8);")
    findings = wire_conformance.check_wire(WIRE, cc, tmp_path)
    assert [f.rule for f in findings] == ["wire-layout"]
    assert "_ACQ_TAIL" in findings[0].message


def test_bulk_head_layout_drift_fires_once(tmp_path):
    # Shift the second f64 of the bulk request head: the native bulk
    # parser no longer matches struct _BULK_REQ_HEAD ("<BddI").
    cc = _mutated_frontend(tmp_path, "double b = rd_f64(p + 9);",
                           "double b = rd_f64(p + 8);")
    findings = wire_conformance.check_wire(WIRE, cc, tmp_path)
    assert [f.rule for f in findings] == ["wire-layout"]
    assert "_BULK_REQ_HEAD" in findings[0].message


def test_bulk_head_size_drift_fires(tmp_path):
    cc = _mutated_frontend(tmp_path,
                           "constexpr size_t kBulkReqHead = 21;",
                           "constexpr size_t kBulkReqHead = 20;")
    findings = wire_conformance.check_wire(WIRE, cc, tmp_path)
    assert [f.rule for f in findings] == ["wire-const"]
    assert "BULK_REQ_HEAD_LEN" in findings[0].message


def test_bulk_kind_constant_drift_fires(tmp_path):
    cc = _mutated_frontend(tmp_path,
                           "constexpr uint8_t BULK_KIND_FWINDOW = 2;",
                           "constexpr uint8_t BULK_KIND_FWINDOW = 3;")
    findings = wire_conformance.check_wire(WIRE, cc, tmp_path)
    # The drifted value both disagrees with wire.py (wire-const) and
    # swallows BULK_KIND_HBUCKET under the C fast lane's kind gate
    # (wire-hier) — both findings are real.
    assert sorted(f.rule for f in findings) == ["wire-const",
                                                "wire-hier"]
    assert any("BULK_KIND_FWINDOW" in f.message for f in findings)


# -- seeded divergences: tenant-extension fallthrough (wire-hier) -----------

def test_hier_gate_removal_fires_once(tmp_path):
    """Dropping the bulk parser's unknown-kind gate would let C misparse
    HBUCKET frames — the rule must catch the gate's absence."""
    cc = _mutated_frontend(
        tmp_path,
        "if (kind > BULK_KIND_FWINDOW) return false;",
        "if (kind > BULK_KIND_HBUCKET) return false;")
    findings = wire_conformance.check_wire(WIRE, cc, tmp_path)
    assert [f.rule for f in findings] == ["wire-hier"]
    assert "handle_bulk_frame" in findings[0].message
    assert findings[0].file.endswith("frontend.cc")


def test_hier_scalar_fastpath_fires_once(tmp_path):
    """Case-listing OP_ACQUIRE_H in the scalar switch would parse the
    tenant-extended frame as the flat keyed shape (silently dropping
    the tenant level) — the rule pins the passthrough."""
    cc = _mutated_frontend(tmp_path, "case OP_ACQUIRE:",
                           "case OP_ACQUIRE_H:\n      case OP_ACQUIRE:")
    findings = wire_conformance.check_wire(WIRE, cc, tmp_path)
    assert [f.rule for f in findings] == ["wire-hier"]
    assert "OP_ACQUIRE_H" in findings[0].message
    # The other side of the diff names wire.py's definition.
    assert any("wire.py" in rf for rf, _, _ in findings[0].related)


def test_hier_surface_removal_fires(tmp_path):
    """A wire.py refactor that drops the extension pieces must fail the
    rule loudly (not read as vacuously clean)."""
    text = WIRE.read_text()
    anchor = "BULK_KIND_HBUCKET = 3"
    assert anchor in text
    mutated = tmp_path / "wire.py"
    mutated.write_text(text.replace(anchor, "_RETIRED_KIND = 3", 1))
    findings = wire_conformance.check_wire(mutated, FRONTEND, tmp_path)
    hier = [f for f in findings if f.rule == "wire-hier"]
    assert len(hier) == 1
    assert "BULK_KIND_HBUCKET" in hier[0].message


def test_bulk_abi_exports_are_bound():
    """Both directions of the round-8 ABI: every fe_bulk_*/fe_hot_*
    export has a ctypes binding and vice versa (the live-tree clean test
    covers it, but pin the symbols so a rename cannot silently drop the
    whole lane back to passthrough)."""
    bound = wire_conformance._py_bound_symbols(NATIVE_PY)
    exported = wire_conformance._c_exported_symbols(FRONTEND)
    for sym in ("fe_bulk_configure", "fe_bulk_meta", "fe_bulk_ptrs",
                "fe_bulk_complete", "fe_bulk_discard", "fe_bulk_fail",
                "fe_bulk_counts", "fe_bulk_id", "fe_hot_harvest"):
        assert sym in bound, sym
        assert sym in exported, sym


def test_shard_abi_exports_are_bound():
    """Both directions of the round-11 multi-shard ABI: the shard
    lifecycle exports (fe_start_sharded / fe_shard_count / fe_shard)
    and the C bulk load generator have ctypes bindings and vice versa —
    a rename on either side would silently degrade every multi-shard
    deployment to single-shard (has_shards feature detection reads the
    same symbols)."""
    bound = wire_conformance._py_bound_symbols(NATIVE_PY)
    exported = wire_conformance._c_exported_symbols(FRONTEND)
    for sym in ("fe_start_sharded", "fe_shard_count", "fe_shard",
                "fe_lg_bulk"):
        assert sym in bound, sym
        assert sym in exported, sym


def test_uring_abi_exports_are_bound():
    """Both directions of the round-16 uring ABI: the transport
    lifecycle exports (fe_start_sharded2 / fe_uring_*) and the uring
    bulk load generator have ctypes bindings and vice versa — a rename
    on either side would silently degrade every uring deployment to
    epoll (has_uring feature detection reads the same symbols)."""
    bound = wire_conformance._py_bound_symbols(NATIVE_PY)
    exported = wire_conformance._c_exported_symbols(FRONTEND)
    for sym in ("fe_start_sharded2", "fe_uring_available",
                "fe_uring_probe", "fe_uring_shards", "fe_uring_reason",
                "fe_uring_counts", "fe_lg_bulk_uring"):
        assert sym in bound, sym
        assert sym in exported, sym


def test_transport_flags_clean_on_live_tree():
    assert wire_conformance.check_transport_flags(
        NATIVE_PY, FRONTEND, ROOT) == []


def test_transport_flag_drift_fires_once(tmp_path):
    """Seeded divergence: drifting kUringSqpoll's value means an
    operator asking for SQPOLL gets a different transport with no error
    anywhere — the rule must catch it with both names in the message."""
    cc = _mutated_frontend(tmp_path, "constexpr int kUringSqpoll = 2;",
                           "constexpr int kUringSqpoll = 3;")
    findings = wire_conformance.check_transport_flags(NATIVE_PY, cc,
                                                      tmp_path)
    assert [f.rule for f in findings] == ["transport-flag"]
    f = findings[0]
    assert "kUringSqpoll" in f.message and "URING_SQPOLL" in f.message
    assert f.file.endswith("frontend.cc")
    assert any("native.py" in rf for rf, _, _ in f.related)


def test_transport_flag_missing_python_side_fires(tmp_path):
    """A native.py refactor that drops a mode constant must fail the
    rule loudly (not read as vacuously clean)."""
    text = NATIVE_PY.read_text()
    anchor = "URING_SQPOLL = 2"
    assert anchor in text
    mutated = tmp_path / "native.py"
    mutated.write_text(text.replace(anchor, "_RETIRED_MODE = 2", 1))
    findings = wire_conformance.check_transport_flags(mutated, FRONTEND,
                                                      tmp_path)
    assert [f.rule for f in findings] == ["transport-flag"]
    assert "URING_SQPOLL" in findings[0].message


def test_missing_fe_export_fires_both_ways(tmp_path):
    # Rename an exported symbol: the binding can't resolve (one finding
    # at the Python binding site) and the renamed export is dead surface
    # (one finding at the C definition site).
    cc = _mutated_frontend(tmp_path, "int fe_batch_n(void* h)",
                           "int fe_batch_count(void* h)")
    findings = wire_conformance.check_abi(NATIVE_PY, [cc, DIRECTORY],
                                          tmp_path)
    rules = sorted((f.rule, "fe_batch_n" in f.message
                    or "fe_batch_count" in f.message) for f in findings)
    assert rules == [("abi-export", True), ("abi-export", True)]
    by_file = {pathlib.Path(f.file).name for f in findings}
    assert by_file == {"native.py", "frontend.cc"}


def test_conditional_pylist_exports_are_recognized():
    """dir_*_pylist live inside #ifdef DRL_WITH_PYTHON; the extractor
    must still see them (they are feature-detected, not absent)."""
    exported = wire_conformance._c_exported_symbols(DIRECTORY)
    assert exported["dir_resolve_pylist"][1] is True  # conditional
    assert exported["dir_new"][1] is False


def test_endianness_must_be_pinned(tmp_path):
    wire = tmp_path / "wire.py"
    wire.write_text(WIRE.read_text().replace(
        '_DECISION = struct.Struct("<Bd")',
        '_DECISION = struct.Struct("Bd")', 1))
    findings = wire_conformance.check_wire(wire, FRONTEND, tmp_path)
    endian = [f for f in findings if f.rule == "wire-endian"]
    assert len(endian) == 1 and "_DECISION" in endian[0].message
    # Dropping '<' also changes the struct's size (native alignment pads
    # "Bd" to 16), so the layout cross-check fires alongside — both
    # symptoms of the same seeded bug, nothing else.
    assert {f.rule for f in findings} == {"wire-endian", "wire-layout"}


# -- seeded divergences: concurrency lint -----------------------------------

def test_lock_across_await_fires_once():
    src = textwrap.dedent("""\
        import asyncio

        class S:
            async def flush(self):
                with self._lock:
                    await self.store.sync()
    """)
    findings = concurrency_lint.check_source(src, "snippet.py")
    assert [(f.rule, f.line) for f in findings] == [
        ("lock-across-await", 5)]


def test_loop_affinity_violation_fires_once():
    src = textwrap.dedent("""\
        import asyncio

        class Pump:
            def on_ready(self, loop, coro):
                return loop.create_task(coro)
    """)
    findings = concurrency_lint.check_source(src, "snippet.py")
    assert [(f.rule, f.line) for f in findings] == [("task-off-loop", 5)]


def test_get_running_loop_guard_exempts():
    src = textwrap.dedent("""\
        import asyncio

        def spawn(coro):
            loop = asyncio.get_running_loop()
            return loop.create_task(coro)
    """)
    assert concurrency_lint.check_source(src, "snippet.py") == []


def test_blocking_call_in_async_fires_once():
    src = textwrap.dedent("""\
        import time

        async def handler():
            time.sleep(0.1)
    """)
    findings = concurrency_lint.check_source(src, "snippet.py")
    assert [(f.rule, f.line) for f in findings] == [("async-blocking", 4)]


def test_unguarded_loop_close_fires_and_guard_exempts():
    bad = textwrap.dedent("""\
        async def aclose(self):
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=5.0)
            self._loop.close()
    """)
    findings = [f for f in concurrency_lint.check_source(bad, "snippet.py")
                if f.rule == "unguarded-loop-close"]
    assert [(f.rule, f.line) for f in findings] == [
        ("unguarded-loop-close", 4)]
    good = bad.replace("self._loop.close()",
                       "if not self._thread.is_alive():\n"
                       "        pass\n"
                       "    else:\n"
                       "        self._loop.close()")
    # is_alive() anywhere in the function counts as the guard.
    assert not [f for f in concurrency_lint.check_source(good, "s.py")
                if f.rule == "unguarded-loop-close"]


def test_suppression_comment_silences_exactly_that_rule():
    src = textwrap.dedent("""\
        import asyncio

        class Pump:
            def on_ready(self, loop, coro):
                # drl-check: ok(task-off-loop)
                return loop.create_task(coro)
    """)
    assert concurrency_lint.check_source(src, "snippet.py") == []
    # A different rule's annotation does NOT silence it.
    wrong = src.replace("ok(task-off-loop)", "ok(async-blocking)")
    assert len(concurrency_lint.check_source(wrong, "snippet.py")) == 1


# -- seeded divergences: JAX lint -------------------------------------------

def test_traced_branch_fires_once():
    src = textwrap.dedent("""\
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("mode",))
        def kernel(x, n, mode="exact"):
            if x.shape[0] > 4:
                return x
            if mode == "exact":
                return x
            if n > 0:
                return x
            return x
    """)
    findings = jax_lint.check_source(src, "snippet.py")
    assert [(f.rule, f.line) for f in findings] == [("traced-branch", 10)]
    assert "'n'" in findings[0].message


def test_jit_rewrap_fires_once_and_cached_builder_exempt():
    src = textwrap.dedent("""\
        import functools
        import jax

        def hot_path(x):
            return jax.jit(lambda y: y + 1)(x)

        @functools.lru_cache
        def builder(n):
            return jax.jit(lambda y: y * n)
    """)
    findings = jax_lint.check_source(src, "snippet.py")
    assert [(f.rule, f.line) for f in findings] == [("jit-rewrap", 5)]


def test_static_unhashable_default_fires_once():
    src = textwrap.dedent("""\
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("cfg",))
        def kernel(x, cfg={}):
            return x
    """)
    findings = jax_lint.check_source(src, "snippet.py")
    assert [(f.rule, f.line) for f in findings] == [
        ("jit-static-unhashable", 5)]


def test_jit_f64_fires_on_each_spelling():
    """The three ways a 64-bit dtype sneaks into a jitted hot path —
    an attribute, an astype string, a dtype= keyword — each fire; the
    32-bit spellings stay silent."""
    src = textwrap.dedent("""\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(x, y):
            a = x.astype(jnp.float64)
            b = y.astype("int64")
            c = jnp.zeros((4,), dtype="float64")
            d = x.astype(jnp.float32) + jnp.int32(0)
            return a + b + c + d
    """)
    findings = jax_lint.check_source(src, "snippet.py")
    assert [(f.rule, f.line) for f in findings] == [
        ("jit-f64", 6), ("jit-f64", 7), ("jit-f64", 8)]
    # un-jitted code may hold f64 freely (host-side accounting)
    assert jax_lint.check_source(
        "import numpy as np\n\ndef host():\n"
        "    return np.float64(0.0)\n", "snippet.py") == []


def test_jit_f64_suppressible():
    src = textwrap.dedent("""\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(x):
            # drl-check: ok(jit-f64)
            return x.astype(jnp.float64)
    """)
    assert jax_lint.check_source(src, "snippet.py") == []


def test_jit_closed_scalar_fires_once_builders_exempt():
    """A nested jitted function closing over an enclosing local bakes
    the value into the trace (the retrace-per-value leak drl-xla's
    xla-retrace probes on the compiled side); an lru_cache'd builder
    and a closed-over helper FUNCTION are the two legitimate shapes."""
    src = textwrap.dedent("""\
        import functools
        import jax

        def make_kernel(cost, scale):
            def helper(v):
                return v + scale

            @jax.jit
            def kernel(x):
                return helper(x) * cost
            return kernel

        @functools.lru_cache(maxsize=8)
        def make_cached(cost):
            @jax.jit
            def kernel(x):
                return x * cost
            return kernel
    """)
    findings = jax_lint.check_source(src, "snippet.py")
    assert [(f.rule, f.line) for f in findings] == [
        ("jit-closed-scalar", 10)]
    assert "'cost'" in findings[0].message
    assert "xla-retrace" in findings[0].message


# -- build freshness --------------------------------------------------------

def _fake_native(tmp_path: pathlib.Path) -> pathlib.Path:
    native = tmp_path / "native"
    (native / "build").mkdir(parents=True)
    (native / "frontend.cc").write_text("// v1\n")
    (native / "directory.cc").write_text("// v1\n")
    return native


def test_stale_binary_fires_on_hash_mismatch(tmp_path):
    native = _fake_native(tmp_path)
    so = native / "build" / "_frontend.so"
    so.write_bytes(b"ELF")
    so.with_name("_frontend.so.hash").write_text("0" * 64 + "\n")
    findings = build_freshness.check_native_dir(native, tmp_path)
    assert [f.rule for f in findings] == ["stale-binary"]
    assert "_frontend.so" in findings[0].file


def test_stale_binary_fires_on_missing_sidecar(tmp_path):
    native = _fake_native(tmp_path)
    (native / "build" / "_directory.so").write_bytes(b"ELF")
    findings = build_freshness.check_native_dir(native, tmp_path)
    assert [f.rule for f in findings] == ["stale-binary"]
    assert "sidecar" in findings[0].message


def test_fresh_binary_is_clean(tmp_path):
    import hashlib

    native = _fake_native(tmp_path)
    so = native / "build" / "_frontend.so"
    so.write_bytes(b"ELF")
    src_hash = hashlib.sha256(
        (native / "frontend.cc").read_bytes()).hexdigest()
    so.with_name("_frontend.so.hash").write_text(src_hash + "\n")
    assert build_freshness.check_native_dir(native, tmp_path) == []


def test_no_binary_at_all_is_clean(tmp_path):
    native = _fake_native(tmp_path)
    assert build_freshness.check_native_dir(native, tmp_path) == []


# -- CLI --------------------------------------------------------------------

def test_cli_exit_codes(tmp_path):
    from tools.drl_check.__main__ import main

    assert main(["--root", str(ROOT)]) == 0
    # A seeded-divergent tree exits 1. Reuse the constant-drift fixture
    # through a minimal tree shim: real wire.py, mutated frontend.cc.
    shim = tmp_path / "repo"
    (shim / "distributedratelimiting" / "redis_tpu" / "runtime").mkdir(
        parents=True)
    (shim / "distributedratelimiting" / "redis_tpu" / "utils").mkdir()
    (shim / "native").mkdir()
    (shim / "distributedratelimiting" / "redis_tpu" / "runtime"
     / "wire.py").write_text(WIRE.read_text())
    (shim / "distributedratelimiting" / "redis_tpu" / "runtime"
     / "server.py").write_text(SERVER.read_text())
    (shim / "distributedratelimiting" / "redis_tpu" / "runtime"
     / "remote.py").write_text(REMOTE.read_text())
    (shim / "distributedratelimiting" / "redis_tpu" / "utils"
     / "native.py").write_text(NATIVE_PY.read_text())
    (shim / "native" / "frontend.cc").write_text(
        FRONTEND.read_text().replace("constexpr uint8_t OP_SEMA = 8;",
                                     "constexpr uint8_t OP_SEMA = 9;", 1))
    (shim / "native" / "directory.cc").write_text(DIRECTORY.read_text())
    assert main(["--root", str(shim), "--only", "wire"]) == 1


# -- seeded divergences: swallowed-exception ---------------------------------

RUNTIME_PATH = "distributedratelimiting/redis_tpu/runtime/snippet.py"


def test_swallowed_exception_fires_in_runtime_scope_only():
    src = textwrap.dedent("""
        def f():
            try:
                g()
            except Exception:
                pass
    """)
    findings = concurrency_lint.check_source(src, RUNTIME_PATH)
    assert [f.rule for f in findings] == ["swallowed-exception"]
    assert findings[0].line == 5
    # Outside runtime/, the identical handler is a deliberate non-goal.
    assert concurrency_lint.check_source(
        src, "distributedratelimiting/redis_tpu/models/snippet.py") == []


def test_swallowed_exception_bare_except_counts():
    src = textwrap.dedent("""
        def f():
            try:
                g()
            except:
                return None
    """)
    assert [f.rule for f in concurrency_lint.check_source(
        src, RUNTIME_PATH)] == ["swallowed-exception"]


def test_swallowed_exception_visible_handlers_exempt():
    bodies = [
        "log.error_evaluating_kernel(exc)",          # structured log
        "logger.warning('down: %r', exc)",           # any log spelling
        "raise",                                     # re-raise
        "self.metrics.sync_failures += 1",           # failure counter
        "fut.set_exception(exc)",                    # error routing
        "self.shed = self.shed + 1",                 # counter assignment
    ]
    for body in bodies:
        src = textwrap.dedent(f"""
            def f():
                try:
                    g()
                except Exception as exc:
                    {body}
        """)
        assert concurrency_lint.check_source(src, RUNTIME_PATH) == [], body


def test_swallowed_exception_typed_handlers_exempt():
    src = textwrap.dedent("""
        def f():
            try:
                g()
            except (ValueError, OSError):
                pass
    """)
    assert concurrency_lint.check_source(src, RUNTIME_PATH) == []


def test_swallowed_exception_suppressible():
    src = textwrap.dedent("""
        def f():
            try:
                g()
            # drl-check: ok(swallowed-exception)
            except Exception:
                pass
    """)
    assert concurrency_lint.check_source(src, RUNTIME_PATH) == []


# -- seeded divergences: wire-dispatch ---------------------------------------

def test_undispatched_op_fires_once(tmp_path):
    """Satellite: every OP_* in wire.py must have a server dispatch
    handler. An op constant nothing in server.py references fires
    wire-dispatch exactly once, with file:line on both sides."""
    mutated = tmp_path / "wire.py"
    text = WIRE.read_text()
    anchor = "OP_MIGRATE_PUSH = 17"
    assert anchor in text, "fixture anchor gone from wire.py"
    mutated.write_text(text.replace(
        anchor, anchor + "\nOP_GHOST = 99", 1))
    findings = wire_conformance.check_dispatch(mutated, SERVER, tmp_path)
    assert [f.rule for f in findings] == ["wire-dispatch"]
    f = findings[0]
    assert "OP_GHOST" in f.message and "99" in f.message
    assert f.file.endswith("wire.py")
    assert any("server.py" in rf for rf, _, _ in f.related)


def test_dispatch_covers_every_live_op():
    """The live pair is clean AND non-vacuously so: the extractor sees
    every op (including the round-6 placement/migration four) and the
    server references each."""
    assert wire_conformance.check_dispatch(WIRE, SERVER, ROOT) == []
    refs = wire_conformance._server_op_references(SERVER)
    py = wire_conformance.extract_py_model(WIRE)
    ops = {n for n in py.constants if n.startswith("OP_")}
    assert {"OP_PLACEMENT", "OP_PLACEMENT_ANNOUNCE", "OP_MIGRATE_PULL",
            "OP_MIGRATE_PUSH"} <= ops
    assert ops <= set(refs)
    assert len(ops) >= 17


# -- reservation lane (round 13: OP_RESERVE / OP_SETTLE) ---------------------

REMOTE_PATH = (ROOT / "distributedratelimiting" / "redis_tpu"
               / "runtime" / "remote.py")


def test_reserve_settle_ops_are_covered_everywhere():
    """Satellite: the two reservation ops exist in wire.py, are
    mirrored (value-diffed) in frontend.cc's passthrough constants,
    are dispatched by server.py, and sit in the client's post-send-
    retryable set (application-idempotent by reservation id)."""
    py = wire_conformance.extract_py_model(WIRE)
    c = wire_conformance.extract_c_model(FRONTEND)
    assert py.constants["OP_RESERVE"][0] == 20
    assert py.constants["OP_SETTLE"][0] == 21
    assert c.constants["OP_RESERVE"][0] == 20
    assert c.constants["OP_SETTLE"][0] == 21
    refs = wire_conformance._server_op_references(SERVER)
    assert {"OP_RESERVE", "OP_SETTLE"} <= set(refs)
    sets = wire_conformance._remote_op_sets(REMOTE_PATH)
    members, _line = sets["_IDEMPOTENT_OPS"]
    assert {"OP_RESERVE", "OP_SETTLE"} <= set(members)


def test_reserve_constant_drift_fires_wire_const(tmp_path):
    """Seeded divergence: frontend.cc disagreeing with wire.py about
    OP_RESERVE's value fires wire-const exactly once (the two new ops
    are diffed like every mirrored constant)."""
    cc = _mutated_frontend(tmp_path,
                           "constexpr uint8_t OP_RESERVE = 20;",
                           "constexpr uint8_t OP_RESERVE = 29;")
    findings = wire_conformance.check_wire(WIRE, cc, tmp_path)
    assert [f.rule for f in findings] == ["wire-const"]
    assert "OP_RESERVE" in findings[0].message


def test_settle_undispatched_fires_wire_dispatch(tmp_path):
    """Seeded divergence: a server.py that stops referencing
    wire.OP_SETTLE fires wire-dispatch for exactly that op."""
    mutated = tmp_path / "server.py"
    text = SERVER.read_text()
    assert "wire.OP_SETTLE" in text
    mutated.write_text(text.replace("wire.OP_SETTLE",
                                    "wire.OP_TRACES"))
    findings = wire_conformance.check_dispatch(WIRE, mutated, tmp_path)
    assert [f.rule for f in findings] == ["wire-dispatch"]
    assert "OP_SETTLE" in findings[0].message


def test_reserve_unclassified_fires_wire_idempotency(tmp_path):
    """Seeded divergence: dropping OP_RESERVE from the client's
    idempotent set (without adding it to the non-idempotent one) fires
    wire-idempotency — a future edit cannot silently make the op
    post-send-retry-unsafe by omission."""
    mutated = tmp_path / "remote.py"
    text = REMOTE_PATH.read_text()
    anchor = "    wire.OP_RESERVE, wire.OP_SETTLE,"
    assert anchor in text, "fixture anchor gone from remote.py"
    mutated.write_text(text.replace(anchor,
                                    "    wire.OP_SETTLE,", 1))
    findings = wire_conformance.check_idempotency(WIRE, mutated,
                                                  tmp_path)
    assert [f.rule for f in findings] == ["wire-idempotency"]
    assert "OP_RESERVE" in findings[0].message


# -- federation lane (round 15: OP_FED_LEASE / RENEW / RECLAIM) --------------

def test_federation_ops_are_covered_everywhere():
    """Satellite: the three federation ops exist in wire.py, are
    mirrored (value-diffed) in frontend.cc's passthrough constants,
    are dispatched by server.py, and sit in the client's post-send-
    retryable set (lease/reclaim replay recorded results, renew is
    absorbing — wire.py documents why)."""
    py = wire_conformance.extract_py_model(WIRE)
    c = wire_conformance.extract_c_model(FRONTEND)
    fed = {"OP_FED_LEASE": 22, "OP_FED_RENEW": 23,
           "OP_FED_RECLAIM": 24}
    for name, value in fed.items():
        assert py.constants[name][0] == value
        assert c.constants[name][0] == value
    refs = wire_conformance._server_op_references(SERVER)
    assert set(fed) <= set(refs)
    sets = wire_conformance._remote_op_sets(REMOTE_PATH)
    members, _line = sets["_IDEMPOTENT_OPS"]
    assert set(fed) <= set(members)


def test_fed_lease_constant_drift_fires_wire_const(tmp_path):
    """Seeded divergence: frontend.cc disagreeing with wire.py about
    OP_FED_LEASE's value fires wire-const exactly once."""
    cc = _mutated_frontend(tmp_path,
                           "constexpr uint8_t OP_FED_LEASE = 22;",
                           "constexpr uint8_t OP_FED_LEASE = 92;")
    findings = wire_conformance.check_wire(WIRE, cc, tmp_path)
    assert [f.rule for f in findings] == ["wire-const"]
    assert "OP_FED_LEASE" in findings[0].message


def test_fed_renew_undispatched_fires_wire_dispatch(tmp_path):
    """Seeded divergence: a server.py that stops referencing
    wire.OP_FED_RENEW fires wire-dispatch for exactly that op."""
    mutated = tmp_path / "server.py"
    text = SERVER.read_text()
    assert "wire.OP_FED_RENEW" in text
    mutated.write_text(text.replace("wire.OP_FED_RENEW",
                                    "wire.OP_FED_LEASE"))
    findings = wire_conformance.check_dispatch(WIRE, mutated, tmp_path)
    assert [f.rule for f in findings] == ["wire-dispatch"]
    assert "OP_FED_RENEW" in findings[0].message


def test_fed_reclaim_unclassified_fires_wire_idempotency(tmp_path):
    """Seeded divergence: dropping OP_FED_RECLAIM from the client's
    idempotent set (without adding it to the non-idempotent one) fires
    wire-idempotency."""
    mutated = tmp_path / "remote.py"
    text = REMOTE_PATH.read_text()
    anchor = ("    wire.OP_FED_LEASE, wire.OP_FED_RENEW, "
              "wire.OP_FED_RECLAIM,")
    assert anchor in text, "fixture anchor gone from remote.py"
    mutated.write_text(text.replace(
        anchor, "    wire.OP_FED_LEASE, wire.OP_FED_RENEW,", 1))
    findings = wire_conformance.check_idempotency(WIRE, mutated,
                                                  tmp_path)
    assert [f.rule for f in findings] == ["wire-idempotency"]
    assert "OP_FED_RECLAIM" in findings[0].message


def test_federation_flight_kind_is_registered():
    """The federation frame kind sits in REGISTERED_KINDS (the PR-14
    flight-kind rule then passes by construction) and the controller's
    federation sensor entries resolve against live registration sites
    (the metric-name rule's contract — checked live here, not just by
    the repo-wide sweep)."""
    from tools.drl_check import flight_kinds, metric_names

    fr = (ROOT / "distributedratelimiting" / "redis_tpu" / "utils"
          / "flight_recorder.py")
    kinds, _line = flight_kinds.registered_kinds(fr)
    assert "federation" in kinds
    controller = (ROOT / "distributedratelimiting" / "redis_tpu"
                  / "runtime" / "controller.py")
    subs = [s for s, _l in
            metric_names.controller_subscriptions(controller)]
    assert "drl_federation_outstanding_leases" in subs
    assert "drl_federation_region_degraded_now" in subs
    assert metric_names.check(ROOT) == []


# -- wire-idempotency (round 7) ---------------------------------------------

REMOTE = (ROOT / "distributedratelimiting" / "redis_tpu" / "runtime"
          / "remote.py")


def test_unclassified_op_fires_once(tmp_path):
    """Satellite: an OP_* in neither _IDEMPOTENT_OPS nor
    _NON_IDEMPOTENT_OPS fires wire-idempotency exactly once, naming the
    wire.py line and both classification sets."""
    mutated = tmp_path / "wire.py"
    text = WIRE.read_text()
    anchor = "OP_CONFIG = 18"
    assert anchor in text, "fixture anchor gone from wire.py"
    mutated.write_text(text.replace(
        anchor, anchor + "\nOP_GHOST = 99", 1))
    findings = wire_conformance.check_idempotency(mutated, REMOTE,
                                                  tmp_path)
    assert [f.rule for f in findings] == ["wire-idempotency"]
    f = findings[0]
    assert "OP_GHOST" in f.message and "neither" in f.message
    assert f.file.endswith("wire.py")
    assert len(f.related) == 2
    assert all(rf.endswith("remote.py") for rf, _, _ in f.related)


def test_doubly_classified_op_fires(tmp_path):
    """An op claimed by BOTH sets is a contradiction, not a pass."""
    mutated = tmp_path / "remote.py"
    text = REMOTE.read_text()
    anchor = "    wire.OP_ACQUIRE, wire.OP_WINDOW"
    assert anchor in text, "fixture anchor gone from remote.py"
    mutated.write_text(text.replace(
        anchor, "    wire.OP_PEEK,\n" + anchor, 1))
    findings = wire_conformance.check_idempotency(WIRE, mutated,
                                                  tmp_path)
    assert [f.rule for f in findings] == ["wire-idempotency"]
    f = findings[0]
    assert "OP_PEEK" in f.message and "BOTH" in f.message
    notes = {note for _, _, note in f.related}
    assert any("_IDEMPOTENT_OPS" in n for n in notes)
    assert any("_NON_IDEMPOTENT_OPS" in n for n in notes)


def test_missing_classification_set_fires(tmp_path):
    """remote.py losing one of the two sets entirely is itself a
    finding — the rule must not silently pass a refactor that deletes
    the classification."""
    mutated = tmp_path / "remote.py"
    mutated.write_text("import wire\n_IDEMPOTENT_OPS = frozenset()\n")
    findings = wire_conformance.check_idempotency(WIRE, mutated,
                                                  tmp_path)
    assert [f.rule for f in findings] == ["wire-idempotency"]
    assert "_NON_IDEMPOTENT_OPS" in findings[0].message


# -- metric-name (round 12: the controller's sensor contract) ---------------

CONTROLLER = (ROOT / "distributedratelimiting" / "redis_tpu" / "runtime"
              / "controller.py")
CLUSTER = (ROOT / "distributedratelimiting" / "redis_tpu" / "runtime"
           / "cluster.py")


def test_metric_names_see_the_real_sources():
    """Non-vacuous cleanliness: the extractor reads a richly populated
    subscription list AND registration map from the live tree."""
    subs = metric_names.controller_subscriptions(CONTROLLER)
    assert len(subs) >= 5
    names = {n for n, _ in subs}
    assert "drl_token_velocity" in names
    assert "drl_cluster_breaker_state" in names
    from tools.drl_check.common import iter_py_files

    exact, prefixes = metric_names.registered_families(
        iter_py_files(ROOT / "distributedratelimiting"))
    assert len(exact) >= 20 and len(prefixes) >= 5
    assert "drl_requests_served" in exact
    assert "drl_controller" in prefixes  # register_numeric_dict family
    assert "drl_controller_actions" in exact  # labeled_counters family


def test_unregistered_sensor_series_fires_once(tmp_path):
    """Satellite: a series the controller subscribes to that no
    registry emits fires metric-name exactly once, file:line on both
    sides (subscription element + nearest registration site)."""
    text = CONTROLLER.read_text()
    anchor = '    "drl_requests_served",'
    assert anchor in text, "fixture anchor gone from controller.py"
    mutated = tmp_path / "controller.py"
    mutated.write_text(text.replace(
        anchor, anchor + '\n    "drl_ghost_series",', 1))
    findings = metric_names.check_sources(
        mutated, [SERVER, CLUSTER], tmp_path)
    assert [f.rule for f in findings] == ["metric-name"]
    f = findings[0]
    assert "drl_ghost_series" in f.message
    assert f.file.endswith("controller.py")
    assert mutated.read_text().splitlines()[f.line - 1].strip() \
        .startswith('"drl_ghost_series",')
    # The other side names a real registration site.
    assert f.related and any(rf.endswith(".py") for rf, _, _ in f.related)


def test_renamed_emitting_family_fires(tmp_path):
    """The drift this rule exists for: renaming the EMITTING family
    (server registry) blinds the subscribed sensor — caught statically,
    not discovered as a zero-reading controller in production."""
    mutated_server = tmp_path / "server.py"
    text = SERVER.read_text()
    anchor = 'reg.counter("admitted_tokens",'
    assert anchor in text, "fixture anchor gone from server.py"
    mutated_server.write_text(text.replace(
        anchor, 'reg.counter("admitted_tokens_renamed",', 1))
    findings = metric_names.check_sources(
        CONTROLLER, [mutated_server, CLUSTER], tmp_path)
    assert [f.rule for f in findings] == ["metric-name"]
    assert "drl_admitted_tokens" in findings[0].message


def test_metric_name_suppressible(tmp_path):
    text = CONTROLLER.read_text()
    anchor = '    "drl_requests_served",'
    mutated = tmp_path / "controller.py"
    mutated.write_text(text.replace(
        anchor,
        anchor + '\n    # drl-check: ok(metric-name)'
                 '\n    "drl_external_series",', 1))
    assert metric_names.check_sources(
        mutated, [SERVER, CLUSTER], tmp_path) == []


def test_numeric_dict_prefix_matches(tmp_path):
    """A subscription under a register_numeric_dict prefix family
    (dynamic per-key suffixes) resolves — e.g. drl_tier0_syncs."""
    text = CONTROLLER.read_text()
    anchor = '    "drl_requests_served",'
    mutated = tmp_path / "controller.py"
    mutated.write_text(text.replace(
        anchor, anchor + '\n    "drl_tier0_syncs",', 1))
    assert metric_names.check_sources(
        mutated, [SERVER, CLUSTER], tmp_path) == []


# -- flight-kind (round 14: the frame-kind registry) -------------------------

FLIGHT = (ROOT / "distributedratelimiting" / "redis_tpu" / "utils"
          / "flight_recorder.py")


def test_flight_kind_extractor_sees_the_real_table():
    """Non-vacuous cleanliness: the registry anchor exists and carries
    every kind the runtime records today."""
    from tools.drl_check import flight_kinds

    kinds, line = flight_kinds.registered_kinds(FLIGHT)
    assert {"flush", "t0_sync", "breaker", "node_error", "controller",
            "reservation", "header"} <= kinds
    assert line > 0
    # A refactor that drops the table must be LOUD, never vacuous.
    import pytest as _pytest
    mutated_text = FLIGHT.read_text().replace("REGISTERED_KINDS",
                                              "_RETIRED_KINDS")
    import tempfile
    with tempfile.NamedTemporaryFile("w", suffix=".py",
                                     delete=False) as f:
        f.write(mutated_text)
    with _pytest.raises(RuntimeError):
        flight_kinds.registered_kinds(pathlib.Path(f.name))


def test_flight_kind_typo_fires_once_both_sides():
    """Seeded divergence: a typo'd record() kind and a typo'd
    frames(kind=) filter each fire exactly once, with the registry
    table as the other side of the diff."""
    from tools.drl_check import flight_kinds

    kinds, line = flight_kinds.registered_kinds(FLIGHT)
    src = ('rec.record("flsh", n=1)\n'
           'frames_list = fr.frames(kind="contoller")\n'
           'import numpy as np\n'
           'np.argsort(x, kind="stable")\n'      # not a frames() call
           'session.record(cmd)\n')              # not a literal kind
    findings = flight_kinds.check_sources([("t.py", src)], kinds,
                                          "fr.py", line)
    assert [(f.rule, f.line) for f in findings] == [
        ("flight-kind", 1), ("flight-kind", 2)]
    assert "flsh" in findings[0].message
    assert "contoller" in findings[1].message
    for f in findings:
        assert f.related and f.related[0][0] == "fr.py"


def test_flight_kind_suppressible_and_live_clean():
    from tools.drl_check import flight_kinds

    kinds, line = flight_kinds.registered_kinds(FLIGHT)
    src = ('# drl-check: ok(flight-kind)\n'
           'rec.record("foreign-kind", n=1)\n')
    assert flight_kinds.check_sources([("t.py", src)], kinds,
                                      "fr.py", line) == []
    assert flight_kinds.check(ROOT) == []


# -- stale-suppression (round 14: dead ok(...) comments) ----------------------

def test_stale_suppression_fires_on_orphaned_comment():
    from tools.drl_check import stale_suppression

    src = ("def f():\n"
           "    # drl-check: ok(task-off-loop)\n"
           "    return 1\n")
    findings = stale_suppression.check_source_entries(
        ROOT, "distributedratelimiting/redis_tpu/runtime/x.py", src)
    assert [(f.rule, f.line) for f in findings] == [
        ("stale-suppression", 2)]
    assert "no longer fires" in findings[0].message


def test_stale_suppression_unknown_and_dead_rules_fire():
    from tools.drl_check import stale_suppression

    unknown = "X = 1  # drl-check: ok(task-of-loop)\n"
    findings = stale_suppression.check_source_entries(
        ROOT, "x.py", unknown)
    assert [f.rule for f in findings] == ["stale-suppression"]
    assert "unknown rule" in findings[0].message

    dead = "X = 1  # drl-check: ok(wire-const)\n"
    findings = stale_suppression.check_source_entries(
        ROOT, "x.py", dead)
    assert [f.rule for f in findings] == ["stale-suppression"]
    assert "never honors inline suppression" in findings[0].message


def test_stale_suppression_live_comment_and_escape_hatch_pass():
    from tools.drl_check import stale_suppression

    live = ("import asyncio\n"
            "class P:\n"
            "    def cb(self, loop, coro):\n"
            "        # drl-check: ok(task-off-loop)\n"
            "        return loop.create_task(coro)\n")
    assert stale_suppression.check_source_entries(
        ROOT, "distributedratelimiting/redis_tpu/runtime/x.py",
        live) == []
    hatch = ("def f():\n"
             "    # drl-check: ok(task-off-loop, stale-suppression)\n"
             "    return 1\n")
    assert stale_suppression.check_source_entries(
        ROOT, "x.py", hatch) == []


def test_stale_suppression_whitespace_tolerant_neutralizer():
    """Review hardening: a live suppression with non-canonical spacing
    (which common.Suppressions honors) must not be falsely staled —
    the neutralizer operates through the SAME regex."""
    from tools.drl_check import stale_suppression

    src = ("import time\n"
           "def f():\n"
           "    time.sleep(1)  # drl-check:  ok(async-blocking)\n")
    # Sync function: async-blocking doesn't fire -> stale, detected
    # even with the odd spacing (the comment IS recognized).
    assert [f.rule for f in stale_suppression.check_source_entries(
        ROOT, "x.py", src)] == ["stale-suppression"]
    live = ("import time\n"
            "async def f():\n"
            "    time.sleep(1)  # drl-check:  ok(async-blocking)\n")
    assert stale_suppression.check_source_entries(
        ROOT, "x.py", live) == []


def test_stale_suppression_metric_name_is_file_scoped():
    """A metric-name suppression OUTSIDE controller.py is dead by
    location — it must fire regardless of any coincidental line-number
    collision with a controller.py finding."""
    from tools.drl_check import stale_suppression

    src = ("X = 1\n# drl-check: ok(metric-name)\nY = 2\n")
    findings = stale_suppression.check_source_entries(
        ROOT, "distributedratelimiting/redis_tpu/runtime/cluster_x.py",
        src)
    assert [f.rule for f in findings] == ["stale-suppression"]


def test_stale_suppression_live_tree_swept_clean():
    """The satellite's sweep: every suppression in the tree either
    still fires its rule or was deleted in this PR."""
    from tools.drl_check import stale_suppression

    assert stale_suppression.check(ROOT) == []


def test_idempotency_covers_every_live_op():
    """The live tree is clean AND non-vacuously so — OP_CONFIG included,
    and both sets are seen with sane populations."""
    assert wire_conformance.check_idempotency(WIRE, REMOTE, ROOT) == []
    sets = wire_conformance._remote_op_sets(REMOTE)
    assert set(sets) == {"_IDEMPOTENT_OPS", "_NON_IDEMPOTENT_OPS"}
    idem = set(sets["_IDEMPOTENT_OPS"][0])
    non = set(sets["_NON_IDEMPOTENT_OPS"][0])
    assert "OP_CONFIG" in idem
    assert "OP_ACQUIRE" in non and "OP_ACQUIRE_MANY" in non
    assert not (idem & non)
    py = wire_conformance.extract_py_model(WIRE)
    ops = {n for n in py.constants if n.startswith("OP_")}
    assert ops == idem | non
    assert len(ops) >= 18
