"""Pallas streaming-sweep kernel vs the XLA reference (interpret mode on
the CPU mesh; the same code path compiles with Mosaic on real TPU)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributedratelimiting.redis_tpu.ops import kernels as K
from distributedratelimiting.redis_tpu.ops.pallas_kernels import (
    sweep_expired_pallas,
)

INTERPRET = jax.devices()[0].platform != "tpu"


def _random_state(n, seed):
    rng = np.random.default_rng(seed)
    return (
        rng.uniform(0, 100, n).astype(np.float32),
        rng.integers(0, 1000, n).astype(np.int32),
        rng.random(n) < 0.5,
    )


@pytest.mark.parametrize("n", [4096, 100_000, 65_536])
def test_matches_xla_sweep(n):
    tokens_np, last_np, exists_np = _random_state(n, seed=n)
    now, cap, rate = 2_000_000, 100.0, 0.001

    new_exists, mask, counts = sweep_expired_pallas(
        jnp.asarray(tokens_np), jnp.asarray(last_np),
        jnp.asarray(exists_np.astype(np.int8)),
        now, cap, rate, interpret=INTERPRET,
    )
    _, freed = K.sweep_expired(
        K.BucketState(jnp.asarray(tokens_np), jnp.asarray(last_np),
                      jnp.asarray(exists_np)),
        jnp.int32(now), jnp.float32(cap), jnp.float32(rate),
    )
    ref = np.asarray(freed)
    assert np.array_equal(np.asarray(mask).astype(bool), ref)
    assert int(np.asarray(counts).sum()) == int(ref.sum())
    assert np.array_equal(np.asarray(new_exists).astype(bool),
                          exists_np & ~ref)


def test_nothing_expired_counts_zero():
    n = 8192
    tokens_np, last_np, exists_np = _random_state(n, seed=1)
    # now == max(last_ts): nothing can have passed its >= 1 s TTL.
    _, mask, counts = sweep_expired_pallas(
        jnp.asarray(tokens_np), jnp.asarray(last_np),
        jnp.asarray(exists_np.astype(np.int8)),
        int(last_np.max()), 100.0, 0.001, interpret=INTERPRET,
    )
    assert int(np.asarray(counts).sum()) == 0
    assert not np.asarray(mask).any()


def test_padding_rows_never_expire():
    # n deliberately NOT a multiple of the kernel tile: padding rows carry
    # exists=0 and must not appear in mask or counts.
    n = 1000
    tokens_np, last_np, exists_np = _random_state(n, seed=2)
    exists_np[:] = True
    _, mask, counts = sweep_expired_pallas(
        jnp.asarray(tokens_np), jnp.asarray(last_np),
        jnp.asarray(exists_np.astype(np.int8)),
        10_000_000, 100.0, 0.001, interpret=INTERPRET,
    )
    assert np.asarray(mask).shape == (n,)
    assert int(np.asarray(counts).sum()) == n  # all live rows expired ...
    assert np.asarray(mask).all()              # ... and only live rows
