"""L1 kernel tests: the jitted batch kernels vs a serial Python simulator.

The simulator replays the reference's Lua semantics one request at a time
(``RedisTokenBucketRateLimiter.cs:176-239``); the batch kernel must agree on
every grant/state when batches are duplicate-free, and must never over-admit
when they are not (conservative in-batch serialization).
"""

import jax.numpy as jnp
import numpy as np

from distributedratelimiting.redis_tpu.ops import bucket_math as bm
from distributedratelimiting.redis_tpu.ops import kernels as K

TPS = bm.TICKS_PER_SECOND


class SerialBucketSim:
    """Pure-Python serial replay of the exact-bucket Lua kernel."""

    def __init__(self, n, capacity, fill_rate_per_tick):
        self.tokens = np.zeros(n)
        self.last_ts = np.zeros(n, np.int64)
        self.exists = np.zeros(n, bool)
        self.cap = capacity
        self.rate = fill_rate_per_tick

    def acquire(self, slot, count, now):
        if not self.exists[slot]:
            refilled = self.cap
        else:
            delta = max(0, now - self.last_ts[slot])
            refilled = min(self.cap, self.tokens[slot] + delta * self.rate)
        granted = refilled >= count
        self.tokens[slot] = refilled - (count if granted else 0)
        self.last_ts[slot] = now
        self.exists[slot] = True
        return granted


def run_batch(state, slots, counts, now, cap, rate, handle_duplicates=True):
    b = len(slots)
    return K.acquire_batch(
        state,
        jnp.asarray(slots, jnp.int32),
        jnp.asarray(counts, jnp.int32),
        jnp.ones((b,), bool),
        jnp.asarray(now, jnp.int32),
        jnp.float32(cap),
        jnp.float32(rate),
        handle_duplicates=handle_duplicates,
    )


class TestAcquireBatch:
    def test_matches_serial_sim_unique_slots(self, rng):
        n, cap, rate = 64, 20.0, 4.0 / TPS
        state = K.init_bucket_state(n)
        sim = SerialBucketSim(n, cap, rate)
        now = 0
        for _ in range(30):
            now += int(rng.integers(0, TPS))
            batch = rng.choice(n, size=16, replace=False)
            counts = rng.integers(0, 8, size=16)
            state, granted, remaining = run_batch(state, batch, counts, now, cap, rate)
            granted = np.asarray(granted)
            for s, c, g in zip(batch, counts, granted):
                assert sim.acquire(s, c, now) == g, (s, c, now)
            np.testing.assert_allclose(
                np.asarray(state.tokens)[batch], sim.tokens[batch], atol=1e-2
            )

    def test_duplicates_never_over_admit(self, rng):
        # Many requests to few slots in one batch: total granted per slot
        # must fit within that slot's refilled balance (invariant 3 at batch
        # granularity), regardless of grant pattern.
        n, cap, rate = 8, 10.0, 0.0
        for trial in range(10):
            state = K.init_bucket_state(n)
            slots = rng.integers(0, n, size=64)
            counts = rng.integers(1, 6, size=64)
            state, granted, _ = run_batch(state, slots, counts, 1, cap, rate)
            granted = np.asarray(granted)
            for s in range(n):
                m = slots == s
                assert counts[m][granted[m]].sum() <= cap

    def test_duplicates_serialize_in_batch_order(self):
        # capacity 10, zero rate: requests [6, 6, 3] to one slot →
        # serial order grants 6, denies 6, conservative prefix denies 3 too
        # (prefix counts the denied 6) — allowed to under-admit, never over.
        state = K.init_bucket_state(4)
        state, granted, _ = run_batch(state, [2, 2, 2], [6, 6, 3], 1, 10.0, 0.0)
        g = list(np.asarray(granted))
        assert g[0] is np.True_
        assert g[1] is np.False_
        assert float(state.tokens[2]) == 4.0

    def test_padding_rows_untouched(self):
        state = K.init_bucket_state(4)
        b = 4
        state, granted, remaining = K.acquire_batch(
            state,
            jnp.asarray([1, -1, 2, -1], jnp.int32),
            jnp.asarray([3, 5, 2, 7], jnp.int32),
            jnp.asarray([True, False, True, False]),
            jnp.int32(10),
            jnp.float32(10.0),
            jnp.float32(0.0),
        )
        assert list(np.asarray(granted)) == [True, False, True, False]
        assert not bool(state.exists[0]) and not bool(state.exists[3])
        assert bool(state.exists[1]) and bool(state.exists[2])

    def test_fast_path_no_duplicates_flag(self, rng):
        n, cap, rate = 32, 15.0, 2.0 / TPS
        s1 = K.init_bucket_state(n)
        s2 = K.init_bucket_state(n)
        slots = rng.choice(n, size=8, replace=False)
        counts = rng.integers(0, 6, size=8)
        s1, g1, r1 = run_batch(s1, slots, counts, 100, cap, rate, True)
        s2, g2, r2 = run_batch(s2, slots, counts, 100, cap, rate, False)
        np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))
        np.testing.assert_allclose(np.asarray(s1.tokens), np.asarray(s2.tokens))


class TestSyncBatch:
    def test_decay_add_and_ewma(self):
        state = K.init_counter_state(4)
        decay = 2.0 / TPS
        # First sync at t=TPS: init-on-miss, v = count, p = now (epoch delta).
        state, v, p = K.sync_batch(
            state, jnp.asarray([1], jnp.int32), jnp.asarray([6.0], jnp.float32),
            jnp.asarray([True]), jnp.int32(TPS), jnp.float32(decay),
        )
        assert float(v[0]) == 6.0
        assert float(p[0]) == TPS
        # Second sync 2 s later: v = max(0, 6 - 4) + 5 = 7.
        state, v, p = K.sync_batch(
            state, jnp.asarray([1], jnp.int32), jnp.asarray([5.0], jnp.float32),
            jnp.asarray([True]), jnp.int32(3 * TPS), jnp.float32(decay),
        )
        assert np.isclose(float(v[0]), 7.0)
        assert np.isclose(float(p[0]), 0.8 * TPS + 0.2 * 2 * TPS)
        assert float(state.value[1]) == float(v[0])

    def test_independent_counters(self):
        state = K.init_counter_state(8)
        state, v, _ = K.sync_batch(
            state, jnp.asarray([0, 5], jnp.int32),
            jnp.asarray([3.0, 9.0], jnp.float32),
            jnp.asarray([True, True]), jnp.int32(10), jnp.float32(0.0),
        )
        assert list(np.asarray(v)) == [3.0, 9.0]
        assert float(state.value[5]) == 9.0


class TestWindowAcquireBatch:
    W = 10 * TPS

    def test_grant_then_deny_at_limit(self):
        state = K.init_window_state(4)
        state, g, r = K.window_acquire_batch(
            state, jnp.asarray([1], jnp.int32), jnp.asarray([8], jnp.int32),
            jnp.asarray([True]), jnp.int32(1), jnp.float32(10.0),
            jnp.int32(self.W),
        )
        assert bool(g[0])
        state, g, r = K.window_acquire_batch(
            state, jnp.asarray([1], jnp.int32), jnp.asarray([5], jnp.int32),
            jnp.asarray([True]), jnp.int32(2), jnp.float32(10.0),
            jnp.int32(self.W),
        )
        assert not bool(g[0])

    def test_window_rolloff_readmits(self):
        state = K.init_window_state(4)
        state, g, _ = K.window_acquire_batch(
            state, jnp.asarray([1], jnp.int32), jnp.asarray([10], jnp.int32),
            jnp.asarray([True]), jnp.int32(1), jnp.float32(10.0),
            jnp.int32(self.W),
        )
        assert bool(g[0])
        # Two full windows later the old consumption is gone entirely.
        state, g, _ = K.window_acquire_batch(
            state, jnp.asarray([1], jnp.int32), jnp.asarray([10], jnp.int32),
            jnp.asarray([True]), jnp.int32(2 * self.W + 1), jnp.float32(10.0),
            jnp.int32(self.W),
        )
        assert bool(g[0])


class TestSweep:
    def test_evicts_idle_full_buckets_only(self):
        cap, rate = 10.0, 1.0 / TPS  # time-to-full from empty = 10 s
        state = K.init_bucket_state(4)
        # Slot 1 drained at t=0; slot 2 untouched (doesn't exist).
        state, g, _ = run_batch(state, [1], [10], 0, cap, rate)
        assert bool(g[0])
        # 5 s later: not yet refillable to full → kept.
        state, freed = K.sweep_expired(
            state, jnp.int32(5 * TPS), jnp.float32(cap), jnp.float32(rate)
        )
        assert not bool(freed[1]) and bool(state.exists[1])
        # 10 s later: bucket would be full → evicted.
        state, freed = K.sweep_expired(
            state, jnp.int32(10 * TPS), jnp.float32(cap), jnp.float32(rate)
        )
        assert bool(freed[1]) and not bool(state.exists[1])
        assert not bool(freed[2])

    def test_evicted_slot_reinitializes_full(self):
        cap, rate = 10.0, 1.0 / TPS
        state = K.init_bucket_state(4)
        state, _, _ = run_batch(state, [1], [10], 0, cap, rate)
        state, _ = K.sweep_expired(
            state, jnp.int32(20 * TPS), jnp.float32(cap), jnp.float32(rate)
        )
        # Init-on-miss semantics: next touch sees a full bucket.
        state, g, _ = run_batch(state, [1], [10], 20 * TPS + 1, cap, rate)
        assert bool(g[0])


class TestPeek:
    def test_readonly_estimate(self):
        cap, rate = 10.0, 2.0 / TPS
        state = K.init_bucket_state(4)
        state, _, _ = run_batch(state, [1], [8], 0, cap, rate)
        est = K.peek_batch(
            state, jnp.asarray([1, 2], jnp.int32), jnp.asarray([True, True]),
            jnp.int32(2 * TPS), jnp.float32(cap), jnp.float32(rate),
        )
        assert float(est[0]) == 6.0   # 2 + 2*2
        assert float(est[1]) == 10.0  # missing key reads full
        # State unchanged by peek.
        assert float(state.tokens[1]) == 2.0


class TestSlotValidation:
    def test_out_of_range_slot_is_denied_not_phantom_granted(self):
        state = K.init_bucket_state(4)
        state, granted, _ = K.acquire_batch(
            state,
            jnp.asarray([7, 1], jnp.int32),  # 7 out of range for N=4
            jnp.asarray([1, 1], jnp.int32),
            jnp.asarray([True, True]),
            jnp.int32(0), jnp.float32(10.0), jnp.float32(0.0),
        )
        assert list(np.asarray(granted)) == [False, True]
        assert not bool(state.exists[3])  # no wrap/clamp write


class TestAuxSweepsAndRebase:
    def test_counter_sweep_86400s_ttl(self):
        state = K.init_counter_state(4)
        state, _, _ = K.sync_batch(
            state, jnp.asarray([2], jnp.int32), jnp.asarray([5.0], jnp.float32),
            jnp.asarray([True]), jnp.int32(0), jnp.float32(0.0),
        )
        state, freed = K.sweep_counters(state, jnp.int32(bm.GLOBAL_COUNTER_TTL_TICKS))
        assert bool(freed[2]) and not bool(state.exists[2])

    def test_window_sweep_two_idle_windows(self):
        W = 10 * TPS
        state = K.init_window_state(4)
        state, g, _ = K.window_acquire_batch(
            state, jnp.asarray([1], jnp.int32), jnp.asarray([1], jnp.int32),
            jnp.asarray([True]), jnp.int32(1), jnp.float32(10.0), jnp.int32(W),
        )
        state, freed = K.sweep_windows(state, jnp.int32(W + 1), jnp.int32(W))
        assert not bool(freed[1])
        state, freed = K.sweep_windows(state, jnp.int32(2 * W + 1), jnp.int32(W))
        assert bool(freed[1]) and not bool(state.exists[1])

    def test_epoch_rebase_preserves_elapsed(self):
        cap, rate = 10.0, 1.0 / TPS
        state = K.init_bucket_state(4)
        state, _, _ = run_batch(state, [1], [10], 5 * TPS, cap, rate)
        # Rebase both the table and the caller's clock by 4 s.
        state = K.rebase_bucket_epoch(state, jnp.int32(4 * TPS))
        # 3 s of refill measured in the new epoch: now = (5-4)+3 = 4 s.
        est = K.peek_batch(
            state, jnp.asarray([1], jnp.int32), jnp.asarray([True]),
            jnp.int32(4 * TPS), jnp.float32(cap), jnp.float32(rate),
        )
        assert float(est[0]) == 3.0


class TestAcquireScanCompact:
    def test_matches_sequential_batches(self):
        import numpy as np
        import jax.numpy as jnp
        from distributedratelimiting.redis_tpu.ops import kernels as K

        rng = np.random.default_rng(7)
        n, b, k = 128, 32, 4
        slots = rng.integers(0, n, (k, b)).astype(np.int32)
        slots[0, :3] = 5  # in-batch duplicates
        counts = rng.integers(1, 4, (k, b)).astype(np.uint8)
        nows = np.arange(1, k + 1, dtype=np.int32) * 10

        s1 = K.init_bucket_state(n)
        s1, granted, remaining = K.acquire_scan_compact(
            s1, jnp.asarray(slots), jnp.asarray(counts), jnp.asarray(nows),
            jnp.float32(6.0), jnp.float32(0.5))

        s2 = K.init_bucket_state(n)
        for i in range(k):
            s2, g2, r2 = K.acquire_batch(
                s2, jnp.asarray(slots[i]), jnp.asarray(counts[i], jnp.int32),
                jnp.ones((b,), bool), jnp.int32(nows[i]), jnp.float32(6.0),
                jnp.float32(0.5))
            np.testing.assert_array_equal(np.asarray(granted[i]),
                                          np.asarray(g2))
            np.testing.assert_allclose(np.asarray(remaining[i]),
                                       np.asarray(r2), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(s1.tokens),
                                   np.asarray(s2.tokens), rtol=1e-6)

    def test_negative_slot_is_padding(self):
        import numpy as np
        import jax.numpy as jnp
        from distributedratelimiting.redis_tpu.ops import kernels as K

        s = K.init_bucket_state(16)
        slots = np.array([[0, -1, 3]], np.int32)
        counts = np.ones((1, 3), np.uint8)
        s, granted, _ = K.acquire_scan_compact(
            s, jnp.asarray(slots), jnp.asarray(counts),
            jnp.asarray([1], np.int32), jnp.float32(5.0), jnp.float32(0.1))
        assert list(np.asarray(granted[0])) == [True, False, True]


class TestAcquireScanCompactFused:
    def test_matches_unfused(self):
        import numpy as np
        import jax.numpy as jnp
        from distributedratelimiting.redis_tpu.ops import kernels as K

        rng = np.random.default_rng(13)
        n, b, k = 70_000, 32, 4  # n > 2**16: exercises all four slot bytes
        slots = rng.integers(0, n, (k, b)).astype(np.int32)
        slots[0, :3] = 5          # duplicates
        slots[1, :2] = -1         # padding rows
        counts = rng.integers(0, 255, (k, b)).astype(np.uint8)
        nows = np.arange(1, k + 1, dtype=np.int32) * 10

        s1 = K.init_bucket_state(n)
        s1, g1, r1 = K.acquire_scan_compact(
            s1, jnp.asarray(slots), jnp.asarray(counts), jnp.asarray(nows),
            jnp.float32(200.0), jnp.float32(0.5))
        s2 = K.init_bucket_state(n)
        s2, g2, r2 = K.acquire_scan_compact_fused(
            s2, jnp.asarray(K.pack_compact5(slots, counts)),
            jnp.asarray(nows), jnp.float32(200.0), jnp.float32(0.5))
        np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))
        np.testing.assert_allclose(np.asarray(r1), np.asarray(r2), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(s1.tokens),
                                   np.asarray(s2.tokens), rtol=1e-6)

    def test_window_fused_bits_matches_packed(self):
        """The window verdict-only bit-packed path (production default for
        window bulk with_remaining=False) must agree with the packed-result
        fused variant bit for bit, for both window families."""
        import numpy as np
        import jax.numpy as jnp
        from distributedratelimiting.redis_tpu.ops import kernels as K

        rng = np.random.default_rng(19)
        n, b, k = 300, 64, 3
        slots = rng.integers(0, n, (k, b)).astype(np.int32)
        slots[1, :5] = -1
        counts = rng.integers(0, 3, (k, b)).astype(np.uint8)
        nows = np.arange(1, k + 1, dtype=np.int32) * 400
        fused = jnp.asarray(K.pack_compact5(slots, counts))
        for interpolate in (True, False):
            s1 = K.init_window_state(n)
            s1, out = K.window_acquire_scan_fused_packed(
                s1, fused, jnp.asarray(nows), jnp.float32(3.0),
                jnp.int32(1024), interpolate=interpolate)
            want = np.asarray(out)[:, 0, :].reshape(-1) > 0.5
            s2 = K.init_window_state(n)
            s2, bits = K.window_acquire_scan_fused_bits(
                s2, fused, jnp.asarray(nows), jnp.float32(3.0),
                jnp.int32(1024), interpolate=interpolate)
            got = np.unpackbits(np.asarray(bits).reshape(-1),
                                bitorder="little")[:k * b].astype(bool)
            np.testing.assert_array_equal(got, want)
            np.testing.assert_allclose(np.asarray(s1.curr_count),
                                       np.asarray(s2.curr_count), rtol=1e-6)

    def test_fused_bits_matches_compact_bits(self):
        import numpy as np
        import jax.numpy as jnp
        from distributedratelimiting.redis_tpu.ops import kernels as K

        rng = np.random.default_rng(17)
        n, b, k = 500, 64, 3
        slots = rng.integers(0, n, (k, b)).astype(np.int32)
        slots[2, :8] = -1
        counts = rng.integers(1, 4, (k, b)).astype(np.uint8)
        nows = np.arange(1, k + 1, dtype=np.int32)
        s1 = K.init_bucket_state(n)
        s1, bits1 = K.acquire_scan_compact_bits(
            s1, jnp.asarray(slots), jnp.asarray(counts), jnp.asarray(nows),
            jnp.float32(4.0), jnp.float32(0.1))
        s2 = K.init_bucket_state(n)
        s2, bits2 = K.acquire_scan_fused_bits(
            s2, jnp.asarray(K.pack_compact5(slots, counts)),
            jnp.asarray(nows), jnp.float32(4.0), jnp.float32(0.1))
        np.testing.assert_array_equal(np.asarray(bits1), np.asarray(bits2))
        np.testing.assert_allclose(np.asarray(s1.tokens),
                                   np.asarray(s2.tokens), rtol=1e-6)

    def test_pack_compact5_layout(self):
        import numpy as np
        from distributedratelimiting.redis_tpu.ops import kernels as K

        slots = np.array([0, 1, 255, 65536, (1 << 24) + 7, -1], np.int32)
        counts = np.array([1, 2, 3, 4, 5, 0], np.uint8)
        fused = K.pack_compact5(slots, counts)
        assert fused.shape == (6, 5)
        # LE i32 reassembly from bytes 0-3, count in byte 4.
        back = fused[:, :4].copy().view("<i4").reshape(-1)
        np.testing.assert_array_equal(back, slots)
        np.testing.assert_array_equal(fused[:, 4], counts)


class TestAcquireScanPacked24:
    def test_matches_sequential_unit_batches(self):
        import numpy as np
        import jax.numpy as jnp
        from distributedratelimiting.redis_tpu.ops import kernels as K

        rng = np.random.default_rng(11)
        n, b, k = 200, 32, 3
        slots = rng.integers(0, n, (k, b)).astype(np.int32)
        slots[1, :4] = 9  # duplicates within one batch
        nows = np.arange(1, k + 1, dtype=np.int32) * 7

        s1 = K.init_bucket_state(n)
        s1, granted, remaining = K.acquire_scan_packed24(
            s1, jnp.asarray(K.pack_slots24(slots)), jnp.asarray(nows),
            jnp.float32(3.0), jnp.float32(0.25))

        s2 = K.init_bucket_state(n)
        for i in range(k):
            s2, g2, r2 = K.acquire_batch(
                s2, jnp.asarray(slots[i]), jnp.ones((b,), jnp.int32),
                jnp.ones((b,), bool), jnp.int32(nows[i]), jnp.float32(3.0),
                jnp.float32(0.25))
            np.testing.assert_array_equal(np.asarray(granted[i]),
                                          np.asarray(g2))
            np.testing.assert_allclose(np.asarray(remaining[i]),
                                       np.asarray(r2), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(s1.tokens),
                                   np.asarray(s2.tokens), rtol=1e-6)

    def test_sentinel_rows_are_padding(self):
        import numpy as np
        import jax.numpy as jnp
        from distributedratelimiting.redis_tpu.ops import kernels as K

        slots = np.array([[2, K.SLOT24_PAD, 4]], np.int32)
        s = K.init_bucket_state(8)
        s, granted, _ = K.acquire_scan_packed24(
            s, jnp.asarray(K.pack_slots24(slots)),
            jnp.asarray([1], np.int32), jnp.float32(5.0), jnp.float32(0.1))
        assert list(np.asarray(granted[0])) == [True, False, True]
        # Padding touched nothing: only slots 2 and 4 exist.
        assert list(np.nonzero(np.asarray(s.exists))[0]) == [2, 4]

    def test_pack_roundtrip_at_boundaries(self):
        import numpy as np
        from distributedratelimiting.redis_tpu.ops import kernels as K

        vals = np.array([0, 1, 255, 256, 65535, 65536, (1 << 24) - 2,
                         K.SLOT24_PAD], np.int32)
        packed = K.pack_slots24(vals)
        restored = (packed[..., 0].astype(np.int32)
                    | (packed[..., 1].astype(np.int32) << 8)
                    | (packed[..., 2].astype(np.int32) << 16))
        np.testing.assert_array_equal(restored, vals)


class TestWindowAcquireScanCompact:
    def test_matches_sequential_window_batches(self):
        import numpy as np
        import jax.numpy as jnp
        from distributedratelimiting.redis_tpu.ops import kernels as K

        rng = np.random.default_rng(13)
        n, b, k = 64, 16, 3
        slots = rng.integers(0, n, (k, b)).astype(np.int32)
        slots[0, 5:] = -1  # bursty: padding tail rows
        counts = rng.integers(1, 3, (k, b)).astype(np.uint8)
        nows = np.array([10, 40, 90], np.int32)

        s1 = K.init_window_state(n)
        s1, granted, _ = K.window_acquire_scan_compact(
            s1, jnp.asarray(slots), jnp.asarray(counts), jnp.asarray(nows),
            jnp.float32(4.0), jnp.int32(32))

        s2 = K.init_window_state(n)
        for i in range(k):
            s2, g2, _ = K.window_acquire_batch(
                s2, jnp.asarray(slots[i]), jnp.asarray(counts[i], jnp.int32),
                jnp.asarray(slots[i] >= 0), jnp.int32(nows[i]),
                jnp.float32(4.0), jnp.int32(32))
            np.testing.assert_array_equal(np.asarray(granted[i]),
                                          np.asarray(g2))
        np.testing.assert_allclose(np.asarray(s1.curr_count),
                                   np.asarray(s2.curr_count), rtol=1e-6)
