"""Multi-shard native front-end (round 11): N epoll shards, one port.

Covers what the 4-shard arms of the differential fuzz
(test_native_parity_fuzz) do not: the shard ABI surface itself
(fe_shard_count / per-shard sub-handles / stale-binary fallback), the
whole-node telemetry merge invariant (the top-level OP_STATS gauges are
the SUM of the per-shard breakdown), the single-envelope bound with the
tier-0 budget split across shards (summed over-admission inside the
SAME flat epsilon as single-shard), and the retire fan-out regression —
a live OP_CONFIG mutation must kill every shard's replicas of the old
config atomically (a config retired on shard 0 but live on shard 3 is
a double-admit window).
"""

from __future__ import annotations

import asyncio
import json
import struct

import pytest

from distributedratelimiting.redis_tpu.models.approximate import (
    headroom_budget,
    overadmit_epsilon,
)
from distributedratelimiting.redis_tpu.runtime import wire
from distributedratelimiting.redis_tpu.runtime.native_frontend import (
    Tier0Config,
    native_bulk_loadgen,
    native_loadgen,
)
from distributedratelimiting.redis_tpu.runtime.remote import RemoteBucketStore
from distributedratelimiting.redis_tpu.runtime.server import BucketStoreServer
from distributedratelimiting.redis_tpu.runtime.store import InProcessBucketStore
from distributedratelimiting.redis_tpu.utils.native import load_frontend_lib

_LIB = load_frontend_lib()
pytestmark = pytest.mark.skipif(
    _LIB is None or not getattr(_LIB, "has_shards", False),
    reason="native front-end library unavailable or predates the shard ABI")


def run(coro):
    return asyncio.run(coro)


async def _roundtrip_raw(host, port, frames: "list[bytes]") -> list[bytes]:
    """Send raw frames on one fresh connection, read one reply each."""
    reader, writer = await asyncio.open_connection(host, port)
    try:
        for f in frames:
            writer.write(f)
        await writer.drain()
        out = []
        for _ in frames:
            hdr = await asyncio.wait_for(reader.readexactly(4), 10.0)
            (ln,) = struct.unpack("<I", hdr)
            out.append(hdr + await asyncio.wait_for(
                reader.readexactly(ln), 10.0))
        return out
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionResetError, BrokenPipeError):
            pass


def test_multishard_serves_one_port_and_stats_merge():
    """4 shards accept on ONE port (kernel balancing spreads the C
    loadgen's connections), every request is answered, and the merged
    top-level gauges are exactly the sum of the per-shard breakdown."""
    async def body():
        async with BucketStoreServer(InProcessBucketStore(),
                                     native_frontend=True,
                                     native_shards=4) as srv:
            assert srv._native.n_shards == 4
            replies, granted, _el = await asyncio.to_thread(
                native_loadgen, srv.host, srv.port, conns=32, depth=8,
                reqs_per_conn=200, keyspace=8)
            assert replies == 32 * 200
            store = RemoteBucketStore(address=(srv.host, srv.port))
            try:
                res = await store.acquire_many(
                    [f"k{i % 8}" for i in range(64)], [1] * 64, 1e7, 1e7)
                assert res.granted.all()
                st = await store.stats()
                assert st["fe_shards"] == 4
                shards = st["shards"]
                assert len(shards) == 4
                assert sum(s["requests_served"] for s in shards) == \
                    st["requests_served"]
                assert sum(s["connections_served"] for s in shards) == \
                    st["connections_served"]
                assert sum(s["native_bulk"]["rows"] for s in shards) == \
                    st["native_bulk"]["rows"]
                # 33 connections over 4 kernel-balanced listeners: the
                # chance every one lands on a single shard is (1/4)^32 —
                # at least two shards must have served.
                assert sum(1 for s in shards
                           if s["connections_served"] > 0) >= 2
            finally:
                await store.aclose()

    run(body())


def test_shard_handle_bounds_and_single_shard_breakdown():
    """fe_shard rejects out-of-range indexes; a single-shard server
    reports no per-shard breakdown (the merged gauges already ARE the
    node) and keeps the exact pre-shard OP_STATS shape."""
    async def body():
        async with BucketStoreServer(InProcessBucketStore(),
                                     native_frontend=True,
                                     native_shards=1) as srv:
            h = srv._native._h
            assert _LIB.fe_shard_count(h) == 1
            assert _LIB.fe_shard(h, 0)
            assert not _LIB.fe_shard(h, 1)
            assert not _LIB.fe_shard(h, -1)
            store = RemoteBucketStore(address=(srv.host, srv.port))
            try:
                await store.acquire("a", 1, 10.0, 1.0)
                st = await store.stats()
                assert "shards" not in st
                assert "fe_shards" not in st
            finally:
                await store.aclose()

    run(body())


def test_stale_binary_fallback_serves_single_shard(monkeypatch):
    """shards>1 against a binary without the shard ABI must serve —
    single-shard, loudly — not fail: availability over scale."""
    async def body():
        monkeypatch.setattr(_LIB, "has_shards", False)
        try:
            async with BucketStoreServer(InProcessBucketStore(),
                                         native_frontend=True,
                                         native_shards=4) as srv:
                assert srv._native.n_shards == 1
                assert srv._native.shard_stats() is None
                store = RemoteBucketStore(address=(srv.host, srv.port))
                try:
                    res = await store.acquire("a", 1, 10.0, 1.0)
                    assert res.granted
                finally:
                    await store.aclose()
        finally:
            monkeypatch.setattr(_LIB, "has_shards", True)

    run(body())


def test_multishard_overadmit_bounded_by_flat_envelope():
    """The single-envelope acceptance bound: with 4 shards deciding
    concurrently from split budget shares, the SUMMED per-key
    over-admission across every connection and shard stays inside the
    SAME flat epsilon envelope as single-shard —
    overadmit_epsilon(headroom_budget(...), fill, sync) computed from
    the UNSPLIT budget, because the per-shard shares sum to at most it
    (native/frontend.cc t0_budget_of; docs/DESIGN.md §16)."""
    capacity, fill = 400.0, 1e-9
    cfg = Tier0Config(sync_interval_s=0.005, min_budget=8.0)
    budget = headroom_budget(capacity, fraction=cfg.budget_fraction,
                             min_budget=cfg.min_budget,
                             max_budget=cfg.max_budget)
    assert budget / 4 >= cfg.min_budget  # split shares must host
    epsilon = overadmit_epsilon(budget, fill, cfg.sync_interval_s)
    n_keys, per_frame, frames, n_conns = 4, 25, 8, 4

    async def body():
        async with BucketStoreServer(InProcessBucketStore(),
                                     native_frontend=True,
                                     native_tier0=cfg,
                                     native_shards=4) as srv:
            stores = [RemoteBucketStore(address=(srv.host, srv.port))
                      for _ in range(n_conns)]
            try:
                keys = [f"h{i}" for i in range(n_keys)]
                frame_keys = [keys[i % n_keys]
                              for i in range(n_keys * per_frame)]
                counts = [1] * len(frame_keys)
                admitted = {k: 0 for k in keys}
                results = await asyncio.gather(
                    *(st.acquire_many(frame_keys, counts, capacity, fill)
                      for st in stores for _ in range(frames)))
                for res in results:
                    for k, g in zip(frame_keys, res.granted):
                        admitted[k] += bool(g)
                for k in keys:
                    # Oracle: with ~zero fill and unit counts, any
                    # serialization admits exactly `capacity` per key.
                    # The bound is the FLAT epsilon — not N times it.
                    assert admitted[k] <= capacity + epsilon, (
                        k, admitted[k], epsilon)
                    assert admitted[k] >= capacity * 0.9, (k, admitted[k])
            finally:
                for st in stores:
                    await st.aclose()

    run(body())


def test_retire_fans_out_to_every_shard():
    """Live OP_CONFIG mutation under multi-shard load: once the sync
    pump retires the old config, NO shard may still answer old-config
    frames from a live replica — fe_t0_retire must sweep every shard's
    slice under one combined critical section (a replica surviving on
    shard 3 after shard 0 retired is the double-admit window this
    regression pins)."""
    old_cap, old_rate = 100000.0, 1e-9
    new_cap, new_rate = 120000.0, 2e-9
    cfg = Tier0Config(sync_interval_s=0.005, min_budget=8.0)

    async def body():
        async with BucketStoreServer(InProcessBucketStore(),
                                     native_frontend=True,
                                     native_tier0=cfg,
                                     native_shards=4) as srv:
            # Load phase: hot old-config bulk traffic over many
            # connections so replicas install across shards' slices.
            await asyncio.to_thread(
                native_bulk_loadgen, srv.host, srv.port, conns=16,
                depth=4, frames_per_conn=40, rows_per_frame=256,
                keyspace=8, capacity=old_cap, fill_rate=old_rate)
            store = RemoteBucketStore(address=(srv.host, srv.port))
            try:
                st = await store.stats()
                hosting = [s["shard"] for s in st["shards"]
                           if s["tier0"]["entries"] > 0]
                assert len(hosting) >= 2, (
                    "load phase must install replicas on several "
                    f"shards to make the fan-out meaningful: {hosting}")
                # Mutate the live config while loadgen traffic is still
                # in flight on other connections.
                load = asyncio.create_task(asyncio.to_thread(
                    native_bulk_loadgen, srv.host, srv.port, conns=8,
                    depth=2, frames_per_conn=40, rows_per_frame=256,
                    keyspace=8, capacity=old_cap, fill_rate=old_rate))
                for payload in ({"prepare": {"kind": "bucket",
                                             "old": [old_cap, old_rate],
                                             "new": [new_cap, new_rate]},
                                 "version": 1},
                                {"commit": 1}):
                    frame = wire.encode_request(900, wire.OP_CONFIG,
                                                key=json.dumps(payload))
                    reply = (await _roundtrip_raw(srv.host, srv.port,
                                                  [frame]))[0]
                    assert reply[9] != wire.RESP_ERROR, reply
                await load
                # Give the sync pump a few rounds to run the retire.
                await asyncio.sleep(cfg.sync_interval_s * 10)
                # Terminal state: EVERY connection (each landing on a
                # kernel-chosen shard) answers old-config frames with
                # the routable config-moved error — a grant here means
                # some shard still holds a live old-config replica.
                for _ in range(16):
                    frame = wire.encode_bulk_request(
                        7, [b"b0", b"b1"], [1, 1], old_cap, old_rate)
                    reply = (await _roundtrip_raw(srv.host, srv.port,
                                                  [frame]))[0]
                    assert reply[9] == wire.RESP_ERROR, reply
                    assert b"config moved" in reply, reply
                    # New config decides normally on the same shard.
                    frame = wire.encode_bulk_request(
                        8, [b"b0", b"b1"], [1, 1], new_cap, new_rate)
                    reply = (await _roundtrip_raw(srv.host, srv.port,
                                                  [frame]))[0]
                    assert reply[9] == wire.RESP_BULK, reply
            finally:
                await store.aclose()

    run(body())


def test_bulk_loadgen_counts_are_consistent():
    """The C bulk load generator's own accounting (frames, rows,
    granted) agrees with the server's gauges — the shard sweep's
    evidence numbers come from it, so it gets its own audit."""
    async def body():
        async with BucketStoreServer(InProcessBucketStore(),
                                     native_frontend=True,
                                     native_tier0=True,
                                     native_shards=2) as srv:
            frames, rows, granted, el = await asyncio.to_thread(
                native_bulk_loadgen, srv.host, srv.port, conns=4,
                depth=2, frames_per_conn=25, rows_per_frame=512,
                keyspace=16)
            assert frames == 4 * 25
            assert rows == frames * 512
            assert granted == rows  # capacity 1e8, unit counts: all grant
            assert el > 0
            store = RemoteBucketStore(address=(srv.host, srv.port))
            try:
                st = await store.stats()
                assert st["native_bulk"]["rows"] == rows
            finally:
                await store.aclose()

    run(body())
