"""Tracing tests — the ProfilingSession seam (SURVEY.md §5.1).

The reference registers a ``Func<ProfilingSession>`` with the Redis
connection and gets per-command timings back; here the profiled commands
are kernel dispatches (device store) and wire round-trips (remote store).
"""

import asyncio

import pytest

from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.remote import RemoteBucketStore
from distributedratelimiting.redis_tpu.runtime.server import BucketStoreServer
from distributedratelimiting.redis_tpu.runtime.store import (
    DeviceBucketStore,
    InProcessBucketStore,
)
from distributedratelimiting.redis_tpu.utils.tracing import (
    ProfiledCommand,
    Profiler,
    ProfilingSession,
)


def run(coro):
    return asyncio.run(coro)


class TestProfiler:
    def test_disabled_profiler_is_allocation_free(self):
        p = Profiler(None)
        assert not p.enabled
        # The no-op span is a shared singleton — same object every call.
        assert p.span("a") is p.span("b")
        with p.span("acquire_batch", 64):
            pass  # must be a usable context manager

    def test_session_records_command_name_duration_rows(self):
        session = ProfilingSession()
        p = Profiler(lambda: session)
        with p.span("acquire_batch", 17):
            pass
        (cmd,) = session.commands
        assert cmd.command == "acquire_batch"
        assert cmd.rows == 17
        assert cmd.duration_s >= 0.0

    def test_factory_may_return_none_to_skip(self):
        # The StackExchange contract: the factory decides per command
        # whether (and to which session) the command is attributed.
        calls = []
        p = Profiler(lambda: calls.append(1) and None)
        with p.span("sync_counter"):
            pass
        assert calls  # factory consulted, nothing recorded, no crash

    def test_session_finish_drains(self):
        session = ProfilingSession()
        session.record(ProfiledCommand("x", 0.0, 1e-6, 1))
        assert len(session.finish()) == 1
        assert session.commands == []


class TestDeviceStoreProfiling:
    def test_dispatches_are_profiled(self):
        session = ProfilingSession()
        store = DeviceBucketStore(
            n_slots=64, counter_slots=8, clock=ManualClock(),
            max_batch=64, profiling_session=lambda: session,
        )
        store.acquire_blocking("k", 1, 10.0, 1.0)
        store.sync_counter_blocking("c", 3.0, 1.0)
        store.window_acquire_blocking("w", 1, 10.0, 1.0)
        names = [c.command for c in session.commands]
        assert "acquire_batch" in names
        assert "sync_counter" in names
        assert "window_acquire_batch" in names
        acq = next(c for c in session.commands if c.command == "acquire_batch")
        assert acq.rows == 1
        assert all(c.duration_s > 0.0 for c in session.commands)

    def test_async_batch_rows_attributed(self):
        session = ProfilingSession()

        async def main():
            store = DeviceBucketStore(
                n_slots=64, counter_slots=8, clock=ManualClock(),
                max_batch=64, max_delay_s=5e-3,
                profiling_session=lambda: session,
            )
            await asyncio.gather(*(
                store.acquire(f"k{i}", 1, 10.0, 1.0) for i in range(8)
            ))
            await store.aclose()

        run(main())
        acq = [c for c in session.commands if c.command == "acquire_batch"]
        assert sum(c.rows for c in acq) == 8

    def test_unprofiled_store_by_default(self):
        store = DeviceBucketStore(n_slots=64, counter_slots=8,
                                  clock=ManualClock(), max_batch=64)
        assert not store.profiler.enabled
        store.acquire_blocking("k", 1, 10.0, 1.0)  # hot path unchanged


class TestRemoteStoreProfiling:
    def test_wire_roundtrips_are_profiled(self):
        session = ProfilingSession()

        async def main():
            async with BucketStoreServer(InProcessBucketStore()) as srv:
                store = RemoteBucketStore(
                    address=(srv.host, srv.port),
                    profiling_session=lambda: session,
                )
                try:
                    await store.acquire("k", 1, 5.0, 1.0)
                    await store.sync_counter("c", 2.0, 1.0)
                    await store.ping()
                finally:
                    await store.aclose()

        run(main())
        names = [c.command for c in session.commands]
        assert names.count("acquire") == 1
        assert "sync_counter" in names
        assert "ping" in names
        # Wire round-trips have real (non-zero) durations.
        assert all(c.duration_s > 0.0 for c in session.commands)
