"""Tracing tests — the ProfilingSession seam (SURVEY.md §5.1) and the
distributed span-tree tracer grown out of it.

The reference registers a ``Func<ProfilingSession>`` with the Redis
connection and gets per-command timings back; here the profiled commands
are kernel dispatches (device store) and wire round-trips (remote store).
The distributed half threads a wire-propagated trace context through
client → server → batcher → store → cluster, tail-samples the span
trees, and exports Perfetto-loadable JSON.
"""

import asyncio
import json

import pytest

from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.cluster import (
    ClusterBucketStore,
)
from distributedratelimiting.redis_tpu.runtime.remote import RemoteBucketStore
from distributedratelimiting.redis_tpu.runtime.server import BucketStoreServer
from distributedratelimiting.redis_tpu.runtime.store import (
    DeviceBucketStore,
    InProcessBucketStore,
)
from distributedratelimiting.redis_tpu.utils import tracing
from distributedratelimiting.redis_tpu.utils.tracing import (
    ProfiledCommand,
    Profiler,
    ProfilingSession,
    TraceContext,
    Tracer,
)


def run(coro):
    return asyncio.run(coro)


@pytest.fixture
def tracer():
    """Enable the process-global tracer for one test, always-record /
    always-keep, and restore the disabled default afterwards."""
    tr = tracing.configure(enabled=True, sample_rate=1.0, keep_rate=1.0,
                           latency_threshold_s=10.0)
    tr.reset()
    yield tr
    tracing.configure(enabled=False)
    tr.reset()


class TestProfiler:
    def test_disabled_profiler_is_allocation_free(self):
        p = Profiler(None)
        assert not p.enabled
        # The no-op span is a shared singleton — same object every call.
        assert p.span("a") is p.span("b")
        with p.span("acquire_batch", 64):
            pass  # must be a usable context manager

    def test_session_records_command_name_duration_rows(self):
        session = ProfilingSession()
        p = Profiler(lambda: session)
        with p.span("acquire_batch", 17):
            pass
        (cmd,) = session.commands
        assert cmd.command == "acquire_batch"
        assert cmd.rows == 17
        assert cmd.duration_s >= 0.0

    def test_factory_may_return_none_to_skip(self):
        # The StackExchange contract: the factory decides per command
        # whether (and to which session) the command is attributed.
        calls = []
        p = Profiler(lambda: calls.append(1) and None)
        with p.span("sync_counter"):
            pass
        assert calls  # factory consulted, nothing recorded, no crash

    def test_session_finish_drains(self):
        session = ProfilingSession()
        session.record(ProfiledCommand("x", 0.0, 1e-6, 1))
        assert len(session.finish()) == 1
        assert session.commands == []


class TestDeviceStoreProfiling:
    def test_dispatches_are_profiled(self):
        session = ProfilingSession()
        store = DeviceBucketStore(
            n_slots=64, counter_slots=8, clock=ManualClock(),
            max_batch=64, profiling_session=lambda: session,
        )
        store.acquire_blocking("k", 1, 10.0, 1.0)
        store.sync_counter_blocking("c", 3.0, 1.0)
        store.window_acquire_blocking("w", 1, 10.0, 1.0)
        names = [c.command for c in session.commands]
        assert "acquire_batch" in names
        assert "sync_counter" in names
        assert "window_acquire_batch" in names
        acq = next(c for c in session.commands if c.command == "acquire_batch")
        assert acq.rows == 1
        assert all(c.duration_s > 0.0 for c in session.commands)

    def test_async_batch_rows_attributed(self):
        session = ProfilingSession()

        async def main():
            store = DeviceBucketStore(
                n_slots=64, counter_slots=8, clock=ManualClock(),
                max_batch=64, max_delay_s=5e-3,
                profiling_session=lambda: session,
            )
            await asyncio.gather(*(
                store.acquire(f"k{i}", 1, 10.0, 1.0) for i in range(8)
            ))
            await store.aclose()

        run(main())
        acq = [c for c in session.commands if c.command == "acquire_batch"]
        assert sum(c.rows for c in acq) == 8

    def test_unprofiled_store_by_default(self):
        store = DeviceBucketStore(n_slots=64, counter_slots=8,
                                  clock=ManualClock(), max_batch=64)
        assert not store.profiler.enabled
        store.acquire_blocking("k", 1, 10.0, 1.0)  # hot path unchanged


class TestRemoteStoreProfiling:
    def test_wire_roundtrips_are_profiled(self):
        session = ProfilingSession()

        async def main():
            async with BucketStoreServer(InProcessBucketStore()) as srv:
                store = RemoteBucketStore(
                    address=(srv.host, srv.port),
                    profiling_session=lambda: session,
                )
                try:
                    await store.acquire("k", 1, 5.0, 1.0)
                    await store.sync_counter("c", 2.0, 1.0)
                    await store.ping()
                finally:
                    await store.aclose()

        run(main())
        names = [c.command for c in session.commands]
        assert names.count("acquire") == 1
        assert "sync_counter" in names
        assert "ping" in names
        # Wire round-trips have real (non-zero) durations.
        assert all(c.duration_s > 0.0 for c in session.commands)


# -- distributed tracer unit behavior ----------------------------------------

class TestTracer:
    def test_disabled_tracer_is_allocation_free(self):
        tr = Tracer()
        s = tr.start_span("a")
        assert s is tr.start_span("b")  # shared null singleton
        assert s.context is None
        with s:
            pass

    def test_span_tree_parenting_and_context(self, tracer):
        with tracer.start_span("root") as root:
            assert tracing.current_span() is root
            with tracer.start_span("child") as child:
                assert child.parent_id == root.span_id
                assert child.trace_hi == root.trace_hi
        (trace,) = tracer.traces()
        assert len(trace["spans"]) == 2
        assert trace["trace_id"] == root.context.trace_id

    def test_remote_parent_context(self, tracer):
        ctx = TraceContext(7, 9, 42, 1)
        with tracer.start_span("server.dispatch", parent=ctx) as sp:
            assert sp.parent_id == 42
            assert sp.trace_hi == 7 and sp.trace_lo == 9
        (trace,) = tracer.traces()
        assert trace["trace_id"] == ctx.trace_id

    def test_head_sampling_gates_recording(self):
        tr = Tracer(enabled=True, sample_rate=0.0)
        assert tr.start_span("never").context is None
        assert tr.snapshot()["spans_recorded"] == 0

    def test_tail_keeps_denied_drops_boring(self):
        tr = Tracer(enabled=True, sample_rate=1.0, keep_rate=0.0,
                    latency_threshold_s=10.0)
        with tr.start_span("boring"):
            pass
        with tr.start_span("bad") as sp:
            sp.set_status("denied")
        traces = tr.traces()
        assert len(traces) == 1
        assert traces[0]["spans"][0]["status"] == "denied"
        assert tr.traces_dropped == 1

    def test_tail_keeps_slow(self):
        tr = Tracer(enabled=True, sample_rate=1.0, keep_rate=0.0,
                    latency_threshold_s=0.0)
        with tr.start_span("slow-by-threshold-zero"):
            pass
        assert len(tr.traces()) == 1

    def test_exception_marks_error_and_keeps(self):
        tr = Tracer(enabled=True, sample_rate=1.0, keep_rate=0.0)
        with pytest.raises(ValueError):
            with tr.start_span("boom"):
                raise ValueError("x")
        (trace,) = tr.traces()
        assert trace["spans"][0]["status"] == "error"

    def test_buffer_bounded_and_drain(self):
        tr = Tracer(enabled=True, sample_rate=1.0, keep_rate=1.0,
                    max_traces=4)
        for i in range(10):
            with tr.start_span(f"s{i}") as sp:
                sp.set_status("denied")
        assert len(tr.traces()) == 4
        assert len(tr.traces(drain=True)) == 4
        assert tr.traces() == []

    def test_late_span_merges_by_trace_id(self, tracer):
        with tracer.start_span("root") as root:
            ctx = root.context
        # A span arriving after the trace finalized (the native tier-0
        # harvest shape) merges into the same exported trace.
        tracer.record_span("fe.tier0", ctx, 0.0, 0.001, status="denied")
        (trace,) = tracer.traces()
        assert {s["name"] for s in trace["spans"]} == {"root", "fe.tier0"}

    def test_export_chrome_shape(self, tracer):
        with tracer.start_span("root", attrs={"k": "v"}) as root:
            with tracer.start_span("child"):
                pass
        out = tracer.export_chrome()
        events = out["traceEvents"]
        xs = [e for e in events if e["ph"] == "X"]
        assert len(xs) == 2
        for e in xs:
            assert e["args"]["trace_id"] == root.context.trace_id
            assert {"ts", "dur", "pid", "tid", "name"} <= set(e)
        # json-serializable end to end (the /traces body)
        json.loads(tracer.export_chrome_json())

    def test_export_chrome_json_size_cap(self, tracer):
        for i in range(50):
            with tracer.start_span(f"span-{i}" * 20) as sp:
                sp.set_status("denied")
        text = tracer.export_chrome_json(max_bytes=4096)
        assert len(text) <= 4096
        json.loads(text)

    def test_export_size_cap_with_drain_still_returns_traces(self,
                                                             tracer):
        """Drain + cap must serialize from ONE buffer snapshot: the
        capped export still carries the newest traces (an earlier
        implementation drained on the first oversized pass and returned
        an empty export)."""
        for i in range(50):
            with tracer.start_span(f"span-{i}" * 20) as sp:
                sp.set_status("denied")
        text = tracer.export_chrome_json(max_bytes=4096, drain=True)
        assert len(text) <= 4096
        xs = [e for e in json.loads(text)["traceEvents"]
              if e["ph"] == "X"]
        assert xs, "capped drain export lost every trace"
        assert tracer.traces() == []  # drained exactly once

    def test_export_single_oversized_trace_respects_cap(self, tracer):
        with tracer.start_span("huge", attrs={"blob": "x" * 8192}) as sp:
            sp.set_status("denied")
        text = tracer.export_chrome_json(max_bytes=1024)
        assert len(text) <= 1024  # bare metadata export, never oversize
        json.loads(text)

    def test_mark_sets_ambient_status(self, tracer):
        with tracer.start_span("root"):
            tracing.mark("queued")
        assert tracer.traces()[0]["spans"][0]["status"] == "queued"

    def test_profiler_span_feeds_tracer_under_ambient_trace(self, tracer):
        p = Profiler(None)
        assert p.span("x") is not tracing._NULL_SPAN or True
        with tracer.start_span("root"):
            with p.span("acquire_batch", 8, annotate=False):
                pass
        (trace,) = tracer.traces()
        names = {s["name"] for s in trace["spans"]}
        assert "store.acquire_batch" in names
        store_span = next(s for s in trace["spans"]
                          if s["name"] == "store.acquire_batch")
        assert store_span["attrs"]["rows"] == 8

    def test_profiler_span_null_without_trace_or_session(self):
        p = Profiler(None)
        assert p.span("anything") is tracing._NULL_SPAN


# -- end-to-end: wire-propagated span trees ----------------------------------

def _span_chain_to_root(spans, leaf):
    """Walk parent links from ``leaf`` up; returns the chain (leaf first)."""
    by_id = {s["span_id"]: s for s in spans}
    chain = [leaf]
    cur = leaf
    while cur["parent_id"] in by_id:
        cur = by_id[cur["parent_id"]]
        chain.append(cur)
    return chain


class TestEndToEndTraces:
    @pytest.mark.jax_backend
    def test_denied_acquire_leaves_full_span_tree(self, tracer, tmp_path):
        """The acceptance path: one denied ACQUIRE through
        RemoteBucketStore → served ClusterBucketStore(DeviceBucketStore)
        yields ONE exported trace with ≥5 causally-linked spans (client
        wire → server dispatch → batcher queue + flush → store launch),
        its trace id visible as a histogram exemplar AND on the
        overlapping flight-recorder frame."""
        async def body():
            backing = DeviceBucketStore(n_slots=256)
            srv = BucketStoreServer(backing, flight_dir=str(tmp_path))
            await srv.start()
            remote = RemoteBucketStore(address=(srv.host, srv.port),
                                       coalesce_requests=False)
            cluster = ClusterBucketStore(stores=[remote])
            try:
                # capacity 5 < count 50: denied deterministically.
                res = await cluster.acquire("victim", 50, 5.0, 1.0)
                assert not res.granted
            finally:
                await cluster.aclose()
                await srv.aclose()
                await backing.aclose()

        run(body())
        traces = [t for t in tracer.traces()
                  if any(s["status"] == "denied" for s in t["spans"])]
        assert traces, tracer.traces()
        trace = traces[0]
        spans = trace["spans"]
        names = [s["name"] for s in spans]
        for expected in ("client.acquire", "server.acquire",
                         "batch.queue", "batch.flush",
                         "store.acquire_batch"):
            assert expected in names, (expected, names)
        assert len(spans) >= 5
        # Causality: the kernel-launch span walks up to the client root.
        launch = next(s for s in spans
                      if s["name"] == "store.acquire_batch")
        chain = [s["name"] for s in _span_chain_to_root(spans, launch)]
        assert chain[-1] == "client.acquire"
        assert "server.acquire" in chain
        assert "batch.flush" in chain

    @pytest.mark.jax_backend
    def test_exemplars_and_flight_frames_carry_trace_id(self, tracer,
                                                        tmp_path):
        async def body():
            backing = DeviceBucketStore(n_slots=256)
            srv = BucketStoreServer(backing, flight_dir=str(tmp_path))
            await srv.start()
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            try:
                res = await store.acquire("victim", 50, 5.0, 1.0)
                assert not res.granted
                exposition = srv.registry.render()
                trace_ids = {t["trace_id"] for t in tracer.traces()}
                assert trace_ids
                # the stage-latency histogram carries an exemplar naming
                # one of the kept traces
                assert "# {trace_id=" in exposition
                assert any(tid in exposition for tid in trace_ids)
                # exemplars are suppressed in the plain-text rendering
                assert "# {trace_id=" not in srv.registry.render(
                    exemplars=False)
                # flight-recorder flush frames cross-reference the trace
                frames = srv.flight_recorder.frames()
                flush_frames = [f for f in frames if f["kind"] == "flush"
                                and f.get("trace_id")]
                assert flush_frames
                assert any(f["trace_id"] in trace_ids
                           for f in flush_frames)
                # and the OP_TRACES wire export round-trips the trace
                out = await store.traces()
                exported = {e["args"]["trace_id"]
                            for e in out["traceEvents"]
                            if e["ph"] == "X"}
                assert exported & trace_ids
            finally:
                await store.aclose()
                await srv.aclose()
                await backing.aclose()

        run(body())

    @pytest.mark.jax_backend
    def test_cluster_fan_out_spans_per_node(self, tracer):
        async def body():
            backings, servers, remotes = [], [], []
            for _ in range(2):
                backing = DeviceBucketStore(n_slots=256)
                srv = BucketStoreServer(backing)
                await srv.start()
                backings.append(backing)
                servers.append(srv)
                remotes.append(RemoteBucketStore(
                    address=(srv.host, srv.port)))
            cluster = ClusterBucketStore(stores=remotes)
            try:
                keys = [f"user{i}" for i in range(64)]
                res = await cluster.acquire_many(keys, [50] * 64, 5.0, 1.0)
                assert not res.granted.any()
            finally:
                await cluster.aclose()
                for srv, backing in zip(servers, backings):
                    await srv.aclose()
                    await backing.aclose()

        run(body())
        traces = tracer.traces()
        fan = [t for t in traces
               if any(s["name"] == "cluster.fan_out" for s in t["spans"])]
        assert fan
        spans = fan[0]["spans"]
        node_spans = [s for s in spans if s["name"] == "cluster.node"]
        assert len(node_spans) == 2
        assert {s["attrs"]["node"] for s in node_spans} == {0, 1}
        # per-node client bulk spans parent on their node span
        client_spans = [s for s in spans
                        if s["name"] == "client.acquire_many"]
        node_ids = {s["span_id"] for s in node_spans}
        assert client_spans and all(s["parent_id"] in node_ids
                                    for s in client_spans)

    def test_old_peer_latches_off_trace_stamping(self, tracer,
                                                 monkeypatch):
        """Against a server that predates the trace tail, the first
        stamped request gets the routable unknown-op error; the client
        latches stamping off, retries bare, and succeeds — the
        OP_METRICS compatibility posture."""
        from distributedratelimiting.redis_tpu.runtime import wire

        # Simulate the old server: its handler never strips the tail.
        monkeypatch.setattr(wire, "strip_trace", lambda b: (b, None))

        async def body():
            srv = BucketStoreServer(InProcessBucketStore())
            await srv.start()
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            try:
                res = await store.acquire("k", 1, 100.0, 1.0)
                assert res.granted
                assert store._peer_traces is False
                # second request goes bare immediately and still works
                res = await store.acquire("k", 1, 100.0, 1.0)
                assert res.granted
            finally:
                await store.aclose()
                await srv.aclose()

        run(body())

    def test_coalesced_acquires_share_flush_span(self, tracer):
        async def body():
            srv = BucketStoreServer(InProcessBucketStore())
            await srv.start()
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=True)
            try:
                await asyncio.gather(*(
                    store.acquire(f"k{i}", 1, 100.0, 1.0)
                    for i in range(8)))
                # a denied request riding the bulk lane: the SERVER span
                # must mark denied too (RESP_BULK decision-bit sniff) so
                # the tail sampler keeps the server-side hop.
                res = await store.acquire("denyme", 99, 5.0, 1.0)
                assert not res.granted
            finally:
                await store.aclose()
                await srv.aclose()

        run(body())
        denied_server = [
            s for t in tracer.traces() for s in t["spans"]
            if s["name"] == "server.acquire_many"
            and s["status"] == "denied"]
        assert denied_server
        assert denied_server[0]["attrs"]["denied_rows"] >= 1
        traces = tracer.traces()
        assert traces
        # every trace has a client.acquire root; the elected trace also
        # carries the shared flush span, and non-elected members name it
        # via their queue span's flush_span_id attr.
        flush_owner = [t for t in traces
                       if any(s["name"] == "batch.flush"
                              for s in t["spans"])]
        assert flush_owner
        linked = [s for t in traces for s in t["spans"]
                  if s["name"] == "batch.queue" and s.get("attrs")
                  and "flush_span_id" in s["attrs"]]
        assert linked


@pytest.mark.slow
def test_head_sampled_tracing_overhead_within_contract():
    """CI regression for the <3% observability contract with tracing ON
    at the production head-sampling default (1%): ABBA-interleaved
    paired windows against the same in-process serving rig as the
    ``serving_metrics_overhead`` bench, median-of-blocks estimator."""
    import time as _time

    async def main() -> float:
        srv = BucketStoreServer(InProcessBucketStore())
        await srv.start()
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)

        async def window(depth: int = 16, reqs: int = 80) -> float:
            async def worker(w: int) -> None:
                for j in range(reqs):
                    await store.acquire(f"user{(w * 13 + j) % 512}", 1,
                                        1e7, 1e7)

            t0 = _time.perf_counter()
            await asyncio.gather(*(worker(w) for w in range(depth)))
            return depth * reqs / (_time.perf_counter() - t0)

        def on() -> None:
            tracing.configure(enabled=True, sample_rate=0.01,
                              keep_rate=0.1)

        def off() -> None:
            tracing.configure(enabled=False)

        try:
            on()
            await window()
            off()
            await window()
            blocks = []
            for _ in range(4):
                on()
                a1 = await window()
                off()
                b1 = await window()
                b2 = await window()
                on()
                a2 = await window()
                blocks.append(((a1 + a2) / 2, (b1 + b2) / 2))
            deltas = sorted((b - a) / b for a, b in blocks)
            return deltas[len(deltas) // 2] * 100.0
        finally:
            tracing.configure(enabled=False)
            tracing.get_tracer().reset()
            await store.aclose()
            await srv.aclose()

    # Best-of-3: a real contract violation measures high consistently;
    # shared-core scheduler noise does not (the same de-flake posture as
    # the bench's max-of-blocks rate estimator).
    measured = []
    for _ in range(3):
        overhead_pct = run(main())
        measured.append(overhead_pct)
        if overhead_pct < 3.0:
            break
    assert min(measured) < 3.0, (
        f"tracing-on overhead {measured} % across attempts")
