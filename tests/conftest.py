"""Test env: force an 8-device virtual CPU platform BEFORE jax initializes.

Multi-chip hardware is not available in CI; sharding tests run on a virtual
8-device CPU mesh (SURVEY.md §4 implication (d) — this replaces the
reference's Orleans-localhost multi-silo trick, ``TestApp/Program.cs:37-104``).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The environment's sitecustomize registers a remote TPU PJRT plugin
# ("axon") at interpreter startup; when its relay is unreachable, *any*
# backend init — even CPU-only — hangs indefinitely. Tests are CPU-only by
# design, so deregister the plugin before the first array op and pin the
# platform at the config level (env vars were already snapshotted).
from distributedratelimiting.redis_tpu.utils.cpu_bootstrap import (
    force_cpu_platform,
    set_virtual_device_count,
)

set_virtual_device_count(os.environ, 8)
force_cpu_platform()

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
