"""Test env: force an 8-device virtual CPU platform BEFORE jax initializes.

Multi-chip hardware is not available in CI; sharding tests run on a virtual
8-device CPU mesh (SURVEY.md §4 implication (d) — this replaces the
reference's Orleans-localhost multi-silo trick, ``TestApp/Program.cs:37-104``).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

# The environment's sitecustomize registers a remote TPU PJRT plugin
# ("axon") at interpreter startup; when its relay is unreachable, *any*
# backend init — even CPU-only — hangs indefinitely. Tests are CPU-only by
# design, so deregister the plugin before the first array op and pin the
# platform at the config level (env vars were already snapshotted).
try:
    from jax._src import xla_bridge

    xla_bridge._backend_factories.pop("axon", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
