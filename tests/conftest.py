"""Test env: force an 8-device virtual CPU platform BEFORE jax initializes.

Multi-chip hardware is not available in CI; sharding tests run on a virtual
8-device CPU mesh (SURVEY.md §4 implication (d) — this replaces the
reference's Orleans-localhost multi-silo trick, ``TestApp/Program.cs:37-104``).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
