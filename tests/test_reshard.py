"""Membership soak: elastic join/leave and live key migration under
seeded chaos load (the acceptance harness for the placement-versioned
cluster data plane — docs/OPERATIONS.md §9, ISSUE 6).

The soak drives a live 3-server TCP topology through **join → hot-shard
split → drain → rejoin** while a *follower* client (stale maps, MOVED
chasing) hammers the keyspace through seeded connection/dispatch chaos,
and then audits the ground truth:

- **Differential dual-ownership audit**: every authoritative admission,
  as recorded by the backing stores themselves, must have been served
  by the key's owner under the epoch timeline — or, inside a
  migration's bounded handoff window, by one of exactly {old, new}
  owner. No key is ever admitted by two owners outside a window.
- **Epsilon envelope**: the hot key's total observed grants stay within
  ``capacity + headroom_budget × episodes`` — each membership episode
  can cost at most one fair-share envelope, the same bound family as
  the PR-5 outage soak and the tier-0 cache.
- **Complete-or-abort**: every entry in the migration log is a commit
  or a clean abort; an abort leaves the epoch (and serving) untouched.
- **Schedule determinism**: the realized fault schedule equals the
  injector's pure-function preview, seam for seam (`make reshard-soak
  SEED=...` replays any run bit-for-bit via ``DRL_RESHARD_SEED``).
"""

from __future__ import annotations

import asyncio
import os
import time

import pytest

from distributedratelimiting.redis_tpu.models.approximate import (
    headroom_budget,
)
from distributedratelimiting.redis_tpu.runtime import wire
from distributedratelimiting.redis_tpu.runtime.cluster import (
    ClusterBucketStore,
    PlacementError,
)
from distributedratelimiting.redis_tpu.runtime.placement import PlacementMap
from distributedratelimiting.redis_tpu.runtime.remote import (
    StoreTimeoutError,
)
from distributedratelimiting.redis_tpu.runtime.server import BucketStoreServer
from distributedratelimiting.redis_tpu.runtime.store import (
    InProcessBucketStore,
)
from distributedratelimiting.redis_tpu.utils import faults
from distributedratelimiting.redis_tpu.utils.faults import (
    FaultInjector,
    FaultRule,
)

SEED = int(os.environ.get("DRL_RESHARD_SEED", "20260803"))

_NET_ERRORS = (ConnectionError, OSError, StoreTimeoutError,
               wire.RemoteStoreError)


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    yield
    faults.uninstall()


class RecordingStore(InProcessBucketStore):
    """Backing store that stamps every authoritative admission — the
    ground truth the dual-ownership audit replays. Envelope decisions
    (degraded or handoff) never reach a store, by design; their totals
    are bounded by the epsilon assertion instead."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.admissions: list[tuple[str, float, bool]] = []

    async def acquire(self, key, count, capacity, fill_rate_per_sec):
        res = await super().acquire(key, count, capacity,
                                    fill_rate_per_sec)
        self.admissions.append((key, time.monotonic(),
                                bool(res.granted and count > 0)))
        return res


def _owner_timeline(initial: PlacementMap, log: list[dict]):
    """Reconstruct the committed map sequence: ``[(t_commit, map), …]``
    starting from the initial map (t = -inf)."""
    timeline = [(float("-inf"), initial)]
    m = initial
    for e in log:
        if e["type"] != "commit":
            continue
        m = m.with_assignments(
            {int(s): int(d) for s, d in e["moves"].items()},
            set_overrides=e["keys"] or None)
        timeline.append((e["t_end"], m))
    return timeline


def _audit_dual_ownership(initial: PlacementMap, log: list[dict],
                          backings: "list[RecordingStore]") -> int:
    """The differential audit: every store-level admission must come
    from the key's owner at that instant — or from {old, new} owner
    inside the admitting migration's handoff window. Returns the number
    of admissions checked (the audit must not be vacuous)."""
    timeline = _owner_timeline(initial, log)
    windows = []  # (t_start, t_end, before_map, after_map, moved-pred)
    for (t0, before), (t1, after), e in zip(
            timeline, timeline[1:],
            [e for e in log if e["type"] == "commit"]):
        moved_slots = {int(s) for s in e["moves"]}
        moved_keys = set(e["keys"])
        windows.append((e["t_start"], e["t_end"], before, after,
                        moved_slots, moved_keys))
    checked = 0
    for node_idx, store in enumerate(backings):
        for key, t, granted in store.admissions:
            if not granted:
                continue
            checked += 1
            # owner under the committed timeline at time t
            owner = next(m for tc, m in reversed(timeline) if tc <= t
                         ).node_of(key)
            if node_idx == owner:
                continue
            in_window = any(
                t_start <= t <= t_end
                and (key in moved_keys
                     or before.slot_of(key) in moved_slots)
                and node_idx in (before.node_of(key), after.node_of(key))
                for t_start, t_end, before, after, moved_slots,
                moved_keys in windows)
            assert in_window, (
                f"key {key!r} admitted by node {node_idx} at t={t:.4f} "
                f"while node {owner} owned it, outside any handoff "
                "window — dual ownership")
    return checked


class TestReshardSoak:
    RULES = {
        "client.connect": (
            FaultRule("reset", probability=0.10),
            FaultRule("delay", probability=0.2, delay_s=0.001,
                      jitter_s=0.002),
        ),
        "server.dispatch": (
            FaultRule("delay", probability=0.05, delay_s=0.002,
                      jitter_s=0.002),
        ),
    }

    def test_soak_membership_invariants(self):
        """Join + hot-split + drain + rejoin under load and wire chaos:
        ≥2 join/leave episodes, ≥1 hot-shard split, bounded
        over-admission, the dual-ownership differential audit, and a
        deterministic schedule."""

        async def main():
            inj = FaultInjector(SEED, self.RULES)
            faults.install(inj)
            backings = [RecordingStore() for _ in range(3)]
            servers = [BucketStoreServer(b) for b in backings]
            for s in servers:
                await s.start()
            addrs = [(s.host, s.port) for s in servers]
            cap_hot = 40.0
            common = dict(coalesce_requests=False, request_timeout_s=1.0,
                          reconnect_backoff_base_s=0.004,
                          resilience_seed=SEED)
            # Coordinator runs membership; follower drives load with a
            # map that goes stale at every commit (MOVED chasing). The
            # follower knows the full node INVENTORY (addresses are
            # deployment config) but starts on the same 2-node epoch-0
            # map — ownership is only ever learned from the map.
            coordinator = ClusterBucketStore(addresses=addrs[:2],
                                             handoff_window_s=3.0,
                                             **common)
            initial = PlacementMap.initial(2)
            follower = ClusterBucketStore(addresses=addrs,
                                          placement=initial, **common)
            assert coordinator.placement == initial

            hot_grants = 0
            cold_ok = 0
            cold_n = 0
            stop = asyncio.Event()

            async def drive():
                nonlocal hot_grants, cold_ok, cold_n
                i = 0
                while not stop.is_set():
                    i += 1
                    try:
                        r = await follower.acquire("hot", 1, cap_hot,
                                                   1e-9)
                        hot_grants += r.granted
                    except _NET_ERRORS:
                        pass
                    cold_n += 1
                    try:
                        r = await follower.acquire(f"cold{i % 16}", 1,
                                                   1e6, 1.0)
                        cold_ok += r.granted
                    except _NET_ERRORS:
                        pass
                    await asyncio.sleep(0)

            async def membership():
                await asyncio.sleep(0.10)
                # Episode 1 — JOIN: node 2 takes an even slot share,
                # with its state, while traffic flows.
                await coordinator.add_node(address=addrs[2])
                await asyncio.sleep(0.10)
                # Episode 2 — HOT-SHARD SPLIT, driven by the servers'
                # space-saving heavy-hitter sketches ('hot' dominates
                # every node's scalar admission lane).
                split = await coordinator.split_hot_keys(top_n=1)
                assert split == ["hot"], split
                await asyncio.sleep(0.10)
                # Episode 3 — LEAVE: drain node 0's slots (and state)
                # onto the survivors.
                await coordinator.drain_node(0)
                await asyncio.sleep(0.10)
                # Episode 4 — REJOIN: fold node 0 back in.
                await coordinator.rejoin_node(0)
                await asyncio.sleep(0.10)
                stop.set()

            driver = asyncio.ensure_future(drive())
            try:
                await asyncio.wait_for(membership(), 60.0)
                await driver
            finally:
                driver.cancel()
                try:
                    await driver
                except (asyncio.CancelledError, Exception):
                    pass

            try:
                log = coordinator.migration_log
                # Every migration completed or cleanly aborted — and
                # this seed's schedule commits all four episodes.
                assert all(e["type"] in ("commit", "abort") for e in log)
                commits = [e for e in log if e["type"] == "commit"]
                assert len(commits) == 4
                assert coordinator.placement.epoch == 4
                assert coordinator.placement.overrides.get("hot") \
                    is not None
                # ≥2 join/leave episodes + ≥1 hot split, by reason.
                reasons = [e["reason"] for e in commits]
                assert sum(r.startswith(("join", "drain", "rejoin"))
                           for r in reasons) >= 3
                assert any(r.startswith("hot-split") for r in reasons)

                # The follower converged on the final epoch via MOVED
                # chasing alone.
                assert follower.placement.epoch == 4

                # Differential dual-ownership audit over the ground
                # truth the stores recorded.
                checked = _audit_dual_ownership(initial, log, backings)
                assert checked >= 50, "audit must not be vacuous"

                # Epsilon envelope: each membership episode can cost at
                # most one fair-share envelope of the hot key's budget
                # (the PULL debit keeps old + new inside one balance;
                # the envelope itself is the bounded slack).
                budget = headroom_budget(cap_hot, fraction=0.5,
                                         min_budget=1.0)
                episodes = len(commits) + 1
                assert hot_grants <= cap_hot + budget * episodes, (
                    hot_grants, budget, episodes)
                assert hot_grants >= 10  # availability through churn
                assert cold_ok >= cold_n * 0.5

                # Schedule determinism: realized == pure preview, and a
                # twin injector under the same seed agrees.
                for seam in self.RULES:
                    realized = [e for e in inj.events if e.seam == seam]
                    assert realized == inj.schedule_preview(
                        seam, inj.occurrence_count(seam))
                twin = FaultInjector(SEED, self.RULES)
                for seam in self.RULES:
                    assert (twin.schedule_preview(
                        seam, inj.occurrence_count(seam))
                        == inj.schedule_preview(
                            seam, inj.occurrence_count(seam)))
            finally:
                await follower.aclose()
                await coordinator.aclose()
                for s in servers:
                    await s.aclose()

        run(main())

    def test_migration_abort_leaves_old_epoch_serving(self):
        """A handoff step failing mid-migration (seeded fault on the
        server.migrate seam) aborts cleanly: epoch unchanged, nothing
        stays parked, and the same change succeeds once the fault
        clears."""

        async def main():
            backings = [InProcessBucketStore() for _ in range(3)]
            servers = [BucketStoreServer(b) for b in backings]
            for s in servers:
                await s.start()
            addrs = [(s.host, s.port) for s in servers]
            cluster = ClusterBucketStore(
                addresses=addrs[:2], coalesce_requests=False,
                request_timeout_s=1.0, retry_policy=None)
            try:
                for i in range(12):
                    await cluster.acquire(f"k{i}", 1, 100.0, 1.0)
                faults.install(FaultInjector(SEED, {
                    "server.migrate": (FaultRule("error",
                                                 probability=1.0),)}))
                with pytest.raises(PlacementError):
                    await cluster.add_node(address=addrs[2])
                assert cluster.placement.epoch == 0
                assert cluster.migration_aborts == 1
                assert cluster.migration_log[-1]["type"] == "abort"
                # the old owners still serve every key authoritatively
                for i in range(12):
                    r = await cluster.acquire(f"k{i}", 0, 100.0, 1.0)
                    assert r.granted
                # fault clears → the SAME reshape commits (node 2 is
                # already a member; it just owns nothing yet)
                faults.uninstall()
                await cluster.rebalance(reason="retry")
                assert cluster.placement.epoch == 1
                assert cluster.placement.slot_counts(3).min() >= 10
                for i in range(12):
                    r = await cluster.acquire(f"k{i}", 0, 100.0, 1.0)
                    assert r.granted
            finally:
                await cluster.aclose()
                for s in servers:
                    await s.aclose()

        run(main())

    def test_fault_on_first_seam_aborts_typed_and_rolls_back_drain(self):
        """Regression (round-6 review): the FIRST cluster.migrate seam
        occurrence used to sit outside _apply_placement's try — an
        injected fault there escaped as a raw FaultInjectedError,
        skipping abort bookkeeping and leaking the drained-set mutation
        (a later innocent rebalance would then silently migrate the
        node's slots away)."""

        async def main():
            cluster = ClusterBucketStore(
                stores=[InProcessBucketStore() for _ in range(3)])
            try:
                faults.install(FaultInjector(SEED, {
                    "cluster.migrate": (FaultRule("error",
                                                  probability=1.0),)}))
                with pytest.raises(PlacementError):
                    await cluster.drain_node(2)
                assert 2 not in cluster.drained  # rollback happened
                assert cluster.placement.epoch == 0
                assert cluster.migration_log[-1]["type"] == "abort"
                faults.uninstall()
                await cluster.drain_node(2)
                assert 2 in cluster.drained
                assert cluster.placement.epoch == 1
            finally:
                faults.uninstall()
                await cluster.aclose()

        run(main())

    def test_abort_after_partial_push_retries_exactly_once(self):
        """Regression (round-6 review): an abort clears the destination
        push ledger for its target epoch — the retry reuses the epoch
        AND the batch ids, and stale ledger entries would dedup-drop the
        re-pushed state (init-on-miss over-admission). Observable: the
        retry's pushes count zero duplicates."""

        async def main():
            backings = [InProcessBucketStore() for _ in range(3)]
            servers = [BucketStoreServer(b) for b in backings]
            for s in servers:
                await s.start()
            addrs = [(s.host, s.port) for s in servers]
            cluster = ClusterBucketStore(
                addresses=addrs[:2], coalesce_requests=False,
                request_timeout_s=1.0, retry_policy=None)
            try:
                # Enough keys that BOTH sources ship nonempty batches to
                # the new owner (seam order: pull, pull, push, push).
                for i in range(40):
                    await cluster.acquire(f"k{i}", 1, 100.0, 0.0)
                faults.install(FaultInjector(SEED, {
                    "server.migrate": (FaultRule("error", after=3,
                                                 probability=1.0),)}))
                with pytest.raises(PlacementError):
                    await cluster.add_node(address=addrs[2])
                # precondition for the regression: attempt 1 really did
                # land a batch on the destination before the abort
                assert servers[2].placement.pushes_applied >= 1
                assert cluster.placement.epoch == 0
                faults.uninstall()
                await cluster.rebalance(reason="retry")
                assert cluster.placement.epoch == 1
                # the retry's re-pushed batches all APPLIED — none were
                # deduped against the aborted attempt's ledger
                assert servers[2].placement.pushes_duplicate == 0
            finally:
                faults.uninstall()
                await cluster.aclose()
                for s in servers:
                    await s.aclose()

        run(main())

    def test_concurrent_membership_ops_serialize(self):
        """Regression (round-6 review): membership ops on one
        coordinator used to race — two overlapping calls both read the
        same epoch, built conflicting targets, and the second commit
        silently overwrote the first's slot moves. The coordinator lock
        serializes them: both commit, at distinct epochs, and the final
        map reflects BOTH changes."""

        async def main():
            cluster = ClusterBucketStore(
                stores=[InProcessBucketStore() for _ in range(3)])
            try:
                hot = next(f"k{i}" for i in range(64)
                           if cluster.node_index_of(f"k{i}") == 0)
                await cluster.acquire(hot, 1, 100.0, 1.0)
                await asyncio.gather(
                    cluster.drain_node(2),
                    cluster.split_hot_key(hot, target=1))
                assert cluster.placement.epoch == 2
                commits = [e for e in cluster.migration_log
                           if e["type"] == "commit"]
                assert len(commits) == 2
                # both changes survive in the final committed map
                assert int(cluster.placement.slot_counts(3)[2]) == 0
                assert cluster.placement.overrides.get(hot) == 1
                r = await cluster.acquire(hot, 0, 100.0, 1.0)
                assert r.granted
            finally:
                await cluster.aclose()

        run(main())

    def test_fresh_coordinator_adopts_fleet_epoch(self):
        """Regression (round-6 review): a coordinator constructed AFTER
        the fleet resharded (its map defaults to epoch 0) used to
        bootstrap-announce the stale map strictly to destinations — the
        nodes refused it as stale and every membership op aborted until
        someone manually called refresh_placement(). The first
        membership op now adopts the fleet's highest epoch first."""

        async def main():
            backings = [InProcessBucketStore() for _ in range(3)]
            servers = [BucketStoreServer(b) for b in backings]
            for s in servers:
                await s.start()
            addrs = [(s.host, s.port) for s in servers]
            first = ClusterBucketStore(
                addresses=addrs, coalesce_requests=False,
                request_timeout_s=1.0, retry_policy=None)
            second = None
            try:
                for i in range(12):
                    await first.acquire(f"k{i}", 1, 100.0, 1.0)
                await first.drain_node(2)
                assert first.placement.epoch == 1
                # a brand-new coordinator process attaches to the fleet
                second = ClusterBucketStore(
                    addresses=addrs, coalesce_requests=False,
                    request_timeout_s=1.0, retry_policy=None)
                assert second.placement.epoch == 0  # stale by default
                # its first membership op adopts epoch 1, then commits
                # on top of it instead of aborting on a stale announce
                await second.rebalance(reason="re-adopt")
                assert second.placement.epoch == 2
                for i in range(12):
                    r = await second.acquire(f"k{i}", 0, 100.0, 1.0)
                    assert r.granted
            finally:
                if second is not None:
                    await second.aclose()
                await first.aclose()
                for s in servers:
                    await s.aclose()

        run(main())

    def test_dead_node_drain_loses_only_its_state(self):
        """Unplanned leave: draining a DEAD node cannot pull its state —
        the survivors adopt its keyspace init-on-miss (the reference's
        wiped-state posture, scoped to one node) and the event records
        the loss."""

        async def main():
            backings = [InProcessBucketStore() for _ in range(2)]
            servers = [BucketStoreServer(b) for b in backings]
            for s in servers:
                await s.start()
            addrs = [(s.host, s.port) for s in servers]
            cluster = ClusterBucketStore(
                addresses=addrs, coalesce_requests=False,
                request_timeout_s=0.3, retry_policy=None,
                reconnect_backoff_base_s=0.01)
            try:
                for i in range(12):
                    await cluster.acquire(f"k{i}", 1, 100.0, 1.0)
                # bootstrap-announce happens on first migration; do a
                # no-op-ish one first so the death test isn't blocked on
                # announcing to the corpse
                await cluster.rebalance(reason="bootstrap")
                await servers[1].aclose()  # node 1 dies hard
                await cluster.drain_node(1)
                assert cluster.placement.slot_counts(2)[1] == 0
                ev = cluster.migration_log[-1]
                assert ev["type"] == "commit"
                assert ev.get("state_lost_from") == [1]
                # every key serves again (node 1's keys: fresh buckets)
                for i in range(12):
                    r = await cluster.acquire(f"k{i}", 1, 100.0, 1.0)
                    assert r.granted
            finally:
                await cluster.aclose()
                for s in servers:
                    await s.aclose()

        run(main())
