"""drl-verify gets verified: (a) the live tree is clean and the claim
is NON-vacuous (extraction sees every guard, the worlds explore real
state counts, every named invariant is wired); (b) every violation
class fires from a seeded divergence — a copy of the REAL source with
one guard removed, extracted and explored, so the extractor-model
coupling is pinned in both directions (a refactor that blinds the
extractor fails the seeded test, not just the live one); (c) every
counterexample is minimized and its generated replay pytest runs
against the real implementation; (d) the lock-order analyzer finds
cycles and sweep-order breaks with file:line on both sides.

Also here: the PROMOTED regression tests for the two real defects the
checker surfaced in runtime/placement.py (ISSUE 14's bugfix budget) —
the expiry-abort reservation dual-home and the stale destination copy
after a coordinator abort, each replayed trace-for-trace against the
real NodePlacementState/ReservationLedger pair and pinned to exactly
one refund."""

from __future__ import annotations

import dataclasses
import pathlib
from types import SimpleNamespace

import pytest

from tools.drl_verify import run_verify
from tools.drl_verify.explorer import explore, replay_trace
from tools.drl_verify.extract import (
    ExtractionError,
    Fact,
    extract_facts,
)
from tools.drl_verify.machines import (
    MODELED_OPS,
    READ_OPS,
    BreakerWorld,
    ConfigWorld,
    MigrationWorld,
    ProductWorld,
    ReservationWorld,
    all_worlds,
    unmodeled_idempotent_ops,
)
from tools.drl_verify import lockorder
from tools.drl_verify.replay import generate_pytest, replay_filename
from tools.drl_verify.replay_harness import replay

ROOT = pathlib.Path(__file__).resolve().parents[1]
RT = ROOT / "distributedratelimiting" / "redis_tpu" / "runtime"
UT = ROOT / "distributedratelimiting" / "redis_tpu" / "utils"
FRONTEND = ROOT / "native" / "frontend.cc"

FACTS = extract_facts(ROOT)


def run(world, **kw):
    kw.setdefault("max_states", 300_000)
    kw.setdefault("max_depth", 48)
    return explore(world, **kw)


# -- the live tree is clean, non-vacuously ----------------------------------

def test_live_tree_all_invariants_hold():
    res = run_verify(ROOT, include_product=False)
    assert res.violations == [], "\n".join(
        v.format() for v in res.violations)
    assert res.lock_findings == [], "\n".join(
        f.format() for f in res.lock_findings)
    assert res.unmodeled == []
    # The acceptance floor: enough invariants, and the base worlds are
    # EXHAUSTIVE (no truncation — the caps exist for the product).
    assert len(res.invariants_checked) >= 6
    for r in res.results:
        assert not r.truncated, r.world
    assert res.total_states >= 5_000


def test_product_world_scales_past_1e5_states():
    """The migration x config product (concurrent reshape + live limit
    mutation) carries the >= 10^5 product-state acceptance criterion;
    a capped run says so loudly instead of claiming exhaustiveness."""
    w = ProductWorld(MigrationWorld(FACTS), ConfigWorld(FACTS))
    r = explore(w, max_states=120_000, max_depth=48)
    assert r.states >= 100_000
    assert r.violations == []
    assert r.truncated_states  # the cap, reported — never silent


def test_extraction_sees_every_guard():
    """Vacuity guard: every fact is PRESENT on the live tree with a
    plausible provenance line, and the breaker table is the real
    4-edge machine."""
    for name, value in vars(FACTS).items():
        if isinstance(value, Fact):
            assert value.present, f"fact {name} not found"
            assert value.line > 0
            assert value.file.endswith(".py")
    assert FACTS.breaker_edges == {
        ("open", "timeout", "half_open"),
        ("half_open", "success", "closed"),
        ("half_open", "failure", "open"),
        ("closed", "failure", "open"),
    }
    assert set(FACTS.idempotent_ops) >= {
        "OP_PEEK", "OP_PLACEMENT_ANNOUNCE", "OP_MIGRATE_PULL",
        "OP_MIGRATE_PUSH", "OP_CONFIG", "OP_RESERVE", "OP_SETTLE"}


def test_every_idempotent_op_has_a_replay_model():
    assert unmodeled_idempotent_ops(FACTS) == []
    for op in FACTS.idempotent_ops:
        assert op in READ_OPS or op in MODELED_OPS, op


def test_full_invariant_coverage_is_wired():
    """The bugfix-budget pin: every invariant each world declares is
    exercised by the seeded-divergence matrix below (a new invariant
    cannot ship untested), and the two real defects this PR fixed have
    promoted replays (test_promoted_*)."""
    declared = set()
    for w in all_worlds(FACTS, include_product=False):
        declared |= set(w.invariants)
    covered = {want for _k, want in _KNOB_MATRIX} \
        | {want for *_x, want in _EDGE_MATRIX} \
        | {"idempotent-replay"}
    assert declared <= covered, declared - covered


# -- seeded divergences: one guard removed, extracted, explored -------------

def _shim(tmp_path, mutate: "dict[str, tuple[str, str]]"
          ) -> pathlib.Path:
    """A minimal tree with copies of the five extraction sources, one
    (or more) mutated by exact-anchor replacement. Asserting the anchor
    exists pins that the extractor still reads the REAL files' shapes."""
    shim = tmp_path / "repo"
    rt = shim / "distributedratelimiting" / "redis_tpu" / "runtime"
    ut = shim / "distributedratelimiting" / "redis_tpu" / "utils"
    rt.mkdir(parents=True)
    ut.mkdir(parents=True)
    for src, dst in [(RT / "remote.py", rt / "remote.py"),
                     (RT / "placement.py", rt / "placement.py"),
                     (RT / "liveconfig.py", rt / "liveconfig.py"),
                     (RT / "reservations.py", rt / "reservations.py"),
                     (RT / "federation.py", rt / "federation.py"),
                     (UT / "resilience.py", ut / "resilience.py")]:
        text = src.read_text()
        if src.name in mutate:
            old, new = mutate[src.name]
            assert old in text, f"fixture anchor gone from {src.name}:" \
                                f" {old!r}"
            text = text.replace(old, new, 1)
        dst.write_text(text)
    return shim


def _explore_shim(tmp_path, mutate) -> "tuple[list, object]":
    shim = _shim(tmp_path, mutate)
    facts = extract_facts(shim)
    violations = []
    for w in all_worlds(facts, include_product=False):
        violations += run(w).violations
    return violations, facts


#: (filename, anchor, replacement, invariant that must fire)
_KNOB_MATRIX = [
    (("placement.py", "pmap.epoch < self.pmap.epoch",
      "pmap.epoch < -1"),
     "epoch-monotonic"),
    (("placement.py",
      "pmap.epoch == self.pmap.epoch and pmap != self.pmap",
      "False and pmap != self.pmap"),
     "same-epoch-map-immutable"),
    (("placement.py", "self._handoffs.get(target_epoch)",
      "self._handoffs.get(-99)"),
     "idempotent-replay"),
    (("placement.py", "if target_epoch in self._aborted_epochs:",
      "if False:"),
     "idempotent-replay"),
    (("placement.py", "if batch in applied:", "if False:"),
     "idempotent-replay"),
    (("placement.py", "self._applied.pop(target_epoch, None)",
      "None"),
     "no-double-admit"),
    # The same dropped reset also strands the retried reservation row
    # (push batch 1 silently deduped) — both symptoms of one bug.
    (("placement.py", "self._applied.pop(target_epoch, None)",
      "None"),
     "res-survives-migration"),
    (("placement.py", "h.ledger.restore_rows(*h.res_stash)",
      "h.res_stash"),
     "abort-restores-old-epoch"),
    # THE shipped bug, un-fixed: expiry abort restoring the stash
    # dual-homes the rid under a slow commit -> double refund.
    (("placement.py",
      "self._abort(h.target_epoch, restore_reservations=False)",
      "self._abort(h.target_epoch)"),
     "settle-dedup"),
    # The SAME revert on the bulk-gate expiry site alone must also
    # drop the (ANDed) fact — the hot path cannot regress unseen.
    (("placement.py",
      "self._abort(e, restore_reservations=False)",
      "self._abort(e)"),
     "settle-dedup"),
    # Its destination half: abort keeping the imported rows.
    (("placement.py", "self._imported_res.pop(target_epoch, None)",
      "None"),
     "settle-dedup"),
    (("liveconfig.py",
      "if version <= self.version:\n            self.stale_announces",
      "if False:\n            self.stale_announces"),
     "config-version-monotonic"),
    (("liveconfig.py", "if staged is not None and staged != rule:",
      "if False:"),
     "same-version-rule-immutable"),
    (("liveconfig.py",
      "if version <= self.version:\n            return self.version"
      "  # idempotent: a retried commit no-ops",
      "if False:\n            return self.version"),
     "idempotent-replay"),
    (("liveconfig.py",
      "if version <= self.version:\n            return self.version"
      "  # idempotent: stale/duplicate no-op",
      "if False:\n            return self.version"),
     "config-version-monotonic"),
    (("liveconfig.py", "old_key = (rule.kind, rule.old[0], rule.old[1])",
      "self.rebased_rows += await _rebase_state(store, rule)\n"
      "        old_key = (rule.kind, rule.old[0], rule.old[1])"),
     "config-rebase-order"),
    (("reservations.py", "dup = self._duplicate_reserve(rid, tenant)",
      "dup = None"),
     "idempotent-replay"),
    (("reservations.py", "recorded = self._settled.get(rid)",
      "recorded = None"),
     "idempotent-replay"),
    (("reservations.py",
      "if rid in self._entries or rid in self._settled:",
      "if False:"),
     "outstanding-conserved"),
    (("reservations.py", "if (tag, tenant) in seen:", "if False:"),
     "debt-conserved"),
    (("resilience.py",
      "if self._probe_inflight:\n            if (self._clock() - "
      "self._probe_started\n                    < self.config."
      "recovery_timeout_s):\n                return \"reject\"",
      "if False:\n                return \"reject\""),
     "breaker-single-probe"),
    (("resilience.py",
      "if (self._clock() - self._probe_started\n"
      "                    < self.config.recovery_timeout_s):\n"
      "                return \"reject\"",
      "return \"reject\""),
     "breaker-no-wedge"),
    # -- federation (runtime/federation.py, ISSUE 15) -----------------
    # Recorded-grant replay dropped: a WAN retry of a granted
    # lease_id re-runs the grant body.
    (("federation.py", "dup = self._duplicate_lease(lease_id)",
      "dup = None"),
     "idempotent-replay"),
    # Region adopts slice epochs in any order: a stale out-of-order
    # reply rolls the applied config back.
    (("federation.py", "if epoch <= lease.epoch:", "if False:"),
     "fed-lease-monotonic"),
    # Expiry keyed on the WALL clock: a skewed clock extends the
    # lease past its monotonic TTL (the WAN-skew hazard the whole
    # design exists to prevent).
    (("federation.py",
      "now = self._clock() if now is None else now\n        n = 0",
      "now = self._wall() if now is None else now\n        n = 0"),
     "fed-no-skew-extension"),
    # The fully-spent presumption dropped: an unreachable region's
    # unreported slice entitlement escapes the global record.
    (("federation.py", "charge = self._conservative_charge(lease)",
      "charge = 0.0"),
     "fed-global-bound"),
    # Heal leaves the expired record behind: a re-delivered
    # renew/reclaim refunds the conservative charge twice.
    (("federation.py", "rec = self._expired.pop(lease_id, None)",
      "rec = self._expired.get(lease_id, None)"),
     "fed-reclaim-idempotent"),
]


@pytest.mark.parametrize(
    "mutation,want",
    _KNOB_MATRIX,
    ids=[f"{i:02d}-{m[0].removesuffix('.py')}-{w}"
         for i, (m, w) in enumerate(_KNOB_MATRIX)])
def test_seeded_divergence_fires(tmp_path, mutation, want):
    fname, old, new = mutation
    violations, _facts = _explore_shim(tmp_path,
                                       {fname: (old, new)})
    fired = {v.invariant for v in violations}
    assert want in fired, (
        f"expected {want!r}, got {sorted(fired)}:\n"
        + "\n".join(v.format() for v in violations))
    # Every violation carries a NON-EMPTY minimized trace whose final
    # action is the violating one (replayable end-violation).
    for v in violations:
        assert v.trace


#: Breaker transition-table mutations: (anchor, replacement, invariant)
_EDGE_MATRIX = [
    # record_failure's HALF_OPEN branch re-closing instead of opening.
    ("if self._state == self.HALF_OPEN:\n"
     "            self._transition(self.OPEN)",
     "if self._state == self.HALF_OPEN:\n"
     "            self._transition(self.CLOSED)",
     "breaker-failure-never-closes"),
    # the CLOSED threshold trip dropped.
    ("if self._failures >= self.config.failure_threshold:\n"
     "                self._transition(self.OPEN)",
     "if self._failures >= self.config.failure_threshold:\n"
     "                pass",
     "breaker-opens-at-threshold"),
    # OPEN -> HALF_OPEN recovery dropped.
    ("self._transition(self.HALF_OPEN)", "None",
     "breaker-no-wedge"),
    # HALF_OPEN success re-close dropped.
    ("if self._successes >= self.config.half_open_successes:\n"
     "                self._transition(self.CLOSED)",
     "if self._successes >= self.config.half_open_successes:\n"
     "                pass",
     "breaker-recloses"),
]


@pytest.mark.parametrize("old,new,want", _EDGE_MATRIX,
                         ids=[w for *_o, w in _EDGE_MATRIX])
def test_breaker_edge_mutation_fires(tmp_path, old, new, want):
    violations, facts = _explore_shim(
        tmp_path, {"resilience.py": (old, new)})
    fired = {v.invariant for v in violations}
    assert want in fired, sorted(fired)


def test_unmodeled_idempotent_op_is_flagged(tmp_path):
    """Adding an op to _IDEMPOTENT_OPS with no replay model must fail
    verification — the set cannot grow past what is verified."""
    shim = _shim(tmp_path, {
        "remote.py": (
            "    wire.OP_FED_LEASE, wire.OP_FED_RENEW, "
            "wire.OP_FED_RECLAIM,",
            "    wire.OP_FED_LEASE, wire.OP_FED_RENEW, "
            "wire.OP_FED_RECLAIM,\n"
            "    wire.OP_SAVE,")})
    facts = extract_facts(shim)
    assert unmodeled_idempotent_ops(facts) == ["OP_SAVE"]


def test_missing_extraction_anchor_is_loud(tmp_path):
    """A refactor that renames a modeled CLASS blinds the checker —
    that is an ExtractionError (CLI exit 2), never a silent clean."""
    shim = _shim(tmp_path, {
        "placement.py": ("class NodePlacementState:",
                         "class NodePlacementStateV2:")})
    with pytest.raises(ExtractionError):
        extract_facts(shim)


# -- counterexample minimization + generated replays ------------------------

def _one_violation(tmp_path, mutation, want):
    violations, facts = _explore_shim(tmp_path, mutation)
    hits = [v for v in violations if v.invariant == want]
    assert hits
    return hits[0], facts


def test_counterexample_is_minimized_and_replayable(tmp_path):
    v, facts = _one_violation(
        tmp_path,
        {"placement.py": (
            "self._abort(h.target_epoch, restore_reservations=False)",
            "self._abort(h.target_epoch)")},
        "settle-dedup")
    # Minimized: re-running with ANY single action dropped must no
    # longer reproduce this violation at the end of the schedule.
    world = MigrationWorld(facts)
    got = replay_trace(world, v.root, v.trace)
    assert got is not None and got[0] == "settle-dedup"
    for i in range(len(v.trace) - 1):
        cand = v.trace[:i] + v.trace[i + 1:]
        again = replay_trace(world, v.root, cand)
        assert again is None or again[0] != "settle-dedup" \
            or again[2] != got[2], (i, v.trace)


def test_generated_replay_pytest_runs_against_live_tree(tmp_path):
    """The generated pytest from a seeded (mutant) violation PASSES on
    the live tree: the real code still carries the guard the mutant
    lost. The model-to-code loop, both directions."""
    v, _facts = _one_violation(
        tmp_path,
        {"placement.py": ("if batch in applied:", "if False:")},
        "idempotent-replay")
    source = generate_pytest(v)
    path = tmp_path / replay_filename(v)
    path.write_text(source)
    ns: dict = {}
    exec(compile(source, str(path), "exec"), ns)   # noqa: S102
    test_fns = [f for n, f in ns.items()
                if n.startswith("test_replay_")]
    assert len(test_fns) == 1
    test_fns[0]()   # must not raise on the (fixed) live tree


# -- PROMOTED regressions: the two real defects this PR fixed ---------------

def test_promoted_expiry_abort_settle_dedup_replay():
    """drl-verify's first real catch: expiry abort racing a slow
    commit used to RESTORE the exported reservation rows while the
    committed destination already held them — a settle retry then
    refunded on both sides. The fixed code forfeits the stash on the
    expiry path; replaying the exact counterexample trace yields ONE
    refund."""
    report = replay(
        "migration",
        ["pull", "push_0", "push_1", "commit_dst", "expire",
         "settle_src", "settle_dst"],
        SimpleNamespace(sb=2, res0=True))
    assert report.ok, report.detail
    assert report.refunds == 1


def test_promoted_coord_abort_drops_dst_copy_replay():
    """The destination half: a coordinator abort used to clear only
    the push-dedup ledger, leaving imported reservation rows live at
    the destination — after a retried migration committed, the stale
    copy refunded a second time. The fixed _abort drops the imported
    rows; the exact counterexample trace yields ONE refund."""
    report = replay(
        "migration",
        ["pull", "push_1", "coord_abort", "retry", "settle_src",
         "pull", "push_0", "push_1", "commit_dst", "settle_dst"],
        SimpleNamespace(sb=2, res0=True))
    assert report.ok, report.detail
    assert report.refunds == 1


def test_promoted_expiry_forfeit_keeps_debt():
    """Review hardening on the expiry-forfeit fix: only RESERVATION
    rows are forfeited — exported DEBT rows come home (dropping them
    would FORGIVE the tenant's overdraft, the over-admission
    direction; dual-homed debt at worst double-collects, bounded by
    the tag dedup)."""
    import asyncio

    async def body():
        from tools.drl_verify.replay_harness import (
            KEY,
            RID,
            TENANT,
            MigrationHarness,
        )

        h = MigrationHarness()
        # Build tenant debt: drain the tenant to 1 token, reserve it,
        # then settle an actual the empty bucket cannot cover.
        await h.src_store.acquire(TENANT, 3, 4.0, 0.0)
        res = await h.src_led.reserve(RID, TENANT, KEY, 1.0,
                                      4.0, 0.0, 2.0, 0.0)
        assert res.granted
        out = await h.src_led.settle(RID, TENANT, 3.0)
        assert out.debt > 0
        debt_before = sum(h.src_led.debts().values())
        assert debt_before > 0
        await h.step("pull")          # exports rows AND debts
        assert sum(h.src_led.debts().values()) == 0
        await h.step("expire")        # forfeit reservations, NOT debt
        assert sum(h.src_led.debts().values()) == pytest.approx(
            debt_before)
        assert h.src.res_stash_forfeited == 0  # rid settled pre-pull

    asyncio.run(body())


def test_promoted_fix_counters_visible():
    """The fix's observability: forfeits and dropped imports are
    counted in placement stats / ledger numeric stats."""
    from distributedratelimiting.redis_tpu.runtime.placement import (
        NodePlacementState,
    )
    from distributedratelimiting.redis_tpu.runtime.store import (
        InProcessBucketStore,
    )

    st = NodePlacementState()
    assert "res_stash_forfeited" in st.stats()
    led = InProcessBucketStore().reservation_ledger()
    assert "aborted_imports" in led.numeric_stats()
    assert led.drop_rids(["nope"]) == 0   # unknown rids: counted no-op


def test_promoted_provenance_eviction_drops_rows():
    """Review hardening: _prune_ledger evicting _imported_res abort
    provenance must DROP the tracked rows (conservative), not strand
    them dual-homed for a later abort to miss."""
    import asyncio

    async def body():
        from distributedratelimiting.redis_tpu.runtime.placement import (
            NodePlacementState,
        )
        from distributedratelimiting.redis_tpu.runtime.store import (
            InProcessBucketStore,
        )

        dst_store = InProcessBucketStore()
        led = dst_store.reservation_ledger()
        dst = NodePlacementState()
        # More in-flight import epochs than the ledger keeps.
        depth = NodePlacementState._LEDGER_EPOCHS
        for e in range(1, depth + 3):
            await dst.push(
                {"target_epoch": e, "batch": 0, "entries": {
                    "reservations": [[f"t{e}", f"rid{e}", "k", 1.0,
                                      2.0, 0.0, 4.0, 0.0, 0, 10.0]],
                }}, dst_store)
        # The evicted (oldest) epochs' rows left the ledger with their
        # provenance; the retained epochs' rows are still outstanding.
        assert "rid1" not in led._entries
        assert "rid2" not in led._entries
        assert f"rid{depth + 2}" in led._entries
        assert led.aborted_imports >= 2

    asyncio.run(body())


# -- lock-order analyzer ----------------------------------------------------

_CYCLE_SRC = '''\
import threading

lock_a = threading.Lock()
lock_b = threading.Lock()


def forward():
    with lock_a:
        with lock_b:
            pass


def backward():
    with lock_b:
        with lock_a:
            pass
'''


def test_lock_cycle_fires_once_with_both_sides():
    fns, bases = lockorder.py_summaries_from_source(
        _CYCLE_SRC, "snippet", "snippet.py")
    graph, _c = lockorder.build_graph(
        ROOT, frontend=pathlib.Path("/nonexistent"),
        py_fns=fns, py_bases=bases)
    findings = lockorder.check_graph(graph)
    assert [f.rule for f in findings] == ["lock-cycle"]
    f = findings[0]
    assert "py:snippet.lock_a" in f.message
    assert "py:snippet.lock_b" in f.message
    # file:line for EVERY edge of the cycle (the two inner withs).
    assert len(f.related) == 2
    assert {ln for _f, ln, _n in f.related} == {9, 15}


def test_lock_cycle_via_call_resolution_fires():
    """A holds its lock and calls a uniquely-named method that takes
    B's lock; B's method does the reverse — a cross-object cycle found
    through call resolution, not lexical nesting."""
    src = '''\
import threading


class Alpha:
    def __init__(self):
        self._alpha_lock = threading.Lock()

    def grab_alpha_then_beta(self, beta):
        with self._alpha_lock:
            beta.grab_beta_then_alpha_inner()

    def grab_alpha_inner(self):
        with self._alpha_lock:
            pass


class Beta:
    def __init__(self):
        self._beta_lock = threading.Lock()

    def grab_beta_then_alpha(self, alpha):
        with self._beta_lock:
            alpha.grab_alpha_inner()

    def grab_beta_then_alpha_inner(self):
        with self._beta_lock:
            pass
'''
    fns, bases = lockorder.py_summaries_from_source(
        src, "snippet", "snippet.py")
    graph, _c = lockorder.build_graph(
        ROOT, frontend=pathlib.Path("/nonexistent"),
        py_fns=fns, py_bases=bases)
    cycles = [f for f in lockorder.check_graph(graph)
              if "Alpha" in f.message]
    assert len(cycles) == 1
    assert "py:Alpha._alpha_lock" in cycles[0].message
    assert "py:Beta._beta_lock" in cycles[0].message


def test_rlock_reentrancy_is_not_a_cycle():
    """self.method() taking the SAME attribute while held is the RLock
    pattern (now_ticks_checked/force_rebase) — no edge, no cycle."""
    src = '''\
import threading


class Store:
    def __init__(self):
        self._lock = threading.RLock()

    def outer(self):
        with self._lock:
            self.inner()

    def inner(self):
        with self._lock:
            pass
'''
    fns, bases = lockorder.py_summaries_from_source(
        src, "snippet", "snippet.py")
    graph, _c = lockorder.build_graph(
        ROOT, frontend=pathlib.Path("/nonexistent"),
        py_fns=fns, py_bases=bases)
    assert not [f for f in lockorder.check_graph(graph)
                if "Store" in f.message]


def test_live_lock_graph_is_clean_and_populated():
    graph, c_fns = lockorder.build_graph(ROOT)
    assert lockorder.check_graph(graph) == []
    assert lockorder.check(ROOT) == []
    # Non-vacuous: the C half sees the shard + slice mutex classes,
    # the documented shard->slice order edge, and the one combined
    # all-slices section (fe_t0_retire).
    assert {"c:FeMutex", "c:T0SpinMutex"} <= graph.nodes
    assert ("c:FeMutex", "c:T0SpinMutex") in graph.edges
    assert len(graph.nodes) >= 20
    locky = [n for n, c in c_fns.items() if c.direct or c.multi]
    assert len(locky) >= 25
    assert c_fns["fe_t0_retire"].multi, \
        "the all-slices combined section went invisible"
    sweep_fns = {n for n, c in c_fns.items() if c.multi}
    assert sweep_fns == {"fe_t0_retire"}


def _mutated_frontend(tmp_path, old: str, new: str) -> pathlib.Path:
    text = FRONTEND.read_text()
    assert old in text, f"fixture anchor gone from frontend.cc: {old!r}"
    out = tmp_path / "frontend.cc"
    out.write_text(text.replace(old, new, 1))
    return out


def test_reversed_slice_sweep_fires_once(tmp_path):
    cc = _mutated_frontend(
        tmp_path,
        "for (T0Part* part : parts) locks.emplace_back(part->mu);",
        "for (auto it = parts.rbegin(); it != parts.rend(); ++it) "
        "locks.emplace_back((*it)->mu);")
    findings = [f for f in lockorder.check(ROOT, frontend=cc)
                if f.rule == "slice-sweep-order"]
    assert len(findings) == 1
    assert "fe_t0_retire" in findings[0].message
    assert "NON-canonical" in findings[0].message


def test_reversed_sweep_multiline_loop_header_fires(tmp_path):
    """Review hardening: the reversed iterator usually lives in the
    `for (...)` header, not on the emplace line — the evidence window
    must include it."""
    cc = _mutated_frontend(
        tmp_path,
        "for (T0Part* part : parts) locks.emplace_back(part->mu);",
        "for (auto it = parts.rbegin(); it != parts.rend(); ++it) {\n"
        "    locks.emplace_back((*it)->mu);\n  }")
    findings = [f for f in lockorder.check(ROOT, frontend=cc)
                if f.rule == "slice-sweep-order"]
    assert len(findings) == 1
    assert "fe_t0_retire" in findings[0].message


def test_sweep_sanctioned_by_name_not_file_order(tmp_path):
    """Review hardening: the sanctioned section is fe_t0_retire BY
    NAME. Renaming it away while another multi-slice section exists
    flags BOTH (neither is sanctioned) — no silent pass, no blaming
    the wrong site."""
    text = FRONTEND.read_text()
    anchor = "int fe_t0_retire"
    assert anchor in text
    mutated = text.replace(anchor, "int fe_t0_retire_gone", 1).replace(
        '}  // extern "C"',
        'int fe_rogue(void* h) {\n'
        '  std::vector<T0Part*> parts = t0parts_of(h);\n'
        '  std::vector<std::unique_lock<T0SpinMutex>> locks;\n'
        '  for (T0Part* part : parts) locks.emplace_back(part->mu);\n'
        '  return 0;\n}\n}  // extern "C"')
    cc = tmp_path / "frontend.cc"
    cc.write_text(mutated)
    findings = [f for f in lockorder.check(ROOT, frontend=cc)
                if f.rule == "slice-sweep-order"]
    assert len(findings) == 2
    assert any("fe_rogue" in f.message for f in findings)
    assert any("fe_t0_retire_gone" in f.message for f in findings)


def test_second_multi_slice_section_fires(tmp_path):
    extra = '''
int fe_rogue_sweep(void* h) {
  std::vector<T0Part*> parts = t0parts_of(h);
  std::vector<std::unique_lock<T0SpinMutex>> locks;
  for (T0Part* part : parts) locks.emplace_back(part->mu);
  return 0;
}
'''
    cc = _mutated_frontend(tmp_path, '}  // extern "C"',
                           extra + '}  // extern "C"')
    findings = [f for f in lockorder.check(ROOT, frontend=cc)
                if f.rule == "slice-sweep-order"]
    assert len(findings) == 1
    assert "fe_rogue_sweep" in findings[0].message
    assert any("fe_t0_retire" in note for _f, _l, note
               in findings[0].related)


def test_nested_same_class_acquisition_fires(tmp_path):
    extra = '''
int fe_rogue_pair(void* h) {
  std::vector<T0Part*> parts = t0parts_of(h);
  std::lock_guard<T0SpinMutex> a(parts[0]->mu);
  std::lock_guard<T0SpinMutex> b(parts[1]->mu);
  return 0;
}
'''
    cc = _mutated_frontend(tmp_path, '}  // extern "C"',
                           extra + '}  // extern "C"')
    findings = [f for f in lockorder.check(ROOT, frontend=cc)
                if f.rule == "slice-sweep-order"]
    assert len(findings) == 1
    assert "fe_rogue_pair" in findings[0].message


def test_one_line_guarded_block_releases_at_line_end(tmp_path):
    """Review hardening: a guard declared inside a same-line brace
    block (`if (x) { lock_guard g(m); }`) dies at end of line — it
    must not be treated as held for the rest of the function and
    fabricate nested-acquisition edges."""
    extra = '''
int fe_rogue_oneline(void* h) {
  Shard* sh = shard_of(h);
  if (h) { std::lock_guard<FeMutex> a(sh->mu); }
  std::vector<T0Part*> parts = t0parts_of(h);
  std::lock_guard<T0SpinMutex> b(parts[0]->mu);
  return 0;
}
'''
    cc = _mutated_frontend(tmp_path, '}  // extern "C"',
                           extra + '}  // extern "C"')
    c_fns = lockorder.c_lock_summaries(cc)
    fn = c_fns["fe_rogue_oneline"]
    assert [k for k, _l in fn.direct] == ["FeMutex", "T0SpinMutex"]
    assert fn.held_acquires == []


def test_cross_language_bridge_edge(tmp_path):
    """A Python function holding a lock while calling an fe_* entry
    point gets an edge into the C lock classes that function takes."""
    src = '''\
import threading

pump_lock = threading.Lock()


def pump(lib, h):
    with pump_lock:
        lib.fe_t0_retire(h, 1.0, 0.0, None, 0, None, None, 0)
'''
    fns, bases = lockorder.py_summaries_from_source(
        src, "snippet", "snippet.py")
    graph, _c = lockorder.build_graph(ROOT, py_fns=fns,
                                      py_bases=bases)
    assert ("py:snippet.pump_lock", "c:T0SpinMutex") in graph.edges


# -- CLI --------------------------------------------------------------------

def test_cli_exit_codes(tmp_path):
    from tools.drl_verify.__main__ import main

    assert main(["--root", str(ROOT), "--no-product",
                 "--max-states", "60000"]) == 0
    # A seeded-divergent tree exits 1 and writes replay tests.
    shim = _shim(tmp_path, {
        "placement.py": ("if batch in applied:", "if False:")})
    out = tmp_path / "replays"
    assert main(["--root", str(shim), "--no-product",
                 "--no-lockorder", "--max-states", "60000",
                 "--emit-replays", str(out)]) == 1
    written = list(out.glob("test_replay_*.py"))
    assert written, "violations must emit replay pytests"
    # Distinct violation classes of ONE invariant get distinct files.
    from tools.drl_verify.explorer import Violation

    a = Violation("migration", "no-double-admit", "d", ("x",), None,
                  key="bound")
    b = Violation("migration", "no-double-admit", "d", ("x",), None,
                  key="dropped-import")
    assert replay_filename(a) != replay_filename(b)
    # A blinded extractor exits 2, never a fake clean.
    shim2 = _shim(tmp_path / "b", {
        "placement.py": ("class NodePlacementState:",
                         "class NodePlacementStateV2:")})
    assert main(["--root", str(shim2), "--no-product"]) == 2
