"""Disk checkpoint tests (SURVEY.md §5.4 — planned-restart snapshots)."""

import os

import pytest

from distributedratelimiting.redis_tpu.runtime.checkpoint import (
    load_snapshot,
    save_snapshot,
)
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.store import (
    DeviceBucketStore,
    InProcessBucketStore,
)


def _store(clock):
    return DeviceBucketStore(n_slots=64, counter_slots=8, clock=clock,
                             max_batch=64)


def test_file_roundtrip_preserves_decisions(tmp_path):
    clock = ManualClock()
    dev = _store(clock)
    dev.acquire_blocking("a", 3, 10.0, 1.0)
    dev.acquire_blocking("b", 9, 10.0, 1.0)
    path = str(tmp_path / "snap.bin")
    save_snapshot(dev, path)

    dev2 = _store(clock)
    load_snapshot(dev2, path)
    assert dev2.acquire_blocking("a", 7, 10.0, 1.0).granted
    assert not dev2.acquire_blocking("b", 7, 10.0, 1.0).granted


def test_restore_into_fresh_clock_epoch_keeps_refilling(tmp_path):
    old_clock = ManualClock(start_ticks=500_000)
    dev = _store(old_clock)
    dev.acquire_blocking("k", 10, 10.0, 1.0)  # drain the bucket
    path = str(tmp_path / "snap.bin")
    save_snapshot(dev, path)

    # "New process": clock starts near zero.
    new_clock = ManualClock(start_ticks=100)
    dev2 = _store(new_clock)
    load_snapshot(dev2, path)
    assert not dev2.acquire_blocking("k", 5, 10.0, 1.0).granted
    new_clock.advance_seconds(5.0)
    assert dev2.acquire_blocking("k", 5, 10.0, 1.0).granted


def test_atomic_write_leaves_previous_checkpoint_on_failure(tmp_path):
    clock = ManualClock()
    dev = _store(clock)
    dev.acquire_blocking("a", 1, 10.0, 1.0)
    path = str(tmp_path / "snap.bin")
    save_snapshot(dev, path)
    before = open(path, "rb").read()

    class UnpicklableSnapshot:
        # Failure must strike MID-WRITE (inside pickle.dump, after the
        # temp file exists) to exercise the cleanup branch.
        def snapshot(self):
            return {"bad": lambda: None}

    with pytest.raises(Exception):
        save_snapshot(UnpicklableSnapshot(), path)
    assert open(path, "rb").read() == before
    # No temp litter left behind.
    assert [p for p in os.listdir(tmp_path)
            if p.startswith(".snapshot-")] == []


def test_rejects_foreign_files(tmp_path):
    path = str(tmp_path / "junk.bin")
    import pickle

    with open(path, "wb") as f:
        pickle.dump({"magic": "other"}, f)
    with pytest.raises(ValueError, match="not a rate-limiter snapshot"):
        load_snapshot(InProcessBucketStore(), path)


def test_works_for_inprocess_store(tmp_path):
    clock = ManualClock()
    s = InProcessBucketStore(clock=clock)
    s.acquire_blocking("x", 4, 10.0, 1.0)
    path = str(tmp_path / "snap.bin")
    save_snapshot(s, path)
    s2 = InProcessBucketStore(clock=clock)
    load_snapshot(s2, path)
    assert s2.acquire_blocking("x", 6, 10.0, 1.0).granted
    assert not s2.acquire_blocking("x", 1, 10.0, 1.0).granted


def test_restore_adopts_snapshot_table_size_after_growth(tmp_path):
    """Regression: a checkpoint taken after a table doubled must restore
    into a fresh (default-sized) store instead of crash-looping."""
    clock = ManualClock()
    dev = DeviceBucketStore(n_slots=4, counter_slots=8, clock=clock,
                            max_batch=64)
    # 4 slots, 6 distinct never-expiring keys -> forces at least one grow.
    for i in range(6):
        dev.acquire_blocking(f"k{i}", 1, 10.0, 1000.0)
    table = dev._table(10.0, 1000.0)
    assert table.n_slots > 4
    path = str(tmp_path / "snap.bin")
    save_snapshot(dev, path)

    dev2 = DeviceBucketStore(n_slots=4, counter_slots=8, clock=clock,
                             max_batch=64)
    load_snapshot(dev2, path)
    t2 = dev2._table(10.0, 1000.0)
    assert t2.n_slots == table.n_slots
    # Restored keys still resolve to their buckets.
    for i in range(6):
        assert t2.dir.lookup(f"k{i}") is not None


def test_inprocess_restore_realigns_clock_epoch():
    """Regression: an in-process snapshot restored into a fresh process
    (clock near zero) must keep refilling from elapsed time."""
    old = ManualClock(start_ticks=5_000_000)
    s = InProcessBucketStore(clock=old)
    s.acquire_blocking("k", 10, 10.0, 1.0)  # drain
    snap = s.snapshot()

    fresh = ManualClock(start_ticks=10)
    s2 = InProcessBucketStore(clock=fresh)
    s2.restore(snap)
    assert not s2.acquire_blocking("k", 5, 10.0, 1.0).granted
    fresh.advance_seconds(5.0)
    assert s2.acquire_blocking("k", 5, 10.0, 1.0).granted


def test_pre_fixed_window_snapshot_keys_normalize_on_restore():
    """Back-compat: snapshots written before the fixed-window feature carry
    2-tuple device wtable keys / 3-tuple in-process window keys; restore
    must map them onto the sliding (interpolate=True) tables."""
    clock = ManualClock()

    # Device store: simulate an old snapshot by rewriting the key tuples.
    dev = DeviceBucketStore(n_slots=64, counter_slots=8, clock=clock,
                            max_batch=64)
    dev.window_acquire_blocking("w", 3, 5.0, 1.0)
    snap = dev.snapshot()
    snap["wtables"] = {k[:2]: v for k, v in snap["wtables"].items()}
    dev2 = DeviceBucketStore(n_slots=64, counter_slots=8, clock=clock,
                             max_batch=64)
    dev2.restore(snap)
    # 3 of 5 consumed in the current window survived the restore.
    assert dev2.window_acquire_blocking("w", 2, 5.0, 1.0).granted
    assert not dev2.window_acquire_blocking("w", 1, 5.0, 1.0).granted

    # In-process store: same rewrite on the 4-tuple window keys.
    s = InProcessBucketStore(clock=clock)
    s.window_acquire_blocking("w", 3, 5.0, 1.0)
    snap = s.snapshot()
    snap["windows"] = {k[:3]: v for k, v in snap["windows"].items()}
    s2 = InProcessBucketStore(clock=clock)
    s2.restore(snap)
    assert s2.window_acquire_blocking("w", 2, 5.0, 1.0).granted
    assert not s2.window_acquire_blocking("w", 1, 5.0, 1.0).granted


def test_fixed_window_table_checkpoint_roundtrip(tmp_path):
    clock = ManualClock()
    dev = DeviceBucketStore(n_slots=64, counter_slots=8, clock=clock,
                            max_batch=64)
    dev.fixed_window_acquire_blocking("f", 4, 5.0, 1.0)
    path = str(tmp_path / "snap.bin")
    save_snapshot(dev, path)
    dev2 = DeviceBucketStore(n_slots=64, counter_slots=8, clock=clock,
                             max_batch=64)
    load_snapshot(dev2, path)
    assert dev2.fixed_window_acquire_blocking("f", 1, 5.0, 1.0).granted
    assert not dev2.fixed_window_acquire_blocking("f", 1, 5.0, 1.0).granted


def test_v1_snapshot_restores_into_v2_build(tmp_path):
    """Rollforward compat: a v1 file (no sema sections, 2-tuple wtable
    keys) loads cleanly — restore treats the newer sections as optional."""
    import pickle

    clock = ManualClock()
    dev = _store(clock)
    dev.acquire_blocking("a", 3, 10.0, 1.0)
    snap = dev.snapshot()
    del snap["semas"], snap["sema_dir"]  # what a v1 writer never wrote
    path = str(tmp_path / "v1.bin")
    with open(path, "wb") as f:
        pickle.dump({"magic": "drl-tpu-snapshot", "version": 1,
                     "snapshot": snap}, f, protocol=5)
    dev2 = _store(clock)
    load_snapshot(dev2, path)
    assert dev2.acquire_blocking("a", 7, 10.0, 1.0).granted
    assert not dev2.acquire_blocking("a", 1, 10.0, 1.0).granted


def test_unknown_newer_version_fails_loudly(tmp_path):
    import pickle

    path = str(tmp_path / "future.bin")
    with open(path, "wb") as f:
        pickle.dump({"magic": "drl-tpu-snapshot", "version": 99,
                     "snapshot": {}}, f, protocol=5)
    with pytest.raises(ValueError, match="version 99 not supported"):
        load_snapshot(_store(ManualClock()), path)


# -- corruption detection (v3 checksum + typed errors) -----------------------

def test_truncated_snapshot_raises_typed_error(tmp_path):
    from distributedratelimiting.redis_tpu.runtime.checkpoint import (
        SnapshotCorruptError,
    )

    clock = ManualClock()
    s = InProcessBucketStore(clock=clock)
    s.acquire_blocking("x", 4, 10.0, 1.0)
    path = str(tmp_path / "snap.bin")
    save_snapshot(s, path)
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[: len(data) // 2])  # torn write
    with pytest.raises(SnapshotCorruptError, match="torn or corrupt"):
        load_snapshot(InProcessBucketStore(), path)
    # The typed error subclasses ValueError: pre-typed catches survive.
    assert issubclass(SnapshotCorruptError, ValueError)


def test_bitflip_fails_checksum(tmp_path):
    from distributedratelimiting.redis_tpu.runtime.checkpoint import (
        SnapshotCorruptError,
    )

    clock = ManualClock()
    s = InProcessBucketStore(clock=clock)
    for i in range(32):
        s.acquire_blocking(f"k{i}", 2, 10.0, 1.0)
    path = str(tmp_path / "snap.bin")
    save_snapshot(s, path)
    data = bytearray(open(path, "rb").read())
    # Flip one bit deep inside the nested snapshot body — past the outer
    # dict's header so the outer pickle still parses.
    data[len(data) * 3 // 4] ^= 0x10
    with open(path, "wb") as f:
        f.write(bytes(data))
    with pytest.raises(SnapshotCorruptError):
        load_snapshot(InProcessBucketStore(), path)


def test_v3_roundtrip_carries_checksum(tmp_path):
    import pickle

    clock = ManualClock()
    s = InProcessBucketStore(clock=clock)
    s.acquire_blocking("x", 4, 10.0, 1.0)
    path = str(tmp_path / "snap.bin")
    save_snapshot(s, path)
    payload = pickle.load(open(path, "rb"))
    assert payload["version"] == 3
    assert "crc32" in payload and "snapshot_pickle" in payload
    s2 = InProcessBucketStore(clock=clock)
    load_snapshot(s2, path)  # round-trips clean
    assert s2.acquire_blocking("x", 6, 10.0, 1.0).granted
    assert not s2.acquire_blocking("x", 1, 10.0, 1.0).granted


def test_placement_epoch_gate(tmp_path):
    """Satellite: placement-versioned checkpoints. A rejoining node held
    to the cluster's current epoch refuses a snapshot from a retired
    one — typed (PlacementMismatchError, a SnapshotCorruptError so every
    init-on-miss fallback already handles it) and BEFORE any state loads."""
    from distributedratelimiting.redis_tpu.runtime.checkpoint import (
        PlacementMismatchError,
        SnapshotCorruptError,
    )

    clock = ManualClock()
    s = InProcessBucketStore(clock=clock)
    s.acquire_blocking("x", 4, 10.0, 1.0)
    path = str(tmp_path / "snap.bin")
    save_snapshot(s, path, placement_epoch=3)

    # matching epoch restores clean
    s2 = InProcessBucketStore(clock=clock)
    load_snapshot(s2, path, expected_placement_epoch=3)
    assert not s2.acquire_blocking("x", 7, 10.0, 1.0).granted

    # mismatched epoch: typed refusal, store untouched
    s3 = InProcessBucketStore(clock=clock)
    with pytest.raises(PlacementMismatchError):
        load_snapshot(s3, path, expected_placement_epoch=5)
    assert s3.snapshot()["buckets"] == {}
    assert issubclass(PlacementMismatchError, SnapshotCorruptError)

    # a file with NO recorded epoch also fails an epoch expectation
    save_snapshot(s, path)
    with pytest.raises(PlacementMismatchError):
        load_snapshot(InProcessBucketStore(), path,
                      expected_placement_epoch=3)
    # …but loads fine with no expectation (single-node deployments)
    load_snapshot(InProcessBucketStore(clock=clock), path)


# -- v4 incremental delta chains (round 7; docs/OPERATIONS.md §10) -----------

from distributedratelimiting.redis_tpu.runtime.checkpoint import (  # noqa: E402
    PlacementMismatchError,
    SnapshotChain,
    SnapshotChainError,
    SnapshotCorruptError,
    load_snapshot_chain,
)


def _chain_store(clock, n=0):
    s = InProcessBucketStore(clock=clock)
    for i in range(n):
        s.acquire_blocking(f"k{i}", 1, 100.0, 0.0)
    return s


def test_chain_roundtrip_preserves_decisions(tmp_path):
    clock = ManualClock()
    s = _chain_store(clock, 8)
    path = str(tmp_path / "snap.bin")
    chain = SnapshotChain(path, compact_ratio=10.0)
    assert chain.save(s) == path  # first save is the base
    s.acquire_blocking("k0", 50, 100.0, 0.0)
    p1 = chain.save(s)
    assert p1 == path + ".delta.1"
    s.acquire_blocking("k1", 99, 100.0, 0.0)
    assert chain.save(s) == path + ".delta.2"

    s2 = InProcessBucketStore(clock=clock)
    assert load_snapshot_chain(s2, path) == 2
    # exact balances survive base + 2 deltas
    assert s2.peek_blocking("k0", 100.0, 0.0) == 49.0
    assert s2.peek_blocking("k1", 100.0, 0.0) == 0.0
    assert s2.peek_blocking("k7", 100.0, 0.0) == 99.0


def test_chain_loader_without_deltas_is_plain_load(tmp_path):
    clock = ManualClock()
    s = _chain_store(clock, 3)
    path = str(tmp_path / "snap.bin")
    save_snapshot(s, path)
    s2 = InProcessBucketStore(clock=clock)
    assert load_snapshot_chain(s2, path) == 0
    assert s2.peek_blocking("k1", 100.0, 0.0) == 99.0


def test_sparse_delta_is_10x_smaller_than_full(tmp_path):
    """Acceptance: a table with <1% dirty slots checkpoints ≥10× smaller
    incrementally than the full v3 snapshot — on the DEVICE store, whose
    slot arrays are exactly what full saves re-serialize every time."""
    clock = ManualClock()
    dev = DeviceBucketStore(n_slots=4096, counter_slots=8, clock=clock,
                            max_batch=256)
    keys = [f"k{i}" for i in range(2000)]
    dev.acquire_many_blocking(keys, [1] * len(keys), 100.0, 0.0)
    dev.enable_dirty_tracking()
    path = str(tmp_path / "snap.bin")
    chain = SnapshotChain(path)
    chain.save(dev)
    base_size = os.path.getsize(path)
    # touch <1% of the table
    dirty = [f"k{i}" for i in range(10)]
    dev.acquire_many_blocking(dirty, [5] * len(dirty), 100.0, 0.0)
    stats = dev.dirty_stats()
    assert 0 < stats["dirty"] <= 40  # the touched slots, tracked
    p1 = chain.save(dev)
    delta_size = os.path.getsize(p1)
    assert delta_size * 10 <= base_size, (delta_size, base_size)

    dev2 = DeviceBucketStore(n_slots=4096, counter_slots=8, clock=clock,
                             max_batch=256)
    assert load_snapshot_chain(dev2, path) == 1
    assert dev2.peek_blocking("k3", 100.0, 0.0) == 94.0
    assert dev2.peek_blocking("k100", 100.0, 0.0) == 99.0


def test_chain_truncated_delta_raises_typed(tmp_path):
    clock = ManualClock()
    s = _chain_store(clock, 4)
    path = str(tmp_path / "snap.bin")
    chain = SnapshotChain(path, compact_ratio=10.0)
    chain.save(s)
    s.acquire_blocking("k0", 9, 100.0, 0.0)
    p1 = chain.save(s)
    data = open(p1, "rb").read()
    with open(p1, "wb") as f:
        f.write(data[: len(data) // 2])
    with pytest.raises(SnapshotChainError):
        load_snapshot_chain(InProcessBucketStore(), path)
    assert issubclass(SnapshotChainError, SnapshotCorruptError)


def test_chain_missing_base_raises_typed(tmp_path):
    clock = ManualClock()
    s = _chain_store(clock, 4)
    path = str(tmp_path / "snap.bin")
    chain = SnapshotChain(path, compact_ratio=10.0)
    chain.save(s)
    s.acquire_blocking("k0", 9, 100.0, 0.0)
    chain.save(s)
    os.unlink(path)  # the base vanishes; the deltas dangle
    with pytest.raises(SnapshotChainError, match="missing"):
        load_snapshot_chain(InProcessBucketStore(), path)


def test_chain_foreign_base_refused(tmp_path):
    """Stale deltas beside a base they do not belong to (operator copy,
    partial restore from backup) must not replay — base_crc is the
    chain's identity."""
    clock = ManualClock()
    s = _chain_store(clock, 4)
    path = str(tmp_path / "snap.bin")
    chain = SnapshotChain(path, compact_ratio=10.0)
    chain.save(s)
    s.acquire_blocking("k0", 9, 100.0, 0.0)
    chain.save(s)
    # an operator copies in a different base file, bypassing the save
    # lanes (which would have retired the chain)
    s.acquire_blocking("k1", 5, 100.0, 0.0)
    other = str(tmp_path / "other.bin")
    save_snapshot(s, other)
    os.replace(other, path)
    with pytest.raises(SnapshotChainError, match="different base"):
        load_snapshot_chain(InProcessBucketStore(), path)


def test_plain_full_save_retires_the_chain(tmp_path):
    """Review regression: a full save_snapshot over a chained path used
    to leave the .delta.* links — the next chain-aware load refused the
    NEW valid base (base_crc mismatch) and wiped to init-on-miss. A
    full save now supersedes the chain (the --snapshot-incremental
    flag can be turned off between restarts safely)."""
    clock = ManualClock()
    s = _chain_store(clock, 4)
    path = str(tmp_path / "snap.bin")
    chain = SnapshotChain(path, compact_ratio=10.0)
    chain.save(s)
    s.acquire_blocking("k0", 9, 100.0, 0.0)
    chain.save(s)
    s.acquire_blocking("k1", 5, 100.0, 0.0)
    save_snapshot(s, path)  # plain full save, chain manager not used
    assert [q for q in os.listdir(tmp_path) if ".delta." in q] == []
    s2 = InProcessBucketStore(clock=clock)
    assert load_snapshot_chain(s2, path) == 0
    assert s2.peek_blocking("k1", 100.0, 0.0) == 94.0


def test_compaction_crash_window_keeps_old_base_loadable(tmp_path):
    """Review regression: compaction used to replace the base BEFORE
    unlinking the old chain — a crash between the two left foreign
    links beside the new base, refused wholesale at load (total state
    loss). Links now go first: a crash mid-compaction restores the OLD
    base's save point, never nothing."""
    clock = ManualClock()
    s = _chain_store(clock, 4)
    path = str(tmp_path / "snap.bin")
    chain = SnapshotChain(path, compact_ratio=10.0, max_chain=1)
    chain.save(s)
    s.acquire_blocking("k0", 9, 100.0, 0.0)
    chain.save(s)
    s.acquire_blocking("k1", 5, 100.0, 0.0)
    # crash INSIDE the compacting full save, after the old chain was
    # retired but before the new base lands
    from distributedratelimiting.redis_tpu.runtime import checkpoint as cp

    orig = cp._atomic_write
    cp._atomic_write = lambda *a: (_ for _ in ()).throw(
        OSError("disk gone"))
    try:
        with pytest.raises(OSError):
            chain.save(s)  # max_chain exceeded → compaction path
    finally:
        cp._atomic_write = orig
    # old base + first delta's state is gone (bounded staleness), but
    # the base itself restores cleanly — no SnapshotChainError, no
    # init-on-miss wipe
    s2 = InProcessBucketStore(clock=clock)
    assert load_snapshot_chain(s2, path) == 0
    assert s2.peek_blocking("k3", 100.0, 0.0) == 99.0


def test_chain_crc_bad_middle_link_raises_typed(tmp_path):
    clock = ManualClock()
    s = _chain_store(clock, 4)
    path = str(tmp_path / "snap.bin")
    chain = SnapshotChain(path, compact_ratio=10.0)
    chain.save(s)
    for i in range(3):
        s.acquire_blocking(f"k{i}", 3, 100.0, 0.0)
        chain.save(s)
    p2 = path + ".delta.2"
    data = bytearray(open(p2, "rb").read())
    data[len(data) * 3 // 4] ^= 0x10
    with open(p2, "wb") as f:
        f.write(bytes(data))
    with pytest.raises(SnapshotChainError, match="checksum"):
        load_snapshot_chain(InProcessBucketStore(), path)


def test_chain_missing_middle_link_raises_typed(tmp_path):
    clock = ManualClock()
    s = _chain_store(clock, 4)
    path = str(tmp_path / "snap.bin")
    chain = SnapshotChain(path, compact_ratio=10.0)
    chain.save(s)
    for i in range(3):
        s.acquire_blocking(f"k{i}", 3, 100.0, 0.0)
        chain.save(s)
    os.unlink(path + ".delta.1")
    with pytest.raises(SnapshotChainError, match="missing link"):
        load_snapshot_chain(InProcessBucketStore(), path)


def test_chain_placement_epoch_mismatch_raises_typed(tmp_path):
    clock = ManualClock()
    s = _chain_store(clock, 4)
    path = str(tmp_path / "snap.bin")
    chain = SnapshotChain(path, compact_ratio=10.0)
    chain.save(s, placement_epoch=3)
    s.acquire_blocking("k0", 3, 100.0, 0.0)
    chain.save(s, placement_epoch=3)
    # matching epoch loads clean
    assert load_snapshot_chain(InProcessBucketStore(clock=clock), path,
                               expected_placement_epoch=3) == 1
    with pytest.raises(PlacementMismatchError):
        load_snapshot_chain(InProcessBucketStore(), path,
                            expected_placement_epoch=5)


def test_chain_epoch_change_compacts_to_fresh_base(tmp_path):
    """A chain is single-epoch by contract: a save under a new placement
    epoch becomes a full base, not a mixed-epoch link."""
    clock = ManualClock()
    s = _chain_store(clock, 4)
    path = str(tmp_path / "snap.bin")
    chain = SnapshotChain(path, compact_ratio=10.0)
    chain.save(s, placement_epoch=1)
    s.acquire_blocking("k0", 3, 100.0, 0.0)
    chain.save(s, placement_epoch=1)
    s.acquire_blocking("k1", 3, 100.0, 0.0)
    p = chain.save(s, placement_epoch=2)  # epoch moved → full save
    assert p == path
    assert [q for q in os.listdir(tmp_path) if ".delta." in q] == []
    assert load_snapshot_chain(InProcessBucketStore(clock=clock), path,
                               expected_placement_epoch=2) == 0


def test_chain_compacts_at_max_length(tmp_path):
    clock = ManualClock()
    s = _chain_store(clock, 4)
    path = str(tmp_path / "snap.bin")
    chain = SnapshotChain(path, max_chain=2, compact_ratio=10.0)
    chain.save(s)
    for i in range(2):
        s.acquire_blocking(f"k{i}", 2, 100.0, 0.0)
        assert chain.save(s) == path + f".delta.{i + 1}"
    s.acquire_blocking("k2", 2, 100.0, 0.0)
    assert chain.save(s) == path  # chain full → compact to fresh base
    assert [q for q in os.listdir(tmp_path) if ".delta." in q] == []
    assert chain.stats()["full_saves"] == 2
    s2 = InProcessBucketStore(clock=clock)
    assert load_snapshot_chain(s2, path) == 0
    assert s2.peek_blocking("k2", 100.0, 0.0) == 97.0


def test_writer_killed_mid_save_leaves_previous_checkpoint(tmp_path):
    """Satellite: SIGKILL strikes INSIDE a save (temp file written,
    fsync stalled, os.replace not reached) — the checkpoint path must
    still hold the previous, CRC-clean file."""
    import signal
    import subprocess
    import sys
    import textwrap

    path = str(tmp_path / "snap.bin")
    child = subprocess.Popen(
        [sys.executable, "-c", textwrap.dedent(f"""
            import os, sys, time
            from distributedratelimiting.redis_tpu.runtime.checkpoint \\
                import save_snapshot
            from distributedratelimiting.redis_tpu.runtime.store \\
                import InProcessBucketStore
            s = InProcessBucketStore()
            s.acquire_blocking("a", 1, 10.0, 0.0)
            save_snapshot(s, {path!r})
            print("READY", flush=True)
            real_fsync = os.fsync
            def stall(fd):
                print("MID", flush=True)
                time.sleep(1e6)
            os.fsync = stall
            s.acquire_blocking("b", 1, 10.0, 0.0)
            save_snapshot(s, {path!r})
        """)],
        stdout=subprocess.PIPE, text=True)
    try:
        assert child.stdout.readline().strip() == "READY"
        assert child.stdout.readline().strip() == "MID"
        child.send_signal(signal.SIGKILL)
        child.wait(30)
    finally:
        if child.poll() is None:
            child.kill()
    # The interrupted save left a temp file but never touched the path:
    # the previous checkpoint loads clean (only "a" was ever saved).
    s2 = InProcessBucketStore()
    load_snapshot(s2, path)
    assert s2.peek_blocking("a", 10.0, 0.0) == 9.0
    assert s2.peek_blocking("b", 10.0, 0.0) == 10.0  # never persisted
