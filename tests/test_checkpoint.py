"""Disk checkpoint tests (SURVEY.md §5.4 — planned-restart snapshots)."""

import os

import pytest

from distributedratelimiting.redis_tpu.runtime.checkpoint import (
    load_snapshot,
    save_snapshot,
)
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.store import (
    DeviceBucketStore,
    InProcessBucketStore,
)


def _store(clock):
    return DeviceBucketStore(n_slots=64, counter_slots=8, clock=clock,
                             max_batch=64)


def test_file_roundtrip_preserves_decisions(tmp_path):
    clock = ManualClock()
    dev = _store(clock)
    dev.acquire_blocking("a", 3, 10.0, 1.0)
    dev.acquire_blocking("b", 9, 10.0, 1.0)
    path = str(tmp_path / "snap.bin")
    save_snapshot(dev, path)

    dev2 = _store(clock)
    load_snapshot(dev2, path)
    assert dev2.acquire_blocking("a", 7, 10.0, 1.0).granted
    assert not dev2.acquire_blocking("b", 7, 10.0, 1.0).granted


def test_restore_into_fresh_clock_epoch_keeps_refilling(tmp_path):
    old_clock = ManualClock(start_ticks=500_000)
    dev = _store(old_clock)
    dev.acquire_blocking("k", 10, 10.0, 1.0)  # drain the bucket
    path = str(tmp_path / "snap.bin")
    save_snapshot(dev, path)

    # "New process": clock starts near zero.
    new_clock = ManualClock(start_ticks=100)
    dev2 = _store(new_clock)
    load_snapshot(dev2, path)
    assert not dev2.acquire_blocking("k", 5, 10.0, 1.0).granted
    new_clock.advance_seconds(5.0)
    assert dev2.acquire_blocking("k", 5, 10.0, 1.0).granted


def test_atomic_write_leaves_previous_checkpoint_on_failure(tmp_path):
    clock = ManualClock()
    dev = _store(clock)
    dev.acquire_blocking("a", 1, 10.0, 1.0)
    path = str(tmp_path / "snap.bin")
    save_snapshot(dev, path)
    before = open(path, "rb").read()

    class UnpicklableSnapshot:
        # Failure must strike MID-WRITE (inside pickle.dump, after the
        # temp file exists) to exercise the cleanup branch.
        def snapshot(self):
            return {"bad": lambda: None}

    with pytest.raises(Exception):
        save_snapshot(UnpicklableSnapshot(), path)
    assert open(path, "rb").read() == before
    # No temp litter left behind.
    assert [p for p in os.listdir(tmp_path)
            if p.startswith(".snapshot-")] == []


def test_rejects_foreign_files(tmp_path):
    path = str(tmp_path / "junk.bin")
    import pickle

    with open(path, "wb") as f:
        pickle.dump({"magic": "other"}, f)
    with pytest.raises(ValueError, match="not a rate-limiter snapshot"):
        load_snapshot(InProcessBucketStore(), path)


def test_works_for_inprocess_store(tmp_path):
    clock = ManualClock()
    s = InProcessBucketStore(clock=clock)
    s.acquire_blocking("x", 4, 10.0, 1.0)
    path = str(tmp_path / "snap.bin")
    save_snapshot(s, path)
    s2 = InProcessBucketStore(clock=clock)
    load_snapshot(s2, path)
    assert s2.acquire_blocking("x", 6, 10.0, 1.0).granted
    assert not s2.acquire_blocking("x", 1, 10.0, 1.0).granted


def test_restore_adopts_snapshot_table_size_after_growth(tmp_path):
    """Regression: a checkpoint taken after a table doubled must restore
    into a fresh (default-sized) store instead of crash-looping."""
    clock = ManualClock()
    dev = DeviceBucketStore(n_slots=4, counter_slots=8, clock=clock,
                            max_batch=64)
    # 4 slots, 6 distinct never-expiring keys -> forces at least one grow.
    for i in range(6):
        dev.acquire_blocking(f"k{i}", 1, 10.0, 1000.0)
    table = dev._table(10.0, 1000.0)
    assert table.n_slots > 4
    path = str(tmp_path / "snap.bin")
    save_snapshot(dev, path)

    dev2 = DeviceBucketStore(n_slots=4, counter_slots=8, clock=clock,
                             max_batch=64)
    load_snapshot(dev2, path)
    t2 = dev2._table(10.0, 1000.0)
    assert t2.n_slots == table.n_slots
    # Restored keys still resolve to their buckets.
    for i in range(6):
        assert t2.dir.lookup(f"k{i}") is not None


def test_inprocess_restore_realigns_clock_epoch():
    """Regression: an in-process snapshot restored into a fresh process
    (clock near zero) must keep refilling from elapsed time."""
    old = ManualClock(start_ticks=5_000_000)
    s = InProcessBucketStore(clock=old)
    s.acquire_blocking("k", 10, 10.0, 1.0)  # drain
    snap = s.snapshot()

    fresh = ManualClock(start_ticks=10)
    s2 = InProcessBucketStore(clock=fresh)
    s2.restore(snap)
    assert not s2.acquire_blocking("k", 5, 10.0, 1.0).granted
    fresh.advance_seconds(5.0)
    assert s2.acquire_blocking("k", 5, 10.0, 1.0).granted


def test_pre_fixed_window_snapshot_keys_normalize_on_restore():
    """Back-compat: snapshots written before the fixed-window feature carry
    2-tuple device wtable keys / 3-tuple in-process window keys; restore
    must map them onto the sliding (interpolate=True) tables."""
    clock = ManualClock()

    # Device store: simulate an old snapshot by rewriting the key tuples.
    dev = DeviceBucketStore(n_slots=64, counter_slots=8, clock=clock,
                            max_batch=64)
    dev.window_acquire_blocking("w", 3, 5.0, 1.0)
    snap = dev.snapshot()
    snap["wtables"] = {k[:2]: v for k, v in snap["wtables"].items()}
    dev2 = DeviceBucketStore(n_slots=64, counter_slots=8, clock=clock,
                             max_batch=64)
    dev2.restore(snap)
    # 3 of 5 consumed in the current window survived the restore.
    assert dev2.window_acquire_blocking("w", 2, 5.0, 1.0).granted
    assert not dev2.window_acquire_blocking("w", 1, 5.0, 1.0).granted

    # In-process store: same rewrite on the 4-tuple window keys.
    s = InProcessBucketStore(clock=clock)
    s.window_acquire_blocking("w", 3, 5.0, 1.0)
    snap = s.snapshot()
    snap["windows"] = {k[:3]: v for k, v in snap["windows"].items()}
    s2 = InProcessBucketStore(clock=clock)
    s2.restore(snap)
    assert s2.window_acquire_blocking("w", 2, 5.0, 1.0).granted
    assert not s2.window_acquire_blocking("w", 1, 5.0, 1.0).granted


def test_fixed_window_table_checkpoint_roundtrip(tmp_path):
    clock = ManualClock()
    dev = DeviceBucketStore(n_slots=64, counter_slots=8, clock=clock,
                            max_batch=64)
    dev.fixed_window_acquire_blocking("f", 4, 5.0, 1.0)
    path = str(tmp_path / "snap.bin")
    save_snapshot(dev, path)
    dev2 = DeviceBucketStore(n_slots=64, counter_slots=8, clock=clock,
                             max_batch=64)
    load_snapshot(dev2, path)
    assert dev2.fixed_window_acquire_blocking("f", 1, 5.0, 1.0).granted
    assert not dev2.fixed_window_acquire_blocking("f", 1, 5.0, 1.0).granted


def test_v1_snapshot_restores_into_v2_build(tmp_path):
    """Rollforward compat: a v1 file (no sema sections, 2-tuple wtable
    keys) loads cleanly — restore treats the newer sections as optional."""
    import pickle

    clock = ManualClock()
    dev = _store(clock)
    dev.acquire_blocking("a", 3, 10.0, 1.0)
    snap = dev.snapshot()
    del snap["semas"], snap["sema_dir"]  # what a v1 writer never wrote
    path = str(tmp_path / "v1.bin")
    with open(path, "wb") as f:
        pickle.dump({"magic": "drl-tpu-snapshot", "version": 1,
                     "snapshot": snap}, f, protocol=5)
    dev2 = _store(clock)
    load_snapshot(dev2, path)
    assert dev2.acquire_blocking("a", 7, 10.0, 1.0).granted
    assert not dev2.acquire_blocking("a", 1, 10.0, 1.0).granted


def test_unknown_newer_version_fails_loudly(tmp_path):
    import pickle

    path = str(tmp_path / "future.bin")
    with open(path, "wb") as f:
        pickle.dump({"magic": "drl-tpu-snapshot", "version": 99,
                     "snapshot": {}}, f, protocol=5)
    with pytest.raises(ValueError, match="version 99 not supported"):
        load_snapshot(_store(ManualClock()), path)


# -- corruption detection (v3 checksum + typed errors) -----------------------

def test_truncated_snapshot_raises_typed_error(tmp_path):
    from distributedratelimiting.redis_tpu.runtime.checkpoint import (
        SnapshotCorruptError,
    )

    clock = ManualClock()
    s = InProcessBucketStore(clock=clock)
    s.acquire_blocking("x", 4, 10.0, 1.0)
    path = str(tmp_path / "snap.bin")
    save_snapshot(s, path)
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[: len(data) // 2])  # torn write
    with pytest.raises(SnapshotCorruptError, match="torn or corrupt"):
        load_snapshot(InProcessBucketStore(), path)
    # The typed error subclasses ValueError: pre-typed catches survive.
    assert issubclass(SnapshotCorruptError, ValueError)


def test_bitflip_fails_checksum(tmp_path):
    from distributedratelimiting.redis_tpu.runtime.checkpoint import (
        SnapshotCorruptError,
    )

    clock = ManualClock()
    s = InProcessBucketStore(clock=clock)
    for i in range(32):
        s.acquire_blocking(f"k{i}", 2, 10.0, 1.0)
    path = str(tmp_path / "snap.bin")
    save_snapshot(s, path)
    data = bytearray(open(path, "rb").read())
    # Flip one bit deep inside the nested snapshot body — past the outer
    # dict's header so the outer pickle still parses.
    data[len(data) * 3 // 4] ^= 0x10
    with open(path, "wb") as f:
        f.write(bytes(data))
    with pytest.raises(SnapshotCorruptError):
        load_snapshot(InProcessBucketStore(), path)


def test_v3_roundtrip_carries_checksum(tmp_path):
    import pickle

    clock = ManualClock()
    s = InProcessBucketStore(clock=clock)
    s.acquire_blocking("x", 4, 10.0, 1.0)
    path = str(tmp_path / "snap.bin")
    save_snapshot(s, path)
    payload = pickle.load(open(path, "rb"))
    assert payload["version"] == 3
    assert "crc32" in payload and "snapshot_pickle" in payload
    s2 = InProcessBucketStore(clock=clock)
    load_snapshot(s2, path)  # round-trips clean
    assert s2.acquire_blocking("x", 6, 10.0, 1.0).granted
    assert not s2.acquire_blocking("x", 1, 10.0, 1.0).granted


def test_placement_epoch_gate(tmp_path):
    """Satellite: placement-versioned checkpoints. A rejoining node held
    to the cluster's current epoch refuses a snapshot from a retired
    one — typed (PlacementMismatchError, a SnapshotCorruptError so every
    init-on-miss fallback already handles it) and BEFORE any state loads."""
    from distributedratelimiting.redis_tpu.runtime.checkpoint import (
        PlacementMismatchError,
        SnapshotCorruptError,
    )

    clock = ManualClock()
    s = InProcessBucketStore(clock=clock)
    s.acquire_blocking("x", 4, 10.0, 1.0)
    path = str(tmp_path / "snap.bin")
    save_snapshot(s, path, placement_epoch=3)

    # matching epoch restores clean
    s2 = InProcessBucketStore(clock=clock)
    load_snapshot(s2, path, expected_placement_epoch=3)
    assert not s2.acquire_blocking("x", 7, 10.0, 1.0).granted

    # mismatched epoch: typed refusal, store untouched
    s3 = InProcessBucketStore(clock=clock)
    with pytest.raises(PlacementMismatchError):
        load_snapshot(s3, path, expected_placement_epoch=5)
    assert s3.snapshot()["buckets"] == {}
    assert issubclass(PlacementMismatchError, SnapshotCorruptError)

    # a file with NO recorded epoch also fails an epoch expectation
    save_snapshot(s, path)
    with pytest.raises(PlacementMismatchError):
        load_snapshot(InProcessBucketStore(), path,
                      expected_placement_epoch=3)
    # …but loads fine with no expectation (single-node deployments)
    load_snapshot(InProcessBucketStore(clock=clock), path)
