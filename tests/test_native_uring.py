"""io_uring data plane (round 16): feature detection and transport
semantics.

The uring transport's contract is DESIGN.md §21: the reply bytes are
the spec — swapping the shard IO loop from epoll to a ring (multishot
accept/recv, provided buffers, linked sends, optional SQPOLL) may
change syscall counts and nothing else. These tests pin the two halves
of that contract the parity fuzz cannot:

- the feature-detection matrix: every way a host can lack io_uring
  (operator kill switch, seccomp EPERM — simulated via the C side's
  DRL_TPU_URING_FAKE_DENY hook, which takes the same probe-failure
  path as a kernel without the syscall — and a stale .so without the
  uring ABI) must fall back to epoll loudly with ZERO behavior change;
- the transport-dependent semantics: the per-connection order contract
  under multishot recv's arbitrary rechunking, the single-envelope
  over-admission bound with 4 uring shards deciding concurrently, and
  a live OP_CONFIG retire sweeping every shard under uring bulk load.
"""

from __future__ import annotations

import asyncio
import json
import struct

import pytest

from distributedratelimiting.redis_tpu.models.approximate import (
    headroom_budget,
    overadmit_epsilon,
)
from distributedratelimiting.redis_tpu.runtime import wire
from distributedratelimiting.redis_tpu.runtime.native_frontend import (
    Tier0Config,
    native_bulk_loadgen,
    uring_probe,
)
from distributedratelimiting.redis_tpu.runtime.remote import RemoteBucketStore
from distributedratelimiting.redis_tpu.runtime.server import BucketStoreServer
from distributedratelimiting.redis_tpu.runtime.store import InProcessBucketStore
from distributedratelimiting.redis_tpu.utils.native import load_frontend_lib

_LIB = load_frontend_lib()
pytestmark = pytest.mark.skipif(
    _LIB is None or not getattr(_LIB, "has_uring", False),
    reason="native front-end library unavailable or predates the "
    "uring ABI")

#: The live-ring tests additionally need the kernel to grant a ring
#: (the fallback tests below do NOT — they run everywhere the ABI
#: exists, which is exactly the point of the matrix).
_URING_OK = bool(_LIB is not None and getattr(_LIB, "has_uring", False)
                 and _LIB.fe_uring_available())
needs_ring = pytest.mark.skipif(
    not _URING_OK, reason="io_uring unavailable on this host (kernel, "
    "seccomp, or io_uring_disabled) — live-ring test skipped")

#: Sanitizer builds (make asan-test / tsan-test) feature-gate the ring
#: off BEFORE the env hooks, so the probe's reason is the sanitizer
#: gate's — the FAKE_DENY arm's EPERM wording can only be observed on
#: an un-sanitized binary. The kill-switch arm is unaffected: its
#: reason is stamped by the mode-coercion path, not the probe.
_SANITIZER_GATED = (_LIB is not None and getattr(_LIB, "has_uring", False)
                    and not _LIB.fe_uring_available()
                    and "sanitizer" in uring_probe()[1])
not_sanitizer = pytest.mark.skipif(
    _SANITIZER_GATED, reason="sanitizer build: the ring is feature-gated "
    "off ahead of the FAKE_DENY hook, so the EPERM reason never surfaces "
    "— covered by the un-sanitized leg")


def run(coro):
    return asyncio.run(coro)


async def _roundtrip_raw(host, port, frames: "list[bytes]") -> list[bytes]:
    reader, writer = await asyncio.open_connection(host, port)
    try:
        for f in frames:
            writer.write(f)
        await writer.drain()
        out = []
        for _ in frames:
            hdr = await asyncio.wait_for(reader.readexactly(4), 10.0)
            (ln,) = struct.unpack("<I", hdr)
            out.append(hdr + await asyncio.wait_for(
                reader.readexactly(ln), 10.0))
        return out
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionResetError, BrokenPipeError):
            pass


async def _serves_normally(srv, transport_visible: bool = False) -> dict:
    """The zero-behavior-change oracle every fallback arm shares:
    scalar + bulk traffic decides correctly. ``transport_visible`` pins
    the OP_STATS shape: when uring was never (effectively) requested
    the epoll stats shape must survive byte-unchanged; when it WAS
    requested and fell back, the fe_transport diagnostic block must
    appear — the fallback is loud on the stats surface too."""
    store = RemoteBucketStore(address=(srv.host, srv.port))
    try:
        res = await store.acquire("fb", 1, 10.0, 1.0)
        assert res.granted
        many = await store.acquire_many([f"k{i % 4}" for i in range(16)],
                                        [1] * 16, 1e7, 1e7)
        assert many.granted.all()
        st = await store.stats()
        assert ("fe_transport" in st) == transport_visible, st.keys()
        return st
    finally:
        await store.aclose()


# -- feature-detection matrix -----------------------------------------------

def test_probe_reports_availability_with_reason():
    ok, reason = uring_probe()
    assert isinstance(ok, bool)
    assert reason, "probe must always explain itself"
    if ok:
        assert "io_uring available" in reason


def test_kill_switch_forces_epoll(monkeypatch):
    """DRL_TPU_NO_URING trumps an explicit uring request: every shard
    serves on epoll, the reason names the switch, behavior unchanged."""
    monkeypatch.setenv("DRL_TPU_NO_URING", "1")

    async def body():
        async with BucketStoreServer(InProcessBucketStore(),
                                     native_frontend=True,
                                     native_shards=2,
                                     native_uring="on") as srv:
            assert srv._native.uring_shards == 0
            ts = srv._native.transport_stats()
            assert ts["uring_shards"] == 0
            assert ts["fallbacks"] == 2
            assert all("DRL_TPU_NO_URING" in r
                       for r in ts["fallback_reasons"].values())
            await _serves_normally(srv, transport_visible=True)

    run(body())


@not_sanitizer
def test_seccomp_denied_falls_back_per_shard(monkeypatch):
    """A seccomp filter answering io_uring_setup with EPERM (simulated
    by the C side's FAKE_DENY hook — the identical code path a kernel
    without the syscall takes) must degrade every shard to epoll with
    the EPERM reason recorded, and the probe must say so too."""
    # An ambient kill switch outranks the hook (its check is first by
    # design) — clear it so the simulated denial is what the probe sees.
    monkeypatch.delenv("DRL_TPU_NO_URING", raising=False)
    monkeypatch.setenv("DRL_TPU_URING_FAKE_DENY", "1")
    ok, reason = uring_probe()
    assert not ok
    assert "EPERM" in reason and "seccomp" in reason

    async def body():
        async with BucketStoreServer(InProcessBucketStore(),
                                     native_frontend=True,
                                     native_shards=2,
                                     native_uring="sqpoll") as srv:
            assert srv._native.uring_shards == 0
            ts = srv._native.transport_stats()
            assert ts["fallbacks"] == 2
            assert all("EPERM" in r
                       for r in ts["fallback_reasons"].values())
            await _serves_normally(srv, transport_visible=True)
            # The uring loadgen arm must ALSO fall back (rc -2 path)
            # and still measure.
            f, r, _g, _el = await asyncio.to_thread(
                native_bulk_loadgen, srv.host, srv.port, conns=2,
                depth=2, frames_per_conn=10, rows_per_frame=32,
                keyspace=4, uring=True)
            assert f == 20 and r == 20 * 32

    run(body())


def test_stale_binary_fallback_serves_epoll(monkeypatch):
    """uring requested against a binary without the uring ABI must
    serve — on epoll, loudly — not fail: availability over throughput
    (the has_shards fallback's posture, one ABI generation later)."""
    async def body():
        monkeypatch.setattr(_LIB, "has_uring", False)
        try:
            async with BucketStoreServer(InProcessBucketStore(),
                                         native_frontend=True,
                                         native_shards=2,
                                         native_uring="on") as srv:
                assert srv._native.uring_mode == 0
                assert srv._native.uring_shards == 0
                assert srv._native.transport_stats() is None
                await _serves_normally(srv)
        finally:
            monkeypatch.setattr(_LIB, "has_uring", True)

    run(body())


def test_epoll_default_untouched_by_uring_abi():
    """No uring request → no uring: the default server must not open a
    ring just because the binary can (the epoll lane is the tier-1
    baseline and must stay bit-for-bit what it was)."""
    async def body():
        async with BucketStoreServer(InProcessBucketStore(),
                                     native_frontend=True,
                                     native_shards=2) as srv:
            assert srv._native.uring_shards == 0
            ts = srv._native.transport_stats()
            assert ts["mode"] == "epoll" and ts["uring_shards"] == 0
            await _serves_normally(srv)

    run(body())


# -- live-ring semantics ----------------------------------------------------

@needs_ring
def test_uring_shards_actually_on_ring():
    """The opt-in actually engages and pays: every shard reports the
    uring transport, the ring counters move, and the self-instrumented
    data-plane syscall counter comes in strictly below what the epoll
    transport spends on the IDENTICAL load (the benchmark sweep owns
    the big pipelined-ratio claim; this pins the direction under the
    pytest-sized load)."""
    async def run_one(uring):
        async with BucketStoreServer(InProcessBucketStore(),
                                     native_frontend=True,
                                     native_tier0=True,
                                     native_shards=2,
                                     native_uring=uring) as srv:
            expect = 2 if uring == "on" else 0
            assert srv._native.uring_shards == expect
            # Many concurrent connections is where the transports
            # diverge: one ring enter drains/submits for EVERY ready
            # conn in a burst, while the epoll loop pays recv+send per
            # ready conn (plus the epoll_wait itself).
            f, r, g, _el = await asyncio.to_thread(
                native_bulk_loadgen, srv.host, srv.port, conns=32,
                depth=4, frames_per_conn=25, rows_per_frame=64,
                keyspace=8, uring=(uring == "on"))
            assert f == 800 and r == 800 * 64 and g == r
            return srv._native.transport_stats()

    async def body():
        epoll = await run_one(None)
        uring = await run_one("on")
        assert uring["uring_shards"] == 2
        assert uring["sqes_submitted"] > 0
        assert uring["cqes_seen"] >= 800  # ≥ one recv CQE per frame burst
        assert epoll["enters"] == 0 and epoll["cqes_seen"] == 0
        assert uring["io_syscalls"] < epoll["io_syscalls"], (uring, epoll)

    run(body())


@needs_ring
def test_chained_chunk_order_under_multishot_recv():
    """The per-connection order contract under the uring transport's
    OWN segmentation: frames dribbled a few bytes at a time arrive as
    many multishot-recv CQEs (a rechunking epoll never produces), and
    a chained successor must still decide strictly AFTER its
    predecessor — including a malformed predecessor whose error reply
    must come back first."""
    async def body():
        async with BucketStoreServer(InProcessBucketStore(),
                                     native_frontend=True,
                                     native_shards=1,
                                     native_uring="on") as srv:
            assert srv._native.uring_shards == 1
            reader, writer = await asyncio.open_connection(srv.host,
                                                           srv.port)
            try:
                async def dribble(blob: bytes, step: int):
                    for i in range(0, len(blob), step):
                        writer.write(blob[i:i + step])
                        await writer.drain()
                        await asyncio.sleep(0.002)

                async def read_reply() -> bytes:
                    hdr = await asyncio.wait_for(
                        reader.readexactly(4), 10.0)
                    (ln,) = struct.unpack("<I", hdr)
                    return hdr + await asyncio.wait_for(
                        reader.readexactly(ln), 10.0)

                # Well-formed head + chained successor, 5 bytes/write.
                f1 = wire.encode_bulk_request(
                    1, [b"a", b"b", b"a"], [1, 1, 1], 100.0, 1.0)
                f2 = wire.encode_bulk_request(
                    2, [b"a", b"c"], [1, 1], 100.0, 1.0, chained=True)
                await dribble(f1 + f2, 5)
                r1, r2 = await read_reply(), await read_reply()
                assert r1[5:9] == struct.pack("<I", 1)
                assert r1[9] == wire.RESP_BULK
                assert r2[5:9] == struct.pack("<I", 2)
                assert r2[9] == wire.RESP_BULK
                # Malformed head (truncated body, re-stamped length) +
                # chained successor: the error must come back FIRST.
                bad = f1[4:-3]
                bad = struct.pack("<I", len(bad)) + bad
                f3 = wire.encode_bulk_request(
                    3, [b"d"], [1], 100.0, 1.0, chained=True)
                await dribble(bad + f3, 7)
                e1, e2 = await read_reply(), await read_reply()
                assert e1[9] == wire.RESP_ERROR
                assert e2[5:9] == struct.pack("<I", 3)
                assert e2[9] == wire.RESP_BULK
                # A pipelined burst after the dribbles: order holds at
                # normal segmentation on the same (parked) connection.
                frames = [wire.encode_bulk_request(
                    100 + i, [b"p%d" % (i % 3)], [1], 100.0, 1.0)
                    for i in range(32)]
                writer.write(b"".join(frames))
                await writer.drain()
                for i in range(32):
                    rep = await read_reply()
                    assert rep[5:9] == struct.pack("<I", 100 + i), i
            finally:
                writer.close()
                try:
                    await writer.wait_closed()
                except (ConnectionResetError, BrokenPipeError):
                    pass

    run(body())


@needs_ring
def test_uring_multishard_overadmit_bounded_by_flat_envelope():
    """The single-envelope acceptance bound survives the transport
    swap: 4 uring shards deciding concurrently from split budget
    shares stay inside the SAME flat epsilon as single-shard epoll
    (the envelope is tier-0 semantics — DESIGN.md §16 — and §21 says
    the transport may not move it)."""
    capacity, fill = 400.0, 1e-9
    cfg = Tier0Config(sync_interval_s=0.005, min_budget=8.0)
    budget = headroom_budget(capacity, fraction=cfg.budget_fraction,
                             min_budget=cfg.min_budget,
                             max_budget=cfg.max_budget)
    assert budget / 4 >= cfg.min_budget
    epsilon = overadmit_epsilon(budget, fill, cfg.sync_interval_s)
    n_keys, per_frame, frames, n_conns = 4, 25, 8, 4

    async def body():
        async with BucketStoreServer(InProcessBucketStore(),
                                     native_frontend=True,
                                     native_tier0=cfg,
                                     native_shards=4,
                                     native_uring="on") as srv:
            assert srv._native.uring_shards == 4
            stores = [RemoteBucketStore(address=(srv.host, srv.port))
                      for _ in range(n_conns)]
            try:
                keys = [f"u{i}" for i in range(n_keys)]
                frame_keys = [keys[i % n_keys]
                              for i in range(n_keys * per_frame)]
                counts = [1] * len(frame_keys)
                admitted = {k: 0 for k in keys}
                results = await asyncio.gather(
                    *(st.acquire_many(frame_keys, counts, capacity, fill)
                      for st in stores for _ in range(frames)))
                for res in results:
                    for k, g in zip(frame_keys, res.granted):
                        admitted[k] += bool(g)
                for k in keys:
                    assert admitted[k] <= capacity + epsilon, (
                        k, admitted[k], epsilon)
                    assert admitted[k] >= capacity * 0.9, (k, admitted[k])
            finally:
                for st in stores:
                    await st.aclose()

    run(body())


@needs_ring
def test_retire_fans_out_under_uring_bulk_load():
    """Live OP_CONFIG mutation with 4 uring shards under bulk load:
    after the sync pump retires the old config NO shard may answer
    old-config frames from a live replica — the fe_t0_retire sweep is
    transport-independent state, and the uring pump-facing submit path
    (fe_bulk_complete & co. queueing SENDs) must not reorder the
    terminal error/grant split."""
    old_cap, old_rate = 100000.0, 1e-9
    new_cap, new_rate = 120000.0, 2e-9
    cfg = Tier0Config(sync_interval_s=0.005, min_budget=8.0)

    async def body():
        async with BucketStoreServer(InProcessBucketStore(),
                                     native_frontend=True,
                                     native_tier0=cfg,
                                     native_shards=4,
                                     native_uring="on") as srv:
            assert srv._native.uring_shards == 4
            await asyncio.to_thread(
                native_bulk_loadgen, srv.host, srv.port, conns=16,
                depth=4, frames_per_conn=40, rows_per_frame=256,
                keyspace=8, capacity=old_cap, fill_rate=old_rate,
                uring=True)
            store = RemoteBucketStore(address=(srv.host, srv.port))
            try:
                st = await store.stats()
                hosting = [s["shard"] for s in st["shards"]
                           if s["tier0"]["entries"] > 0]
                assert len(hosting) >= 2, hosting
                load = asyncio.create_task(asyncio.to_thread(
                    native_bulk_loadgen, srv.host, srv.port, conns=8,
                    depth=2, frames_per_conn=40, rows_per_frame=256,
                    keyspace=8, capacity=old_cap, fill_rate=old_rate,
                    uring=True))
                for payload in ({"prepare": {"kind": "bucket",
                                             "old": [old_cap, old_rate],
                                             "new": [new_cap, new_rate]},
                                 "version": 1},
                                {"commit": 1}):
                    frame = wire.encode_request(900, wire.OP_CONFIG,
                                                key=json.dumps(payload))
                    reply = (await _roundtrip_raw(srv.host, srv.port,
                                                  [frame]))[0]
                    assert reply[9] != wire.RESP_ERROR, reply
                await load
                await asyncio.sleep(cfg.sync_interval_s * 10)
                for _ in range(16):
                    frame = wire.encode_bulk_request(
                        7, [b"b0", b"b1"], [1, 1], old_cap, old_rate)
                    reply = (await _roundtrip_raw(srv.host, srv.port,
                                                  [frame]))[0]
                    assert reply[9] == wire.RESP_ERROR, reply
                    assert b"config moved" in reply, reply
                    frame = wire.encode_bulk_request(
                        8, [b"b0", b"b1"], [1, 1], new_cap, new_rate)
                    reply = (await _roundtrip_raw(srv.host, srv.port,
                                                  [frame]))[0]
                    assert reply[9] == wire.RESP_BULK, reply
            finally:
                await store.aclose()

    run(body())
