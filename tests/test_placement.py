"""Placement-versioned cluster data plane: the epoch map, the wire
control ops, the server-side gate/handoff state, the store export/import
lanes, and the cluster's membership API (runtime/placement.py,
docs/DESIGN.md §12).

Load-bearing invariants pinned here:

- **Epoch-0 compatibility**: the initial map routes bit-identically to
  the legacy ``crc32 % N`` for every N — adopting the map is not itself
  a resharding event.
- **Monotonic epochs**: stale announces are typed, routable errors;
  re-announcing the current epoch is idempotent.
- **Exactly-once handoff**: a re-delivered MIGRATE_PUSH batch applies
  exactly once; a re-delivered PULL returns the cached (already
  debited) export.
- **The dual-ownership budget split**: the exported balance plus the
  old owner's envelope can never exceed the original balance plus one
  envelope.
- **Auto-abort**: an expired handoff window (dead coordinator) reverts
  the old owner to authoritative serving — no stranded keyspace.
- **Rejoin debit** (satellite bugfix): degraded-envelope grants are
  charged to the authoritative bucket when the node rejoins, not
  silently discarded.
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.models.approximate import (
    headroom_budget,
)
from distributedratelimiting.redis_tpu.parallel.sharded_store import (
    shard_of_key,
)
from distributedratelimiting.redis_tpu.runtime import placement, wire
from distributedratelimiting.redis_tpu.runtime.cluster import (
    ClusterBucketStore,
    PlacementError,
)
from distributedratelimiting.redis_tpu.runtime.placement import (
    NodePlacementState,
    PlacementMap,
    StalePlacementError,
)
from distributedratelimiting.redis_tpu.runtime.remote import RemoteBucketStore
from distributedratelimiting.redis_tpu.runtime.server import BucketStoreServer
from distributedratelimiting.redis_tpu.runtime.store import (
    InProcessBucketStore,
)
from distributedratelimiting.redis_tpu.utils.resilience import BreakerConfig


def run(coro):
    return asyncio.run(coro)


KEYS = ["hot", "alpha", "beta", "k" * 40, "\udc80bytes", "zeta", "t:9"]


# -- the map ------------------------------------------------------------------

class TestPlacementMap:
    def test_initial_routing_matches_legacy_modulus(self):
        for n in (1, 2, 3, 5, 8, 13):
            m = PlacementMap.initial(n)
            for k in KEYS:
                assert m.node_of(k) == shard_of_key(k, n), (n, k)

    def test_route_vectorized_matches_scalar_with_overrides(self):
        m = PlacementMap.initial(3).with_assignments(
            {0: 2}, set_overrides={"hot": 1})
        assert m.node_of("hot") == 1
        routed = m.route(list(KEYS))
        assert routed.tolist() == [m.node_of(k) for k in KEYS]

    def test_with_assignments_bumps_epoch_and_preserves_rest(self):
        m = PlacementMap.initial(2)
        m2 = m.with_assignments({3: 1})
        assert m2.epoch == 1 and m.epoch == 0
        assert int(m2.slot_owner[3]) == 1
        changed = np.nonzero(m.slot_owner != m2.slot_owner)[0]
        assert changed.tolist() in ([], [3])

    def test_json_round_trip(self):
        m = PlacementMap.initial(4).with_assignments(
            {1: 0, 2: 3}, set_overrides={"hot": 2})
        assert PlacementMap.from_json(m.to_json()) == m

    def test_rebalance_moves_even_out_counts(self):
        m = PlacementMap.initial(3)  # 48 slots
        m2 = m.with_assignments(m.rebalance_moves([0, 1, 2, 3]))
        assert sorted(m2.slot_counts(4).tolist()) == [12, 12, 12, 12]
        # leave: node 1 out, its slots redistribute
        m3 = m2.with_assignments(m2.rebalance_moves([0, 2, 3]))
        counts = m3.slot_counts(4)
        assert counts[1] == 0 and sorted(counts.tolist()) == [0, 16, 16, 16]

    def test_rebalance_already_balanced_is_empty(self):
        m = PlacementMap.initial(4)
        assert m.rebalance_moves(list(range(4))) == {}


# -- wire ops -----------------------------------------------------------------

class TestPlacementWire:
    def test_text_ops_round_trip(self):
        payload = '{"target_epoch": 3, "slots": [1, 2]}'
        for op in (wire.OP_PLACEMENT_ANNOUNCE, wire.OP_MIGRATE_PULL,
                   wire.OP_MIGRATE_PUSH):
            frame = wire.encode_request(9, op, payload)
            seq, dop, text, count, a, b = wire.decode_request(frame[4:])
            assert (seq, dop, text) == (9, op, payload)

    def test_fetch_is_empty_payload(self):
        frame = wire.encode_request(4, wire.OP_PLACEMENT)
        seq, op, key, *_ = wire.decode_request(frame[4:])
        assert (seq, op, key) == (4, wire.OP_PLACEMENT, "")

    def test_oversized_control_payload_raises(self):
        with pytest.raises(ValueError, match="MAX_FRAME"):
            wire.encode_request(1, wire.OP_MIGRATE_PUSH,
                                "x" * (wire.MAX_FRAME + 1))

    def test_op_names(self):
        assert wire.op_name(wire.OP_PLACEMENT) == "placement"
        assert wire.op_name(wire.OP_MIGRATE_PUSH) == "migrate_push"


# -- the node-side state ------------------------------------------------------

def _announce(ps: NodePlacementState, m: PlacementMap, node_id: int) -> int:
    return ps.announce({"map": m.to_dict(), "node_id": node_id})


class TestNodePlacementState:
    def test_announce_monotonic_idempotent_stale(self):
        ps = NodePlacementState()
        m = PlacementMap.initial(2)
        assert not ps.active
        assert _announce(ps, m, 0) == 0
        assert ps.active and ps.node_id == 0
        assert _announce(ps, m, 0) == 0  # idempotent
        m2 = m.with_assignments({0: 1})
        assert _announce(ps, m2, 0) == 1
        with pytest.raises(StalePlacementError):
            _announce(ps, m, 0)
        assert ps.stale_announces == 1

    def test_gate_serve_moved_and_override(self):
        ps = NodePlacementState()
        m = PlacementMap.initial(2)
        own = m.node_of("hot")
        _announce(ps, m, own)
        assert ps.gate("hot") is None  # owned → serve
        _announce(ps, m.with_assignments(
            set_overrides={"hot": 1 - own}), own)
        verdict = ps.gate("hot")
        assert verdict == ("moved", 1 - own)
        assert ps.moved_errors == 1

    def test_pull_idempotent_and_envelope_debits_export(self):
        async def main():
            store = InProcessBucketStore()
            await store.acquire("hot", 2, 40.0, 0.0)  # 38 tokens left
            ps = NodePlacementState()
            m = PlacementMap.initial(1)
            _announce(ps, m, 0)
            slot = m.slot_of("hot")
            req = {"target_epoch": 1, "slots": [slot], "window_s": 30.0}
            out = await ps.pull(req, store)
            assert out["cached"] is False
            budget = headroom_budget(40.0, fraction=0.5, min_budget=1.0)
            row = [r for r in out["entries"]["buckets"]
                   if r[0] == "hot"][0]
            assert row[3] == pytest.approx(38.0 - budget)
            again = await ps.pull(req, store)
            assert again["cached"] is True
            assert again["entries"] == out["entries"]
            assert ps.pulls == 1
            # parked: admission serves the envelope, bounded by budget
            grants = 0
            for _ in range(int(budget) + 10):
                verdict = ps.gate("hot")
                assert verdict is not None and verdict[0] == "envelope"
                granted, _rem = ps.envelope_acquire(
                    verdict[1], "hot", 1, 40.0, 0.0, "bucket")
                grants += granted
            assert grants == int(budget)

        run(main())

    def test_push_applies_exactly_once(self):
        async def main():
            store = InProcessBucketStore()
            entries = {"buckets": [["hot", 40.0, 0.0, 15.0, 0]]}
            ps = NodePlacementState()
            req = {"target_epoch": 1, "batch": 7, "entries": entries}
            assert await ps.push(req, store) == 1
            # re-delivered batch: counted no-op, state untouched
            assert await ps.push(req, store) == 0
            assert ps.pushes_duplicate == 1
            assert store._buckets[("hot", 40.0, 0.0)][0] == 15.0
            # a different batch id applies
            assert await ps.push({"target_epoch": 1, "batch": 8,
                                  "entries": {"buckets": [
                                      ["cold", 10.0, 1.0, 3.0, 0]]}},
                                 store) == 1

        run(main())

    def test_abort_resets_push_ledger(self):
        """Regression (round-6 review): a retried migration REUSES the
        aborted target epoch, so the abort must clear the exactly-once
        ledger — deduping attempt 2's batches against attempt 1 would
        silently drop re-pushed state (over-admission via init-on-miss);
        re-applying is merely conservative."""

        async def main():
            store = InProcessBucketStore()
            ps = NodePlacementState()
            req = {"target_epoch": 1, "batch": 7,
                   "entries": {"buckets": [["hot", 40.0, 0.0, 15.0, 0]]}}
            assert await ps.push(req, store) == 1
            ps.announce({"abort_epoch": 1})
            retry = {"target_epoch": 1, "batch": 7,
                     "entries": {"buckets": [["hot", 40.0, 0.0, 12.0, 0]]}}
            assert await ps.push(retry, store) == 1
            assert ps.pushes_duplicate == 0
            # the re-apply stayed conservative: never above attempt 1
            assert store._buckets[("hot", 40.0, 0.0)][0] <= 15.0

        run(main())

    def test_concurrent_duplicate_pull_debits_source_once(self):
        """Regression (round-6 review): pull's idempotency check spans
        an await (the off-thread export), so an in-flight duplicate — a
        post-send retry racing the original — used to run a SECOND
        export + source debit. The control lock makes the second caller
        wait and hit the cache."""

        async def main():
            store = InProcessBucketStore()
            await store.acquire("hot", 2, 40.0, 0.0)  # 38 left
            ps = NodePlacementState()
            m = PlacementMap.initial(1)
            _announce(ps, m, 0)
            req = {"target_epoch": 1, "slots": [m.slot_of("hot")],
                   "window_s": 30.0}
            a, b = await asyncio.gather(ps.pull(req, store),
                                        ps.pull(req, store))
            assert ps.pulls == 1
            assert sorted([a["cached"], b["cached"]]) == [False, True]
            assert a["entries"] == b["entries"]
            budget = headroom_budget(40.0, fraction=0.5, min_budget=1.0)
            # debited exactly once: the source holds ONE envelope, not
            # the twice-debited floor
            assert (store._buckets[("hot", 40.0, 0.0)][0]
                    == pytest.approx(budget))

        run(main())

    def test_expired_abort_tombstones_pull_until_abort_announce(self):
        """Regression (round-7 review): OP_MIGRATE_PULL is post-send
        retry-safe only while the cached export lives. If the handoff
        window expires (auto-abort — coordinator presumed dead) between
        the original pull and its wire-level retry, a silent re-export
        would debit the source a SECOND envelope. The late retry must
        hit a typed refusal; the coordinator's abort announce re-arms
        the epoch for the deliberate retry-same-epoch path."""

        async def main():
            now = [0.0]
            store = InProcessBucketStore()
            await store.acquire("hot", 2, 40.0, 0.0)  # 38 left
            ps = NodePlacementState(clock=lambda: now[0])
            m = PlacementMap.initial(1)
            _announce(ps, m, 0)
            req = {"target_epoch": 1, "slots": [m.slot_of("hot")],
                   "window_s": 2.0}
            await ps.pull(req, store)
            budget = headroom_budget(40.0, fraction=0.5, min_budget=1.0)
            assert (store._buckets[("hot", 40.0, 0.0)][0]
                    == pytest.approx(budget))
            now[0] = 3.0  # window expired: serving path auto-aborts
            assert ps.gate("hot") is None  # authoritative again
            assert ps.expired_aborts == 1
            # The late wire retry of the original pull: typed refusal,
            # NOT a second export + debit.
            with pytest.raises(PlacementError,
                               match="aborted on this node"):
                await ps.pull(req, store)
            assert (store._buckets[("hot", 40.0, 0.0)][0]
                    == pytest.approx(budget))  # still ONE envelope
            assert ps.pulls == 1
            # Coordinator acknowledges the abort → a deliberate retry
            # of the SAME target epoch works again (and knowingly
            # charges the documented second envelope).
            ps.announce({"abort_epoch": 1})
            out = await ps.pull(req, store)
            assert out["cached"] is False
            assert ps.pulls == 2

        run(main())

    def test_pull_pages_large_export(self, monkeypatch):
        """A pull whose export outgrows one frame pages: the reply
        carries one chunk + the total page count, later pages come from
        the handoff cache, and the reassembled pages equal the full
        export. Out-of-range pages are typed errors."""

        async def main():
            monkeypatch.setattr(placement, "_CHUNK_BYTE_BUDGET", 150)
            store = InProcessBucketStore()
            for i in range(6):
                await store.acquire(f"k{i}", 1, 40.0, 0.0)
            ps = NodePlacementState()
            m = PlacementMap.initial(1)
            _announce(ps, m, 0)
            req = {"target_epoch": 1,
                   "slots": list(range(m.n_slots)), "window_s": 30.0}
            first = await ps.pull(req, store)
            assert first["pages"] > 1
            assert ps.pulls == 1  # later pages never re-park/re-debit
            entries = first["entries"]
            for page in range(1, first["pages"]):
                more = await ps.pull({**req, "page": page}, store)
                assert more["cached"] is True
                assert more["pages"] == first["pages"]
                entries = placement.merge_entries(entries,
                                                  more["entries"])
            assert ps.pulls == 1
            assert ({r[0] for r in entries["buckets"]}
                    == {f"k{i}" for i in range(6)})
            with pytest.raises(PlacementError):
                await ps.pull({**req, "page": first["pages"]}, store)

        run(main())

    def test_pull_unions_slots_and_keys_override_independent(self):
        """Regression (caught by the round-6 drive): a drain that moves
        a node's slots AND an override pinned there must export BOTH —
        and a slot move must never drag along a key pinned elsewhere."""

        async def main():
            store = InProcessBucketStore()
            await store.acquire("hot", 1, 40.0, 0.0)     # override, here
            await store.acquire("alpha", 1, 40.0, 0.0)   # slot member
            ps = NodePlacementState()
            m = PlacementMap.initial(1).with_assignments(
                set_overrides={"hot": 0, "elsewhere": 1})
            _announce(ps, m, 0)
            slots = sorted({m.slot_of("alpha"), m.slot_of("hot"),
                            m.slot_of("elsewhere")})
            out = await ps.pull({"target_epoch": 2, "slots": slots,
                                 "keys": ["hot"], "window_s": 30.0},
                                store)
            exported = {r[0] for r in out["entries"]["buckets"]}
            assert exported == {"hot", "alpha"}
            # 'elsewhere' is pinned to another node: its slot moving
            # must not export it even if it had state here.

        run(main())

    def test_expired_window_auto_aborts_to_authoritative(self):
        async def main():
            t = [0.0]
            ps = NodePlacementState(clock=lambda: t[0])
            store = InProcessBucketStore()
            await store.acquire("hot", 1, 40.0, 0.0)
            m = PlacementMap.initial(1)
            _announce(ps, m, 0)
            await ps.pull({"target_epoch": 1,
                           "slots": [m.slot_of("hot")],
                           "window_s": 2.0}, store)
            assert ps.gate("hot")[0] == "envelope"
            t[0] = 5.0  # the commit never came
            assert ps.gate("hot") is None  # authoritative again
            assert ps.expired_aborts == 1

        run(main())

    def test_pull_debits_source_store_to_envelope(self):
        """The expiry-race bound: at pull time the source's OWN store is
        charged for the shipped amount, so its authoritative residual is
        exactly the envelope. Even if the handoff expires after a slow
        commit already announced the target epoch to the destinations,
        old (residual) + new (shipped) can never exceed the original
        balance plus one envelope."""

        async def main():
            t = [0.0]
            ps = NodePlacementState(clock=lambda: t[0])
            store = InProcessBucketStore()
            await store.acquire("hot", 2, 40.0, 0.0)  # 38 tokens left
            m = PlacementMap.initial(1)
            _announce(ps, m, 0)
            out = await ps.pull({"target_epoch": 1,
                                 "slots": [m.slot_of("hot")],
                                 "window_s": 2.0}, store)
            budget = headroom_budget(40.0, fraction=0.5, min_budget=1.0)
            shipped = [r for r in out["entries"]["buckets"]
                       if r[0] == "hot"][0][3]
            residual = store._buckets[("hot", 40.0, 0.0)][0]
            assert shipped == pytest.approx(38.0 - budget)
            assert residual == pytest.approx(budget)
            # expiry-abort resumes authoritative serving from the
            # residual — shipped + residual == the original balance.
            t[0] = 5.0
            assert ps.gate("hot") is None
            assert shipped + residual == pytest.approx(38.0)

        run(main())

    def test_announce_conflicting_same_epoch_map_raises(self):
        """Two coordinators racing to the same target epoch with
        different maps must not split-brain: the second, conflicting
        announce loses loudly (re-announcing the adopted map itself
        stays idempotent)."""
        ps = NodePlacementState()
        m = PlacementMap.initial(2)
        _announce(ps, m, 0)
        target = m.with_assignments({0: 1})
        _announce(ps, target, 0)
        twin = m.with_assignments({1: 0})  # same epoch, different map
        with pytest.raises(StalePlacementError):
            ps.announce({"map": twin.to_dict(), "node_id": 0})
        assert ps.epoch == target.epoch
        assert ps.pmap == target
        # the adopted map re-announced is still an idempotent no-op
        assert ps.announce({"map": target.to_dict(),
                            "node_id": 0}) == target.epoch

    def test_commit_drops_parked_and_answers_moved(self):
        async def main():
            ps = NodePlacementState()
            store = InProcessBucketStore()
            await store.acquire("hot", 1, 40.0, 0.0)
            m = PlacementMap.initial(2)
            own = m.node_of("hot")
            _announce(ps, m, own)
            slot = m.slot_of("hot")
            target = m.with_assignments({slot: 1 - own})
            await ps.pull({"target_epoch": target.epoch,
                           "slots": [slot], "window_s": 30.0}, store)
            _announce(ps, target, own)  # commit
            verdict = ps.gate("hot")
            assert verdict == ("moved", 1 - own)

        run(main())

    def test_abort_announce_unparks(self):
        async def main():
            ps = NodePlacementState()
            store = InProcessBucketStore()
            m = PlacementMap.initial(1)
            _announce(ps, m, 0)
            await ps.pull({"target_epoch": 1, "slots": [m.slot_of("hot")],
                           "window_s": 30.0}, store)
            assert ps.gate("hot")[0] == "envelope"
            ps.announce({"abort_epoch": 1})
            assert ps.gate("hot") is None
            assert ps.aborts == 1

        run(main())

    def test_bulk_gate_fast_path_and_masks(self):
        async def main():
            ps = NodePlacementState()
            m = PlacementMap.initial(2)
            own = m.node_of("alpha")
            _announce(ps, m, own)
            mine = [k for k in KEYS if m.node_of(k) == own]
            assert ps.bulk_gate(mine) is None  # all owned → fast path
            g = ps.bulk_gate(list(KEYS))
            assert g is not None
            serve_mask, env_rows, moved_mask = g
            for i, k in enumerate(KEYS):
                assert serve_mask[i] == (m.node_of(k) == own)
                assert moved_mask[i] == (m.node_of(k) != own)
            assert env_rows == []

        run(main())


# -- store export/import lanes ------------------------------------------------

class TestStateLanes:
    async def _seeded_store(self):
        s = InProcessBucketStore()
        await s.acquire("hot", 5, 50.0, 1.0)
        await s.acquire("cold", 1, 10.0, 2.0)
        await s.window_acquire("w", 3, 20.0, 10.0)
        await s.fixed_window_acquire("f", 2, 9.0, 5.0)
        await s.sync_counter("ctr", 4.0, 1.0)
        await s.concurrency_acquire("sem", 2, 8)
        return s

    def test_export_filters_by_predicate(self):
        s = run(self._seeded_store())
        entries = s.export_entries(lambda k: k in ("hot", "w", "ctr"))
        assert [r[0] for r in entries["buckets"]] == ["hot"]
        assert [r[0] for r in entries["windows"]] == ["w"]
        assert [r[0] for r in entries["counters"]] == ["ctr"]
        assert entries["semas"] == []

    def test_exact_lane_round_trip(self):
        async def main():
            src = await self._seeded_store()
            entries = src.export_entries(lambda k: True)
            dst = InProcessBucketStore()
            n = await placement.import_entries(dst, entries)
            assert n == placement.entry_count(entries)
            assert dst._buckets[("hot", 50.0, 1.0)][0] == pytest.approx(
                45.0, abs=1.0)
            assert dst._semas["sem"] == 2
            # idempotent-conservative: re-import never inflates
            await placement.import_entries(dst, entries)
            assert dst._buckets[("hot", 50.0, 1.0)][0] <= 45.0

        run(main())

    def test_generic_lane_uses_debit_kernel(self):
        async def main():
            src = await self._seeded_store()
            entries = src.export_entries(lambda k: True)

            class NoExact(InProcessBucketStore):
                import_entries = None  # force the generic replay lane

            dst = NoExact()
            await placement.import_entries(dst, entries)
            # debit lane lands the bucket balance exactly
            assert dst._buckets[("hot", 50.0, 1.0)][0] == pytest.approx(
                45.0, abs=1.0)
            # current-window usage replays (conservative direction)
            res = await dst.window_acquire("w", 18, 20.0, 10.0)
            assert not res.granted  # 3 already charged

        run(main())

    def test_unknown_snapshot_schema_fails_loudly(self):
        with pytest.raises(ValueError, match="snapshot schema"):
            placement.extract_entries({"now_ticks": 0, "weird": {}},
                                      lambda k: True)

    def test_chunk_and_split(self):
        entries = {"buckets": [[f"k{i}", 1.0, 1.0, 1.0, 0]
                               for i in range(10)]}
        chunks = placement.chunk_entries(entries, max_rows=4)
        assert [placement.entry_count(c) for c in chunks] == [4, 4, 2]
        split = placement.split_entries(entries,
                                        lambda k: int(k[1:]) % 3)
        assert sorted(split) == [0, 1, 2]
        assert sum(placement.entry_count(s)
                   for s in split.values()) == 10

    def test_chunk_sizes_keys_as_serialized(self):
        """Regression (round-6 review): chunk sizing must count the
        JSON-escaped key length, not characters — ensure_ascii expands
        every non-ASCII / surrogate-escaped char to a 6-byte \\uXXXX
        escape, so a 60 KiB hostile key serializes ~6x its character
        count and a character-counted chunk could exceed MAX_FRAME
        (wedging the migration on every retry)."""
        import json

        hostile = "\udc80é" * 30_000  # 60k chars, ~420KB escaped
        entries = {"buckets": [[hostile + str(i), 1.0, 1.0, 1.0, 0]
                               for i in range(6)]}
        chunks = placement.chunk_entries(entries)
        assert len(chunks) > 1  # character-counting packed all 6
        for c in chunks:
            assert len(json.dumps(c)) < wire.MAX_FRAME


# -- cluster integration ------------------------------------------------------

class FlakyNode(InProcessBucketStore):
    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.fail = False

    async def acquire(self, *a, **kw):
        if self.fail:
            raise ConnectionError("injected node outage")
        return await super().acquire(*a, **kw)


class TestClusterPlacement:
    def test_default_routing_identical_to_legacy(self):
        async def main():
            nodes = [InProcessBucketStore() for _ in range(3)]
            cluster = ClusterBucketStore(stores=nodes)
            for k in KEYS:
                assert cluster.node_index_of(k) == shard_of_key(k, 3)
            assert cluster.node_of(KEYS[0]) is nodes[shard_of_key(KEYS[0], 3)]
            await cluster.aclose()

        run(main())

    def test_in_process_membership_and_state_move(self):
        async def main():
            nodes = [InProcessBucketStore() for _ in range(2)]
            cluster = ClusterBucketStore(stores=nodes)
            for _ in range(5):
                await cluster.acquire("hot", 1, 50.0, 0.0)
            joined = await cluster.add_node(InProcessBucketStore())
            assert joined == 2 and cluster.placement.epoch == 1
            counts = cluster.placement.slot_counts(3)
            assert counts.min() >= 10  # 32 slots over 3 nodes
            # admission state followed any moved keys (in-process lane
            # has no envelope: balances move exactly)
            r = await cluster.acquire("hot", 1, 50.0, 0.0)
            assert r.remaining == pytest.approx(44.0, abs=1.0)
            await cluster.drain_node(0)
            assert cluster.placement.slot_counts(3)[0] == 0
            r = await cluster.acquire("hot", 1, 50.0, 0.0)
            assert r.remaining == pytest.approx(43.0, abs=1.0)
            assert [e["type"] for e in cluster.migration_log] == \
                ["commit", "commit"]
            await cluster.aclose()

        run(main())

    def test_in_process_pull_drains_source_exactly(self):
        """The in-process lane ships balances exactly AND drains the
        source in the same breath — a task interleaving between the
        pull and the commit cannot spend a balance the new owner
        already received."""

        async def main():
            nodes = [InProcessBucketStore() for _ in range(2)]
            cluster = ClusterBucketStore(stores=nodes)
            await cluster.acquire("hot", 5, 50.0, 0.0)  # 45 left
            src = cluster.node_index_of("hot")
            await cluster.drain_node(src)
            drained = nodes[src]._buckets.get(("hot", 50.0, 0.0))
            assert drained is not None and drained[0] == pytest.approx(0.0)
            r = await cluster.acquire("hot", 1, 50.0, 0.0)
            assert r.remaining == pytest.approx(44.0)
            await cluster.aclose()

        run(main())

    def test_handoff_deferral_does_not_advance_breaker(self):
        """A parked-key deferral is a HEALTHY node mid-handoff: it must
        not advance the node's circuit breaker (a trip would quarantine
        the node's entire keyspace as a side effect of a routine
        migration)."""

        async def main():
            nodes = [InProcessBucketStore() for _ in range(2)]
            cluster = ClusterBucketStore(
                stores=nodes,
                breaker=BreakerConfig(failure_threshold=1,
                                      recovery_timeout_s=60.0))

            async def deferred():
                raise wire.RemoteStoreError(
                    placement.HANDOFF_DEFERRAL_PREFIX
                    + " for this key (target epoch 3); retry shortly")

            with pytest.raises(wire.RemoteStoreError):
                await cluster._guarded_call(0, deferred)
            assert cluster._breakers[0].allow() == "allow"
            r = await cluster.acquire("hot", 1, 10.0, 1.0)
            assert r.granted
            await cluster.aclose()

        run(main())

    def test_bulk_moved_settles_half_open_probe(self):
        """Regression (round-6 review): the bulk fan-out's MOVED branch
        must settle a half-open breaker probe as a success, like the
        scalar lane — a healthy node answering 'placement moved' to a
        stale bulk frame used to leak the probe slot and quarantine the
        node's keyspace for a full recovery window."""

        async def main():
            class MovedBulkNode(FlakyNode):
                moved_bulk = False

                async def acquire_many(self, keys, counts, *a, **kw):
                    if self.moved_bulk:
                        raise wire.RemoteStoreError(
                            placement.MOVED_ERROR_PREFIX
                            + ": key routes to node 1 at epoch 1")
                    return await super().acquire_many(keys, counts,
                                                      *a, **kw)

            nodes = [MovedBulkNode(), InProcessBucketStore()]
            cluster = ClusterBucketStore(
                stores=nodes, partial_failures="deny",
                breaker=BreakerConfig(failure_threshold=1,
                                      recovery_timeout_s=0.05))
            key = next(k for k in KEYS if cluster.node_index_of(k) == 0)
            # open the breaker, then age into HALF_OPEN
            nodes[0].fail = True
            with pytest.raises(ConnectionError):
                await cluster.acquire(key, 1, 10.0, 1.0)
            nodes[0].fail = False
            await asyncio.sleep(0.06)
            # the probe-winning request is a bulk frame answered MOVED
            nodes[0].moved_bulk = True
            res = await cluster.acquire_many([key], [1], 10.0, 1.0)
            assert not res.granted[0]  # rows follow partial_failures
            # the node is healthy: probe settled, breaker re-closed
            assert cluster._breakers[0].allow() == "allow"
            nodes[0].moved_bulk = False
            r = await cluster.acquire(key, 1, 10.0, 1.0)
            assert r.granted
            await cluster.aclose()

        run(main())

    def test_health_gate_blocks_unfit_owner(self):
        async def main():
            nodes = [InProcessBucketStore(), FlakyNode()]
            cluster = ClusterBucketStore(
                stores=nodes,
                breaker=BreakerConfig(failure_threshold=1,
                                      recovery_timeout_s=60.0))
            nodes[1].fail = True
            key = next(k for k in KEYS if cluster.node_index_of(k) == 1)
            with pytest.raises(ConnectionError):
                await cluster.acquire(key, 1, 10.0, 1.0)
            # node 1's breaker is open → it cannot take ownership:
            # draining node 0 (whose slots would land on 1) must abort
            # cleanly at the health gate, epoch unchanged.
            with pytest.raises(PlacementError):
                await cluster.drain_node(0)
            assert cluster.migration_aborts == 1
            assert cluster.placement.epoch == 0
            assert cluster.migration_log[-1]["type"] == "abort"
            await cluster.aclose()

        run(main())

    def test_drain_last_active_node_refused(self):
        async def main():
            cluster = ClusterBucketStore(stores=[InProcessBucketStore()])
            with pytest.raises(PlacementError):
                await cluster.drain_node(0)
            await cluster.aclose()

        run(main())

    def test_rejoin_debit_reconciles_degraded_grants(self):
        """Satellite bugfix: grants served by the degraded envelope
        during an outage are debited against the node's buckets on
        rejoin — not silently discarded."""

        async def main():
            cap = 40.0
            nodes = [FlakyNode(), FlakyNode()]
            cluster = ClusterBucketStore(
                stores=nodes,
                breaker=BreakerConfig(failure_threshold=2,
                                      recovery_timeout_s=0.1),
                degraded_fallback=True, degraded_fraction=0.5)
            j = cluster.node_index_of("hot")
            nodes[j].fail = True
            grants = 0
            for _ in range(10):
                res = await cluster.acquire("hot", 1, cap, 0.0)
                grants += res.granted
            assert grants > 0
            assert cluster.degraded_decisions >= grants
            nodes[j].fail = False
            await asyncio.sleep(0.15)
            # next call probes, re-closes the breaker, and schedules the
            # rejoin debit
            await cluster.acquire("hot", 1, cap, 0.0)
            for _ in range(50):
                if cluster.rejoin_debits:
                    break
                await asyncio.sleep(0.01)
            assert cluster.rejoin_debits >= 1
            # the authoritative bucket was charged for the outage grants
            tokens = nodes[j]._buckets[("hot", cap, 0.0)][0]
            assert tokens <= cap - grants - 1  # -1: the probe-winning call
            st = await cluster.stats()
            assert st["resilience"]["rejoin_debits"] >= 1
            assert st["resilience"]["degraded_keys"] == 0
            await cluster.aclose()

        run(main())

    def test_degraded_grants_ledger_batch_eviction(self, monkeypatch):
        """The grants-ledger cap sheds the smallest debts in one
        amortized batch (review finding, round 7): a per-insert min()
        scan of a 128K-entry dict would turn the degraded fallback into
        an O(n) hotspot on exactly the path meant to keep serving while
        a node is down. Semantics preserved: the bound holds and the
        LARGEST debts (most unaccounted admission) survive for the
        rejoin debit."""
        from distributedratelimiting.redis_tpu.runtime.cluster import (
            _DegradedKeyspace,
        )

        monkeypatch.setattr(_DegradedKeyspace, "_MAX_KEYS", 16)
        monkeypatch.setattr(_DegradedKeyspace, "_EVICT_BATCH", 8)
        dk = _DegradedKeyspace(fraction=1.0)
        cap_entries = 2 * 16
        big = {f"big{i}" for i in range(8)}
        for k in sorted(big):
            assert dk.acquire(0, k, 5, 100.0, 0.0).granted
        i = 0
        while len(dk._grants) < cap_entries:
            assert dk.acquire(0, f"small{i}", 1, 100.0, 0.0).granted
            i += 1
        # The insert that hits the cap evicts one BATCH of the smallest
        # debts, not one entry — and every big debt survives it.
        dk.acquire(0, "overflow", 1, 100.0, 0.0)
        assert len(dk._grants) == cap_entries - 8 + 1
        survivors = {k[1] for k in dk._grants}
        assert big <= survivors
        # The ledger stays bounded under continued pressure.
        for j in range(64):
            dk.acquire(0, f"more{j}", 1, 100.0, 0.0)
        assert len(dk._grants) <= cap_entries
        drained = dict((row[0], row[4]) for row in dk.drain_node(0))
        for k in big:
            assert drained[k] == pytest.approx(5.0)

    def test_moved_error_refresh_and_retry_over_tcp(self):
        """A client whose map is stale chases exactly one MOVED redirect:
        refetch from the fleet, re-route, serve."""

        async def main():
            backings = [InProcessBucketStore() for _ in range(2)]
            servers = [BucketStoreServer(b) for b in backings]
            for s in servers:
                await s.start()
            coordinator = ClusterBucketStore(
                addresses=[(s.host, s.port) for s in servers],
                coalesce_requests=False)
            follower = ClusterBucketStore(
                addresses=[(s.host, s.port) for s in servers],
                coalesce_requests=False)
            try:
                await coordinator.acquire("hot", 1, 50.0, 0.0)
                slot = coordinator.placement.slot_of("hot")
                own = coordinator.node_index_of("hot")
                target = coordinator.placement.with_assignments(
                    {slot: 1 - own})
                await coordinator._apply_placement(
                    target, {slot: 1 - own}, reason="test-move")
                # the follower still holds epoch 0 → routes to the old
                # owner → gets MOVED → refreshes → serves
                res = await follower.acquire("hot", 1, 50.0, 0.0)
                assert res.granted
                assert follower.placement.epoch == target.epoch
            finally:
                await coordinator.aclose()
                await follower.aclose()
                for s in servers:
                    await s.aclose()

        run(main())

    def test_submitter_chases_moved_over_tcp(self):
        """Regression (round-6 review): the non-resilient hoisted
        submitter lane must chase a MOVED exactly like _routed — a
        stale-mapped submitter would otherwise fail every call for a
        migrated key forever."""

        async def main():
            backings = [InProcessBucketStore() for _ in range(2)]
            servers = [BucketStoreServer(b) for b in backings]
            for s in servers:
                await s.start()
            addrs = [(s.host, s.port) for s in servers]
            coordinator = ClusterBucketStore(addresses=addrs,
                                             coalesce_requests=False)
            follower = ClusterBucketStore(addresses=addrs,
                                          coalesce_requests=False)
            try:
                assert not follower._resilient  # the fast lane under test
                submit = follower.acquire_submitter(50.0, 0.0)
                assert (await submit("hot", 1)).granted
                slot = coordinator.placement.slot_of("hot")
                own = coordinator.node_index_of("hot")
                target = coordinator.placement.with_assignments(
                    {slot: 1 - own})
                await coordinator._apply_placement(
                    target, {slot: 1 - own}, reason="test-move")
                res = await submit("hot", 1)
                assert res.granted
                assert follower.placement.epoch == target.epoch
            finally:
                await coordinator.aclose()
                await follower.aclose()
                for s in servers:
                    await s.aclose()

        run(main())

    def test_drain_pages_oversized_export_over_tcp(self):
        """Regression (round-6 review): an export bigger than MAX_FRAME
        must not wedge the drain — the pull pages, and every migrated
        balance still lands exactly (minus the one envelope debit)."""

        async def main():
            backings = [InProcessBucketStore() for _ in range(2)]
            servers = [BucketStoreServer(b) for b in backings]
            for s in servers:
                await s.start()
            cluster = ClusterBucketStore(
                addresses=[(s.host, s.port) for s in servers],
                coalesce_requests=False)
            try:
                # ~20 × 60 KiB keys ≈ 1.2 MiB of export JSON > MAX_FRAME
                keys = [f"K{i:02d}" + "x" * 60_000 for i in range(20)]
                for k in keys:
                    assert (await cluster.acquire(k, 1, 40.0, 0.0)).granted
                moved = [k for k in keys
                         if cluster.node_index_of(k) == 0]
                assert moved  # the drained node held some of them
                await cluster.drain_node(0)
                budget = headroom_budget(40.0, fraction=0.5,
                                         min_budget=1.0)
                for k in keys:
                    want = (40.0 - 1 - budget) if k in moved else 39.0
                    got = backings[1]._buckets[(k, 40.0, 0.0)][0]
                    assert got == pytest.approx(want), k[:8]
            finally:
                await cluster.aclose()
                for s in servers:
                    await s.aclose()

        run(main())

    def test_metrics_carry_placement_families(self):
        async def main():
            cluster = ClusterBucketStore(
                stores=[InProcessBucketStore(),
                        InProcessBucketStore()])
            await cluster.add_node(InProcessBucketStore())
            text = cluster.metrics_registry().render()
            assert "drl_cluster_placement_epoch 1" in text
            assert "drl_cluster_migrations_total 1" in text
            assert "drl_cluster_migration_aborts_total 0" in text
            assert "drl_cluster_rejoin_debits_total 0" in text
            await cluster.aclose()

        run(main())


class TestServerPlacementSurface:
    def test_stats_and_metrics_expose_placement(self):
        async def main():
            backing = InProcessBucketStore()
            async with BucketStoreServer(backing) as srv:
                client = RemoteBucketStore(address=(srv.host, srv.port),
                                           coalesce_requests=False)
                try:
                    st = await client.stats()
                    assert "placement" not in st  # dormant until announced
                    m = PlacementMap.initial(1)
                    await client.placement_announce(
                        {"map": m.to_dict(), "node_id": 0})
                    st = await client.stats()
                    assert st["placement"]["epoch"] == 0
                    assert st["placement"]["owned_slots"] == m.n_slots
                    text = await client.metrics()
                    assert "drl_placement_epoch 0" in text
                finally:
                    await client.aclose()

        run(main())

    def test_bulk_lane_respects_gate(self):
        async def main():
            backing = InProcessBucketStore()
            async with BucketStoreServer(backing) as srv:
                client = RemoteBucketStore(address=(srv.host, srv.port),
                                           coalesce_requests=False)
                try:
                    m = PlacementMap.initial(2)
                    own = 0
                    await client.placement_announce(
                        {"map": m.to_dict(), "node_id": own})
                    keys = list(KEYS)
                    # A frame with ANY misrouted row answers a routable
                    # frame-level moved error (all-or-error: no row is
                    # applied) — the only refresh trigger a bulk-only
                    # client has; silent denial would strand its stale
                    # map forever.
                    with pytest.raises(wire.RemoteStoreError,
                                       match="placement moved"):
                        await client.acquire_many(
                            keys, [1] * len(keys), 100.0, 1.0)
                    for k in keys:  # no row touched the store
                        assert all(bk[0] != k for bk in backing._buckets)
                    # A correctly-routed frame (owned rows only) serves.
                    mine = [k for k in keys if m.node_of(k) == own]
                    res = await client.acquire_many(
                        mine, [1] * len(mine), 100.0, 1.0)
                    assert all(res.granted)
                finally:
                    await client.aclose()

        run(main())

    def test_native_frontend_batch_lane_respects_gate(self):
        """The C batch fast lane must honor keyspace ownership exactly
        like the asyncio lanes (review finding, round 6): misrouted hot
        scalar ops answer the routable MOVED error (per-row, via the
        kRowSkip fe_send lane) — never authoritatively admitted by a
        non-owner, and never silently denied (a stale client needs the
        error to converge its map)."""
        from distributedratelimiting.redis_tpu.utils.native import (
            load_frontend_lib,
        )

        if load_frontend_lib() is None:
            pytest.skip("native front-end library unavailable")

        async def main():
            backing = InProcessBucketStore()
            async with BucketStoreServer(backing,
                                         native_frontend=True) as srv:
                client = RemoteBucketStore(address=(srv.host, srv.port),
                                           coalesce_requests=False)
                try:
                    m = PlacementMap.initial(2)
                    await client.placement_announce(
                        {"map": m.to_dict(), "node_id": 0})
                    mine = next(k for k in KEYS if m.node_of(k) == 0)
                    foreign = next(k for k in KEYS if m.node_of(k) == 1)
                    res = await client.acquire(mine, 1, 100.0, 1.0)
                    assert res.granted
                    with pytest.raises(wire.RemoteStoreError,
                                       match="placement moved"):
                        await client.acquire(foreign, 1, 100.0, 1.0)
                    assert all(bk[0] != foreign
                               for bk in backing._buckets)
                finally:
                    await client.aclose()

        run(main())

    def test_native_frontend_parked_sema_release_defers(self):
        """A SEMA release for a parked key on the C batch lane must NOT
        be swallowed as a denial (the permit would leak for the
        migrated semaphore's lifetime): it answers the same transient
        handoff-deferral error as the asyncio lane, and succeeds once
        the handoff aborts/commits."""
        from distributedratelimiting.redis_tpu.utils.native import (
            load_frontend_lib,
        )

        if load_frontend_lib() is None:
            pytest.skip("native front-end library unavailable")

        async def main():
            backing = InProcessBucketStore()
            async with BucketStoreServer(backing,
                                         native_frontend=True) as srv:
                client = RemoteBucketStore(address=(srv.host, srv.port),
                                           coalesce_requests=False)
                try:
                    m = PlacementMap.initial(1)
                    await client.placement_announce(
                        {"map": m.to_dict(), "node_id": 0})
                    key = "sema-key"
                    res = await client.concurrency_acquire(key, 1, 1)
                    assert res.granted
                    # Park the key's slot: a pull for a pending epoch.
                    await client.migrate_pull(
                        {"target_epoch": 1,
                         "slots": [m.slot_of(key)],
                         "window_s": 30.0})
                    with pytest.raises(
                            wire.RemoteStoreError,
                            match=placement.HANDOFF_DEFERRAL_PREFIX):
                        await client.concurrency_release(key, 1)
                    # Abort the handoff: the release now lands and the
                    # permit is actually returned (a second acquire at
                    # limit 1 grants — nothing leaked).
                    await client.placement_announce({"abort_epoch": 1})
                    await client.concurrency_release(key, 1)
                    res = await client.concurrency_acquire(key, 1, 1)
                    assert res.granted
                finally:
                    await client.aclose()

        run(main())

    def test_gated_scalar_ops_answer_moved(self):
        async def main():
            backing = InProcessBucketStore()
            async with BucketStoreServer(backing) as srv:
                client = RemoteBucketStore(address=(srv.host, srv.port),
                                           coalesce_requests=False)
                try:
                    m = PlacementMap.initial(2)
                    await client.placement_announce(
                        {"map": m.to_dict(), "node_id": 0})
                    foreign = next(k for k in KEYS if m.node_of(k) == 1)
                    with pytest.raises(wire.RemoteStoreError,
                                       match="placement moved"):
                        await client.acquire(foreign, 1, 10.0, 1.0)
                    with pytest.raises(wire.RemoteStoreError,
                                       match="placement moved"):
                        await client.sync_counter(foreign, 1.0, 1.0)
                finally:
                    await client.aclose()

        run(main())
