"""Waiter-queue semantics (SURVEY.md §2 #5, invariant 8) — including the
regression test for the reference's cancelled-waiter double-count defect."""

import asyncio

from distributedratelimiting.redis_tpu.runtime.queueing import (
    QueueProcessingOrder,
    WaiterQueue,
)


def run(coro):
    return asyncio.run(coro)


LEASE_OK = object()
LEASE_FAIL = object()


def test_queue_limit_counts_cumulative_permits():
    async def main():
        q = WaiterQueue(10, QueueProcessingOrder.OLDEST_FIRST)
        f1, _ = q.try_enqueue(6)
        f2, _ = q.try_enqueue(4)
        assert f1 is not None and f2 is not None
        assert q.queue_count == 10
        f3, _ = q.try_enqueue(1)  # would exceed 10 cumulative permits
        assert f3 is None

    run(main())


def test_single_request_larger_than_queue_limit_rejected():
    async def main():
        q = WaiterQueue(5, QueueProcessingOrder.NEWEST_FIRST)
        f, evicted = q.try_enqueue(6)
        assert f is None and not evicted

    run(main())


def test_newest_first_evicts_oldest_to_make_room():
    async def main():
        q = WaiterQueue(10, QueueProcessingOrder.NEWEST_FIRST)
        f1, _ = q.try_enqueue(6)
        f2, _ = q.try_enqueue(4)
        f3, evicted = q.try_enqueue(8)  # evicts f1 then f2
        assert f3 is not None
        assert [r.future for r in evicted] == [f1, f2]
        assert q.queue_count == 8

    run(main())


def test_oldest_first_drain_order():
    async def main():
        q = WaiterQueue(100, QueueProcessingOrder.OLDEST_FIRST)
        futures = [q.try_enqueue(c)[0] for c in (5, 3, 2)]
        available = [6]

        def try_grant(c):
            if available[0] >= c:
                available[0] -= c
                return True
            return False

        granted = q.drain(try_grant, lambda: LEASE_OK)
        # Oldest (5) granted, next (3) doesn't fit the remaining 1 → stop.
        assert granted == 1
        assert futures[0].result() is LEASE_OK
        assert not futures[1].done()
        assert q.queue_count == 5

    run(main())


def test_newest_first_drain_order():
    async def main():
        q = WaiterQueue(100, QueueProcessingOrder.NEWEST_FIRST)
        futures = [q.try_enqueue(c)[0] for c in (5, 3, 2)]
        available = [5]

        def try_grant(c):
            if available[0] >= c:
                available[0] -= c
                return True
            return False

        granted = q.drain(try_grant, lambda: LEASE_OK)
        # Newest (2) then (3) granted; oldest (5) doesn't fit remaining 0.
        assert granted == 2
        assert futures[2].result() is LEASE_OK
        assert futures[1].result() is LEASE_OK
        assert not futures[0].done()

    run(main())


def test_cancelled_waiter_unwinds_accounting_no_double_count():
    """Regression for the reference defect at ``:486-492``: a waiter
    cancelled while parked must neither hold queue room nor consume."""

    async def main():
        q = WaiterQueue(10, QueueProcessingOrder.OLDEST_FIRST)
        f1, _ = q.try_enqueue(6)
        f2, _ = q.try_enqueue(4)
        f1.cancel()
        await asyncio.sleep(0)  # let done-callback run
        assert q.queue_count == 4  # f1's 6 permits released immediately
        # Room freed by cancellation is usable again.
        f3, _ = q.try_enqueue(6)
        assert f3 is not None

        consumed = []

        def try_grant(c):
            consumed.append(c)
            return True

        granted = q.drain(try_grant, lambda: LEASE_OK)
        assert granted == 2
        # The cancelled waiter's 6 permits were never consumed: only 4 + 6.
        assert sorted(consumed) == [4, 6]
        assert q.queue_count == 0

    run(main())


def test_cancelled_at_head_skipped_during_drain():
    async def main():
        q = WaiterQueue(10, QueueProcessingOrder.OLDEST_FIRST)
        f1, _ = q.try_enqueue(6)
        f2, _ = q.try_enqueue(4)
        # Cancel but don't yield: callback runs on cancel() synchronously in
        # asyncio.Future — drain must still skip it safely either way.
        f1.cancel()
        granted = q.drain(lambda c: True, lambda: LEASE_OK)
        assert granted == 1
        assert f2.result() is LEASE_OK
        assert q.queue_count == 0

    run(main())


def test_fail_all_completes_everyone():
    async def main():
        q = WaiterQueue(10, QueueProcessingOrder.OLDEST_FIRST)
        f1, _ = q.try_enqueue(6)
        f2, _ = q.try_enqueue(4)
        failed = q.fail_all(lambda: LEASE_FAIL)
        assert failed == 2
        assert f1.result() is LEASE_FAIL and f2.result() is LEASE_FAIL
        assert q.queue_count == 0 and len(q) == 0

    run(main())


def test_drain_async_eviction_cannot_race_inflight_grant():
    # Regression: a NEWEST_FIRST eviction arriving while the head waiter's
    # store grant is in flight must neither fail that waiter nor leak the
    # granted tokens — drain_async checks the waiter out of the deque for
    # the duration of the round-trip.
    async def main():
        q = WaiterQueue(2, QueueProcessingOrder.NEWEST_FIRST)
        w1, _ = q.try_enqueue(2)
        gate = asyncio.Event()

        grants = [True, False]  # only the in-flight round-trip succeeds

        async def slow_grant(count):
            await gate.wait()
            return grants.pop(0)

        drain = asyncio.ensure_future(q.drain_async(slow_grant, lambda: LEASE_OK))
        await asyncio.sleep(0)  # drain checks w1 out, parks on the gate
        # A newcomer that would previously have evicted w1:
        w2, evicted = q.try_enqueue(2)
        assert evicted == []          # w1 is checked out — untouchable
        gate.set()
        await drain
        assert w1.result() is LEASE_OK  # the in-flight grant landed
        assert not w2.done()
        q.fail_all(lambda: LEASE_FAIL)

    run(main())


def test_drain_async_declined_waiter_keeps_turn():
    async def main():
        q = WaiterQueue(10, QueueProcessingOrder.OLDEST_FIRST)
        w1, _ = q.try_enqueue(5)
        w2, _ = q.try_enqueue(1)
        granted = await q.drain_async(lambda c: _ret(c <= 1), lambda: LEASE_OK)
        # Head (5 permits) declined and re-queued at the head; w2 not
        # overtaken past it.
        assert granted == 0
        assert not w1.done() and not w2.done()
        assert q.queue_count == 6 and len(q) == 2
        q.fail_all(lambda: LEASE_FAIL)

    async def _ret(v):
        return v

    run(main())


def test_drain_async_cancelled_drain_restores_waiter():
    async def main():
        q = WaiterQueue(10, QueueProcessingOrder.OLDEST_FIRST)
        w1, _ = q.try_enqueue(3)
        gate = asyncio.Event()

        async def hanging_grant(count):
            await gate.wait()
            return True

        drain = asyncio.ensure_future(q.drain_async(hanging_grant, lambda: LEASE_OK))
        await asyncio.sleep(0)
        drain.cancel()  # disposal path cancels the refresh task
        try:
            await drain
        except asyncio.CancelledError:
            pass
        # The checked-out waiter was handed back; fail_all can settle it.
        assert len(q) == 1
        q.fail_all(lambda: LEASE_FAIL)
        assert w1.result() is LEASE_FAIL

    run(main())


def test_drain_async_fail_all_during_inflight_grant_settles_waiter():
    # Regression: dispose (fail_all) racing an in-flight store grant must
    # settle the checked-out waiter on return — never re-park it in a
    # disposed queue where it would hang forever.
    async def main():
        q = WaiterQueue(10, QueueProcessingOrder.OLDEST_FIRST)
        w1, _ = q.try_enqueue(3)
        gate = asyncio.Event()

        async def slow_grant(count):
            await gate.wait()
            return False  # store declined

        drain = asyncio.ensure_future(q.drain_async(slow_grant, lambda: LEASE_OK))
        await asyncio.sleep(0)
        q.fail_all(lambda: LEASE_FAIL)  # dispose while round-trip in flight
        gate.set()
        await drain
        assert w1.result() is LEASE_FAIL
        assert q.queue_count == 0

    run(main())


def test_drain_async_fail_all_during_inflight_grant_honors_grant():
    # Same race, but the store GRANTED before disposal: the waiter gets the
    # successful lease (tokens were consumed on its behalf).
    async def main():
        q = WaiterQueue(10, QueueProcessingOrder.OLDEST_FIRST)
        w1, _ = q.try_enqueue(3)
        gate = asyncio.Event()

        async def slow_grant(count):
            await gate.wait()
            return True

        drain = asyncio.ensure_future(q.drain_async(slow_grant, lambda: LEASE_OK))
        await asyncio.sleep(0)
        q.fail_all(lambda: LEASE_FAIL)
        gate.set()
        await drain
        assert w1.result() is LEASE_OK
        assert q.queue_count == 0

    run(main())
