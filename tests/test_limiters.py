"""Limiter API contract tests (SURVEY.md §2 invariant 7) and the
multi-client convergence property the approximate algorithm exists for.

These run against the in-process store (the ConnectionMultiplexerFactory
seam, §4 implication (b)); device-store equivalence is covered by
test_store.py, so semantics proven here hold on TPU too.
"""

import asyncio

import pytest

from distributedratelimiting.redis_tpu.models.approximate import (
    ApproximateTokenBucketRateLimiter,
)
from distributedratelimiting.redis_tpu.models.base import MetadataName
from distributedratelimiting.redis_tpu.models.options import (
    ApproximateTokenBucketOptions,
    SlidingWindowOptions,
    TokenBucketOptions,
)
from distributedratelimiting.redis_tpu.models.partitioned import PartitionedRateLimiter
from distributedratelimiting.redis_tpu.models.sliding_window import (
    SlidingWindowRateLimiter,
)
from distributedratelimiting.redis_tpu.models.token_bucket import (
    TokenBucketRateLimiter,
)
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.queueing import QueueProcessingOrder
from distributedratelimiting.redis_tpu.runtime.store import InProcessBucketStore


def run(coro):
    return asyncio.run(coro)


@pytest.fixture
def clock():
    return ManualClock()


@pytest.fixture
def store(clock):
    return InProcessBucketStore(clock=clock)


class TestOptionsValidation:
    def test_rejects_nonpositive_limits(self):
        with pytest.raises(ValueError):
            TokenBucketOptions(token_limit=0)
        with pytest.raises(ValueError):
            TokenBucketOptions(tokens_per_period=0)

    def test_rejects_zero_period(self):
        # Reference defect: TimeSpan.Zero passed validation. We reject it.
        with pytest.raises(ValueError):
            TokenBucketOptions(replenishment_period_s=0.0)

    def test_rejects_negative_queue(self):
        with pytest.raises(ValueError):
            ApproximateTokenBucketOptions(queue_limit=-1)

    def test_fill_rate_derived(self):
        opts = TokenBucketOptions(tokens_per_period=10, replenishment_period_s=2.0)
        assert opts.fill_rate_per_second == 5.0


class TestExactLimiter:
    def test_sync_acquire_actually_works(self, store):
        # The reference's sync Acquire silently always failed (:53-56) —
        # ours performs a real decision.
        lim = TokenBucketRateLimiter(TokenBucketOptions(token_limit=5), store)
        assert lim.acquire(5).is_acquired
        assert not lim.acquire(1).is_acquired

    def test_async_acquire(self, store):
        lim = TokenBucketRateLimiter(TokenBucketOptions(token_limit=5), store)

        async def main():
            assert (await lim.acquire_async(3)).is_acquired
            assert (await lim.acquire_async(2)).is_acquired
            assert not (await lim.acquire_async(1)).is_acquired

        run(main())

    def test_over_limit_raises(self, store):
        lim = TokenBucketRateLimiter(TokenBucketOptions(token_limit=5), store)
        with pytest.raises(ValueError):
            lim.acquire(6)

    def test_zero_permit_probe(self, store, clock):
        lim = TokenBucketRateLimiter(
            TokenBucketOptions(token_limit=5, tokens_per_period=5), store)
        assert lim.acquire(0).is_acquired      # tokens available
        lim.acquire(5)
        assert not lim.acquire(0).is_acquired  # drained
        clock.advance_seconds(1.0)
        lim.acquire(1)  # refresh the estimate via a real decision
        assert lim.acquire(0).is_acquired

    def test_available_permits_estimate(self, store, clock):
        lim = TokenBucketRateLimiter(
            TokenBucketOptions(token_limit=10, tokens_per_period=2), store)
        assert lim.available_permits() == 10  # peek before any acquire
        lim.acquire(4)
        assert lim.available_permits() == 6   # cached from decision reply

    def test_retry_after_corrected_math(self, store):
        # 5-token ask against an empty 5-cap bucket at 2 tokens/s →
        # retry_after ≈ 2.5 s (deficit/rate), NOT deficit*rate = 10 s.
        opts = TokenBucketOptions(
            token_limit=5, tokens_per_period=2, replenishment_period_s=1.0)
        lim = TokenBucketRateLimiter(opts, store)
        lim.acquire(5)
        lease = lim.acquire(5)
        ok, retry = lease.try_get_metadata(MetadataName.RETRY_AFTER)
        assert ok and abs(retry - 2.5) < 0.01

    def test_shared_bucket_across_instances(self, store):
        # Two limiter instances, same instance_name, same store = one bucket
        # (the reference's InstanceName semantics).
        opts = TokenBucketOptions(token_limit=5, instance_name="shared")
        a = TokenBucketRateLimiter(opts, store)
        b = TokenBucketRateLimiter(opts, store)
        assert a.acquire(3).is_acquired
        assert not b.acquire(3).is_acquired

    def test_idle_duration(self, store):
        lim = TokenBucketRateLimiter(TokenBucketOptions(token_limit=5), store)
        assert lim.idle_duration is not None
        lim.acquire(1)
        assert lim.idle_duration is None


class TestApproximateLimiter:
    def opts(self, **kw):
        kw.setdefault("token_limit", 100)
        kw.setdefault("tokens_per_period", 10)
        kw.setdefault("replenishment_period_s", 1.0)
        return ApproximateTokenBucketOptions(**kw)

    def test_local_decisions_no_store_traffic(self, store):
        lim = ApproximateTokenBucketRateLimiter(self.opts(), store)
        # Before any sync: global score 0, instances 1 → full share local.
        for _ in range(100):
            assert lim.acquire(1).is_acquired
        assert not lim.acquire(1).is_acquired
        assert store._counters == {}  # hot path touched the store zero times

    def test_refresh_pushes_and_pulls_global(self, store, clock):
        lim = ApproximateTokenBucketRateLimiter(self.opts(), store)

        async def main():
            for _ in range(40):
                lim.acquire(1)
            clock.advance_seconds(1.0)
            await lim.refresh()
            assert lim._global_score == 40.0
            assert lim._local_score == 0.0  # harvested

        run(main())

    def test_fair_share_formula_after_sync(self, store, clock):
        lim = ApproximateTokenBucketRateLimiter(self.opts(), store)

        async def main():
            lim._global_score = 40.0
            lim._instance_count = 4
            # ceil((100-40)/4) = 15 available to this instance.
            assert lim.available_permits() == 15

        run(main())

    def test_degraded_mode_on_store_failure(self, store, clock):
        lim = ApproximateTokenBucketRateLimiter(self.opts(), store)

        class Boom(Exception):
            pass

        async def failing_sync(*a, **kw):
            raise Boom()

        store.sync_counter = failing_sync

        async def main():
            for _ in range(30):
                lim.acquire(1)
            await lim.refresh()
            # Sync failed: logged, skipped, local consumption NOT lost.
            assert lim.metrics.sync_failures == 1
            assert lim._local_score == 30.0
            # Still serving from last-known state (availability > 0).
            assert lim.acquire(1).is_acquired

        run(main())

    def test_queueing_and_drain_on_refresh(self, store, clock):
        lim = ApproximateTokenBucketRateLimiter(
            self.opts(token_limit=10, tokens_per_period=10, queue_limit=20),
            store)

        async def main():
            for _ in range(10):
                assert (await lim.acquire_async(1)).is_acquired
            waiter = asyncio.ensure_future(lim.acquire_async(5))
            await asyncio.sleep(0.01)
            assert not waiter.done()  # parked
            # One period later the global decays fully (decay=fill=10/s,
            # score 10 → 0 after 1 s... but local sync pushes 10 first).
            clock.advance_seconds(2.0)
            await lim.refresh()   # push 10; decayed-to-0 +10 → score 10
            clock.advance_seconds(2.0)
            await lim.refresh()   # 10 decays to 0 → waiter drains
            lease = await asyncio.wait_for(waiter, 1.0)
            assert lease.is_acquired
            await lim.aclose()

        run(main())

    def test_dispose_fails_queued_waiters(self, store):
        lim = ApproximateTokenBucketRateLimiter(
            self.opts(token_limit=5, queue_limit=10), store)

        async def main():
            for _ in range(5):
                await lim.acquire_async(1)
            waiter = asyncio.ensure_future(lim.acquire_async(3))
            await asyncio.sleep(0.01)
            await lim.aclose()
            lease = await asyncio.wait_for(waiter, 1.0)
            assert not lease.is_acquired  # failed, not hung

        run(main())

    def test_cancellation_releases_queue_room(self, store):
        lim = ApproximateTokenBucketRateLimiter(
            self.opts(token_limit=5, queue_limit=5), store)

        async def main():
            for _ in range(5):
                await lim.acquire_async(1)
            w1 = asyncio.ensure_future(lim.acquire_async(5))
            await asyncio.sleep(0.01)
            assert lim._queue.queue_count == 5
            w1.cancel()
            await asyncio.sleep(0.01)
            assert lim._queue.queue_count == 0
            await lim.aclose()

        run(main())

    def test_instance_estimate_from_sync_cadence(self, store, clock):
        """Two clients syncing at alternating half-period offsets → each
        estimates ~2 instances (membership-free elasticity, §5.3d)."""
        a = ApproximateTokenBucketRateLimiter(self.opts(), store)
        b = ApproximateTokenBucketRateLimiter(self.opts(), store)

        async def main():
            for _ in range(12):
                clock.advance_seconds(0.5)
                await a.refresh()
                clock.advance_seconds(0.5)
                await b.refresh()
            assert a._instance_count == 2
            assert b._instance_count == 2

        run(main())

    def test_multi_client_convergence(self, store, clock):
        """THE property (SURVEY.md §4 implication (c)): N greedy clients
        sharing one store converge to ≤ fill-rate aggregate throughput."""
        n_clients = 4
        limit, per_period = 100, 10
        clients = [
            ApproximateTokenBucketRateLimiter(
                self.opts(token_limit=limit, tokens_per_period=per_period),
                store)
            for _ in range(n_clients)
        ]

        async def main():
            # Warm up sync cadence so each client learns the peer count
            # (cold-start over-admission is bounded by n_clients×limit and
            # is inherent to the reference algorithm's first period).
            for _ in range(8):
                for c in clients:
                    clock.advance_seconds(1.0 / n_clients)
                    await c.refresh()
            assert all(c._instance_count == n_clients for c in clients)

            grants_per_period = []
            for period in range(40):
                grants = 0
                for i, c in enumerate(clients):
                    # Greedy: consume until denied.
                    while c.acquire(1).is_acquired:
                        grants += 1
                    clock.advance_seconds(1.0 / n_clients)
                    await c.refresh()
                grants_per_period.append(grants)
            # Steady state: aggregate admission ≈ decay rate = 10/period.
            steady = grants_per_period[-10:]
            avg = sum(steady) / len(steady)
            assert avg <= per_period * 1.5, grants_per_period
            assert avg >= per_period * 0.5, grants_per_period
            # Burst capacity never exceeded the shared limit after warmup.
            assert grants_per_period[0] <= limit + per_period, grants_per_period

        run(main())


class TestSlidingWindowLimiter:
    def test_grant_deny_rollover(self, store, clock):
        lim = SlidingWindowRateLimiter(
            SlidingWindowOptions(permit_limit=10, window_s=5.0), store)
        assert lim.acquire(8).is_acquired
        assert not lim.acquire(5).is_acquired
        clock.advance_seconds(11.0)  # two windows → old consumption gone
        assert lim.acquire(10).is_acquired

    def test_over_limit_raises(self, store):
        lim = SlidingWindowRateLimiter(
            SlidingWindowOptions(permit_limit=10, window_s=5.0), store)
        with pytest.raises(ValueError):
            lim.acquire(11)

    def test_retry_after_scales_with_deficit(self, store):
        """The denied lease's retry_after is the sliding release bound
        ``deficit / limit × window`` (clamped to one window), not a flat
        window constant: the interpolated window releases the previous
        count linearly as it slides."""
        lim = SlidingWindowRateLimiter(
            SlidingWindowOptions(permit_limit=10, window_s=5.0), store)
        assert lim.acquire(8).is_acquired
        denied = lim.acquire(5)  # remaining 2 → deficit 3
        assert not denied.is_acquired
        ok, retry = denied.try_get_metadata(MetadataName.RETRY_AFTER)
        assert ok and retry == pytest.approx(3 / 10 * 5.0)
        # A tiny deficit asks a tiny wait; never more than one window.
        denied2 = lim.acquire(3)  # deficit 1
        _, retry2 = denied2.try_get_metadata(MetadataName.RETRY_AFTER)
        assert retry2 == pytest.approx(1 / 10 * 5.0)
        assert retry2 <= 5.0


class TestApproximateBulk:
    def test_bulk_matches_sequential_acquires(self, store):
        opts = ApproximateTokenBucketOptions(
            token_limit=20, tokens_per_period=1,
            replenishment_period_s=1000.0)
        a = ApproximateTokenBucketRateLimiter(opts, store)
        b = ApproximateTokenBucketRateLimiter(opts, store)
        counts = [3, 5, 2, 8, 1, 4, 2]  # all-fit prefix then denials
        res = a.acquire_many(counts)
        seq = [b.acquire(c).is_acquired for c in counts]
        assert [bool(g) for g in res.granted] == seq
        assert a._local_score == b._local_score  # identical consumption

    def test_bulk_probe_and_conservative_prefix(self, store):
        opts = ApproximateTokenBucketOptions(
            token_limit=10, tokens_per_period=1,
            replenishment_period_s=1000.0)
        lim = ApproximateTokenBucketRateLimiter(opts, store)
        # 6 fits; 7 denied but reserves; 2 denied conservatively (6+7+2>10);
        # probe at the end: nothing left -> denied.
        res = lim.acquire_many([6, 7, 2, 0])
        assert [bool(g) for g in res.granted] == [True, False, False, False]
        assert lim._local_score == 6.0  # only grants consume

    def test_bulk_respects_oldest_first_queue_gate(self, store):
        opts = ApproximateTokenBucketOptions(
            token_limit=5, tokens_per_period=1, queue_limit=5,
            replenishment_period_s=1000.0)
        lim = ApproximateTokenBucketRateLimiter(opts, store)

        async def main():
            lim.acquire(5)  # drain
            waiter = asyncio.ensure_future(lim.acquire_async(1))
            await asyncio.sleep(0)  # parked
            res = lim.acquire_many([1, 1])
            assert not res.granted.any()  # must not overtake the waiter
            waiter.cancel()
            try:
                await waiter
            except asyncio.CancelledError:
                pass
            await lim.aclose()

        run(main())

    def test_bulk_over_limit_raises(self, store):
        lim = ApproximateTokenBucketRateLimiter(
            ApproximateTokenBucketOptions(token_limit=5), store)
        with pytest.raises(ValueError):
            lim.acquire_many([1, 6])


class TestPartitionedWindowLimiter:
    def test_partitions_independent_sliding(self, store, clock):
        from distributedratelimiting.redis_tpu.models.partitioned_window import (
            PartitionedWindowRateLimiter,
        )

        lim = PartitionedWindowRateLimiter(
            SlidingWindowOptions(permit_limit=3, window_s=1.0,
                                 instance_name="w"), store)
        assert lim.acquire("alice", 3).is_acquired
        assert lim.acquire("bob", 3).is_acquired     # separate window
        denied = lim.acquire("alice", 2)
        assert not denied.is_acquired
        ok, retry = denied.try_get_metadata(MetadataName.RETRY_AFTER)
        assert ok and 0 < retry <= 1.0
        clock.advance_seconds(2.5)
        assert lim.acquire("alice", 3).is_acquired   # window slid away

    def test_fixed_options_select_fixed_semantics(self, store, clock):
        from distributedratelimiting.redis_tpu.models.options import (
            FixedWindowOptions,
        )
        from distributedratelimiting.redis_tpu.models.partitioned_window import (
            PartitionedWindowRateLimiter,
        )

        lim = PartitionedWindowRateLimiter(
            FixedWindowOptions(permit_limit=2, window_s=1.0,
                               instance_name="f"), store)
        assert lim.fixed
        assert lim.acquire("x", 2).is_acquired
        denied = lim.acquire("x", 1)
        assert not denied.is_acquired
        _, retry = denied.try_get_metadata(MetadataName.RETRY_AFTER)
        assert retry == 1.0  # fixed: the sure full-window bound
        clock.advance_seconds(1.0)  # boundary reset, not gradual release
        assert lim.acquire("x", 2).is_acquired

    def test_bulk_acquire_many(self, store):
        from distributedratelimiting.redis_tpu.models.partitioned_window import (
            PartitionedWindowRateLimiter,
        )

        lim = PartitionedWindowRateLimiter(
            SlidingWindowOptions(permit_limit=2, window_s=5.0,
                                 instance_name="wb"), store)

        async def main():
            res = await lim.acquire_many(
                [f"u{i % 4}" for i in range(12)], 1)
            assert [bool(g) for g in res.granted] == [True] * 8 + [False] * 4
            assert lim.metrics.decisions == 12

        run(main())

    def test_over_limit_raises(self, store):
        from distributedratelimiting.redis_tpu.models.partitioned_window import (
            PartitionedWindowRateLimiter,
        )

        lim = PartitionedWindowRateLimiter(
            SlidingWindowOptions(permit_limit=5, window_s=1.0), store)
        with pytest.raises(ValueError):
            lim.acquire("x", 6)
        with pytest.raises(ValueError):
            lim.acquire_many_blocking(["a", "b"], [1, 9])


class TestPartitionedLimiter:
    def test_partitions_independent(self, store):
        lim = PartitionedRateLimiter(
            TokenBucketOptions(token_limit=3, instance_name="api"), store)
        assert lim.acquire("alice", 3).is_acquired
        assert lim.acquire("bob", 3).is_acquired      # separate bucket
        assert not lim.acquire("alice", 1).is_acquired

    def test_async_batched_partitions(self, store):
        lim = PartitionedRateLimiter(
            TokenBucketOptions(token_limit=2, instance_name="api"), store)

        async def main():
            results = await asyncio.gather(*(
                lim.acquire_async(f"user{i}") for i in range(16)
            ))
            assert all(r.is_acquired for r in results)

        run(main())

    def test_key_concatenation(self, store):
        lim = PartitionedRateLimiter(
            TokenBucketOptions(token_limit=3, instance_name="api"), store)
        lim.acquire("x", 1)
        assert any(k[0] == "api:x" for k in store._buckets)


class TestRegistry:
    def test_di_registration_and_resolve(self, store):
        from distributedratelimiting.redis_tpu.utils.registry import (
            ServiceRegistry,
            add_tpu_approximate_token_bucket_rate_limiter,
            add_tpu_token_bucket_rate_limiter,
        )

        reg = ServiceRegistry()
        add_tpu_token_bucket_rate_limiter(
            reg, lambda: TokenBucketOptions(token_limit=5), store=store)
        lim = reg.resolve("rate_limiter")
        assert isinstance(lim, TokenBucketRateLimiter)
        assert reg.resolve("rate_limiter") is lim  # singleton
        # Same-name double registration is an error (reference allowed the
        # ambiguity; we don't).
        with pytest.raises(ValueError):
            add_tpu_approximate_token_bucket_rate_limiter(
                reg, lambda: ApproximateTokenBucketOptions(), store=store)
        add_tpu_approximate_token_bucket_rate_limiter(
            reg, lambda: ApproximateTokenBucketOptions(), store=store,
            service_name="approx")
        assert isinstance(
            reg.resolve("approx"), ApproximateTokenBucketRateLimiter)

        from distributedratelimiting.redis_tpu.models.partitioned_window import (
            PartitionedWindowRateLimiter,
        )
        from distributedratelimiting.redis_tpu.utils.registry import (
            add_tpu_partitioned_window_rate_limiter,
        )

        add_tpu_partitioned_window_rate_limiter(
            reg, lambda: SlidingWindowOptions(permit_limit=5),
            store=store, service_name="pwin")
        assert isinstance(reg.resolve("pwin"), PartitionedWindowRateLimiter)


class TestSyncOnlyRefresh:
    def test_sync_only_usage_replenishes(self, store, clock):
        """Regression: a limiter used purely via the sync API (no event
        loop) must still sync+harvest once per period, not exhaust forever."""
        opts = ApproximateTokenBucketOptions(
            token_limit=10, tokens_per_period=10, replenishment_period_s=0.05)
        lim = ApproximateTokenBucketRateLimiter(opts, store)
        for _ in range(10):
            assert lim.acquire(1).is_acquired
        assert not lim.acquire(1).is_acquired
        import time as _t
        # Let wall time pass for the inline-refresh pacing, and store time
        # pass for the decay.
        _t.sleep(0.06)
        clock.advance_seconds(1.0)
        lim.acquire(0)  # probe triggers inline refresh (harvest 10 → global)
        _t.sleep(0.06)
        clock.advance_seconds(1.0)  # global decays 10 → 0
        assert lim.acquire(1).is_acquired  # replenished without any loop
        assert lim.metrics.syncs >= 2


class TestStatistics:
    def test_get_statistics_counts_and_queue(self):
        # ≙ the modern .NET RateLimiter.GetStatistics() (parity-plus):
        # lifetime grant/denial counts, availability estimate, queued.
        import asyncio

        from distributedratelimiting.redis_tpu.models.approximate import (
            ApproximateTokenBucketRateLimiter,
        )
        from distributedratelimiting.redis_tpu.models.base import (
            RateLimiterStatistics,
        )
        from distributedratelimiting.redis_tpu.models.options import (
            ApproximateTokenBucketOptions,
        )
        from distributedratelimiting.redis_tpu.runtime.clock import (
            ManualClock,
        )
        from distributedratelimiting.redis_tpu.runtime.store import (
            InProcessBucketStore,
        )

        lim = ApproximateTokenBucketRateLimiter(
            ApproximateTokenBucketOptions(
                token_limit=3, tokens_per_period=1,
                replenishment_period_s=3600.0, instance_name="stats"),
            InProcessBucketStore(clock=ManualClock()))
        for _ in range(5):
            lim.acquire(1)
        stats = lim.get_statistics()
        assert isinstance(stats, RateLimiterStatistics)
        assert stats.total_successful_leases == 3
        assert stats.total_failed_leases == 2
        assert stats.current_available_permits == 0
        assert stats.current_queued_count == 0
        asyncio.run(lim.aclose())

    def test_get_statistics_reports_queued_waiters(self):
        import asyncio

        from distributedratelimiting.redis_tpu.models.approximate import (
            ApproximateTokenBucketRateLimiter,
        )
        from distributedratelimiting.redis_tpu.models.options import (
            ApproximateTokenBucketOptions,
        )
        from distributedratelimiting.redis_tpu.runtime.clock import (
            ManualClock,
        )
        from distributedratelimiting.redis_tpu.runtime.store import (
            InProcessBucketStore,
        )

        async def main():
            lim = ApproximateTokenBucketRateLimiter(
                ApproximateTokenBucketOptions(
                    token_limit=3, tokens_per_period=1,
                    replenishment_period_s=3600.0, queue_limit=4,
                    instance_name="qstats"),
                InProcessBucketStore(clock=ManualClock()))
            assert lim.acquire(3).is_acquired
            # 3 permits from ONE waiter: CurrentQueuedCount counts queued
            # permits, not parked tasks (.NET semantics; the reference
            # sums permit counts too, RedisTokenBucketRateLimiter.cs:129).
            waiter = asyncio.ensure_future(lim.acquire_async(3))
            await asyncio.sleep(0)  # parks on the waiter queue
            assert lim.get_statistics().current_queued_count == 3
            waiter.cancel()
            try:
                await waiter
            except asyncio.CancelledError:
                pass
            assert lim.get_statistics().current_queued_count == 0
            await lim.aclose()

        asyncio.run(main())

    def test_partitioned_get_statistics_per_resource(self):
        """≙ PartitionedRateLimiter<TResource>.GetStatistics(resource):
        available permits are a per-resource read-only peek; lease
        counters are limiter-wide (partitions share one table here —
        documented deviation); the family never queues."""
        import asyncio

        from distributedratelimiting.redis_tpu.models.partitioned import (
            PartitionedRateLimiter,
        )
        from distributedratelimiting.redis_tpu.models.options import (
            TokenBucketOptions,
        )
        from distributedratelimiting.redis_tpu.runtime.clock import (
            ManualClock,
        )
        from distributedratelimiting.redis_tpu.runtime.store import (
            InProcessBucketStore,
        )

        lim = PartitionedRateLimiter(
            TokenBucketOptions(token_limit=3, tokens_per_period=1,
                               replenishment_period_s=3600.0,
                               instance_name="pstats"),
            InProcessBucketStore(clock=ManualClock()))
        for _ in range(4):
            lim.acquire("a", 1)
        s_a = lim.get_statistics("a")
        s_b = lim.get_statistics("b")
        assert s_a.current_available_permits == 0
        assert s_b.current_available_permits == 3  # untouched partition
        assert s_a.total_successful_leases == 3
        assert s_a.total_failed_leases == 1
        assert s_a.current_queued_count == 0
        asyncio.run(lim.aclose())
