"""Queueing + exact hybrid limiter tests — the finished form of the
reference's dead ``TokenBucketWithQueue`` component (SURVEY.md §2 #14).

Every grant is an exact store round-trip; declined acquires park on the
waiter queue and are drained by ``refresh()`` (stepped manually here — the
ManualClock keeps the store's refill arithmetic deterministic)."""

import asyncio

import pytest

from distributedratelimiting.redis_tpu.models.options import (
    QueueingTokenBucketOptions,
)
from distributedratelimiting.redis_tpu.models.queueing_token_bucket import (
    QueueingTokenBucketRateLimiter,
)
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.queueing import QueueProcessingOrder
from distributedratelimiting.redis_tpu.runtime.store import InProcessBucketStore


def run(coro):
    return asyncio.run(coro)


@pytest.fixture
def clock():
    return ManualClock()


@pytest.fixture
def store(clock):
    return InProcessBucketStore(clock=clock)


def make(store, **kw):
    defaults = dict(token_limit=5, tokens_per_period=5,
                    replenishment_period_s=1.0, queue_limit=10,
                    instance_name="q-bucket")
    defaults.update(kw)
    return QueueingTokenBucketRateLimiter(
        QueueingTokenBucketOptions(**defaults), store)


class TestExactGrants:
    def test_sync_acquire_is_exact(self, store):
        lim = make(store)
        assert lim.acquire(5).is_acquired
        assert not lim.acquire(1).is_acquired

    def test_async_immediate_grant(self, store):
        lim = make(store)

        async def main():
            assert (await lim.acquire_async(3)).is_acquired
            assert lim.available_permits() == 2
            await lim.aclose()

        run(main())

    def test_over_limit_raises(self, store):
        lim = make(store)
        with pytest.raises(ValueError):
            lim.acquire(6)

    def test_two_limiters_share_one_bucket(self, store):
        # Exact semantics: same instance_name ⇒ same store bucket.
        a, b = make(store), make(store)
        assert a.acquire(5).is_acquired
        assert not b.acquire(1).is_acquired


class TestQueueing:
    def test_declined_acquire_parks_then_drains(self, store, clock):
        lim = make(store)

        async def main():
            assert (await lim.acquire_async(5)).is_acquired
            waiter = asyncio.ensure_future(lim.acquire_async(2))
            await asyncio.sleep(0.01)
            assert not waiter.done()
            clock.advance_seconds(1.0)  # store refills 5 tokens
            await lim.refresh()
            lease = await waiter
            assert lease.is_acquired
            await lim.aclose()

        run(main())

    def test_oldest_first_rejects_overflow(self, store):
        lim = make(store, queue_limit=2)

        async def main():
            assert (await lim.acquire_async(5)).is_acquired
            w1 = asyncio.ensure_future(lim.acquire_async(2))
            await asyncio.sleep(0.01)
            # Queue holds 2 cumulative permits — newcomer of 1 overflows.
            lease = await lim.acquire_async(1)
            assert not lease.is_acquired
            assert lease.retry_after is not None
            w1.cancel()
            with pytest.raises(asyncio.CancelledError):
                await w1
            await lim.aclose()

        run(main())

    def test_newest_first_evicts_oldest(self, store, clock):
        lim = make(store, queue_limit=2,
                   queue_processing_order=QueueProcessingOrder.NEWEST_FIRST)

        async def main():
            assert (await lim.acquire_async(5)).is_acquired
            w1 = asyncio.ensure_future(lim.acquire_async(2))
            await asyncio.sleep(0.01)
            w2 = asyncio.ensure_future(lim.acquire_async(2))
            await asyncio.sleep(0.01)
            # w1 was evicted with a failed lease to make room for w2.
            assert (await w1).is_acquired is False
            clock.advance_seconds(1.0)
            await lim.refresh()
            assert (await w2).is_acquired
            await lim.aclose()

        run(main())

    def test_queue_respects_fifo_no_overtake(self, store, clock):
        # While a waiter is parked under OLDEST_FIRST, a later async acquire
        # must not jump the queue even if the store could serve it.
        lim = make(store)

        async def main():
            assert (await lim.acquire_async(5)).is_acquired
            w_big = asyncio.ensure_future(lim.acquire_async(4))
            await asyncio.sleep(0.01)
            w_small = asyncio.ensure_future(lim.acquire_async(1))
            await asyncio.sleep(0.01)
            clock.advance_seconds(1.0)  # 5 tokens available: serves both in order
            await lim.refresh()
            assert (await w_big).is_acquired
            assert (await w_small).is_acquired
            await lim.aclose()

        run(main())

    def test_cancellation_unwinds_accounting(self, store, clock):
        lim = make(store, queue_limit=2)

        async def main():
            assert (await lim.acquire_async(5)).is_acquired
            w1 = asyncio.ensure_future(lim.acquire_async(2))
            await asyncio.sleep(0.01)
            w1.cancel()
            with pytest.raises(asyncio.CancelledError):
                await w1
            # Queue space freed: another waiter fits and is served.
            w2 = asyncio.ensure_future(lim.acquire_async(2))
            await asyncio.sleep(0.01)
            clock.advance_seconds(1.0)
            await lim.refresh()
            assert (await w2).is_acquired
            # The cancelled waiter consumed nothing from the store.
            assert lim.metrics.cancelled == 1
            await lim.aclose()

        run(main())

    def test_dispose_fails_waiters(self, store):
        lim = make(store)

        async def main():
            assert (await lim.acquire_async(5)).is_acquired
            w = asyncio.ensure_future(lim.acquire_async(2))
            await asyncio.sleep(0.01)
            await lim.aclose()
            assert (await w).is_acquired is False

        run(main())


class TestDegradedMode:
    def test_store_failure_parks_instead_of_crashing(self, clock):
        class FailingStore(InProcessBucketStore):
            fail = True

            async def acquire(self, *a, **kw):
                if self.fail:
                    raise ConnectionError("store down")
                return await super().acquire(*a, **kw)

        store = FailingStore(clock=clock)
        lim = make(store)

        async def main():
            w = asyncio.ensure_future(lim.acquire_async(1))
            await asyncio.sleep(0.01)
            assert not w.done()          # parked, not crashed
            assert lim.metrics.sync_failures >= 1
            store.fail = False           # store recovers
            await lim.refresh()
            assert (await w).is_acquired
            await lim.aclose()

        run(main())

    def test_refresh_failure_keeps_waiters(self, clock):
        class FlakyStore(InProcessBucketStore):
            fail = False

            async def acquire(self, *a, **kw):
                if self.fail:
                    raise ConnectionError("store down")
                return await super().acquire(*a, **kw)

        store = FlakyStore(clock=clock)
        lim = make(store)

        async def main():
            assert (await lim.acquire_async(5)).is_acquired
            w = asyncio.ensure_future(lim.acquire_async(2))
            await asyncio.sleep(0.01)
            store.fail = True
            clock.advance_seconds(1.0)
            await lim.refresh()          # drain fails, waiter survives
            assert not w.done()
            store.fail = False
            await lim.refresh()
            assert (await w).is_acquired
            await lim.aclose()

        run(main())
