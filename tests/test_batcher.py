"""Micro-batcher: coalescing, deadline flush, size flush, failure fan-out."""

import asyncio

from distributedratelimiting.redis_tpu.runtime.batcher import MicroBatcher


def run(coro):
    return asyncio.run(coro)


def test_aclose_immediately_after_sequential_submits_terminates():
    """Regression: aclose right after a submit resumes (the flush task
    finished but its done-callback discard is still queued on the loop)
    must terminate — flush_now's drain loop used to spin forever because
    awaiting a gather of already-finished tasks never yields."""

    async def main():
        async def flush(reqs):
            return [r * 2 for r in reqs]

        b = MicroBatcher(flush, max_batch=4, max_delay_s=1e-4)
        for i in range(5):
            assert await b.submit(i) == i * 2
        # No intervening yield: the last flush task is done but still in
        # b._tasks when aclose starts.
        await asyncio.wait_for(b.aclose(), timeout=5)

    run(main())


def test_concurrent_submits_share_one_flush():
    async def main():
        batches = []

        async def flush(reqs):
            batches.append(list(reqs))
            return [r * 10 for r in reqs]

        b = MicroBatcher(flush, max_batch=64, max_delay_s=0.005)
        results = await asyncio.gather(*(b.submit(i) for i in range(8)))
        assert results == [i * 10 for i in range(8)]
        assert len(batches) == 1 and len(batches[0]) == 8

    run(main())


def test_max_batch_triggers_immediate_flush():
    async def main():
        batches = []

        async def flush(reqs):
            batches.append(list(reqs))
            return list(reqs)

        b = MicroBatcher(flush, max_batch=4, max_delay_s=10.0)  # long deadline
        await asyncio.gather(*(b.submit(i) for i in range(8)))
        assert [len(x) for x in batches] == [4, 4]

    run(main())


def test_deadline_flush_fires_without_fill():
    async def main():
        async def flush(reqs):
            return [True for _ in reqs]

        b = MicroBatcher(flush, max_batch=1000, max_delay_s=0.002)
        res = await asyncio.wait_for(b.submit("x"), timeout=1.0)
        assert res is True

    run(main())


def test_flush_failure_fans_out_to_all_waiters():
    async def main():
        async def flush(reqs):
            raise RuntimeError("device on fire")

        b = MicroBatcher(flush, max_batch=64, max_delay_s=0.001)
        results = await asyncio.gather(
            *(b.submit(i) for i in range(3)), return_exceptions=True
        )
        assert all(isinstance(r, RuntimeError) for r in results)

    run(main())


def test_cancelled_submitter_does_not_break_batch():
    async def main():
        async def flush(reqs):
            await asyncio.sleep(0.01)
            return [r for r in reqs]

        b = MicroBatcher(flush, max_batch=2, max_delay_s=0.001)
        t1 = asyncio.ensure_future(b.submit(1))
        t2 = asyncio.ensure_future(b.submit(2))
        await asyncio.sleep(0)
        t1.cancel()
        res2 = await t2
        assert res2 == 2

    run(main())


def test_closed_batcher_rejects():
    async def main():
        async def flush(reqs):
            return list(reqs)

        b = MicroBatcher(flush)
        await b.aclose()
        try:
            await b.submit(1)
            raise AssertionError("expected RuntimeError")
        except RuntimeError:
            pass

    run(main())


def test_flush_now_waits_for_inflight_results():
    """Regression: a shutdown drain must not strand submitters whose flush
    task is still awaiting device results."""

    async def main():
        async def flush(reqs):
            await asyncio.sleep(0.05)  # slow device fetch
            return [r * 2 for r in reqs]

        b = MicroBatcher(flush, max_batch=10, max_delay_s=0.001)
        sub = asyncio.ensure_future(b.submit(21))
        await asyncio.sleep(0.005)  # timer flush fired; task in flight
        await b.aclose()
        assert sub.done() and sub.result() == 42

    run(main())
