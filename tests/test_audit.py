"""Conservation audit plane (ISSUE 18): ε-ledger, burn-rate watchdog,
and black-box incident bundles.

The acceptance differential is THE seeded leak soak: an injected
``audit.leak`` fault (utils/faults.py — a deny flipped into a granted
reply WITHOUT the store debit, the exact bug class "two is worse than
one" warns about) must breach the reply/witness conservation identity
within three watchdog ticks and yield EXACTLY ONE black-box incident
bundle carrying correlated flight frames, exemplar-matched kept traces,
and the raw witnessing counter deltas. The same seed reproduces the
identical alert schedule bit for bit (``make audit-soak SEED=…``,
DRL_AUDIT_SEED). The negative arms pin the zero-false-alarm posture:
clean traffic with legitimate denies, a rolling restart (counter
reset), and a live federation lease flow must raise nothing.
"""

from __future__ import annotations

import asyncio
import json
import os

import pytest

from distributedratelimiting.redis_tpu.runtime.audit import (
    AuditConfig,
    EPSILON_SOURCES,
)
from distributedratelimiting.redis_tpu.runtime.remote import (
    RemoteBucketStore,
)
from distributedratelimiting.redis_tpu.runtime.server import (
    BucketStoreServer,
)
from distributedratelimiting.redis_tpu.runtime.store import (
    InProcessBucketStore,
)
from distributedratelimiting.redis_tpu.utils import faults, tracing
from distributedratelimiting.redis_tpu.utils.faults import (
    FaultInjector,
    FaultRule,
)
from distributedratelimiting.redis_tpu.utils.flight_recorder import (
    REGISTERED_KINDS,
    FlightRecorder,
)
from distributedratelimiting.redis_tpu.utils.slo import (
    SLO_SERIES,
    BurnRateWatchdog,
    SLOConfig,
)

SEED = int(os.environ.get("DRL_AUDIT_SEED", "20260803"))


def run(coro):
    return asyncio.run(coro)


async def _drain_audit_task(srv: BucketStoreServer) -> None:
    """Cancel the wall-clock audit pacer so the test owns every tick —
    the counted-not-clocked determinism contract."""
    if srv._audit_task is not None:
        srv._audit_task.cancel()
        try:
            await srv._audit_task
        except asyncio.CancelledError:
            pass
        srv._audit_task = None


# -- burn-rate watchdog unit surface -----------------------------------------

#: Small windows so trips are reachable in a handful of ticks;
#: overadmit armed alone so the math below stays exact.
_WD_CFG = SLOConfig(overadmit_ratio=1e-3, latency_slo_s=None,
                    shed_ratio=None, goodput_floor_rps=None,
                    fast_ticks=2, slow_ticks=6, burn_fast=10.0,
                    burn_slow=5.0, trip_streak=1, clear_streak=2)


def _feed(wd: BurnRateWatchdog, *, ticks: int, admitted_per_tick: float,
          over_jump: float = 0.0, start: dict | None = None) -> dict:
    """Feed ``ticks`` cumulative samples; ``over_jump`` lands whole on
    the first of them. Returns the final cumulative state."""
    cum = dict(start or {"requests": 0.0, "shed": 0.0,
                         "admitted_tokens": 0.0,
                         "overadmitted_tokens": 0.0,
                         "latency_total": 0.0, "latency_bad": 0.0})
    for i in range(ticks):
        cum["requests"] += admitted_per_tick
        cum["admitted_tokens"] += admitted_per_tick
        if i == 0:
            cum["overadmitted_tokens"] += over_jump
        wd.tick(cum)
    return cum


class TestBurnRateWatchdog:
    def test_trip_requires_both_windows(self):
        """A spike hot enough for the fast window but diluted below the
        slow threshold must NOT page — the multi-window point."""
        # fast: X/2000/1e-3 >= 10 needs X >= 20;
        # slow: X/6000/1e-3 >= 5 needs X >= 30.
        wd = BurnRateWatchdog(_WD_CFG)
        cum = _feed(wd, ticks=8, admitted_per_tick=1000.0)
        _feed(wd, ticks=1, admitted_per_tick=1000.0, over_jump=25.0,
              start=cum)
        assert wd.trips == 0 and wd.tripped() == []

    def test_trip_then_hysteresis_clear(self):
        wd = BurnRateWatchdog(_WD_CFG)
        cum = _feed(wd, ticks=8, admitted_per_tick=1000.0)
        cum = _feed(wd, ticks=1, admitted_per_tick=1000.0,
                    over_jump=40.0, start=cum)
        assert wd.tripped() == ["overadmit"]
        (trip,) = wd.alert_log
        assert trip["state"] == "trip" and trip["slo"] == "overadmit"
        assert trip["burn_fast"] >= _WD_CFG.burn_fast
        assert trip["burn_slow"] >= _WD_CFG.burn_slow
        # The spike ages out of the fast window; clear_streak clean
        # ticks later the dimension untrips — exactly one clear alert.
        _feed(wd, ticks=6, admitted_per_tick=1000.0, start=cum)
        assert wd.tripped() == []
        assert [a["state"] for a in wd.alert_log] == ["trip", "clear"]

    def test_goodput_arming_latch(self):
        """A warming-up server (rate below floor from birth) never
        alarms; once the floor has been reached, collapse trips."""
        cfg = SLOConfig(overadmit_ratio=None, latency_slo_s=None,
                        shed_ratio=None, goodput_floor_rps=100.0,
                        fast_ticks=2, slow_ticks=4, burn_fast=2.0,
                        burn_slow=2.0, trip_streak=1, clear_streak=2,
                        tick_s=1.0)
        wd = BurnRateWatchdog(cfg)
        cum = {"requests": 0.0, "shed": 0.0, "admitted_tokens": 0.0,
               "overadmitted_tokens": 0.0, "latency_total": 0.0,
               "latency_bad": 0.0}
        for _ in range(5):          # zero traffic: disarmed, silent
            wd.tick(cum)
        assert wd.alerts == 0
        for _ in range(6):          # 200 rps >= floor: arms, silent
            cum = dict(cum, requests=cum["requests"] + 200.0)
            wd.tick(cum)
        assert wd.alerts == 0
        for _ in range(5):          # collapse to zero: trips
            wd.tick(cum)
        assert wd.tripped() == ["goodput"]

    def test_same_stream_same_alert_log(self):
        """The alert log is a pure function of the sample stream."""
        def one() -> list[dict]:
            wd = BurnRateWatchdog(_WD_CFG)
            cum = _feed(wd, ticks=8, admitted_per_tick=1000.0)
            cum = _feed(wd, ticks=2, admitted_per_tick=1000.0,
                        over_jump=60.0, start=cum)
            _feed(wd, ticks=8, admitted_per_tick=1000.0, start=cum)
            return list(wd.alert_log)

        assert json.dumps(one()) == json.dumps(one())

    def test_alerts_land_as_slo_flight_frames(self):
        assert "slo" in REGISTERED_KINDS and "audit" in REGISTERED_KINDS
        fr = FlightRecorder(capacity=64)
        wd = BurnRateWatchdog(_WD_CFG, flight_recorder=fr)
        cum = _feed(wd, ticks=8, admitted_per_tick=1000.0)
        _feed(wd, ticks=1, admitted_per_tick=1000.0, over_jump=40.0,
              start=cum)
        (frame,) = fr.frames(kind="slo")
        assert frame["state"] == "trip"
        # The tuple filter (the bundle assembler's query shape).
        assert fr.frames(kind=("slo", "audit")) == [frame]
        assert fr.frames(kind=("audit",)) == []

    def test_slo_series_is_declared(self):
        # The drl-check metric-name rule resolves each entry against a
        # live registration site; here just pin the subscription shape.
        assert "drl_audit_overadmitted_tokens" in SLO_SERIES
        assert "drl_epsilon_budget_used_ratio" in SLO_SERIES


# -- conservation identities over the wire surfaces --------------------------

class TestConservationIdentities:
    def test_reservation_flow_identity_closes(self):
        run(self._reservation_body())

    async def _reservation_body(self):
        srv = BucketStoreServer(InProcessBucketStore(), port=0)
        await srv.start()
        await _drain_audit_task(srv)
        st = RemoteBucketStore(address=(srv.host, srv.port),
                               coalesce_requests=False)
        try:
            # Over-settle, under-settle, and an outstanding hold.
            await st.reserve("r1", "t", "k", 10.0, 1e6, 0.0, 1e5, 0.0)
            await st.settle("r1", "t", 25.0)     # extra debit
            await st.reserve("r2", "t", "k", 40.0, 1e6, 0.0, 1e5, 0.0)
            await st.settle("r2", "t", 5.0)      # refund
            await st.reserve("r3", "t", "k", 8.0, 1e6, 0.0, 1e5, 0.0)
            rc = srv.reservations.conservation()
            assert rc["outstanding"] == pytest.approx(8.0)
            assert rc["residue"] == pytest.approx(0.0, abs=1e-6)
            out = srv.auditor.tick()
            assert "reservation" not in out["breaches"]
            assert out["residues"]["reservation"] == pytest.approx(
                0.0, abs=1e-6)
        finally:
            await st.aclose()
            await srv.aclose()

    def test_federation_cover_identity_nonnegative(self):
        run(self._federation_body())

    async def _federation_body(self):
        backing = InProcessBucketStore()
        backing.federation_ledger(default_ttl_s=30.0)
        srv = BucketStoreServer(backing, port=0)
        await srv.start()
        await _drain_audit_task(srv)
        st = RemoteBucketStore(address=(srv.host, srv.port),
                               coalesce_requests=False)
        try:
            r = await st.fed_lease({"region": "r0", "lease_id": "L1",
                                    "tenant": "t", "demand": 2.0,
                                    "global_cap": 600.0,
                                    "global_rate": 0.0})
            assert r["granted"]
            n = await st.fed_renew({"region": "r0", "lease_id": "L1",
                                    "tenant": "t", "total": 25.0,
                                    "demand": 2.0})
            assert n["outcome"] == "ok"
            fc = srv.federation.conservation()
            # Charges (+ conservative pending) COVER regional reports:
            # never negative in correct operation.
            assert fc["residue"] >= -1e-6
            assert fc["admitted"] == pytest.approx(25.0)
            out = srv.auditor.tick()
            assert "federation" not in out["breaches"]
        finally:
            await st.aclose()
            await srv.aclose()


# -- the audit plane's serving surfaces --------------------------------------

async def _http_get(host: str, port: int, path: str) -> tuple[int, bytes]:
    reader, writer = await asyncio.open_connection(host, port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: t\r\n\r\n".encode())
    await writer.drain()
    data = await reader.read()
    writer.close()
    try:
        await writer.wait_closed()
    except (ConnectionResetError, BrokenPipeError):
        pass
    head, _, body = data.partition(b"\r\n\r\n")
    return int(head.split()[1]), body


class TestAuditSurfaces:
    def test_clean_traffic_surfaces_and_zero_breaches(self):
        run(self._clean_body())

    async def _clean_body(self):
        srv = BucketStoreServer(InProcessBucketStore(), port=0,
                                metrics_port=0)
        await srv.start()
        await _drain_audit_task(srv)
        st = RemoteBucketStore(address=(srv.host, srv.port),
                               coalesce_requests=False)
        try:
            for i in range(40):
                await st.acquire(f"k{i % 7}", 1, 1e6, 1e6)
            for _ in range(3):
                out = srv.auditor.tick()
                assert out["breaches"] == [] and out["alerts"] == []
            stats = srv.auditor.numeric_stats()
            assert stats["ticks"] == 3 and stats["breaches"] == 0
            assert stats["bundles_assembled"] == 0
            for s in EPSILON_SOURCES:
                assert 0.0 <= srv.auditor.epsilon_used[s] <= 1.0
            # OP_AUDIT round-trip.
            snap = await st.audit()
            assert snap["enabled"] and snap["bundle_ids"] == []
            assert snap["slo"]["tripped"] == []
            # OP_STATS carries the audit section.
            payload = await st.stats()
            assert payload["audit"]["breaches"] == 0
            # The OpenMetrics families render.
            text = await st.metrics()
            assert "drl_audit_breaches_total 0" in text
            assert "drl_slo_trips_total 0" in text
            assert ('drl_epsilon_budget_used_ratio{source="tier0"}'
                    in text)
            # GET /audit (+ the bundles query param).
            status, body = await _http_get(srv.host, srv.metrics_port,
                                           "/audit")
            assert status == 200 and json.loads(body)["enabled"]
            status, body = await _http_get(srv.host, srv.metrics_port,
                                           "/audit?bundles=2")
            assert status == 200 and json.loads(body)["bundles"] == []
        finally:
            await st.aclose()
            await srv.aclose()

    def test_audit_false_is_a_true_ablation(self):
        run(self._ablation_body())

    async def _ablation_body(self):
        srv = BucketStoreServer(InProcessBucketStore(), port=0,
                                audit=False)
        await srv.start()
        st = RemoteBucketStore(address=(srv.host, srv.port),
                               coalesce_requests=False)
        try:
            assert srv.auditor is None and srv._audit_task is None
            snap = await st.audit()
            assert snap == {"enabled": False}
            payload = await st.stats()
            assert "audit" not in payload
        finally:
            await st.aclose()
            await srv.aclose()


# -- THE seeded leak soak ----------------------------------------------------

#: Tight windows so the acceptance "within three ticks" bound is a real
#: detection-latency bound, not slack in a 60-tick window.
_SOAK_AUDIT_CFG = AuditConfig(slo=SLOConfig(
    fast_ticks=2, slow_ticks=6, trip_streak=1, clear_streak=2))


async def _leak_soak(seed: int) -> dict:
    """One deterministic leak episode. Returns the full observable
    schedule — alert log, bundle identity, detection tick — for the
    bit-for-bit same-seed comparison."""
    tracing.configure(enabled=True, sample_rate=1.0, keep_rate=1.0,
                      latency_threshold_s=10.0)
    tracing.get_tracer().reset()
    srv = BucketStoreServer(InProcessBucketStore(), port=0,
                            audit=_SOAK_AUDIT_CFG)
    await srv.start()
    await _drain_audit_task(srv)
    st = RemoteBucketStore(address=(srv.host, srv.port),
                           coalesce_requests=False)
    try:
        # Clean warm-up: traffic + ticks, zero alarms.
        for i in range(30):
            await st.acquire(f"warm{i % 5}", 1, 1e6, 1e6)
        for _ in range(3):
            out = srv.auditor.tick()
            assert out["breaches"] == [] and out["alerts"] == []
        # The injected double-admit: every deny on the exhausted bucket
        # flips into a granted reply with NO store debit.
        inj = FaultInjector(seed, {"audit.leak": (
            FaultRule(kind="error", probability=1.0),)})
        faults.install(inj)
        try:
            for _ in range(30):
                await st.acquire("hot", 50, 100.0, 0.0)
        finally:
            faults.uninstall()
        assert inj.events, "the leak seam never fired"
        detect_tick = None
        for i in range(3):                     # acceptance: <= 3 ticks
            out = srv.auditor.tick()
            if out["breaches"]:
                detect_tick = out["tick"]
                assert out["breaches"] == ["reply_witness"]
                assert out["residues"]["reply_witness"] > 0.0
                break
        assert detect_tick is not None, "leak not detected in 3 ticks"
        # The episode keeps burning; hysteresis must hold it to ONE
        # bundle (the leak trips the ledger AND the overadmit SLO).
        for _ in range(4):
            srv.auditor.tick()
        assert srv.auditor.bundles_assembled == 1
        (bundle,) = srv.auditor.bundles
        assert bundle["reasons"][0] == "conservation:reply_witness"
        w = bundle["witness_deltas"]
        assert (w["replied_tokens_delta"]
                > w["witnessed_tokens_delta"])   # the witnessing deltas
        # Correlation: exemplar trace ids resolve into kept traces.
        assert len(bundle["trace_ids"]) >= 1
        kept = {t.get("trace_id") for t in srv.tracer.traces()}
        assert set(bundle["trace_ids"]) & kept
        assert bundle["flight_frames"], "no correlated flight frames"
        # The wire surface ships the same bundle.
        snap = await st.audit(bundles=4)
        assert [b["id"] for b in snap["bundles"]] == [bundle["id"]]
        return {
            "detect_tick": detect_tick,
            "injected": len(inj.events),
            "alert_log": list(srv.auditor.watchdog.alert_log),
            "bundle": {"id": bundle["id"], "tick": bundle["tick"],
                       "reasons": bundle["reasons"],
                       "residues": bundle["residues"],
                       "witness_deltas": bundle["witness_deltas"]},
        }
    finally:
        await st.aclose()
        await srv.aclose()
        tracing.configure(enabled=False)
        tracing.get_tracer().reset()


class TestLeakSoak:
    def test_injected_leak_one_bundle_within_three_ticks(self):
        sched = run(_leak_soak(SEED))
        assert sched["detect_tick"] <= 3 + 3   # 3 warm-up + 3 allowed
        assert sched["bundle"]["id"] == "bundle-0000"

    def test_same_seed_identical_alert_schedule(self):
        a = run(_leak_soak(SEED))
        b = run(_leak_soak(SEED))
        assert json.dumps(a, sort_keys=True) == \
            json.dumps(b, sort_keys=True)

    # -- negative arms: the zero-false-alarm posture --

    def test_legitimate_denies_raise_nothing(self):
        """Honest denies move NEITHER witness counter — the reshard/
        upgrade soaks' deny-heavy traffic must not read as a leak."""
        run(self._denies_body())

    async def _denies_body(self):
        srv = BucketStoreServer(InProcessBucketStore(), port=0,
                                audit=_SOAK_AUDIT_CFG)
        await srv.start()
        await _drain_audit_task(srv)
        st = RemoteBucketStore(address=(srv.host, srv.port),
                               coalesce_requests=False)
        try:
            denied = 0
            for _ in range(40):
                r = await st.acquire("tight", 50, 100.0, 0.0)
                denied += 0 if r.granted else 1
            assert denied >= 30
            for _ in range(6):
                out = srv.auditor.tick()
                assert out["breaches"] == [] and out["alerts"] == []
            assert srv.auditor.bundles_assembled == 0
        finally:
            await st.aclose()
            await srv.aclose()

    def test_rolling_restart_raises_nothing(self):
        """A restarted server re-fronting the same store (the upgrade
        soak's move) resets the witness counters — the delta windows
        must re-anchor, not read the restart as drift."""
        run(self._restart_body())

    async def _restart_body(self):
        backing = InProcessBucketStore()
        srv = BucketStoreServer(backing, port=0, audit=_SOAK_AUDIT_CFG)
        await srv.start()
        await _drain_audit_task(srv)
        st = RemoteBucketStore(address=(srv.host, srv.port),
                               coalesce_requests=False)
        for i in range(20):
            await st.acquire(f"k{i}", 1, 1e6, 1e6)
        for _ in range(3):
            assert srv.auditor.tick()["breaches"] == []
        await st.aclose()
        await srv.aclose()
        srv2 = BucketStoreServer(backing, port=0,
                                 audit=_SOAK_AUDIT_CFG)
        await srv2.start()
        await _drain_audit_task(srv2)
        st2 = RemoteBucketStore(address=(srv2.host, srv2.port),
                                coalesce_requests=False)
        try:
            for i in range(20):
                await st2.acquire(f"k{i}", 1, 1e6, 1e6)
            for _ in range(6):
                out = srv2.auditor.tick()
                assert out["breaches"] == [] and out["alerts"] == []
            assert srv2.auditor.bundles_assembled == 0
        finally:
            await st2.aclose()
            await srv2.aclose()

    def test_federation_flow_raises_nothing(self):
        """The federation soak's lease/renew/reclaim flow sits on the
        CONSERVATIVE side of the cover identity — never an alarm."""
        run(self._fed_body())

    async def _fed_body(self):
        backing = InProcessBucketStore()
        backing.federation_ledger(default_ttl_s=30.0)
        srv = BucketStoreServer(backing, port=0,
                                audit=_SOAK_AUDIT_CFG)
        await srv.start()
        await _drain_audit_task(srv)
        st = RemoteBucketStore(address=(srv.host, srv.port),
                               coalesce_requests=False)
        try:
            for rid in ("A", "B", "C"):
                r = await st.fed_lease({"region": f"r{rid}",
                                        "lease_id": rid, "tenant": "t",
                                        "demand": 1.0,
                                        "global_cap": 600.0,
                                        "global_rate": 0.0})
                assert r["granted"]
            for rid in ("A", "B"):
                await st.fed_renew({"region": f"r{rid}", "lease_id": rid,
                                    "tenant": "t", "total": 10.0,
                                    "demand": 1.0})
            await st.fed_reclaim({"region": "rC", "lease_id": "C",
                                  "tenant": "t", "total": 5.0})
            for _ in range(6):
                out = srv.auditor.tick()
                assert out["breaches"] == [] and out["alerts"] == []
            assert srv.auditor.bundles_assembled == 0
        finally:
            await st.aclose()
            await srv.aclose()


# -- the <3% steady-state overhead contract ----------------------------------

@pytest.mark.slow
def test_audit_overhead_within_contract():
    """CI regression for the audit plane's <3% serving-overhead
    contract: ABBA-interleaved paired windows against two otherwise
    identical in-process rigs — audit ticking at 10x the production
    cadence vs the ``audit=False`` ablation — the same median-of-blocks
    estimator as the bench's ``audit_overhead`` section."""
    import time as _time

    async def main() -> float:
        srv_a = BucketStoreServer(
            InProcessBucketStore(), port=0,
            audit=AuditConfig(tick_s=0.05))      # 10x production rate
        srv_b = BucketStoreServer(InProcessBucketStore(), port=0,
                                  audit=False)
        await srv_a.start()
        await srv_b.start()
        st_a = RemoteBucketStore(address=(srv_a.host, srv_a.port),
                                 coalesce_requests=False)
        st_b = RemoteBucketStore(address=(srv_b.host, srv_b.port),
                                 coalesce_requests=False)

        async def window(store, depth: int = 16,
                         reqs: int = 80) -> float:
            async def worker(w: int) -> None:
                for j in range(reqs):
                    await store.acquire(f"user{(w * 13 + j) % 512}", 1,
                                        1e7, 1e7)

            t0 = _time.perf_counter()
            await asyncio.gather(*(worker(w) for w in range(depth)))
            return depth * reqs / (_time.perf_counter() - t0)

        try:
            await window(st_a)       # warm both rigs
            await window(st_b)
            blocks = []
            for _ in range(4):
                a1 = await window(st_a)
                b1 = await window(st_b)
                b2 = await window(st_b)
                a2 = await window(st_a)
                blocks.append(((a1 + a2) / 2, (b1 + b2) / 2))
            deltas = sorted((b - a) / b for a, b in blocks)
            return deltas[len(deltas) // 2] * 100.0
        finally:
            await st_a.aclose()
            await st_b.aclose()
            await srv_a.aclose()
            await srv_b.aclose()

    measured = []
    for _ in range(3):
        overhead_pct = run(main())
        measured.append(overhead_pct)
        if overhead_pct < 3.0:
            break
    assert min(measured) < 3.0, (
        f"audit-on overhead {measured} % across attempts")
