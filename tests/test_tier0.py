"""Tier-0 admission cache (native/frontend.cc replica table + the
runtime/native_frontend.py sync pump) and its store-side reconciliation
entry points (``debit_many`` / ``sync_counters_many``).

The load-bearing guarantees under test:

- **Bounded over-admission** (the differential test): for every key in a
  hot-key trace, total admitted ≤ a device-only oracle's admitted count
  plus the DOCUMENTED epsilon — ``overadmit_epsilon(headroom_budget(
  capacity, ...), fill_rate, sync_interval)`` from models/approximate.py,
  the same formula docs/OPERATIONS.md quotes.
- **Graceful degradation**: with the store failing (the r04/r05 outage
  mode), tier-0 keeps serving within its last-acked envelope instead of
  stalling, carries un-reconciled grants across failed sync rounds, and
  reconciles exactly after recovery.
- **Semantic invisibility** below the confidence gate: small buckets
  never install replicas, so exact per-request semantics are untouched
  (the parity fuzz covers this end to end with tier-0 enabled).
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.models.approximate import (
    headroom_budget,
    overadmit_epsilon,
)
from distributedratelimiting.redis_tpu.runtime.remote import RemoteBucketStore
from distributedratelimiting.redis_tpu.runtime.server import BucketStoreServer
from distributedratelimiting.redis_tpu.runtime.store import (
    BucketStore,
    InProcessBucketStore,
)
from distributedratelimiting.redis_tpu.utils.native import load_frontend_lib

_LIB = load_frontend_lib()
pytestmark = pytest.mark.skipif(
    _LIB is None or not getattr(_LIB, "has_tier0", False),
    reason="native front-end library (with tier-0 ABI) unavailable")


def run(coro):
    return asyncio.run(coro)


def _tier0_config(**kw):
    from distributedratelimiting.redis_tpu.runtime.native_frontend import (
        Tier0Config,
    )

    kw.setdefault("min_budget", 8.0)
    kw.setdefault("sync_interval_s", 0.01)
    kw.setdefault("max_stale_s", 10.0)
    return Tier0Config(**kw)


# -- policy helpers (shared with the C mirror) ------------------------------

def test_headroom_budget_policy():
    assert headroom_budget(1000.0, fraction=0.5, min_budget=64.0) == 500.0
    # Below the confidence floor: not hosted locally at all.
    assert headroom_budget(100.0, fraction=0.5, min_budget=64.0) == 0.0
    # Ceiling bounds epsilon for huge buckets.
    assert headroom_budget(1e12, fraction=0.5, min_budget=64.0,
                           max_budget=1024.0) == 1024.0


def test_overadmit_epsilon_formula():
    assert overadmit_epsilon(50.0, 0.0, 0.01) == 100.0
    assert overadmit_epsilon(0.0, 10.0, 0.5) == pytest.approx(5.0)


# -- store reconciliation entry points --------------------------------------

def test_debit_many_inprocess_saturates_and_reports_shortfall():
    async def body():
        store = InProcessBucketStore()
        await store.acquire("k", 10, 100.0, 1e-9)  # 90 left
        remaining, shortfall = await store.debit_many(
            ["k", "fresh"], [50.0, 120.0], 100.0, 1e-9)
        assert remaining[0] == pytest.approx(40.0)
        assert shortfall[0] == 0.0
        # Unknown key init-on-miss to full, then saturating debit.
        assert remaining[1] == pytest.approx(0.0)
        assert shortfall[1] == pytest.approx(20.0)

    run(body())


@pytest.mark.jax_backend
def test_debit_many_device_matches_inprocess():
    from distributedratelimiting.redis_tpu.runtime.store import (
        DeviceBucketStore,
    )

    async def body():
        store = DeviceBucketStore(n_slots=256, counter_slots=64,
                                  max_batch=64)
        await store.acquire("k", 10, 100.0, 1e-9)
        remaining, shortfall = await store.debit_many(
            ["k", "fresh"], [50.0, 120.0], 100.0, 1e-9)
        assert remaining[0] == pytest.approx(40.0)
        assert shortfall[0] == 0.0
        assert remaining[1] == pytest.approx(0.0)
        assert shortfall[1] == pytest.approx(20.0)
        # The debit is authoritative: the exact path sees the new balance.
        r = await store.acquire("k", 41, 100.0, 1e-9)
        assert not r.granted
        r = await store.acquire("k", 40, 100.0, 1e-9)
        assert r.granted
        await store.aclose()

    run(body())


@pytest.mark.jax_backend
def test_sync_counters_many_one_launch_matches_singles():
    from distributedratelimiting.redis_tpu.runtime.store import (
        DeviceBucketStore,
    )

    async def body():
        bulk = DeviceBucketStore(n_slots=256, counter_slots=64,
                                 max_batch=64)
        serial = DeviceBucketStore(n_slots=256, counter_slots=64,
                                   max_batch=64)
        keys = [f"c{i}" for i in range(5)]
        counts = [float(i + 1) for i in range(5)]
        scores, periods = await bulk.sync_counters_many(keys, counts, 1.0)
        singles = [await serial.sync_counter(k, c, 1.0)
                   for k, c in zip(keys, counts)]
        np.testing.assert_allclose(
            scores, [s.global_score for s in singles], rtol=1e-6)
        # Second round accumulates into the decaying counters.
        scores2, _ = await bulk.sync_counters_many(keys, counts, 1.0)
        assert (scores2 >= scores - 1e-3).all()
        await bulk.aclose()
        await serial.aclose()

    run(body())


def test_base_store_debit_many_is_feature_detectable():
    class Bare(InProcessBucketStore):
        debit_many = BucketStore.debit_many

    async def body():
        with pytest.raises(NotImplementedError):
            await Bare().debit_many(["k"], [1.0], 10.0, 1.0)

    run(body())


# -- tier-0 through the native server ---------------------------------------

def test_tier0_hot_key_serves_locally_and_reconciles():
    async def body():
        backing = InProcessBucketStore()
        async with BucketStoreServer(backing, native_frontend=True,
                                     native_tier0=_tier0_config()) as srv:
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            try:
                grants = 0
                for _ in range(300):
                    r = await store.acquire("hot", 1, 1000.0, 1e-9)
                    grants += r.granted
                assert grants == 300
                await asyncio.sleep(0.06)  # a few sync rounds
                st = await store.stats()
                t0 = st["tier0"]
                assert t0["installs"] >= 1
                assert t0["hits"] >= 250  # ~all but the seeding decision
                assert t0["syncs"] >= 1
                assert t0["overadmit_total"] == 0.0
                # Reconciled exactly: the backing bucket was debited for
                # every locally-granted permit.
                tokens, _ = backing._buckets[("hot", 1000.0, 1e-9)]
                assert tokens == pytest.approx(1000.0 - grants, abs=1.0)
            finally:
                await store.aclose()

    run(body())


def test_tier0_overadmit_bounded_vs_device_only_oracle():
    """THE acceptance differential: per key, admitted ≤ oracle + epsilon,
    with epsilon computed from the documented formula. Fill rate ≈ 0
    makes the oracle order-independent: exactly ``capacity`` grants per
    key no matter how the server interleaves the trace."""
    capacity, fill = 100.0, 1e-9
    per_key, n_keys = 600, 4
    cfg = _tier0_config(sync_interval_s=0.005, budget_fraction=0.5)
    budget = headroom_budget(capacity, fraction=cfg.budget_fraction,
                             min_budget=cfg.min_budget,
                             max_budget=cfg.max_budget)
    assert budget > 0  # the test must exercise tier-0, not bypass it
    epsilon = overadmit_epsilon(budget, fill, cfg.sync_interval_s)

    async def body():
        backing = InProcessBucketStore()
        async with BucketStoreServer(backing, native_frontend=True,
                                     native_tier0=cfg) as srv:
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            try:
                keys = [f"h{i}" for i in range(n_keys)]
                trace = [keys[i % n_keys] for i in range(n_keys * per_key)]
                results = await asyncio.gather(
                    *(store.acquire(k, 1, capacity, fill) for k in trace))
                admitted = {k: 0 for k in keys}
                for k, r in zip(trace, results):
                    admitted[k] += bool(r.granted)
                # Device-only oracle on the same trace: with ~zero fill
                # and unit counts, any serialization admits exactly
                # floor(capacity) per key.
                oracle = {k: int(capacity) for k in keys}
                for k in keys:
                    assert admitted[k] <= oracle[k] + epsilon, (
                        k, admitted[k], oracle[k], epsilon)
                    # Sanity floor: tier-0 must not collapse throughput
                    # either (the authoritative table still empties).
                    assert admitted[k] >= int(capacity) * 0.9, (
                        k, admitted[k])
                st = await store.stats()
                if st["tier0"]["hits"] == 0:
                    # Slow hosts (the sanitizer legs) can starve the sync
                    # pump until the storm has drained every key — and a
                    # drained key offers no cost headroom, so tier-0 never
                    # installs and the guard below would be a race, not a
                    # check. Seed FRESH keys (full headroom), give the
                    # pump a few ticks, and drive sequential traffic at
                    # the now-live replicas. The epsilon bound above ran
                    # on the original keys and is untouched; only
                    # non-vacuity is being established here.
                    for attempt in range(10):
                        k = f"heal{attempt}"
                        for _ in range(20):
                            await store.acquire(k, 1, capacity, fill)
                        st = await store.stats()
                        if st["tier0"]["hits"] > 0:
                            break
                        await asyncio.sleep(cfg.sync_interval_s * 4)
                assert st["tier0"]["hits"] > 0  # not vacuous
            finally:
                await store.aclose()

    run(body())


def test_tier0_weighted_cost_overadmit_bounded():
    """The token-denominated differential (ISSUE 10 satellite): the
    same epsilon bound as the unit-count oracle test, with N-TOKEN
    costs — per key, admitted TOKENS ≤ the device-only oracle's tokens
    plus ``overadmit_epsilon`` (a formula already denominated in
    tokens; a 4K-token grant cannot hide inside a 1-permit epsilon).
    Mixed costs per key exercise the replica's budget math at several
    grant sizes."""
    capacity, fill = 4096.0, 1e-9
    n_keys, per_key = 3, 220
    cfg = _tier0_config(sync_interval_s=0.005, budget_fraction=0.5)
    budget = headroom_budget(capacity, fraction=cfg.budget_fraction,
                             min_budget=cfg.min_budget,
                             max_budget=cfg.max_budget)
    assert budget > 0
    epsilon = overadmit_epsilon(budget, fill, cfg.sync_interval_s)

    async def body():
        backing = InProcessBucketStore()
        async with BucketStoreServer(backing, native_frontend=True,
                                     native_tier0=cfg) as srv:
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            try:
                rng = np.random.default_rng(10)
                keys = [f"w{i}" for i in range(n_keys)]
                trace = [(keys[i % n_keys],
                          int(rng.choice((4, 16, 64))))
                         for i in range(n_keys * per_key)]
                results = await asyncio.gather(
                    *(store.acquire(k, c, capacity, fill)
                      for k, c in trace))
                admitted = {k: 0 for k in keys}
                for (k, c), r in zip(trace, results):
                    if r.granted:
                        admitted[k] += c
                # Device-only oracle: with ~zero fill, ANY serialization
                # admits at most `capacity` tokens per key (the bucket
                # only empties), and at least capacity - max_cost.
                for k in keys:
                    assert admitted[k] <= capacity + epsilon, (
                        k, admitted[k], epsilon)
                    assert admitted[k] >= (capacity - 64) * 0.9, (
                        k, admitted[k])
                st = await store.stats()
                assert st["tier0"]["hits"] > 0          # lane exercised
                assert st["tier0"]["installs"] >= 1
                # Differential audit over the store's own records: the
                # authoritative balance equals capacity − admitted −
                # un-reconciled carry (≤ epsilon, in tokens).
                await asyncio.sleep(0.05)  # let syncs drain
                for k in keys:
                    tokens, _ = backing._buckets[(k, capacity, fill)]
                    assert tokens == pytest.approx(
                        capacity - admitted[k], abs=epsilon)
            finally:
                await store.aclose()

    run(body())


def test_tier0_install_requires_cost_headroom():
    """A replica whose budget cannot cover even ONE request of the
    cost that seeded it is never installed (the count>1 install-terms
    fix: min_budget is denominated in tokens, and so is the install
    gate). Semantics stay exact — every decision keeps the device
    path."""
    async def body():
        backing = InProcessBucketStore()
        # capacity 1000 → budget 500; every request costs 600 > budget.
        cfg = _tier0_config(min_budget=8.0)
        async with BucketStoreServer(backing, native_frontend=True,
                                     native_tier0=cfg) as srv:
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            try:
                r1 = await store.acquire("big", 600, 1000.0, 1e-9)
                assert r1.granted and r1.remaining == pytest.approx(400.0)
                r2 = await store.acquire("big", 600, 1000.0, 1e-9)
                assert not r2.granted
                st = await store.stats()
                # The granted 600-token fall-through must NOT have
                # installed a replica its budget (≤ 500) can't serve.
                assert st["tier0"]["installs"] == 0
                assert st["tier0"]["hits"] == 0
                # Unit-cost traffic on a fresh key still installs.
                for _ in range(3):
                    await store.acquire("small", 1, 1000.0, 1e-9)
                st = await store.stats()
                assert st["tier0"]["installs"] >= 1
            finally:
                await store.aclose()

    run(body())


class _OutageStore(InProcessBucketStore):
    """Backing store whose device-touching paths can be failed on demand
    (the r04/r05 outage mode, as seen by the front-end)."""

    def __init__(self):
        super().__init__()
        self.fail = False

    def _check(self):
        if self.fail:
            raise RuntimeError("simulated device outage")

    async def acquire_many(self, *a, **kw):
        self._check()
        return await super().acquire_many(*a, **kw)

    async def debit_many(self, *a, **kw):
        self._check()
        return await super().debit_many(*a, **kw)


def test_tier0_serves_through_outage_and_reconciles_after():
    async def body():
        backing = _OutageStore()
        cfg = _tier0_config(sync_interval_s=0.02)
        async with BucketStoreServer(backing, native_frontend=True,
                                     native_tier0=cfg) as srv:
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            try:
                # Warm: seed the replica, confirm local serving.
                warm = 0
                for _ in range(50):
                    warm += (await store.acquire("hot", 1, 10000.0,
                                                 1e-9)).granted
                assert warm == 50
                await asyncio.sleep(0.05)

                backing.fail = True
                outage_grants = 0
                for _ in range(200):
                    r = await store.acquire("hot", 1, 10000.0, 1e-9)
                    outage_grants += r.granted
                # Tier-0 kept serving from the last-known envelope.
                assert outage_grants == 200
                await asyncio.sleep(0.08)  # failing sync rounds
                st = await store.stats()
                assert st["tier0"]["sync_failures"] >= 1
                syncs_during = st["tier0"]["syncs"]

                backing.fail = False
                await asyncio.sleep(0.1)
                st2 = await store.stats()
                assert st2["tier0"]["syncs"] > syncs_during
                assert st2["tier0"]["carry_keys"] == 0  # carry drained
                # Every grant (warm + outage window) reconciled into the
                # authoritative bucket — nothing was dropped.
                tokens, _ = backing._buckets[("hot", 10000.0, 1e-9)]
                assert tokens == pytest.approx(10000.0 - warm
                                               - outage_grants, abs=1.0)
            finally:
                await store.aclose()

    run(body())


def test_tier0_disabled_for_store_without_debit_many():
    class NoDebit(InProcessBucketStore):
        debit_many = BucketStore.debit_many

    async def body():
        async with BucketStoreServer(NoDebit(), native_frontend=True,
                                     native_tier0=_tier0_config()) as srv:
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            try:
                # Serves fine, just without tier-0 (feature-detected off).
                assert (await store.acquire("k", 1, 1000.0, 1e-9)).granted
                st = await store.stats()
                assert "tier0" not in st
            finally:
                await store.aclose()

    run(body())


def test_tier0_small_buckets_keep_exact_semantics():
    """Capacity below the confidence gate: every decision stays on the
    exact device path — grant/deny boundaries are bit-identical to the
    tier-0-off server (the parity fuzz extends this end to end)."""
    async def body():
        backing = InProcessBucketStore()
        cfg = _tier0_config(min_budget=64.0)  # cap 10 → budget 5 → gated
        async with BucketStoreServer(backing, native_frontend=True,
                                     native_tier0=cfg) as srv:
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            try:
                r = await store.acquire("k", 4, 10.0, 1e-9)
                assert r.granted and r.remaining == pytest.approx(6.0)
                assert not (await store.acquire("k", 7, 10.0,
                                                1e-9)).granted
                assert (await store.acquire("k", 6, 10.0, 1e-9)).granted
                st = await store.stats()
                assert st["tier0"]["installs"] == 0
                assert st["tier0"]["hits"] == 0
            finally:
                await store.aclose()

    run(body())


def test_tier0_streak_trips_flight_recorder_and_clears(tmp_path):
    """Satellite coverage for the degraded-mode streak
    (native_frontend.py `_t0_record_round`): T0_STREAK_DUMP consecutive
    failed sync rounds are degraded entry — the flight recorder dumps —
    and ONE successful round clears the streak and drains the carried
    rows."""

    async def body():
        backing = _OutageStore()
        cfg = _tier0_config(sync_interval_s=0.02)
        async with BucketStoreServer(backing, native_frontend=True,
                                     native_tier0=cfg,
                                     flight_dir=str(tmp_path)) as srv:
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            try:
                warm = 0
                for _ in range(50):
                    warm += (await store.acquire("hot", 1, 10000.0,
                                                 1e-9)).granted
                assert warm == 50
                await asyncio.sleep(0.05)

                backing.fail = True
                for _ in range(50):  # keep the replica harvesting
                    await store.acquire("hot", 1, 10000.0, 1e-9)
                # ≥ T0_STREAK_DUMP failing rounds: the carry keeps each
                # round non-empty, so the streak advances even without
                # fresh traffic.
                fe = srv._native
                await asyncio.sleep(0.02 * (fe.T0_STREAK_DUMP + 4))
                assert fe._t0_fail_streak >= fe.T0_STREAK_DUMP
                snap = srv.flight_recorder.snapshot()
                assert snap["dumps_written"] >= 1
                assert "t0_sync_streak" in snap["last_dump_path"]
                st = await store.stats()
                assert st["tier0"]["carry_keys"] >= 1   # rows carried
                assert st["tier0"]["sync_failures"] >= fe.T0_STREAK_DUMP

                backing.fail = False
                await asyncio.sleep(0.1)  # one good round is enough
                assert fe._t0_fail_streak == 0          # streak cleared
                st2 = await store.stats()
                assert st2["tier0"]["carry_keys"] == 0  # carry drained
                # Nothing was dropped: warm + outage grants reconciled.
                tokens, _ = backing._buckets[("hot", 10000.0, 1e-9)]
                assert tokens <= 10000.0 - warm
            finally:
                await store.aclose()

    run(body())
