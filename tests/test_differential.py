"""Randomized differential tests: device kernels vs the serial Python store.

The InProcessBucketStore implements the reference semantics as
straight-line Python (one "script" per op). The device kernels must make
IDENTICAL decisions on any operation trace — random keys, counts, clock
advances, bucket configs — which catches whole classes of kernel bugs
(masking, duplicate serialization, refill clamps, window rollover) that
hand-picked cases miss. Seeded, so failures reproduce.
"""

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.store import (
    DeviceBucketStore,
    InProcessBucketStore,
)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_bucket_decisions_match_serial_reference(seed):
    rng = np.random.default_rng(seed)
    clock_a = ManualClock()
    clock_b = ManualClock()
    dev = DeviceBucketStore(n_slots=32, counter_slots=8, clock=clock_a,
                            max_batch=64)
    ref = InProcessBucketStore(clock=clock_b)
    configs = [(10.0, 2.0), (5.0, 0.5)]
    keys = [f"k{i}" for i in range(6)]

    for step in range(120):
        key = keys[rng.integers(0, len(keys))]
        count = int(rng.integers(0, 4))
        cap, rate = configs[rng.integers(0, len(configs))]
        a = dev.acquire_blocking(key, count, cap, rate)
        b = ref.acquire_blocking(key, count, cap, rate)
        assert a.granted == b.granted, (
            f"seed={seed} step={step} key={key} count={count} "
            f"cap={cap} rate={rate}: device={a} reference={b}"
        )
        assert a.remaining == pytest.approx(b.remaining, abs=1e-3)
        if rng.random() < 0.3:
            dt = float(rng.random() * 3.0)
            clock_a.advance_seconds(dt)
            clock_b.advance_seconds(dt)


@pytest.mark.parametrize("seed", [10, 11])
def test_window_decisions_match_serial_reference(seed):
    rng = np.random.default_rng(seed)
    clock_a = ManualClock()
    clock_b = ManualClock()
    dev = DeviceBucketStore(n_slots=32, counter_slots=8, clock=clock_a,
                            max_batch=64)
    ref = InProcessBucketStore(clock=clock_b)
    keys = [f"w{i}" for i in range(4)]

    for step in range(100):
        key = keys[rng.integers(0, len(keys))]
        count = int(rng.integers(1, 3))
        a = dev.window_acquire_blocking(key, count, 6.0, 1.0)
        b = ref.window_acquire_blocking(key, count, 6.0, 1.0)
        assert a.granted == b.granted, (
            f"seed={seed} step={step} key={key} count={count}: "
            f"device={a} reference={b}"
        )
        if rng.random() < 0.4:
            dt = float(rng.random() * 1.5)
            clock_a.advance_seconds(dt)
            clock_b.advance_seconds(dt)


@pytest.mark.parametrize("seed", [20, 21])
def test_counter_sync_matches_serial_reference(seed):
    rng = np.random.default_rng(seed)
    clock_a = ManualClock()
    clock_b = ManualClock()
    dev = DeviceBucketStore(n_slots=32, counter_slots=8, clock=clock_a,
                            max_batch=64)
    ref = InProcessBucketStore(clock=clock_b)

    for step in range(60):
        key = f"c{rng.integers(0, 3)}"
        local = float(rng.integers(0, 20))
        a = dev.sync_counter_blocking(key, local, 2.0)
        b = ref.sync_counter_blocking(key, local, 2.0)
        assert a.global_score == pytest.approx(b.global_score, rel=1e-4), (
            f"seed={seed} step={step} key={key} local={local}"
        )
        assert a.period_ewma_ticks == pytest.approx(
            b.period_ewma_ticks, rel=1e-4)
        dt = float(rng.random() * 2.0)
        clock_a.advance_seconds(dt)
        clock_b.advance_seconds(dt)


def test_batched_duplicates_match_serialized_singles():
    """One batch containing duplicates must decide exactly like the same
    requests arriving one-by-one (invariant 3 at batch granularity)."""
    import asyncio

    rng = np.random.default_rng(42)
    for trial in range(4):
        reqs = [(f"d{rng.integers(0, 3)}", int(rng.integers(1, 3)))
                for _ in range(12)]

        clock_a = ManualClock()
        dev = DeviceBucketStore(n_slots=16, counter_slots=8, clock=clock_a,
                                max_batch=16, max_delay_s=5e-3)

        async def batched():
            return await asyncio.gather(*(
                dev.acquire(k, c, 8.0, 1.0) for k, c in reqs
            ))

        batched_res = asyncio.run(batched())

        ref = InProcessBucketStore(clock=ManualClock())
        serial_res = [ref.acquire_blocking(k, c, 8.0, 1.0) for k, c in reqs]
        assert [r.granted for r in batched_res] == \
            [r.granted for r in serial_res], f"trial={trial} reqs={reqs}"


@pytest.mark.parametrize("seed", [20, 21])
@pytest.mark.parametrize("directory", ["host", "fp"])
def test_bulk_paths_match_serial_reference(seed, directory):
    """Differential fuzz of the BULK surfaces (buckets + sliding/fixed
    windows, grouped coalescing on), parametrized over BOTH key-directory
    homes: duplicate-free random bulk calls must decide identically to a
    serial per-request replay — the directory must be decision-invisible.
    Time advances between calls exercise refill/rollover inside the bulk
    kernels; the randomized ``with_remaining`` flag exercises both result
    encodings (f32 fused and, on the fp store, bit-plane verdicts), and
    ``remaining`` is asserted against the reference whenever present."""
    from distributedratelimiting.redis_tpu.runtime.fp_store import (
        FingerprintBucketStore,
    )

    rng = np.random.default_rng(seed)
    clock_a = ManualClock()
    clock_b = ManualClock()
    cls = DeviceBucketStore if directory == "host" else FingerprintBucketStore
    # 256 slots for 40 keys: pressure-free for the fp directory's 16-cell
    # probe windows. Under window pressure the fp store's documented
    # deny-and-heal contract legitimately diverges from the serial
    # reference for one call (observed at 64 slots: one full window →
    # a zero-count probe came back remaining=0); the equivalence claim
    # fuzzed here is the pressure-free one, asserted at the bottom.
    dev = cls(n_slots=256, counter_slots=8, clock=clock_a,
              max_batch=16)  # forces multi-chunk dispatches
    ref = InProcessBucketStore(clock=clock_b)
    keys = [f"k{i}" for i in range(40)]

    for step in range(25):
        picked = rng.choice(len(keys), size=24, replace=False)
        sub = [keys[i] for i in picked]
        counts = [int(c) for c in rng.integers(0, 4, size=24)]
        family = step % 3
        wr = bool(rng.random() < 0.5)
        if family == 0:
            got = dev.acquire_many_blocking(sub, counts, 8.0, 2.0,
                                            with_remaining=wr)
            want = [ref.acquire_blocking(k, c, 8.0, 2.0)
                    for k, c in zip(sub, counts)]
        elif family == 1:
            got = dev.window_acquire_many_blocking(sub, counts, 6.0, 1.0,
                                                   with_remaining=wr)
            want = [ref.window_acquire_blocking(k, c, 6.0, 1.0)
                    for k, c in zip(sub, counts)]
        else:
            got = dev.window_acquire_many_blocking(sub, counts, 6.0, 1.0,
                                                   fixed=True,
                                                   with_remaining=wr)
            want = [ref.fixed_window_acquire_blocking(k, c, 6.0, 1.0)
                    for k, c in zip(sub, counts)]
        for i, (w, k, c) in enumerate(zip(want, sub, counts)):
            assert bool(got.granted[i]) == w.granted, (
                f"seed={seed} step={step} family={family} wr={wr} "
                f"dir={directory} key={k} count={c}: "
                f"device={bool(got.granted[i])} reference={w}")
            if wr:
                assert got.remaining[i] == pytest.approx(w.remaining,
                                                         abs=1e-3), (
                    f"seed={seed} step={step} family={family} "
                    f"dir={directory} key={k}: remaining "
                    f"{got.remaining[i]} != {w.remaining}")
        if rng.random() < 0.5:
            dt = float(rng.random() * 2.0)
            clock_a.advance_seconds(dt)
            clock_b.advance_seconds(dt)
    if directory == "fp":
        assert dev.metrics.fp_unresolved == 0, \
            "trace hit window pressure — the fuzz no longer tests the " \
            "pressure-free equivalence contract; grow n_slots"


@pytest.mark.parametrize("seed", [30, 31])
def test_bulk_duplicates_conserve_and_order(seed):
    """With in-call duplicates (Zipf-ish) and randomized N-token costs,
    the bulk paths must never over-admit a key beyond its
    capacity/limit IN TOKENS, and grants within one call land on the
    EARLIEST occurrences (request-order serialization). Fixed counts=1
    widened to random costs by ISSUE 10 (weighted-cost parity)."""
    rng = np.random.default_rng(seed)
    clock = ManualClock()
    dev = DeviceBucketStore(n_slots=64, counter_slots=8, clock=clock,
                            max_batch=16)
    cap = 9.0
    for step in range(10):
        n = 40
        # Uniform cost PER KEY each step (mixed per-key costs void the
        # order property across chunk boundaries: a denied big-cost
        # row's reservation dies with its launch, and a later cheap row
        # can legitimately fit the residue).
        cost_of = {f"h{i}": int(rng.integers(1, 4)) for i in range(6)}
        keys = [f"h{rng.zipf(1.3) % 6}" for _ in range(n)]
        counts = [cost_of[k] for k in keys]
        res = dev.acquire_many_blocking(keys, counts, cap, 0.0)
        granted_per: dict[str, int] = {}
        last_granted_rank: dict[str, int] = {}
        occurrence: dict[str, int] = {}
        for k, c, g in zip(keys, counts, res.granted):
            rank = occurrence.get(k, 0)
            occurrence[k] = rank + 1
            if g:
                granted_per[k] = granted_per.get(k, 0) + c
                # Order: a grant may not follow a denial of the same key
                # within the call (conservative serialization means a
                # denied row's demand still reserves ahead, so once any
                # row of a key denies, every later row must too).
                assert last_granted_rank.get(k, rank - 1) == rank - 1, (
                    f"seed={seed} step={step} key={k}: grant after denial")
                last_granted_rank[k] = rank
        clock.advance_seconds(10.0)  # full refill between steps
        assert all(v <= cap for v in granted_per.values())


@pytest.mark.parametrize("seed", [40, 41])
def test_hierarchical_matches_serial_reference(seed):
    """Differential for the fused two-level kernel
    (acquire_hierarchical_packed): with DISTINCT tenants and keys per
    call the device decisions must be bit-identical to the serial
    reference (InProcessBucketStore._hier_core) — grant, remaining
    (min of the binding constraints), refill across time advances, and
    the both-or-neither refund contract."""
    rng = np.random.default_rng(seed)
    clock_a = ManualClock()
    clock_b = ManualClock()
    dev = DeviceBucketStore(n_slots=64, counter_slots=8, clock=clock_a,
                            max_batch=8)
    ref = InProcessBucketStore(clock=clock_b)

    async def run():
        for step in range(30):
            perm = rng.permutation(12)
            tenants = [f"t{i}" for i in perm[:6]]
            keys = [f"k{i}" for i in perm[:6]]
            counts = [int(c) for c in rng.integers(0, 6, 6)]
            a = await dev.acquire_hierarchical_many(
                tenants, keys, counts, 15.0, 1.0, 9.0, 2.0)
            b = await ref.acquire_hierarchical_many(
                tenants, keys, counts, 15.0, 1.0, 9.0, 2.0)
            for i in range(6):
                assert bool(a.granted[i]) == bool(b.granted[i]), (
                    f"seed={seed} step={step} row={i} "
                    f"tenant={tenants[i]} key={keys[i]} "
                    f"count={counts[i]}: device={a[i]} reference={b[i]}")
                assert a.remaining[i] == pytest.approx(b.remaining[i],
                                                       abs=1e-3)
            if rng.random() < 0.5:
                dt = float(rng.random() * 2.0)
                clock_a.advance_seconds(dt)
                clock_b.advance_seconds(dt)
        await dev.aclose()

    import asyncio

    asyncio.run(run())


def test_weighted_cost_parity_across_lanes():
    """ISSUE 10 satellite: ONE seeded schedule of N-token acquires must
    produce IDENTICAL grant/deny sequences through all four serving
    lanes — InProcess direct, remote scalar (OP_ACQUIRE), asyncio bulk
    (OP_ACQUIRE_MANY), and the native bulk lane — each against its own
    fresh in-memory backing on a never-advancing clock (decisions are
    then a pure function of the schedule)."""
    import asyncio

    from distributedratelimiting.redis_tpu.runtime.remote import (
        RemoteBucketStore,
    )
    from distributedratelimiting.redis_tpu.runtime.server import (
        BucketStoreServer,
    )
    from distributedratelimiting.redis_tpu.utils.native import (
        load_frontend_lib,
    )

    rng = np.random.default_rng(7)
    cap, rate = 1024.0, 1e-9
    n = 160
    keys = [f"u{rng.zipf(1.4) % 12}" for _ in range(n)]
    # Heavy-tailed token costs (the LLM shape): clamp into the capacity.
    costs = np.minimum(
        np.maximum(rng.lognormal(4.0, 1.2, n).astype(np.int64), 1),
        3000)

    async def lane_inprocess():
        st = InProcessBucketStore(clock=ManualClock())
        return [
            (await st.acquire(k, int(c), cap, rate)).granted
            for k, c in zip(keys, costs)]

    async def lane_remote_scalar():
        backing = InProcessBucketStore(clock=ManualClock())
        async with BucketStoreServer(backing) as srv:
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            try:
                return [
                    (await store.acquire(k, int(c), cap, rate)).granted
                    for k, c in zip(keys, costs)]
            finally:
                await store.aclose()

    async def lane_bulk(native: bool):
        backing = InProcessBucketStore(clock=ManualClock())
        async with BucketStoreServer(backing,
                                     native_frontend=native) as srv:
            if native and srv._native is None:
                return None  # no compiler: lane unavailable, skip
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            try:
                out: list[bool] = []
                # Several frames, sequential — in-frame duplicates ride
                # the same serial backing, so decisions stay exact.
                for s in range(0, n, 40):
                    res = await store.acquire_many(
                        keys[s:s + 40], costs[s:s + 40], cap, rate)
                    out.extend(bool(g) for g in res.granted)
                return out
            finally:
                await store.aclose()

    async def main():
        lanes = {
            "inprocess": await lane_inprocess(),
            "remote_scalar": await lane_remote_scalar(),
            "asyncio_bulk": await lane_bulk(False),
        }
        if load_frontend_lib() is not None:
            lanes["native_bulk"] = await lane_bulk(True)
        want = lanes["inprocess"]
        assert any(want) and not all(want)  # schedule crosses the edge
        for name, got in lanes.items():
            if got is None:
                continue
            assert got == want, (
                f"lane {name} diverged at row "
                f"{next(i for i, (x, y) in enumerate(zip(got, want)) if x != y)}")

    asyncio.run(main())
