"""Randomized differential tests: device kernels vs the serial Python store.

The InProcessBucketStore implements the reference semantics as
straight-line Python (one "script" per op). The device kernels must make
IDENTICAL decisions on any operation trace — random keys, counts, clock
advances, bucket configs — which catches whole classes of kernel bugs
(masking, duplicate serialization, refill clamps, window rollover) that
hand-picked cases miss. Seeded, so failures reproduce.
"""

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.store import (
    DeviceBucketStore,
    InProcessBucketStore,
)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_bucket_decisions_match_serial_reference(seed):
    rng = np.random.default_rng(seed)
    clock_a = ManualClock()
    clock_b = ManualClock()
    dev = DeviceBucketStore(n_slots=32, counter_slots=8, clock=clock_a,
                            max_batch=64)
    ref = InProcessBucketStore(clock=clock_b)
    configs = [(10.0, 2.0), (5.0, 0.5)]
    keys = [f"k{i}" for i in range(6)]

    for step in range(120):
        key = keys[rng.integers(0, len(keys))]
        count = int(rng.integers(0, 4))
        cap, rate = configs[rng.integers(0, len(configs))]
        a = dev.acquire_blocking(key, count, cap, rate)
        b = ref.acquire_blocking(key, count, cap, rate)
        assert a.granted == b.granted, (
            f"seed={seed} step={step} key={key} count={count} "
            f"cap={cap} rate={rate}: device={a} reference={b}"
        )
        assert a.remaining == pytest.approx(b.remaining, abs=1e-3)
        if rng.random() < 0.3:
            dt = float(rng.random() * 3.0)
            clock_a.advance_seconds(dt)
            clock_b.advance_seconds(dt)


@pytest.mark.parametrize("seed", [10, 11])
def test_window_decisions_match_serial_reference(seed):
    rng = np.random.default_rng(seed)
    clock_a = ManualClock()
    clock_b = ManualClock()
    dev = DeviceBucketStore(n_slots=32, counter_slots=8, clock=clock_a,
                            max_batch=64)
    ref = InProcessBucketStore(clock=clock_b)
    keys = [f"w{i}" for i in range(4)]

    for step in range(100):
        key = keys[rng.integers(0, len(keys))]
        count = int(rng.integers(1, 3))
        a = dev.window_acquire_blocking(key, count, 6.0, 1.0)
        b = ref.window_acquire_blocking(key, count, 6.0, 1.0)
        assert a.granted == b.granted, (
            f"seed={seed} step={step} key={key} count={count}: "
            f"device={a} reference={b}"
        )
        if rng.random() < 0.4:
            dt = float(rng.random() * 1.5)
            clock_a.advance_seconds(dt)
            clock_b.advance_seconds(dt)


@pytest.mark.parametrize("seed", [20, 21])
def test_counter_sync_matches_serial_reference(seed):
    rng = np.random.default_rng(seed)
    clock_a = ManualClock()
    clock_b = ManualClock()
    dev = DeviceBucketStore(n_slots=32, counter_slots=8, clock=clock_a,
                            max_batch=64)
    ref = InProcessBucketStore(clock=clock_b)

    for step in range(60):
        key = f"c{rng.integers(0, 3)}"
        local = float(rng.integers(0, 20))
        a = dev.sync_counter_blocking(key, local, 2.0)
        b = ref.sync_counter_blocking(key, local, 2.0)
        assert a.global_score == pytest.approx(b.global_score, rel=1e-4), (
            f"seed={seed} step={step} key={key} local={local}"
        )
        assert a.period_ewma_ticks == pytest.approx(
            b.period_ewma_ticks, rel=1e-4)
        dt = float(rng.random() * 2.0)
        clock_a.advance_seconds(dt)
        clock_b.advance_seconds(dt)


def test_batched_duplicates_match_serialized_singles():
    """One batch containing duplicates must decide exactly like the same
    requests arriving one-by-one (invariant 3 at batch granularity)."""
    import asyncio

    rng = np.random.default_rng(42)
    for trial in range(4):
        reqs = [(f"d{rng.integers(0, 3)}", int(rng.integers(1, 3)))
                for _ in range(12)]

        clock_a = ManualClock()
        dev = DeviceBucketStore(n_slots=16, counter_slots=8, clock=clock_a,
                                max_batch=16, max_delay_s=5e-3)

        async def batched():
            return await asyncio.gather(*(
                dev.acquire(k, c, 8.0, 1.0) for k, c in reqs
            ))

        batched_res = asyncio.run(batched())

        ref = InProcessBucketStore(clock=ManualClock())
        serial_res = [ref.acquire_blocking(k, c, 8.0, 1.0) for k, c in reqs]
        assert [r.granted for r in batched_res] == \
            [r.granted for r in serial_res], f"trial={trial} reqs={reqs}"


@pytest.mark.parametrize("seed", [20, 21])
@pytest.mark.parametrize("directory", ["host", "fp"])
def test_bulk_paths_match_serial_reference(seed, directory):
    """Differential fuzz of the BULK surfaces (buckets + sliding/fixed
    windows, grouped coalescing on), parametrized over BOTH key-directory
    homes: duplicate-free random bulk calls must decide identically to a
    serial per-request replay — the directory must be decision-invisible.
    Time advances between calls exercise refill/rollover inside the bulk
    kernels; the randomized ``with_remaining`` flag exercises both result
    encodings (f32 fused and, on the fp store, bit-plane verdicts), and
    ``remaining`` is asserted against the reference whenever present."""
    from distributedratelimiting.redis_tpu.runtime.fp_store import (
        FingerprintBucketStore,
    )

    rng = np.random.default_rng(seed)
    clock_a = ManualClock()
    clock_b = ManualClock()
    cls = DeviceBucketStore if directory == "host" else FingerprintBucketStore
    # 256 slots for 40 keys: pressure-free for the fp directory's 16-cell
    # probe windows. Under window pressure the fp store's documented
    # deny-and-heal contract legitimately diverges from the serial
    # reference for one call (observed at 64 slots: one full window →
    # a zero-count probe came back remaining=0); the equivalence claim
    # fuzzed here is the pressure-free one, asserted at the bottom.
    dev = cls(n_slots=256, counter_slots=8, clock=clock_a,
              max_batch=16)  # forces multi-chunk dispatches
    ref = InProcessBucketStore(clock=clock_b)
    keys = [f"k{i}" for i in range(40)]

    for step in range(25):
        picked = rng.choice(len(keys), size=24, replace=False)
        sub = [keys[i] for i in picked]
        counts = [int(c) for c in rng.integers(0, 4, size=24)]
        family = step % 3
        wr = bool(rng.random() < 0.5)
        if family == 0:
            got = dev.acquire_many_blocking(sub, counts, 8.0, 2.0,
                                            with_remaining=wr)
            want = [ref.acquire_blocking(k, c, 8.0, 2.0)
                    for k, c in zip(sub, counts)]
        elif family == 1:
            got = dev.window_acquire_many_blocking(sub, counts, 6.0, 1.0,
                                                   with_remaining=wr)
            want = [ref.window_acquire_blocking(k, c, 6.0, 1.0)
                    for k, c in zip(sub, counts)]
        else:
            got = dev.window_acquire_many_blocking(sub, counts, 6.0, 1.0,
                                                   fixed=True,
                                                   with_remaining=wr)
            want = [ref.fixed_window_acquire_blocking(k, c, 6.0, 1.0)
                    for k, c in zip(sub, counts)]
        for i, (w, k, c) in enumerate(zip(want, sub, counts)):
            assert bool(got.granted[i]) == w.granted, (
                f"seed={seed} step={step} family={family} wr={wr} "
                f"dir={directory} key={k} count={c}: "
                f"device={bool(got.granted[i])} reference={w}")
            if wr:
                assert got.remaining[i] == pytest.approx(w.remaining,
                                                         abs=1e-3), (
                    f"seed={seed} step={step} family={family} "
                    f"dir={directory} key={k}: remaining "
                    f"{got.remaining[i]} != {w.remaining}")
        if rng.random() < 0.5:
            dt = float(rng.random() * 2.0)
            clock_a.advance_seconds(dt)
            clock_b.advance_seconds(dt)
    if directory == "fp":
        assert dev.metrics.fp_unresolved == 0, \
            "trace hit window pressure — the fuzz no longer tests the " \
            "pressure-free equivalence contract; grow n_slots"


@pytest.mark.parametrize("seed", [30, 31])
def test_bulk_duplicates_conserve_and_order(seed):
    """With in-call duplicates (Zipf-ish), the bulk paths must never
    over-admit a key beyond its capacity/limit, and grants within one
    call land on the EARLIEST occurrences (request-order serialization)."""
    rng = np.random.default_rng(seed)
    clock = ManualClock()
    dev = DeviceBucketStore(n_slots=64, counter_slots=8, clock=clock,
                            max_batch=16)
    cap = 5.0
    for step in range(10):
        n = 40
        keys = [f"h{rng.zipf(1.3) % 6}" for _ in range(n)]
        res = dev.acquire_many_blocking(keys, [1] * n, cap, 0.0)
        granted_per: dict[str, int] = {}
        last_granted_rank: dict[str, int] = {}
        occurrence: dict[str, int] = {}
        for k, g in zip(keys, res.granted):
            rank = occurrence.get(k, 0)
            occurrence[k] = rank + 1
            if g:
                granted_per[k] = granted_per.get(k, 0) + 1
                # Order: a grant may not follow a denial of the same key
                # within the call.
                assert last_granted_rank.get(k, rank - 1) == rank - 1, (
                    f"seed={seed} step={step} key={k}: grant after denial")
                last_granted_rank[k] = rank
        clock.advance_seconds(10.0)  # full refill between steps
        assert all(v <= cap for v in granted_per.values())
